"""Zero-downtime model rollout: canaried, quality-gated live weight swap
with automatic rollback.

The serving plane survives dead chips (replica quarantine + resurrection),
dead hosts (the multi-host router), corrupt stores (verified reads), and
overload (elastic admission) — but until this module, a *model update*
required killing the pod and cold-restarting every replica, warmup
compiles and all.  :class:`RolloutController` ships new weights while the
pod serves, judges the new version against the old with the SAME label-
free quality signals + PSI drift gate the accuracy sentinel uses
(observability/quality.py), and rolls back automatically when the canary
regresses — zero lost requests in either direction.

State machine (``rollout_phase`` events record every edge)::

    IDLE -> STAGING -> CANARY -> PROMOTING -> COMPLETE
              |           |          |
              +-----------+----------+--> ROLLING_BACK -> ROLLED_BACK
              |
              +--> IDLE   (refused: bad checksum/shape, too few replicas)

  * **STAGING** — resolve the candidate via the versioned-checkpoint
    loader (newest complete ``step_<N>``), refuse it on payload-sha256 or
    architecture mismatch BEFORE touching any replica, then borrow ONE
    replica: drain it (in-flight batches finish; the rest of the pool
    keeps serving at N-1 capacity), swap its weights, and re-warm the
    bucket ladder off the dispatch path (fresh memory-ledger rows).
  * **CANARY** — re-admit the swapped replica and route a configured
    traffic fraction to it (``ReplicaPool.set_canary`` — a deterministic
    credit accumulator, no RNG).  Every ``serve_result``/``quality`` event
    and per-version metric family carries ``model_version``, so the judge
    splits new from old by construction.  Once both versions have enough
    results, new-vs-old is judged on three axes: PSI over the per-signal
    quality digests (``psi`` > threshold = drift), error-rate delta, and
    the latency EWMA ratio.
  * **PROMOTING** — the remaining replicas swap one drained ladder step at
    a time: capacity degrades by exactly one replica at any instant,
    availability never.  Only after the last swap does the pod identity
    (health-doc ``model_version``) advance and the feature store move to
    the new weights' fingerprint generation (superseded generations GC
    with a grace — the rollback target's cache survives, satellite of
    ``FeatureStore.gc_superseded``).
  * **ROLLBACK** — the same ladder in reverse, triggered automatically by
    a canary breach or a failed swap.  The old params are still resident
    (captured at staging), so rollback is another drained swap, not a
    restart.

**Crash consistency.**  The durable pointer (``state_path``) is two-phase:
the candidate is recorded at staging, but ``current`` only advances at
COMPLETE — so a SIGKILL at ANY phase (the ``kill_at_weight_swap`` chaos
seam fires inside the swap window) restarts on exactly one consistent
version: the old one before COMPLETE, the new one after.
:func:`resolve_serving_checkpoint` is the restart-side half.

Locking: the controller's ``_lock`` guards only its own stats/phase.  The
service calls ``observe_result``/``observe_failure``/``status`` (controller
lock only, sometimes while holding the service lock); the controller calls
``service.rollout_*`` seams (service lock only) — never while holding its
own lock.  One consistent lock order, no deadlock.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.metrics import Histogram
from ncnet_tpu.observability.quality import (
    DEFAULT_PSI_THRESHOLD,
    DIGEST_BINS,
    QUALITY_SIGNALS,
    SIGNAL_RANGE,
    psi,
)

log = get_logger("rollout")

# rollout phases (the ``rollout_phase`` event vocabulary)
ROLLOUT_IDLE = "IDLE"
ROLLOUT_STAGING = "STAGING"
ROLLOUT_CANARY = "CANARY"
ROLLOUT_PROMOTING = "PROMOTING"
ROLLOUT_COMPLETE = "COMPLETE"
ROLLOUT_ROLLING_BACK = "ROLLING_BACK"
ROLLOUT_ROLLED_BACK = "ROLLED_BACK"

_ALLOWED = {
    ROLLOUT_IDLE: (ROLLOUT_STAGING,),
    # STAGING -> IDLE is the refusal edge: nothing was touched
    ROLLOUT_STAGING: (ROLLOUT_CANARY, ROLLOUT_IDLE, ROLLOUT_ROLLING_BACK),
    ROLLOUT_CANARY: (ROLLOUT_PROMOTING, ROLLOUT_ROLLING_BACK),
    ROLLOUT_PROMOTING: (ROLLOUT_COMPLETE, ROLLOUT_ROLLING_BACK),
    ROLLOUT_ROLLING_BACK: (ROLLOUT_ROLLED_BACK,),
    ROLLOUT_COMPLETE: (),
    ROLLOUT_ROLLED_BACK: (),
}

_EWMA_ALPHA = 0.3  # same memory as the admission/replica wall EWMAs

ROLLOUT_STATE_SCHEMA = 1


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    """Knobs of one live rollout (README "Live rollout")."""

    # canary routing + judging
    canary_fraction: float = 0.25      # share of decisions the canary gets
    canary_min_results: int = 16       # per-version results before judging
                                       # (0 = skip judging: promote blind)
    canary_timeout_s: float = 60.0     # starved canary -> rollback
    drain_timeout_s: float = 30.0      # per-replica drain bound
    # judge gates (breach any one -> rollback)
    psi_threshold: float = DEFAULT_PSI_THRESHOLD
    judge_signals: Tuple[str, ...] = QUALITY_SIGNALS
    error_rate_margin: float = 0.10    # new error rate may exceed old by this
    latency_factor: float = 3.0        # new EWMA > factor * old EWMA = breach
    min_latency_samples: int = 8       # EWMAs compared only past this
    # durability + store grace
    state_path: Optional[str] = None   # two-phase version pointer (None = off)
    gc_keep_generations: int = 1       # superseded store generations kept


# ---------------------------------------------------------------------------
# durable version pointer (two-phase: candidate at staging, current at
# COMPLETE) — the SIGKILL-consistency contract
# ---------------------------------------------------------------------------


def write_rollout_state(path: str, state: Dict[str, Any]) -> None:
    """Atomic tmp+rename+fsync, like every durable artifact here."""
    doc = {"schema": ROLLOUT_STATE_SCHEMA, **state}
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_rollout_state(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def resolve_serving_checkpoint(state_path: Optional[str],
                               default: Optional[str]) -> Optional[str]:
    """Which checkpoint a restarting pod should serve: the state file's
    ``current`` pointer when one was ever committed (i.e. a rollout
    COMPLETEd), else ``default`` (the operator's configured checkpoint).
    A SIGKILL mid-swap left ``current`` un-advanced, so the restart lands
    on the OLD version — one consistent version, never a mix."""
    if state_path:
        state = read_rollout_state(state_path)
        if state and state.get("current"):
            return state["current"]
    return default


# ---------------------------------------------------------------------------
# per-version live stats (the judge's evidence)
# ---------------------------------------------------------------------------


class _VersionStats:
    """One model version's canary-window evidence: result/failure counts,
    a wall EWMA, and per-signal quality digests binned EXACTLY like the
    drift sentinel's (SIGNAL_RANGE x DIGEST_BINS — ``psi`` requires
    identical binning)."""

    def __init__(self):
        self.results = 0
        self.failures = 0
        self.ewma_wall_ms: Optional[float] = None
        self.digests: Dict[str, Histogram] = {}

    def note_result(self, wall_ms: float,
                    quality: Optional[Dict[str, float]]) -> None:
        self.results += 1
        w = float(wall_ms)
        self.ewma_wall_ms = w if self.ewma_wall_ms is None else (
            _EWMA_ALPHA * w + (1.0 - _EWMA_ALPHA) * self.ewma_wall_ms)
        if quality:
            for name, v in quality.items():
                h = self.digests.get(name)
                if h is None:
                    lo, hi = SIGNAL_RANGE.get(name, (0.0, 1.0))
                    h = self.digests[name] = Histogram(lo, hi, DIGEST_BINS)
                h.add(float(v))

    def error_rate(self) -> Optional[float]:
        n = self.results + self.failures
        return (self.failures / n) if n else None

    def snapshot(self) -> Dict[str, Any]:
        return {
            "results": self.results,
            "failures": self.failures,
            "ewma_wall_ms": (round(self.ewma_wall_ms, 3)
                             if self.ewma_wall_ms is not None else None),
        }


class RolloutRefused(RuntimeError):
    """The candidate never touched a replica: payload/arch mismatch, same
    version, or the pool cannot spare a canary."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


class RolloutController:
    """One live rollout, driven through a ``MatchService``'s ``rollout_*``
    seams.  Construct, then :meth:`run` (or let
    ``MatchService.start_rollout`` run it on a background thread)::

        ctl = RolloutController(service, RolloutConfig(state_path=...))
        outcome = ctl.run("/ckpts")          # COMPLETE | ROLLED_BACK | IDLE

    ``loader`` (or ``service.rollout_loader`` — the test seam) replaces
    the default checkpoint loader; it takes the candidate string and
    returns ``(resolved_path, version, model_config_or_None, params)``.
    """

    def __init__(self, service, config: RolloutConfig = RolloutConfig(), *,
                 loader: Optional[Callable[[str], Tuple]] = None):
        self.service = service
        self.cfg = config
        self._loader = loader
        self._lock = threading.Lock()
        self.phase = ROLLOUT_IDLE
        self.reason: Optional[str] = None
        self.old_version: Optional[str] = None
        self.new_version: Optional[str] = None
        self.candidate_path: Optional[str] = None
        self._old_params = None
        self._new_params = None
        self._stats: Dict[str, _VersionStats] = {}
        self._verdict: Optional[Dict[str, Any]] = None
        # baseline (old-version) evidence starts accumulating at attach
        service.attach_rollout(self)

    # -- service-facing (controller lock ONLY; may be called under the
    #    service lock) --------------------------------------------------

    def observe_result(self, version: str, wall_ms: float,
                       quality: Optional[Dict[str, float]]) -> None:
        with self._lock:
            self._stats.setdefault(
                version, _VersionStats()).note_result(wall_ms, quality)

    def observe_failure(self, version: str) -> None:
        with self._lock:
            self._stats.setdefault(version, _VersionStats()).failures += 1

    def status(self) -> Dict[str, Any]:
        """The health document's ``rollout`` section (and GET /rollout)."""
        with self._lock:
            return {
                "phase": self.phase,
                "reason": self.reason,
                "old_version": self.old_version,
                "new_version": self.new_version,
                "candidate": self.candidate_path,
                "canary_fraction": self.cfg.canary_fraction,
                "versions": {v: s.snapshot()
                             for v, s in sorted(self._stats.items())},
                "verdict": self._verdict,
            }

    # -- internals -------------------------------------------------------

    def _to(self, phase: str, reason: str = "") -> None:
        with self._lock:
            if phase not in _ALLOWED[self.phase]:
                raise RuntimeError(
                    f"illegal rollout transition {self.phase} -> {phase}")
            self.phase = phase
            self.reason = reason or None
        obs_events.emit("rollout_phase", phase=phase, reason=reason or None,
                        old_version=self.old_version,
                        new_version=self.new_version)

    def _persist(self, current: Optional[str]) -> None:
        if not self.cfg.state_path:
            return
        prior = read_rollout_state(self.cfg.state_path) or {}
        write_rollout_state(self.cfg.state_path, {
            "current": current if current is not None
            else prior.get("current"),
            "candidate": self.candidate_path,
            "candidate_version": self.new_version,
            "old_version": self.old_version,
            "phase": self.phase,
            "t": time.time(),
        })

    def _load_candidate(self, candidate: str):
        """Resolve + verify the candidate BEFORE any replica is touched.
        Raises :class:`RolloutRefused` with a classified reason."""
        from ncnet_tpu.models.checkpoint import CheckpointPayloadError

        loader = self._loader or self._default_loader
        try:
            resolved, version, config, params = loader(candidate)
        except RolloutRefused:
            raise
        except CheckpointPayloadError as e:
            raise RolloutRefused(str(e), reason="payload_sha_mismatch")
        except Exception as e:  # noqa: BLE001 — any load failure refuses,
            # never crashes the serving process driving the rollout
            raise RolloutRefused(
                f"candidate {candidate!r} failed to load: "
                f"{type(e).__name__}: {e}", reason="load_failed")
        if version == self.service.model_version:
            raise RolloutRefused(
                f"candidate resolves to the live version {version!r}",
                reason="same_version")
        base = getattr(self.service, "_model_config", None)
        if base is not None and config is not None:
            from ncnet_tpu.models.checkpoint import _ARCH_FIELDS

            bad = [k for k in _ARCH_FIELDS
                   if getattr(config, k) != getattr(base, k)]
            if bad:
                raise RolloutRefused(
                    f"candidate architecture differs on {bad} — a rollout "
                    "swaps weights, not architectures", reason="arch_mismatch")
        return resolved, version, params

    def _default_loader(self, candidate: str):
        """PR 1's newest-complete resolution + the payload-sha gate + the
        ``corrupt_candidate_checkpoint`` chaos seam (bit-flips the loaded
        tree so the sha gate has real corruption to catch)."""
        from ncnet_tpu.models.checkpoint import (
            load_params,
            resolve_checkpoint_dir,
            verify_checkpoint_payload,
        )
        from ncnet_tpu.utils import faults

        resolved = resolve_checkpoint_dir(candidate)
        base = getattr(self.service, "_model_config", None)
        if base is not None:
            config, params = load_params(resolved, base)
        else:
            config, params = load_params(resolved)
        params = faults.corrupt_candidate_hook(resolved, params)
        verify_checkpoint_payload(resolved, params)
        version = os.path.basename(os.path.normpath(resolved))
        return resolved, version, config, params

    # -- the rollout itself ----------------------------------------------

    def run(self, candidate: str) -> str:
        """Drive the full state machine; returns the terminal phase
        (COMPLETE / ROLLED_BACK / IDLE-on-refusal).  Never raises for
        operational failures — a rollout is a maintenance action on a
        LIVE service, and its failure modes all end in a consistent,
        serving pod."""
        svc = self.service
        with self._lock:
            self.old_version = svc.model_version
            self._old_params = getattr(svc, "_model_params", None)
        self._to(ROLLOUT_STAGING)
        try:
            resolved, version, params = self._load_candidate(candidate)
        except RolloutRefused as e:
            obs_events.emit("rollout_refused", candidate=candidate,
                            reason=e.reason, error=str(e)[:300])
            log.warning(f"rollout refused ({e.reason}): {e}", kind="io")
            self._to(ROLLOUT_IDLE, f"refused:{e.reason}")
            return ROLLOUT_IDLE
        with self._lock:
            self.candidate_path = resolved
            self.new_version = version
            self._new_params = params
        self._persist(current=None)  # phase 1: candidate recorded only

        # detach the store from swapped replicas when the backbone weights
        # actually changed (committing new-weight features into the old
        # generation would poison the cache); an NC-filter-only fine-tune
        # keeps the same generation and stays attached
        detach = False
        if getattr(svc, "_store", None) is not None:
            try:
                from ncnet_tpu.store import weights_digest

                detach = (self._old_params is None
                          or weights_digest(params)
                          != weights_digest(self._old_params))
            except Exception:  # noqa: BLE001 — unknown trees: stay safe
                detach = True

        # stage on ONE drained replica while the rest of the pool serves
        try:
            canary = svc.rollout_pick_canary()
        except RuntimeError as e:
            obs_events.emit("rollout_refused", candidate=candidate,
                            reason="no_spare_replica", error=str(e)[:300])
            self._to(ROLLOUT_IDLE, "refused:no_spare_replica")
            return ROLLOUT_IDLE
        if not svc.rollout_drain(canary, self.cfg.drain_timeout_s):
            svc.rollout_readmit(canary, reason="rollout_drain_timeout")
            obs_events.emit("rollout_refused", candidate=candidate,
                            reason="drain_timeout", error=None)
            self._to(ROLLOUT_IDLE, "refused:drain_timeout")
            return ROLLOUT_IDLE
        try:
            svc.rollout_swap(canary, params, version, detach_store=detach)
        except Exception as e:  # noqa: BLE001 — a failed swap rolls back
            log.error(f"canary swap failed ({type(e).__name__}: {e}); "
                      "rolling back", kind="device")
            return self._rollback("canary_swap_failed", [canary])

        # CANARY: re-admit + route the fraction; judge once fed
        self._to(ROLLOUT_CANARY)
        with self._lock:
            self._stats = {}  # the judge window starts here, both versions
        svc.rollout_readmit(canary, reason="canary")
        svc.rollout_set_canary(canary, self.cfg.canary_fraction)
        breach = None
        if self.cfg.canary_min_results > 0:
            breach = self._canary_wait_and_judge()
        if breach is not None:
            svc.rollout_clear_canary()
            return self._rollback(breach, [canary])

        # PROMOTING: the remaining replicas, one drained swap at a time
        self._to(ROLLOUT_PROMOTING)
        svc.rollout_clear_canary()
        for rep in svc.rollout_replicas():
            if rep.model_version == version:
                continue
            if not svc.rollout_drain(rep, self.cfg.drain_timeout_s):
                svc.rollout_readmit(rep, reason="rollout_drain_timeout")
                return self._rollback("promote_drain_timeout",
                                      self._swapped_replicas())
            try:
                svc.rollout_swap(rep, params, version, detach_store=detach)
            except Exception as e:  # noqa: BLE001
                log.error(f"promotion swap on {rep.id} failed "
                          f"({type(e).__name__}: {e}); rolling back",
                          kind="device")
                return self._rollback("promote_swap_failed",
                                      self._swapped_replicas())
            svc.rollout_readmit(rep, reason="promoted")

        # COMPLETE: advance the pod identity, THEN the durable pointer,
        # THEN let the store GC superseded generations (with grace)
        svc.rollout_set_version(version, params)
        self._to(ROLLOUT_COMPLETE)
        self._persist(current=resolved)  # phase 2: the pointer advances
        svc.rollout_switch_store(params)
        svc.rollout_gc_store(self.cfg.gc_keep_generations)
        log.info(f"rollout complete: {self.old_version} -> {version}",
                 kind="io")
        return ROLLOUT_COMPLETE

    def _swapped_replicas(self) -> List[Any]:
        return [r for r in self.service.rollout_replicas()
                if r.model_version == self.new_version]

    def _canary_wait_and_judge(self) -> Optional[str]:
        """Wait until both versions have ``canary_min_results`` results
        (or the window times out), then judge.  Returns the breach reason
        (→ rollback) or None (→ promote)."""
        deadline = time.monotonic() + self.cfg.canary_timeout_s
        need = self.cfg.canary_min_results
        while True:
            with self._lock:
                new = self._stats.get(self.new_version)
                old = self._stats.get(self.old_version)
                fed = (new is not None and new.results >= need
                       and old is not None and old.results >= need)
            if fed:
                break
            if time.monotonic() >= deadline:
                # a canary that cannot even absorb its fraction is its own
                # verdict — the stream may have stopped, but promoting on
                # zero evidence is how silent regressions ship
                return "canary_starved"
            time.sleep(0.02)
        return self._judge()

    def _judge(self) -> Optional[str]:
        """New-vs-old on three axes; ANY breach rolls back.  Emits ONE
        ``rollout_canary_verdict`` event carrying every input — the replay
        (``run_report --rollout``) re-reads the decision, not a summary."""
        with self._lock:
            new = self._stats.get(self.new_version) or _VersionStats()
            old = self._stats.get(self.old_version) or _VersionStats()
            psi_by_signal: Dict[str, float] = {}
            for name in self.cfg.judge_signals:
                ho, hn = old.digests.get(name), new.digests.get(name)
                if ho is None or hn is None or not ho.count or not hn.count:
                    continue
                psi_by_signal[name] = round(psi(ho, hn), 4)
            new_err, old_err = new.error_rate(), old.error_rate()
            new_ms, old_ms = new.ewma_wall_ms, old.ewma_wall_ms
            enough_latency = (new.results >= self.cfg.min_latency_samples
                              and old.results >= self.cfg.min_latency_samples)
        breach = None
        drifted = [n for n, v in psi_by_signal.items()
                   if v > self.cfg.psi_threshold]
        if drifted:
            breach = f"quality_drift:{','.join(sorted(drifted))}"
        elif (new_err is not None and old_err is not None
              and new_err - old_err > self.cfg.error_rate_margin):
            breach = "error_rate"
        elif (enough_latency and new_ms is not None and old_ms
              and new_ms > self.cfg.latency_factor * old_ms):
            breach = "latency"
        verdict = {
            "breach": breach,
            "psi": psi_by_signal,
            "psi_threshold": self.cfg.psi_threshold,
            "error_rate": {"old": old_err, "new": new_err,
                           "margin": self.cfg.error_rate_margin},
            "latency_ewma_ms": {"old": old_ms, "new": new_ms,
                                "factor": self.cfg.latency_factor},
            "results": {"old": old.results, "new": new.results},
        }
        with self._lock:
            self._verdict = verdict
        obs_events.emit("rollout_canary_verdict",
                        old_version=self.old_version,
                        new_version=self.new_version, **verdict)
        return breach

    def _rollback(self, reason: str, replicas: List[Any]) -> str:
        """The ladder in reverse: every replica on the new version swaps
        back to the still-resident old params, one drained step at a
        time.  The durable pointer never advanced, so even a crash DURING
        rollback restarts on the old version."""
        svc = self.service
        self._to(ROLLOUT_ROLLING_BACK, reason)
        svc.rollout_clear_canary()
        stuck: List[str] = []
        for rep in replicas:
            if not svc.rollout_drain(rep, self.cfg.drain_timeout_s):
                # a replica that cannot drain cannot be safely swapped;
                # it stays DRAINING (no traffic) as an operator signal —
                # the rest of the pod still converges on the old version
                stuck.append(rep.id)
                log.error(f"rollback: {rep.id} failed to drain; left out "
                          "of rotation", kind="device")
                continue
            try:
                svc.rollout_swap(rep, self._old_params, self.old_version)
            except Exception as e:  # noqa: BLE001 — a replica that cannot
                # swap back stays out of rotation; availability degrades,
                # correctness does not
                stuck.append(rep.id)
                log.error(f"rollback swap on {rep.id} failed "
                          f"({type(e).__name__}: {e}); left out of "
                          "rotation", kind="device")
                continue
            svc.rollout_readmit(rep, reason="rolled_back")
        svc.rollout_set_version(self.old_version, self._old_params)
        svc.rollout_reattach_store()
        self._to(ROLLOUT_ROLLED_BACK, reason)
        self._persist(current=None)
        obs_events.emit("rollout_rolled_back", reason=reason,
                        old_version=self.old_version,
                        new_version=self.new_version,
                        stuck_replicas=stuck or None)
        log.warning(f"rollout rolled back ({reason}): pod back on "
                    f"{self.old_version}", kind="device")
        return ROLLOUT_ROLLED_BACK
