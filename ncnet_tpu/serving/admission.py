"""Admission control + backpressure for the match service.

Load shedding at the door is the difference between a service that degrades
(rejects the overflow with a classified error and a retry hint, keeps its
admitted work inside deadline) and one that collapses (admits everything,
queues grow without bound, EVERY request deadline-blows).  The controller
enforces four independent bounds, checked in one place under the service
lock:

  * **queue depth** — total queued requests across buckets may not exceed
    the EFFECTIVE queue bound; the overflow sheds with
    ``reason="queue_full"`` and a ``retry_after_s`` hint derived from
    actual aggregate throughput, so well-behaved clients back off
    proportionally to real load.  With a replica pool the bound is
    elastic: ``max_queue`` scaled by the live ready/total replica fraction
    (floored at one batch), so a 4-replica pool running on 2 survivors
    advertises half the queue instead of buffering work it can no longer
    drain in time.
  * **per-client in-flight cap** — one misbehaving client (a runaway retry
    loop, a fan-out bug) may not occupy the whole queue; beyond
    ``max_in_flight_per_client`` outstanding (queued or dispatched)
    requests, that client's submissions shed with ``reason="client_cap"``
    while other clients keep being admitted.
  * **pool capacity** — zero READY replicas admits nothing
    (``reason="no_capacity"``): queueing behind a dead pool would turn
    every admission into a deadline blow; the retry hint is the
    resurrection-probe period, the soonest capacity could return.
  * **lifecycle** — a draining or stopped service admits nothing
    (``reason="draining"`` / ``"stopped"``), so SIGTERM can complete the
    admitted work without the queue refilling behind it.

The ``retry_after_s`` hint derives from the AGGREGATE pool cadence: the
pool drains ``ready_replicas`` batches per measured batch wall, so the
estimate is ``batches_ahead x batch_wall / ready_replicas`` — it stays
honest as replicas die (fewer drains per wall → longer hints) and
resurrect (hints shrink back), which is what keeps shed clients from
hammering a half-dead pool at full-pool cadence.

**The capacity-units contract** (:meth:`AdmissionController.note_capacity`).
One controller serves two tiers, so the ``ready``/``total`` feed is defined
once: they are **drain-lane units**, not processes and not devices.  A
single-process ``MatchService`` feeds its READY/total REPLICA counts (one
lane per engine); the multi-host ``MatchRouter`` feeds the SUM of ready
replicas across its live backends over the pod's provisioned total — the
pod's true drain lanes, which is what its queue bound must track (a router
admitting against its *local* device count would buffer a dead pod's worth
of work, and one admitting per *backend process* would halve its bound
when a 4-replica host loses one chip).  Both tiers' elastic bounds then
compose: each backend sheds at its own live-replica bound, the router
sheds at the pod's, and the same ``units``-scaled cadence maths keeps both
tiers' ``retry_after_s`` hints honest.

The controller holds no lock of its own: the service serializes every call
under its condition lock, and the throughput EWMA is a single float write.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from ncnet_tpu.serving.request import Overloaded


class AdmissionController:
    """Bounds + the retry-after estimator (see module docstring)."""

    _ALPHA = 0.3  # batch-wall EWMA: ~6-sample memory, enough to track load

    def __init__(self, max_queue: int = 64,
                 max_in_flight_per_client: int = 16,
                 max_batch: int = 8, *, elastic: bool = True,
                 dead_retry_after_s: float = 5.0):
        if max_queue < 1 or max_in_flight_per_client < 1 or max_batch < 1:
            raise ValueError(
                f"bad admission knobs: max_queue={max_queue} "
                f"per_client={max_in_flight_per_client} max_batch={max_batch}"
            )
        self.max_queue = int(max_queue)
        self.max_in_flight_per_client = int(max_in_flight_per_client)
        self.max_batch = int(max_batch)
        self.elastic = bool(elastic)
        self.dead_retry_after_s = float(dead_retry_after_s)
        self._per_client: Dict[str, int] = {}
        self._batch_wall_ewma: Optional[float] = None
        # live pool capacity (single-engine services never call
        # note_capacity and keep the 1/1 default — PR 8 semantics exactly)
        self._ready = 1
        self._total = 1

    # -- accounting (service-lock serialized) -------------------------------

    def note_admit(self, client: str) -> None:
        self._per_client[client] = self._per_client.get(client, 0) + 1

    def note_done(self, client: str) -> None:
        """Called on EVERY terminal outcome of an admitted request — the
        cap tracks outstanding work, so a leak here would slowly choke the
        client out (the chaos suite pins the pairing)."""
        n = self._per_client.get(client, 0) - 1
        if n <= 0:
            self._per_client.pop(client, None)
        else:
            self._per_client[client] = n

    def note_batch_wall(self, seconds: float) -> None:
        s = float(seconds)
        self._batch_wall_ewma = s if self._batch_wall_ewma is None else (
            self._ALPHA * s + (1.0 - self._ALPHA) * self._batch_wall_ewma
        )

    def note_capacity(self, ready: int, total: int) -> None:
        """Live capacity changed.  ``ready``/``total`` are DRAIN-LANE
        UNITS (the module-docstring contract): READY/total replicas for a
        pool-backed service, the pod-wide sum of ready replicas across
        live backends for a router — never the local process's device
        count.  The elastic queue bound and the retry-after cadence both
        re-derive from the live unit count."""
        self._ready = max(0, int(ready))
        self._total = max(1, int(total))

    def outstanding(self, client: str) -> int:
        return self._per_client.get(client, 0)

    # -- the decision -------------------------------------------------------

    def effective_max_queue(self) -> int:
        """The live queue bound: ``max_queue`` scaled by the ready/total
        unit fraction (elastic pools only), floored at one batch so a
        single surviving drain lane still coalesces full batches (the
        router's drain unit is one request — ``max_batch=1`` — so its
        floor is one)."""
        if not self.elastic or self._total <= 1:
            return self.max_queue
        share = self.max_queue * self._ready / self._total
        return max(self.max_batch, int(math.ceil(share)))

    def retry_after_s(self, queue_depth: int) -> float:
        """When a shed client should retry: the time to drain the current
        queue at the recent AGGREGATE pool cadence (``ready`` replicas
        drain in parallel, so batches-ahead x wall / ready), floored at
        50 ms (an empty estimate must not invite an instant hammer-retry).
        With zero ready replicas the honest hint is the resurrection-probe
        period — the soonest any capacity can come back."""
        if self._ready == 0:
            return round(self.dead_retry_after_s, 3)
        wall = self._batch_wall_ewma if self._batch_wall_ewma else 0.1
        batches_ahead = max(1.0, queue_depth / self.max_batch)
        return max(0.05, round(batches_ahead * wall / self._ready, 3))

    def admit(self, client: str, queue_depth: int) -> None:
        """Raise :class:`Overloaded` when the request must shed; returns
        None when admissible.  The caller (service.submit, under its lock)
        then enqueues and calls :meth:`note_admit` — check and commit are
        one critical section."""
        if self._ready == 0:
            raise Overloaded(
                f"no ready replicas ({self._total} in pool, all dead; "
                "resurrection probes pending)",
                reason="no_capacity",
                retry_after_s=self.retry_after_s(queue_depth),
            )
        bound = self.effective_max_queue()
        if queue_depth >= bound:
            raise Overloaded(
                f"queue full ({queue_depth}/{bound}"
                + (f", {self._ready}/{self._total} replicas ready"
                   if self._total > 1 else "") + ")",
                reason="queue_full",
                retry_after_s=self.retry_after_s(queue_depth),
            )
        if self.outstanding(client) >= self.max_in_flight_per_client:
            raise Overloaded(
                f"client {client!r} has "
                f"{self.outstanding(client)} requests in flight "
                f"(cap {self.max_in_flight_per_client})",
                reason="client_cap",
                retry_after_s=self.retry_after_s(queue_depth),
            )
