"""Padded shape buckets: the bounded-jit-cache discipline for serving.

Variable-resolution queries cannot each get their own compiled program — a
jit cache keyed by raw shapes grows without bound under adversarial (or
merely diverse) traffic, and every new shape pays a full trace+compile on
the serving hot path.  The bucketer maps each incoming ``(H, W)`` to the
smallest padded bucket shape that holds it (round up to a multiple, capped
at ``max_side``), and bounds the number of DISTINCT pair buckets the
service will ever compile (``max_buckets``): a request whose bucket would
exceed the bound is shed with a classified ``Overloaded(reason=
"bucket_capacity")`` instead of silently compiling program #41.

Padding is with zero bytes (black pixels).  Matching over a padded pair is
well-defined — the backbone/correlation see the padding as content — and
match coordinates come back normalized over the PADDED grid; callers that
need original-image coordinates rescale by ``orig/bucket`` (documented in
the README "Serving" section).  The demo-shaped workload (fixed 400² pairs)
always lands in one bucket and never pads.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from ncnet_tpu.serving.request import Bucket, Overloaded


class ShapeBucketer:
    """Thread-safe shape→bucket mapper with a bounded bucket set.

    ``multiple``: pad H and W up to this granularity (feature stride is 16,
    so 64 keeps feature-grid waste under 4 cells per axis).  ``fixed``:
    optional explicit per-side bucket ladder ``[(h, w), ...]`` — the
    smallest fixed shape that fits is used and the round-up rule is off
    (production serving pins its ladder; the round-up rule is the
    zero-config default).  ``max_buckets`` bounds DISTINCT (src, tgt) pair
    buckets ever admitted — the compiled-program budget.
    """

    def __init__(self, multiple: int = 64, max_side: int = 1024,
                 max_buckets: int = 4,
                 fixed: Optional[Sequence[Tuple[int, int]]] = None):
        if multiple < 1 or max_side < multiple or max_buckets < 1:
            raise ValueError(
                f"bad bucketer knobs: multiple={multiple} "
                f"max_side={max_side} max_buckets={max_buckets}"
            )
        self.multiple = int(multiple)
        self.max_side = int(max_side)
        self.max_buckets = int(max_buckets)
        self.fixed = (sorted((int(h), int(w)) for h, w in fixed)
                      if fixed else None)
        self._seen: Set[Bucket] = set()
        self._lock = threading.Lock()

    def _side(self, h: int, w: int) -> Optional[Tuple[int, int]]:
        if self.fixed is not None:
            for bh, bw in self.fixed:
                if h <= bh and w <= bw:
                    return (bh, bw)
            return None
        if h > self.max_side or w > self.max_side:
            return None
        m = self.multiple
        return (-(-h // m) * m, -(-w // m) * m)

    def peek(self, src_hw: Tuple[int, int],
             tgt_hw: Tuple[int, int]) -> Bucket:
        """The pair bucket for one request WITHOUT consuming budget.
        Raises :class:`Overloaded` with reason ``unservable_shape`` (too
        large for any bucket — a retry can never help) or
        ``bucket_capacity`` (a NEW bucket would exceed the
        compiled-program budget; retry with a ladder shape).  Peek and
        :meth:`commit` are split so admission can still SHED the request
        (queue full, client cap) after bucketing without permanently
        burning one of the ``max_buckets`` slots on work that never ran."""
        sb = self._side(*src_hw)
        tb = self._side(*tgt_hw)
        if sb is None or tb is None:
            raise Overloaded(
                f"shape {src_hw}/{tgt_hw} exceeds every serving bucket "
                f"(max side {self.max_side})", reason="unservable_shape",
            )
        bucket: Bucket = (sb, tb)
        with self._lock:
            if bucket not in self._seen and \
                    len(self._seen) >= self.max_buckets:
                raise Overloaded(
                    f"bucket {bucket} would exceed the compiled-program "
                    f"budget ({self.max_buckets} buckets in use)",
                    reason="bucket_capacity",
                )
        return bucket

    def commit(self, bucket: Bucket) -> None:
        """Consume a budget slot for an ADMITTED request's bucket (the
        capacity re-check closes the peek/commit race for callers that do
        not serialize the two under their own lock)."""
        with self._lock:
            if bucket not in self._seen:
                if len(self._seen) >= self.max_buckets:
                    raise Overloaded(
                        f"bucket {bucket} would exceed the compiled-"
                        f"program budget ({self.max_buckets} in use)",
                        reason="bucket_capacity",
                    )
                self._seen.add(bucket)

    def bucket_for(self, src_hw: Tuple[int, int],
                   tgt_hw: Tuple[int, int]) -> Bucket:
        """peek + commit in one step (warmup, standalone callers)."""
        b = self.peek(src_hw, tgt_hw)
        self.commit(b)
        return b

    # warmup pre-registration is the same operation now that budget is
    # tracked per BUCKET, not per request
    register = bucket_for

    @property
    def buckets(self) -> List[Bucket]:
        with self._lock:
            return sorted(self._seen)


def pad_to_bucket(imgs: Sequence[Optional[np.ndarray]], hw: Tuple[int, int]
                  ) -> np.ndarray:
    """Stack ``(H, W, 3)`` uint8 images into one zero-padded
    ``(B, bh, bw, 3)`` batch at the bucket shape.  ``None`` entries are
    batch-dimension padding (all-zero rows — the service pads coalesced
    batches up to a power-of-two ladder so the batch dim cannot multiply
    the compiled-program budget)."""
    bh, bw = hw
    out = np.zeros((len(imgs), bh, bw, 3), dtype=np.uint8)
    for i, img in enumerate(imgs):
        if img is None:
            continue
        h, w = img.shape[:2]
        out[i, :h, :w] = img
    return out
