"""Batched warm matcher: the device-facing half of the match service.

``make_point_matcher`` (models/ncnet.py) is the batch-1 serving program; the
service needs the same program shape at batch B so continuous batching can
amortize the dispatch/tunnel cost the r05 bench measured (5.5 ms device vs
~681 ms serial wall at bs1).  One jitted program per shape bucket (jit's
per-shape cache does the bucketing; ``serving/buckets.py`` bounds it):
raw uint8 pairs in, ImageNet-normalized on device, full forward, compact
per-pair match tables out, with the per-pair quality signals
(observability/quality.py) appended as one extra table row so the batch's
single device→host pull carries accuracy telemetry too.

The engine exposes the same ``dispatch``/``fetch``/``retrace`` seam as the
eval matchers: ``dispatch`` enqueues without blocking (jax async dispatch),
``fetch`` blocks on the device result, and ``retrace`` drops the compiled
programs so :func:`~ncnet_tpu.models.ncnet.recover_from_device_failure` can
demote a poisoned Pallas tier and rebuild on the survivor — the service's
degraded-mode path.

**Store-backed pair path** (``store=``, ncnet_tpu/store/): with a
persistent feature store attached, each dispatched batch resolves its
SOURCE rows' backbone features through verified cached entries (content
digest of the padded uint8 row + the weights fingerprint) and runs a
cached-pair program — the localization-as-a-service shape, where the
source side is a fixed database image repeating across requests and a warm
store halves the extraction work per pair.  The store's degradation ladder
(``FeatureStore.resolve``) guarantees it can only make a batch SLOWER
(recompute), never wrong and never fatal; ``store=None`` (the default)
leaves the engine bit-identical to the pre-store path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ncnet_tpu.config import ModelConfig


class BatchMatchEngine:
    """Resident batched matcher over pre-staged weights.

    ``dispatch(src_u8, tgt_u8)`` takes ``(B, H, W, 3)`` uint8 batches
    (already padded to one bucket) and returns an on-device handle;
    ``fetch`` pulls the ``(B, 6, N)`` float32 table — rows 0-4 are the
    match table (xA, yA, xB, yB, score), row 5 carries the pair's quality
    signals in its first ``len(QUALITY_SIGNALS)`` slots (``(B, 5, N)``
    when the grid is too narrow for the row; :meth:`split` detects which).
    """

    def __init__(self, config: ModelConfig, params, *,
                 do_softmax: bool = True, scale: str = "centered",
                 device=None, store=None):
        import jax
        import jax.numpy as jnp

        from ncnet_tpu.models.ncnet import (
            ResilientJit,
            extract_features,
            ncnet_forward,
            ncnet_forward_from_features,
        )
        from ncnet_tpu.observability.quality import append_quality_rows
        from ncnet_tpu.ops import corr_to_matches
        from ncnet_tpu.ops.image import normalize_imagenet

        self.config = config
        self.device = device
        # persistent feature store (ncnet_tpu/store/): when given, dispatch
        # resolves each SOURCE row's backbone features through it (content
        # digest of the padded uint8 row) and runs the cached-pair program
        # — the localization-as-a-service shape where the src side is a
        # fixed database image that repeats across requests.  Fail-open by
        # construction: store trouble only means recompute.
        self._store = store
        # staged once, every batch; committing the params to an explicit
        # device pins every jit dispatch there — the replica-pool seam
        # (serving/replica.py): one engine per visible device
        self._params = (jax.device_put(params, device)
                        if device is not None else jax.device_put(params))
        k = max(config.relocalization_k_size, 1)

        def tables_from(out):
            """THE match-extraction tail, shared by both pair programs —
            the store-backed path must never silently diverge from the
            default path's table shape or quality-row wire layout."""
            m = corr_to_matches(
                out.corr, delta4d=out.delta4d, k_size=k,
                do_softmax=do_softmax, scale=scale,
            )
            table = jnp.stack(
                [v.astype(jnp.float32) for v in m], axis=1)  # (B, 5, N)
            # the quality-row wire layout has ONE home (quality.py): the
            # pair's signals ride as row 5 → (B, 6, N), narrow grids skip
            return append_quality_rows(table, out.corr)

        def run(p, src, tgt):
            src = normalize_imagenet(src.astype(jnp.float32))
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            return tables_from(ncnet_forward(config, p, src, tgt))

        def run_cached(p, fa, tgt):
            # the store-backed pair: src features precomputed (verified
            # store bytes or a just-committed recompute), tgt extracted
            # in-program — ONE backbone extraction per pair instead of two
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            return tables_from(
                ncnet_forward_from_features(config, p, fa, tgt))

        def run_feat(p, src):
            # THE extraction program store misses replay — its output
            # bytes are what the store commits, so a hit is bitwise what a
            # miss would have computed
            return extract_features(
                config, p, normalize_imagenet(src.astype(jnp.float32)))

        from ncnet_tpu.observability.quality import active_tier

        self._jitted = ResilientJit(
            run, label="serve_batch",
            # compiled-program memory ledger (observability/memory.py):
            # one row per (bucket, padded batch) program this engine
            # compiles — the serving plane sums these rows into its
            # predicted-footprint gauge (memory.SERVE_PROGRAM)
            ledger_program="serve_batch",
            ledger_key_fn=lambda p, s, t: (
                f"{s.shape[1]}x{s.shape[2]}-{t.shape[1]}x{t.shape[2]}"
                f"xb{s.shape[0]}"),
            ledger_tier=lambda: active_tier(self.half_precision),
        )
        # the cached-pair + extraction programs (store path only; never
        # dispatched when store is None, so the default engine's injected-
        # fault ordinals and numerics are untouched)
        self._jitted_cached = ResilientJit(
            run_cached, label="serve_batch",
            ledger_program="serve_batch",
            ledger_key_fn=lambda p, fa, t: (
                f"feat{'x'.join(str(d) for d in fa.shape[1:])}"
                f"-{t.shape[1]}x{t.shape[2]}xb{fa.shape[0]}"),
            ledger_tier=lambda: active_tier(self.half_precision),
        )
        self._feat = ResilientJit(run_feat, hook=False)
        self.feature_extractions = 0  # executed trunk dispatches (the spy)

    def dispatch(self, src_u8: np.ndarray, tgt_u8: np.ndarray):
        """Enqueue upload + forward + match extraction; returns the
        on-device handle without blocking.  The fault-injection seam
        (``faults.device_fail_calls``) lives on the ResilientJit dispatch,
        exactly like the eval pair programs.

        With a feature store attached, each SOURCE row resolves through it
        first (verified hit / recompute + commit) and the batch runs the
        cached-pair program — the resolve is the one blocking step (a miss
        pulls the computed features to host to commit them)."""
        import jax.numpy as jnp

        if self._store is None:
            return self._jitted(self._params, jnp.asarray(src_u8),
                                jnp.asarray(tgt_u8))
        from ncnet_tpu.store import content_digest

        rows = []
        for i in range(src_u8.shape[0]):
            row = np.ascontiguousarray(src_u8[i])

            def compute(row=row) -> np.ndarray:
                self.feature_extractions += 1
                return np.asarray(
                    self._feat(self._params, jnp.asarray(row[None])),
                    dtype=np.float32)[0]

            arr, _status = self._store.resolve(content_digest(row), compute)
            rows.append(arr)
        fa = jnp.asarray(np.stack(rows))
        return self._jitted_cached(self._params, fa, jnp.asarray(tgt_u8))

    def fetch(self, handle) -> np.ndarray:
        """Block on the device result; one pull per batch."""
        return np.asarray(handle, dtype=np.float32)

    def retrace(self) -> None:
        """Drop every cached executable (all shape buckets): the next
        dispatch re-traces through the tier chooser — the demote-retrace
        recovery seam."""
        self._jitted.retrace()
        self._jitted_cached.retrace()
        self._feat.retrace()

    def swap_params(self, params) -> None:
        """Live weight swap (the rollout controller's per-replica seam):
        re-stage ``params`` on this engine's device and drop every compiled
        program — the new tree may differ structurally (a CP-rank
        fine-tune changes the NC-filter leaves), so the old executables
        are invalid, and the rollout's bucket-ladder warmup recompiles
        them off the dispatch path (fresh memory-ledger rows included).
        Must only be called on a DRAINED replica: a fetcher racing the
        re-staging would mix old handles with new params."""
        import jax

        self._params = (jax.device_put(params, self.device)
                        if self.device is not None
                        else jax.device_put(params))
        self.retrace()

    def attach_store(self, store) -> None:
        """Attach (or detach with ``None``) the persistent feature store.
        The rollout controller detaches the store from a replica swapped
        to DIFFERENT backbone weights — letting it resolve through the old
        generation would commit features computed under the new weights
        into the old fingerprint's directory (silent cache poisoning);
        recompute-only until the pod converges is the safe degradation."""
        self._store = store

    @property
    def half_precision(self) -> bool:
        return bool(self.config.half_precision)

    @staticmethod
    def split(table: np.ndarray
              ) -> Tuple[np.ndarray, Optional[List[Dict[str, float]]]]:
        """``(B, 5|6, N)`` fetched table → ``(match_tables (B, 5, N),
        per-pair quality dicts | None)`` — delegates to the wire layout's
        one home, :func:`~ncnet_tpu.observability.quality.
        split_quality_rows`."""
        from ncnet_tpu.observability.quality import split_quality_rows

        return split_quality_rows(table)
