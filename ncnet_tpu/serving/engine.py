"""Batched warm matcher: the device-facing half of the match service.

``make_point_matcher`` (models/ncnet.py) is the batch-1 serving program; the
service needs the same program shape at batch B so continuous batching can
amortize the dispatch/tunnel cost the r05 bench measured (5.5 ms device vs
~681 ms serial wall at bs1).  One jitted program per shape bucket (jit's
per-shape cache does the bucketing; ``serving/buckets.py`` bounds it):
raw uint8 pairs in, ImageNet-normalized on device, full forward, compact
per-pair match tables out, with the per-pair quality signals
(observability/quality.py) appended as one extra table row so the batch's
single device→host pull carries accuracy telemetry too.

The engine exposes the same ``dispatch``/``fetch``/``retrace`` seam as the
eval matchers: ``dispatch`` enqueues without blocking (jax async dispatch),
``fetch`` blocks on the device result, and ``retrace`` drops the compiled
programs so :func:`~ncnet_tpu.models.ncnet.recover_from_device_failure` can
demote a poisoned Pallas tier and rebuild on the survivor — the service's
degraded-mode path.

**Store-backed pair path** (``store=``, ncnet_tpu/store/): with a
persistent feature store attached, each dispatched batch resolves its
SOURCE rows' backbone features through verified cached entries (content
digest of the padded uint8 row + the weights fingerprint) and runs a
cached-pair program — the localization-as-a-service shape, where the
source side is a fixed database image repeating across requests and a warm
store halves the extraction work per pair.  The store's degradation ladder
(``FeatureStore.resolve``) guarantees it can only make a batch SLOWER
(recompute), never wrong and never fatal; ``store=None`` (the default)
leaves the engine bit-identical to the pre-store path.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ncnet_tpu.config import ModelConfig

# bounded per-engine digest→features memo for the TRACKED path when no
# persistent store is attached: a stream re-matches one reference image for
# many frames, so a handful of entries covers every live stream on a replica
_TRACKED_FEATURE_CACHE_ENTRIES = 16


class BatchMatchEngine:
    """Resident batched matcher over pre-staged weights.

    ``dispatch(src_u8, tgt_u8)`` takes ``(B, H, W, 3)`` uint8 batches
    (already padded to one bucket) and returns an on-device handle;
    ``fetch`` pulls the ``(B, 6, N)`` float32 table — rows 0-4 are the
    match table (xA, yA, xB, yB, score), row 5 carries the pair's quality
    signals in its first ``len(QUALITY_SIGNALS)`` slots (``(B, 5, N)``
    when the grid is too narrow for the row; :meth:`split` detects which).
    """

    def __init__(self, config: ModelConfig, params, *,
                 do_softmax: bool = True, scale: str = "centered",
                 device=None, store=None):
        import jax
        import jax.numpy as jnp

        from ncnet_tpu.models.ncnet import (
            ResilientJit,
            extract_features,
            ncnet_forward,
            ncnet_forward_from_features,
            ncnet_forward_tracked,
        )
        from ncnet_tpu.observability.quality import append_quality_rows
        from ncnet_tpu.ops import corr_to_matches
        from ncnet_tpu.ops.image import normalize_imagenet

        self.config = config
        self.device = device
        # persistent feature store (ncnet_tpu/store/): when given, dispatch
        # resolves each SOURCE row's backbone features through it (content
        # digest of the padded uint8 row) and runs the cached-pair program
        # — the localization-as-a-service shape where the src side is a
        # fixed database image that repeats across requests.  Fail-open by
        # construction: store trouble only means recompute.
        self._store = store
        # staged once, every batch; committing the params to an explicit
        # device pins every jit dispatch there — the replica-pool seam
        # (serving/replica.py): one engine per visible device
        self._params = (jax.device_put(params, device)
                        if device is not None else jax.device_put(params))
        k = max(config.relocalization_k_size, 1)

        def tables_from(out):
            """THE match-extraction tail, shared by both pair programs —
            the store-backed path must never silently diverge from the
            default path's table shape or quality-row wire layout."""
            m = corr_to_matches(
                out.corr, delta4d=out.delta4d, k_size=k,
                do_softmax=do_softmax, scale=scale,
            )
            table = jnp.stack(
                [v.astype(jnp.float32) for v in m], axis=1)  # (B, 5, N)
            # the quality-row wire layout has ONE home (quality.py): the
            # pair's signals ride as row 5 → (B, 6, N), narrow grids skip
            return append_quality_rows(table, out.corr)

        def run(p, src, tgt):
            src = normalize_imagenet(src.astype(jnp.float32))
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            return tables_from(ncnet_forward(config, p, src, tgt))

        def run_cached(p, fa, tgt):
            # the store-backed pair: src features precomputed (verified
            # store bytes or a just-committed recompute), tgt extracted
            # in-program — ONE backbone extraction per pair instead of two
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            return tables_from(
                ncnet_forward_from_features(config, p, fa, tgt))

        def run_feat(p, src):
            # THE extraction program store misses replay — its output
            # bytes are what the store commits, so a hit is bitwise what a
            # miss would have computed
            return extract_features(
                config, p, normalize_imagenet(src.astype(jnp.float32)))

        def run_tracked(p, fa, tgt, prior_ab, prior_ba):
            # the streaming frame program: reference features precomputed
            # (resolved once per stream), target frame extracted in-program,
            # match volume built from the previous frame's priors — NO
            # coarse pass (models.ncnet_forward_tracked)
            tgt = normalize_imagenet(tgt.astype(jnp.float32))
            return tables_from(ncnet_forward_tracked(
                config, p, fa, tgt, prior_ab, prior_ba))

        from ncnet_tpu.observability.quality import active_tier

        self._jitted = ResilientJit(
            run, label="serve_batch",
            # compiled-program memory ledger (observability/memory.py):
            # one row per (bucket, padded batch) program this engine
            # compiles — the serving plane sums these rows into its
            # predicted-footprint gauge (memory.SERVE_PROGRAM)
            ledger_program="serve_batch",
            ledger_key_fn=lambda p, s, t: (
                f"{s.shape[1]}x{s.shape[2]}-{t.shape[1]}x{t.shape[2]}"
                f"xb{s.shape[0]}"),
            ledger_tier=lambda: active_tier(self.half_precision),
        )
        # the cached-pair + extraction programs (store path only; never
        # dispatched when store is None, so the default engine's injected-
        # fault ordinals and numerics are untouched)
        self._jitted_cached = ResilientJit(
            run_cached, label="serve_batch",
            ledger_program="serve_batch",
            ledger_key_fn=lambda p, fa, t: (
                f"feat{'x'.join(str(d) for d in fa.shape[1:])}"
                f"-{t.shape[1]}x{t.shape[2]}xb{fa.shape[0]}"),
            ledger_tier=lambda: active_tier(self.half_precision),
        )
        self._feat = ResilientJit(run_feat, hook=False)
        self._jitted_tracked = ResilientJit(
            run_tracked, label="serve_batch",
            ledger_program="serve_batch",
            ledger_key_fn=lambda p, fa, t, pa, pb: (
                f"trk{'x'.join(str(d) for d in fa.shape[1:])}"
                f"-{t.shape[1]}x{t.shape[2]}xb{fa.shape[0]}"),
            ledger_tier=lambda: active_tier(self.half_precision),
        )
        self.feature_extractions = 0  # executed trunk dispatches (the spy)
        # coarse-pass spy (streaming acceptance contract): counts batches
        # dispatched through a program whose candidate selection pays the
        # full coarse (or dense) filter — i.e. every non-tracked forward.
        # A steady tracked stream must leave this flat.
        self.coarse_passes = 0
        self.tracked_dispatches = 0
        self.swap_fastpath_hits = 0
        # digest→features memo for tracked streams without a store
        self._feat_cache: "collections.OrderedDict[str, np.ndarray]" = (
            collections.OrderedDict())

    def dispatch(self, src_u8: np.ndarray, tgt_u8: np.ndarray,
                 src_digests: Optional[Sequence[Optional[str]]] = None):
        """Enqueue upload + forward + match extraction; returns the
        on-device handle without blocking.  The fault-injection seam
        (``faults.device_fail_calls``) lives on the ResilientJit dispatch,
        exactly like the eval pair programs.

        With a feature store attached, each SOURCE row resolves through it
        first (verified hit / recompute + commit) and the batch runs the
        cached-pair program — the resolve is the one blocking step (a miss
        pulls the computed features to host to commit them).
        ``src_digests`` lets a caller that already knows a row's content
        digest (a stream session memoizes its reference image's — the
        image is unchanged frame over frame) skip the per-request sha256
        of that row; ``None`` entries hash as before."""
        import jax.numpy as jnp

        self.coarse_passes += 1
        if self._store is None:
            return self._jitted(self._params, jnp.asarray(src_u8),
                                jnp.asarray(tgt_u8))
        from ncnet_tpu.store import content_digest

        rows = []
        for i in range(src_u8.shape[0]):
            row = np.ascontiguousarray(src_u8[i])
            digest = src_digests[i] if src_digests is not None else None
            if digest is None:
                digest = content_digest(row)

            def compute(row=row) -> np.ndarray:
                self.feature_extractions += 1
                return np.asarray(
                    self._feat(self._params, jnp.asarray(row[None])),
                    dtype=np.float32)[0]

            arr, _status = self._store.resolve(digest, compute)
            rows.append(arr)
        fa = jnp.asarray(np.stack(rows))
        return self._jitted_cached(self._params, fa, jnp.asarray(tgt_u8))

    def _resolve_src_features(self, row: np.ndarray,
                              digest: Optional[str]) -> np.ndarray:
        """One source row's backbone features for the tracked path: the
        persistent store when attached (same resolve ladder as the pair
        path), else a small in-engine digest→features memo — either way a
        steady stream extracts its reference trunk ONCE, not per frame."""
        from ncnet_tpu.store import content_digest

        import jax.numpy as jnp

        row = np.ascontiguousarray(row)
        if digest is None:
            digest = content_digest(row)

        def compute() -> np.ndarray:
            self.feature_extractions += 1
            return np.asarray(
                self._feat(self._params, jnp.asarray(row[None])),
                dtype=np.float32)[0]

        if self._store is not None:
            arr, _status = self._store.resolve(digest, compute)
            return arr
        hit = self._feat_cache.get(digest)
        if hit is not None:
            self._feat_cache.move_to_end(digest)
            return hit
        arr = compute()
        self._feat_cache[digest] = arr
        while len(self._feat_cache) > _TRACKED_FEATURE_CACHE_ENTRIES:
            self._feat_cache.popitem(last=False)
        return arr

    def dispatch_tracked(self, src_u8: np.ndarray, tgt_u8: np.ndarray,
                         prior_ab: np.ndarray, prior_ba: np.ndarray, *,
                         src_digests: Optional[Sequence[Optional[str]]]
                         = None):
        """Enqueue a TRACKED batch: per-row reference features resolved
        once per stream (store or in-engine memo), target frames extracted
        in-program, candidates seeded from the rows' prior pairs — zero
        coarse passes (``coarse_passes`` stays flat; ``tracked_dispatches``
        counts these).  ``prior_ab``/``prior_ba`` are ``(B, Nc)`` int32
        per-coarse-cell priors (``ops/temporal.prior_from_table``); padded
        rows can carry any valid prior (their outputs are dropped).
        Callers gate shape eligibility via :meth:`tracking_feasible`."""
        import jax.numpy as jnp

        self.tracked_dispatches += 1
        rows = []
        for i in range(src_u8.shape[0]):
            digest = src_digests[i] if src_digests is not None else None
            rows.append(self._resolve_src_features(src_u8[i], digest))
        fa = jnp.asarray(np.stack(rows))
        return self._jitted_tracked(
            self._params, fa, jnp.asarray(tgt_u8),
            jnp.asarray(prior_ab, dtype=np.int32),
            jnp.asarray(prior_ba, dtype=np.int32))

    def tracking_feasible(self, src_hw: Tuple[int, int],
                          tgt_hw: Tuple[int, int]) -> bool:
        """Host-side eligibility of the tracked pipeline for an IMAGE shape
        bucket (the serving layer decides per stream before batch
        assembly; the in-program tier consult re-checks at trace time).
        Feature grids follow from the uniform stride-16 trunks."""
        from ncnet_tpu.ops.sparse_corr import tracking_feasible
        from ncnet_tpu.ops.sparse_topk import resolve_halo
        from ncnet_tpu.ops.temporal import FEATURE_STRIDE

        ha, wa = (d // FEATURE_STRIDE for d in src_hw)
        hb, wb = (d // FEATURE_STRIDE for d in tgt_hw)
        if min(ha, wa, hb, wb) <= 0:
            return False
        return tracking_feasible(
            ha, wa, hb, wb,
            factor=self.config.sparse_factor,
            halo=resolve_halo(self.config.sparse_halo,
                              self.config.sparse_factor),
            radius=self.config.track_radius,
            reloc_k=self.config.relocalization_k_size,
        )

    @property
    def feature_stride(self) -> int:
        from ncnet_tpu.ops.temporal import FEATURE_STRIDE

        return FEATURE_STRIDE

    def fetch(self, handle) -> np.ndarray:
        """Block on the device result; one pull per batch."""
        return np.asarray(handle, dtype=np.float32)

    def retrace(self) -> None:
        """Drop every cached executable (all shape buckets): the next
        dispatch re-traces through the tier chooser — the demote-retrace
        recovery seam."""
        self._jitted.retrace()
        self._jitted_cached.retrace()
        self._feat.retrace()
        self._jitted_tracked.retrace()
        self._feat_cache.clear()

    def swap_params(self, params) -> None:
        """Live weight swap (the rollout controller's per-replica seam):
        re-stage ``params`` on this engine's device.

        **Same-structure fast path**: params enter every jitted program as
        an ARGUMENT, so the compiled executables are keyed on the tree's
        abstract values (structure + leaf shape/dtype), not its numbers.
        When the incoming tree matches the staged one abstractly — the
        common rollout shape: same architecture, new weights — the old
        executables stay valid verbatim and the swap skips the retrace;
        the rollout's bucket-ladder warmup then replays straight cache
        hits (and the tier decisions they embody) instead of re-probing
        and recompiling, which is what dominated the measured CPU
        live-swap wall.  ``swap_fastpath_hits`` counts these.

        A structurally DIFFERENT tree (a CP-rank fine-tune changes the
        NC-filter leaves) still drops every compiled program, and the
        warmup recompiles off the dispatch path (fresh memory-ledger rows
        included).  Either way the digest→features memo is flushed —
        cached features were computed under the old trunk.  Must only be
        called on a DRAINED replica: a fetcher racing the re-staging
        would mix old handles with new params."""
        import jax

        def _abstract(tree):
            leaves, treedef = jax.tree.flatten(tree)
            return treedef, [(getattr(x, "shape", None),
                              getattr(x, "dtype", None)) for x in leaves]

        same = _abstract(self._params) == _abstract(params)
        self._params = (jax.device_put(params, self.device)
                        if self.device is not None
                        else jax.device_put(params))
        if same:
            self.swap_fastpath_hits += 1
            self._feat_cache.clear()
            return
        self.retrace()

    def attach_store(self, store) -> None:
        """Attach (or detach with ``None``) the persistent feature store.
        The rollout controller detaches the store from a replica swapped
        to DIFFERENT backbone weights — letting it resolve through the old
        generation would commit features computed under the new weights
        into the old fingerprint's directory (silent cache poisoning);
        recompute-only until the pod converges is the safe degradation."""
        self._store = store

    @property
    def half_precision(self) -> bool:
        return bool(self.config.half_precision)

    @staticmethod
    def split(table: np.ndarray
              ) -> Tuple[np.ndarray, Optional[List[Dict[str, float]]]]:
        """``(B, 5|6, N)`` fetched table → ``(match_tables (B, 5, N),
        per-pair quality dicts | None)`` — delegates to the wire layout's
        one home, :func:`~ncnet_tpu.observability.quality.
        split_quality_rows`."""
        from ncnet_tpu.observability.quality import split_quality_rows

        return split_quality_rows(table)
