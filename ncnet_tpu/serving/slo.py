"""SLO accounting for the match service: latency objectives + error budget.

The serving stack's availability story so far is *mechanical* (outcome-total
settlement, failover, drains); this module adds the *contractual* one: a
per-bucket latency objective and an error budget, tracked live so the
``/metrics`` plane, the ``slo`` event stream, ``run_report --slo`` and the
perf-store gate all answer the operator question "are we inside our SLO,
and how fast are we burning the budget?"

Definitions (pinned here so every consumer agrees):

  * An admitted request is **SLO-bad** when it terminates as
    ``deadline`` / ``quarantined`` / an admitted ``shed`` (an aborted
    shutdown or crash rejected it), or as a ``result`` whose end-to-end
    wall exceeds its bucket's latency objective (``slo_ms`` /
    ``slo_ms_by_bucket``; no objective configured ⇒ results are always
    good).  Rejections at the door (never admitted) are capacity policy,
    not SLO violations — they are counted separately by admission metrics.
  * **Budget burn** is the bad fraction measured against the allowed bad
    fraction: ``burn_pct = 100 · (bad/admitted) / (slo_budget_pct/100)``.
    100 means the budget is exactly spent; >100 means the SLO is blown.
  * The **window burn** is the same ratio over the last ``slo_window``
    terminated requests — the live "are we burning NOW" signal that a
    long healthy history cannot dilute.

Exact-replay contract: the tracker classifies from the SAME values the
event log records (the rounded ``wall_ms`` of ``serve_result``, the
``admitted`` flags of ``serve_deadline``/``serve_shed``), so
``tools/run_report.py --slo`` replaying a dead service's log recomputes
counters that match the final ``/metrics`` scrape EXACTLY — the
scrape-vs-replay consistency bar in the tier-1 acceptance chain.

The tracker holds no lock: the service serializes ``observe`` under its
condition lock exactly like the admission controller, and ``snapshot`` is
called from the introspection thread under the same lock.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

# the SLO-bad classes (latency misses are the fourth, implicit class)
BAD_OUTCOMES = ("deadline", "quarantined", "shed")


class SLOTracker:
    """Sliding-window error-budget accounting (see module docstring).

    ``registry`` (optional) receives mirror counters/gauges so the
    ``/metrics`` exposition and the in-process snapshot can never drift:
    ``slo_admitted``, ``slo_ok``, ``slo_miss_<class>``,
    ``slo_budget_burn_pct``, ``slo_window_burn_pct``.
    """

    def __init__(self, *, default_ms: Optional[float] = None,
                 by_bucket: Tuple[Tuple[str, float], ...] = (),
                 budget_pct: float = 1.0, window: int = 256,
                 emit_every: int = 32, registry=None):
        if budget_pct <= 0 or budget_pct > 100:
            raise ValueError(f"slo_budget_pct must be in (0, 100], "
                             f"got {budget_pct}")
        if window < 1 or emit_every < 1:
            raise ValueError(
                f"bad SLO knobs: window={window} emit_every={emit_every}")
        self.default_ms = float(default_ms) if default_ms else None
        self.by_bucket: Dict[str, float] = {
            str(k): float(v) for k, v in by_bucket}
        self.budget_pct = float(budget_pct)
        self.emit_every = int(emit_every)
        self.admitted = 0
        self.ok = 0
        self.bad: Dict[str, int] = {k: 0 for k in BAD_OUTCOMES}
        self.bad["latency"] = 0
        self._window: Deque[bool] = deque(maxlen=int(window))
        self._registry = registry
        self._since_emit = 0

    # -- objectives ---------------------------------------------------------

    def objective_ms(self, bucket: Optional[str]) -> Optional[float]:
        """The latency objective for one bucket label (per-bucket override
        first, then the default; None = no latency objective)."""
        if bucket is not None and bucket in self.by_bucket:
            return self.by_bucket[bucket]
        return self.default_ms

    def config(self) -> Dict[str, Any]:
        """The objectives document stamped into ``serve_start`` and every
        ``slo`` event — what lets ``run_report --slo`` replay a log with
        the exact thresholds the live tracker used."""
        return {
            "default_ms": self.default_ms,
            "by_bucket": dict(self.by_bucket),
            "budget_pct": self.budget_pct,
            "window": self._window.maxlen,
        }

    # -- accounting (service-lock serialized) -------------------------------

    def observe(self, outcome: str, *, bucket: Optional[str] = None,
                wall_ms: Optional[float] = None) -> bool:
        """Record one admitted request's terminal outcome; returns True when
        an ``slo`` event is due (the CALLER emits it outside the lock, with
        :meth:`snapshot` as the payload — events under the service lock
        would serialize admission behind the fsync)."""
        self.admitted += 1
        miss: Optional[str] = None
        if outcome == "result":
            obj = self.objective_ms(bucket)
            if obj is not None and wall_ms is not None and wall_ms > obj:
                miss = "latency"
        elif outcome in BAD_OUTCOMES:
            miss = outcome
        elif outcome != "result":
            raise ValueError(f"unknown SLO outcome {outcome!r}")
        if miss is None:
            self.ok += 1
        else:
            self.bad[miss] += 1
        self._window.append(miss is not None)
        if self._registry is not None:
            self._registry.counter("slo_admitted").inc()
            if miss is None:
                self._registry.counter("slo_ok").inc()
            else:
                self._registry.counter(f"slo_miss_{miss}").inc()
            self._registry.gauge("slo_budget_burn_pct").set(
                self.budget_burn_pct())
            self._registry.gauge("slo_window_burn_pct").set(
                self.window_burn_pct())
        self._since_emit += 1
        if self._since_emit >= self.emit_every:
            self._since_emit = 0
            return True
        return False

    # -- derived ------------------------------------------------------------

    def bad_total(self) -> int:
        return sum(self.bad.values())

    def _burn(self, bad: int, n: int) -> float:
        if not n:
            return 0.0
        return round(100.0 * (bad / n) / (self.budget_pct / 100.0), 4)

    def budget_burn_pct(self) -> float:
        """Cumulative burn: 100 = budget exactly spent, >100 = SLO blown."""
        return self._burn(self.bad_total(), self.admitted)

    def window_burn_pct(self) -> float:
        return self._burn(sum(self._window), len(self._window))

    def snapshot(self) -> Dict[str, Any]:
        """The ``slo`` event payload / health-document section — plain data,
        byte-for-byte reproducible from the event log by
        ``run_report --slo``."""
        return {
            "objectives": self.config(),
            "admitted": self.admitted,
            "ok": self.ok,
            "bad": dict(self.bad),
            "bad_total": self.bad_total(),
            "budget_burn_pct": self.budget_burn_pct(),
            "window": {
                "n": len(self._window),
                "bad": int(sum(self._window)),
                "burn_pct": self.window_burn_pct(),
            },
        }
