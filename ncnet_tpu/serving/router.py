"""Pod-level match routing: a fronting tier over per-host ``MatchService``s.

The replica pool (PR 10) proved the robustness ladder INSIDE one process:
health-scored routing, off-budget failover, quarantine + resurrection,
elastic admission.  This module lifts that ladder one level up, across
process and network boundaries, where the failure modes are harsher — a
SIGKILLed host, a hung socket, a partitioned backend.  The
:class:`MatchRouter` fronts N per-host services (each exposing the
``serving/wire.py`` data plane + the PR 11 ``/healthz`` probe document)
and gives every admitted request the SAME outcome-total contract the
in-process service gives: exactly one of
``{result, deadline, overloaded, quarantined}``, never a silent drop.

  * **Scoring, one level up.**  Each backend carries the PR 10 formula fed
    by cross-process signals: the per-backend request-wall EWMA (measured
    by the router itself — the only latency number that includes the wire)
    × (1 + in-flight attempts) × 2^consecutive-failures, scaled by the
    backend's OWN ``/healthz`` document — its queue fill and its pool's
    ready fraction — so a host whose replicas are dying is de-prioritized
    before it starts failing the data plane.
  * **Failover, off-budget.**  A transport failure (connection refused or
    reset, a socket hung past ``request_timeout_s``, a wire frame this
    build refuses) re-routes the request to a survivor WITHOUT charging
    its retry budget — the failure was the backend's fault.  Zero lost
    admitted requests, event-log proven (``run_report --serving`` at the
    router level).  ``backend_max_failures`` consecutive failures
    quarantine the BACKEND into DEAD, where periodic ``/healthz`` probes
    are the only way back; a whole-pod-dead router parks admitted work
    off-budget behind the probes and sheds new admissions
    ``Overloaded(reason="no_capacity")`` with the probe period as the
    honest hint.
  * **Backpressure propagation.**  A backend answering ``Overloaded`` is
    NOT a failed backend, and retrying it would be exactly the hammering
    its retry hint asks to prevent: the router records the shed, tries a
    backend that has not shed this request, and — once every live backend
    has — surfaces ``Overloaded(reason="backpressure")`` to the edge with
    the honest AGGREGATE hint (the soonest any backend promised capacity).
  * **Deadline propagation.**  The edge budget rides the wire as REMAINING
    seconds (``serving/wire.py``), is re-checked at router dequeue, bounds
    the socket wait per attempt, and is checked once more when a result
    lands — an expired edge deadline always surfaces as a classified
    ``DeadlineExceeded`` naming the checkpoint that caught it, never as a
    silent backend timeout or a zombie success.
  * **Coordinated drain.**  SIGTERM (or :meth:`request_drain`) closes
    admission, answers 503 on the router's own ``/healthz``, and completes
    every admitted request against the backends before stopping.  The
    reverse direction also holds: a backend whose probe document says
    DRAINING is demoted out of routing — without burning a failure streak —
    before its own drain completes, so pod rollouts drain hosts one at a
    time with zero edge-visible errors.

Elastic admission composes across the tiers (the
``AdmissionController.note_capacity`` capacity-units contract,
``serving/admission.py``): the router feeds the SUM of ready replicas
across live backends — the pod's true drain lanes — so its queue bound
tracks live backend capacity, never the local process's devices.

Telemetry mirrors the service tier with ``route_*`` events (``route_admit``
/ ``route_result`` / ``route_shed`` / ``route_deadline`` /
``route_quarantine`` / ``route_backend`` / ``route_backend_probe`` /
``route_health`` / ``route_drain``; re-routes ride the shared ``retry``
event with ``scope="router"`` and a ``backend`` tag), ``ncnet_route_*``
exposition families on ``/metrics``, and an aggregate activity stamp +
per-backend staleness rows on ``/healthz`` for
``tools/stall_watchdog.py --url`` — one wedged host cannot flag a healthy
pod STALLED.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Set

import numpy as np

from ncnet_tpu.observability import MetricsRegistry, events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.tracing import TraceContext, adopt_trace
from ncnet_tpu.observability.export import Family, render
from ncnet_tpu.serving.admission import AdmissionController
from ncnet_tpu.serving.health import (
    ADMITTING,
    DEGRADED,
    DRAINING,
    HEALTH_DOC_SCHEMA,
    READY,
    STARTING,
    STOPPED,
    HealthMachine,
)
from ncnet_tpu.serving.introspect import IntrospectionServer
from ncnet_tpu.serving.request import (
    DeadlineExceeded,
    MatchFuture,
    MatchResult,
    Overloaded,
    RequestQuarantined,
    as_pair_image,
)
from ncnet_tpu.serving.wire import MatchClient, WireError

log = get_logger("router")

# router health-document schema: bump when the nesting or field meanings
# change so cross-host consumers (stall_watchdog --url, a higher routing
# tier) can refuse documents they do not understand
ROUTER_DOC_SCHEMA = 1

# backend lifecycle states.  READY <-> DEAD mirrors the replica pool;
# DRAINING is the third, cross-process-only state: the backend ANSWERED its
# probe but is refusing admissions (rollout drain) — demoted out of routing
# without a failure streak, watched until it either re-admits (READY) or
# stops answering (DEAD)
BACKEND_READY = "READY"
BACKEND_DEAD = "DEAD"
BACKEND_DRAINING = "DRAINING"

# routing prior for a backend with no measured wall yet (same rationale as
# the replica prior, scaled for a wire round trip on top of a batch wall)
_PRIOR_WALL_S = 0.1

_EWMA_ALPHA = 0.3  # the shared ~6-sample telemetry memory

# transport-level exceptions that classify as a BACKEND failure (re-route
# off-budget + failure streak); everything the wire decodes into a serving
# outcome class is the REQUEST's terminal state instead
_TRANSPORT_ERRORS = (OSError, socket.timeout, WireError)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Knobs of the fronting match router (README "Multi-host serving")."""

    # admission / backpressure (AdmissionController capacity-units contract:
    # the queue bound scales with live BACKEND capacity — the sum of ready
    # replicas across live backends — not this process's devices)
    max_queue: int = 256
    max_in_flight_per_client: int = 32
    elastic_admission: bool = True
    # concurrency: in-flight wire attempts per READY backend (the router's
    # pipeline depth — also the worker-thread budget, so a wedged backend
    # can absorb at most this many workers while survivors keep draining)
    per_backend_depth: int = 4
    max_workers: int = 16
    # failure policy
    retries: int = 1                  # budgeted retries per request
    backend_max_failures: int = 3     # consecutive failures -> backend DEAD
    resurrect_after_s: float = 2.0    # /healthz probe period for DEAD backends
    probe_period_s: float = 2.0       # doc-refresh period for live backends
    probe_timeout_s: float = 5.0
    # per-attempt socket ceiling for BUDGET-LESS requests: a hung socket
    # surfaces as a classified retryable failure within this bound.  A
    # budgeted attempt is bounded by its own budget + the wire settle
    # margin instead (never capped below it — see _attempt), so a long
    # edge deadline cannot masquerade as a backend failure.
    request_timeout_s: float = 30.0
    default_deadline_s: Optional[float] = None
    # lifecycle / liveness
    install_sigterm: bool = False
    latency_hist_ms: float = 4000.0
    # the router's own introspection plane (/metrics + /healthz + /statusz
    # + POST /match — the router is itself a wire backend, so tiers chain)
    introspect_port: Optional[int] = None
    introspect_host: str = "127.0.0.1"


class Backend:
    """One per-host ``MatchService`` as the router sees it: the wire
    client pool + cross-process health state.  All mutable fields are
    owned by the router's condition lock; only :meth:`acquire` /
    :meth:`release` (connection pooling) and the actual wire calls run
    outside it."""

    def __init__(self, bid: str, url: str):
        self.id = bid
        self.url = url.rstrip("/")
        self.state = BACKEND_READY  # optimistic: the data plane corrects
        # health signals (the routing-score inputs)
        self.ewma_wall_s: Optional[float] = None
        self.consecutive_failures = 0
        self.inflight = 0            # wire attempts currently out
        # probe-document signals (refreshed every probe_period_s)
        self.doc_state: Optional[str] = None
        self.model_version: Optional[str] = None  # live-rollout visibility
        self.ready_replicas = 1
        self.total_replicas = 1
        self.queue_fill = 0.0        # backend queue depth / its live bound
        self.schema_refused = False  # logged once per backend
        # backpressure memory (never part of the failure streak)
        self.backpressure = 0
        self.retry_after_s: Optional[float] = None
        # counters / timeline
        self.requests = 0
        self.results = 0
        self.failures = 0
        self.deaths = 0
        self.dead_since: Optional[float] = None
        self.last_probe_t: Optional[float] = None
        self.probing = False
        self.last_result_t: Optional[float] = None
        self._clients: List[MatchClient] = []

    # -- connection pool (router-lock free) ---------------------------------

    def acquire(self, timeout_s: float) -> MatchClient:
        try:
            client = self._clients.pop()
            client.timeout_s = timeout_s
            return client
        except IndexError:
            return MatchClient(self.url, timeout_s=timeout_s)

    def release(self, client: MatchClient, *, broken: bool = False) -> None:
        if broken or len(self._clients) >= 8:
            client.close()
        else:
            self._clients.append(client)

    # -- health (router-lock owned) -----------------------------------------

    def health_score(self) -> float:
        """Routing cost, lower = route here — the PR 10 replica formula
        one level up.  Base cost is the measured per-request wall EWMA
        (wire included), scaled by in-flight attempts (a busy backend
        queues the request behind them), doubled per consecutive failure,
        and scaled by the backend's own probe document: its queue fill
        (a backend near its bound is about to shed) and its pool's
        degraded fraction (a host on 2/4 replicas drains half as fast)."""
        wall = self.ewma_wall_s if self.ewma_wall_s else _PRIOR_WALL_S
        streak = 2.0 ** min(self.consecutive_failures, 4)
        pool_penalty = self.total_replicas / max(1, self.ready_replicas)
        return (wall * (1.0 + self.inflight) * streak
                * (1.0 + self.queue_fill) * pool_penalty)

    def note_success(self, wall_s: float) -> None:
        self.results += 1
        self.consecutive_failures = 0
        w = float(wall_s)
        self.ewma_wall_s = w if self.ewma_wall_s is None else (
            _EWMA_ALPHA * w + (1.0 - _EWMA_ALPHA) * self.ewma_wall_s)
        self.last_result_t = time.monotonic()

    def note_failure(self) -> None:
        self.failures += 1
        self.consecutive_failures += 1

    def ingest_doc(self, doc: Dict[str, Any]) -> None:
        """Fold one accepted ``/healthz`` document into the score inputs.
        Reads BOTH document shapes: a service document's ``pool``
        (replica ready/total) and a sub-ROUTER document's ``pod``
        (replica units across its backends) — tiers chain, so a backend
        may itself be a router fronting a sub-pod."""
        self.doc_state = str(doc.get("state"))
        # which model generation this backend serves (live rollout): the
        # doc refresh every probe_period_s makes a mid-rollout version
        # change visible at the router without any new wire machinery
        mv = doc.get("model_version")
        if isinstance(mv, str) and mv:
            self.model_version = mv
        if doc.get("role") == "router":
            pod = doc.get("pod") or {}
            ready, total = pod.get("replicas_ready"), \
                pod.get("replicas_total")
        else:
            pool = doc.get("pool") or {}
            ready, total = pool.get("ready"), pool.get("total")
        if isinstance(ready, int):
            self.ready_replicas = max(0, ready)
        if isinstance(total, int):
            self.total_replicas = max(1, total)
        q = doc.get("queue") or {}
        depth, bound = q.get("depth"), q.get("effective_max_queue")
        if isinstance(depth, (int, float)) and \
                isinstance(bound, (int, float)) and bound:
            self.queue_fill = max(0.0, float(depth) / float(bound))

    def probe_row(self) -> Dict[str, Any]:
        """This backend's row in the router health document — the
        per-backend staleness breakdown ``stall_watchdog --url`` consumes
        (``last_result_age_s``) plus everything an operator needs to see
        why routing prefers or shuns this host."""
        now = time.monotonic()
        return {
            "id": self.id,
            "url": self.url,
            "state": self.state,
            "model_version": self.model_version,
            "score": round(self.health_score(), 6),
            "ewma_wall_ms": (round(self.ewma_wall_s * 1e3, 3)
                             if self.ewma_wall_s else None),
            "consecutive_failures": self.consecutive_failures,
            "inflight": self.inflight,
            "requests": self.requests,
            "results": self.results,
            "failures": self.failures,
            "backpressure": self.backpressure,
            # the last overload hint this backend gave (operator signal:
            # how far away it said its capacity was)
            "retry_after_s": self.retry_after_s,
            "deaths": self.deaths,
            "replicas_ready": self.ready_replicas,
            "replicas_total": self.total_replicas,
            "queue_fill": round(self.queue_fill, 4),
            "dead_age_s": (round(now - self.dead_since, 3)
                           if self.dead_since is not None else None),
            "last_result_age_s": (round(now - self.last_result_t, 3)
                                  if self.last_result_t is not None
                                  else None),
        }


@dataclasses.dataclass(eq=False)  # identity semantics: requests live in
class _RouterRequest:             # the ownership set, never compared
    """One admitted edge request moving through the router."""

    id: str
    client: str
    src: np.ndarray
    tgt: np.ndarray
    future: MatchFuture
    submitted_t: float
    deadline_t: Optional[float] = None
    attempts: int = 0                     # budgeted failures only
    failed_on: Set[str] = dataclasses.field(default_factory=set)
    shed_by: Set[str] = dataclasses.field(default_factory=set)
    shed_hints: List[float] = dataclasses.field(default_factory=list)
    parked_logged: bool = False           # awaiting_capacity emitted once
    # the pod-wide trace context: stamped (or adopted from the edge
    # caller) at router admission, propagated on every wire attempt, and
    # carried by every route_*/retry event this request produces
    trace: Optional[TraceContext] = None

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def remaining_s(self, now: float) -> Optional[float]:
        if self.deadline_t is None:
            return None
        return self.deadline_t - now


def build_router_document(machine: HealthMachine,
                          backends: List[Dict[str, Any]], *,
                          queue: Dict[str, Any],
                          counters: Dict[str, Any],
                          activity: Dict[str, Any]) -> Dict[str, Any]:
    """THE router health document (``ROUTER_DOC_SCHEMA``-versioned): the
    router's ``/healthz`` body, :meth:`MatchRouter.health` return value,
    and the final ``route_health_doc`` event payload.  Shape mirrors the
    service document (``serving/health.py::build_health_document``) with
    ``pod`` in place of ``pool``: backend rows instead of replica rows,
    plus the pod's aggregate replica capacity (the admission units)."""
    ready = sum(1 for b in backends if b.get("state") == BACKEND_READY)
    # the distinct model versions the pod's backends report (live
    # rollout): >1 entry = a mixed-version pod mid-rollout — an operator
    # signal, not an error (the router keeps routing across versions)
    versions = sorted({b["model_version"] for b in backends
                       if b.get("model_version")})
    return {
        "schema": ROUTER_DOC_SCHEMA,
        "role": "router",
        "state": machine.state,
        "service": machine.probe(),
        "pod": {
            "ready": ready,
            "total": len(backends),
            "replicas_ready": sum(
                b.get("replicas_ready") or 0 for b in backends
                if b.get("state") == BACKEND_READY),
            "replicas_total": sum(
                b.get("replicas_total") or 1 for b in backends),
            "model_versions": versions,
            "backends": list(backends),
        },
        "queue": dict(queue),
        "counters": dict(counters),
        "activity": dict(activity),
    }


class MatchRouter:
    """The fronting router over per-host wire backends.

    Usage::

        router = MatchRouter(["http://hostA:8080", "http://hostB:8080"],
                             RouterConfig(...)).start()
        fut = router.submit(src_u8, tgt_u8, deadline_s=0.5, client="cam0")
        result = fut.result(timeout=5.0)   # MatchResult, or classified error
        router.stop()                       # drains admitted work, then stops

    The submit/result surface is the ``MatchService`` surface — callers
    (and the wire's ``serve_match``, so routers chain) cannot tell the
    tiers apart.
    """

    def __init__(self, backends: Sequence[str],
                 routing: RouterConfig = RouterConfig(), *,
                 registry: Optional[MetricsRegistry] = None):
        if not backends:
            raise ValueError("a router needs at least one backend url")
        self.cfg = routing
        self.backends: List[Backend] = [
            Backend(f"b{i}", url) for i, url in enumerate(backends)]
        if len({b.url for b in self.backends}) != len(self.backends):
            raise ValueError(f"duplicate backend urls: {list(backends)}")
        self._registry = registry or MetricsRegistry(scope="router")
        self._admission = AdmissionController(
            max_queue=routing.max_queue,
            max_in_flight_per_client=routing.max_in_flight_per_client,
            # the router's drain unit is one request (backends coalesce
            # batches on their side), so the elastic floor is per-unit
            max_batch=1,
            elastic=routing.elastic_admission,
            dead_retry_after_s=routing.resurrect_after_s,
        )
        self._health = HealthMachine(event="route_health")
        self._cond = threading.Condition()
        self._queue: Deque[_RouterRequest] = deque()
        # requests popped by a worker and not yet settled or requeued: the
        # force-settle set for a shutdown that outlives a wedged attempt
        self._owned: Set[_RouterRequest] = set()
        self._workers: List[threading.Thread] = []
        self._supervisor: Optional[threading.Thread] = None
        self._workers_stop = False
        self._draining = False
        self._drain_requested = False   # set from the signal handler: no lock
        self._stop_now = False
        self._finishing = False
        self._req_seq = 0
        self._old_sigterm = None
        self._activity_t = time.monotonic()
        self._introspect: Optional[IntrospectionServer] = None
        self._n = {"admitted": 0, "results": 0, "deadline": 0,
                   "quarantined": 0, "shed": 0}
        self._note_capacity_locked()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "MatchRouter":
        if self._supervisor is not None:
            raise RuntimeError("router already started")
        if self.cfg.introspect_port is not None:
            try:
                self._introspect = _RouterIntrospectionServer(
                    self, host=self.cfg.introspect_host,
                    port=self.cfg.introspect_port).start()
            except Exception as e:  # noqa: BLE001 — same fail-open bar as
                # the service plane: telemetry never kills the data plane
                self._introspect = None
                log.warning(
                    f"router introspection failed to bind "
                    f"{self.cfg.introspect_host}:{self.cfg.introspect_port}"
                    f" ({type(e).__name__}: {e}); routing without "
                    "/metrics + /healthz", kind="io")
        obs_events.emit(
            "route_start",
            backends={b.id: b.url for b in self.backends},
            max_queue=self.cfg.max_queue, retries=self.cfg.retries,
            per_backend_depth=self.cfg.per_backend_depth,
            backend_max_failures=self.cfg.backend_max_failures,
            resurrect_after_s=self.cfg.resurrect_after_s,
            default_deadline_s=self.cfg.default_deadline_s,
            introspect_port=(self._introspect.port
                             if self._introspect is not None else None),
        )
        if self.cfg.install_sigterm and \
                threading.current_thread() is threading.main_thread():
            self._old_sigterm = signal.signal(signal.SIGTERM, self._on_sigterm)
        n_workers = min(self.cfg.max_workers,
                        max(2, self.cfg.per_backend_depth
                            * len(self.backends)))
        for i in range(n_workers):
            t = threading.Thread(target=self._run_worker,
                                 name=f"route-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        self._supervisor = threading.Thread(
            target=self._run, name="route-supervise", daemon=True)
        self._supervisor.start()
        with self._cond:
            if self._health.state == STARTING:
                self._health.to(READY, "routing")
        return self

    def _on_sigterm(self, signum, frame):
        # handler discipline (PR 1): flip a flag, os.write, act at the
        # supervisor's next loop edge — no locks from a signal handler
        self._drain_requested = True
        os.write(2, b"[router] received SIGTERM; draining admitted work to "
                    b"the backends, admission closed\n")

    def request_drain(self, reason: str = "drain") -> None:
        """Close admission and finish admitted work against the backends
        (the SIGTERM path, callable programmatically).  The router's own
        ``/healthz`` answers 503 from this point — a higher tier demotes
        this router exactly like this router demotes a draining backend."""
        with self._cond:
            if not self._draining:
                self._draining = True
                if self._health.state != STOPPED:
                    self._health.to(DRAINING, reason)
            self._cond.notify_all()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Stop the router.  ``drain=True`` completes every admitted
        request first; ``drain=False`` aborts — queued work settles
        ``Overloaded(reason="shutdown")`` (classified, never dropped); an
        attempt already on the wire completes or times out at its socket
        bound first."""
        with self._cond:
            if drain:
                if not self._draining:
                    self._draining = True
                    if self._health.state != STOPPED:
                        self._health.to(DRAINING, "stop")
            else:
                self._stop_now = True
            self._cond.notify_all()
        sup = self._supervisor
        if sup is not None and sup is not threading.current_thread():
            sup.join(timeout)
        if self._old_sigterm is not None:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None

    def __enter__(self) -> "MatchRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, src, tgt, *, deadline_s: Optional[float] = None,
               client: str = "default",
               trace: Optional[str] = None) -> MatchFuture:
        """Admit one match query against the pod.  Same contract as
        :meth:`MatchService.submit`: returns a :class:`MatchFuture`,
        raises classified :class:`Overloaded` / :class:`DeadlineExceeded`
        synchronously at the door.  The router is the pod's trace-stamping
        tier: it ADOPTS ``trace`` (a traceparent header an upstream tier
        propagated) or STAMPS a fresh context, and every backend attempt
        carries it — so one edge request is one trace across every log it
        touches."""
        src = as_pair_image(src, "src")
        tgt = as_pair_image(tgt, "tgt")
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        shed: Optional[Overloaded] = None
        expired = False
        req: Optional[_RouterRequest] = None
        with self._cond:
            if self._supervisor is None or self._finishing \
                    or self._stop_now or self._health.state == STOPPED:
                shed = Overloaded("router is not running", reason="stopped")
            elif self._draining or self._drain_requested:
                shed = Overloaded("router is draining", reason="draining")
            elif deadline_s is not None and deadline_s <= 0:
                expired = True
            else:
                depth = len(self._queue)
                try:
                    self._admission.admit(client, depth)
                except Overloaded as e:
                    shed = e
                else:
                    self._req_seq += 1
                    req = _RouterRequest(
                        id=f"q{self._req_seq}", client=client, src=src,
                        tgt=tgt, future=MatchFuture(f"q{self._req_seq}"),
                        submitted_t=now,
                        deadline_t=(now + deadline_s) if deadline_s
                        else None,
                        trace=adopt_trace(trace),
                    )
                    self._admission.note_admit(client)
                    self._n["admitted"] += 1
                    self._registry.counter("admitted").inc()
            if shed is not None:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
        # event emission outside the lock: the log fsyncs per append, and
        # the disk must not serialize every client's admission
        if expired:
            obs_events.emit("route_deadline", request=None, client=client,
                            where="admission", admitted=False)
            raise DeadlineExceeded(
                f"deadline budget {deadline_s}s already expired at "
                "router admission", where="admission")
        if shed is not None:
            obs_events.emit("route_shed", client=client, reason=shed.reason,
                            retry_after_s=shed.retry_after_s,
                            admitted=False)
            raise shed
        obs_events.emit(
            "route_admit", request=req.id, client=client,
            deadline_s=round(deadline_s, 6) if deadline_s else None,
            trace=req.trace_id)
        # phase 2 (the service's admit discipline): make the admitted
        # request visible to the workers only after its admit event is on
        # disk, settling it ourselves if the router died in the window
        with self._cond:
            dead = self._finishing or self._stop_now \
                or self._health.state == STOPPED
            if not dead:
                self._queue.append(req)
                self._cond.notify_all()
        if dead:
            exc = Overloaded(
                f"router stopped before request {req.id} was queued",
                reason="stopped")
            req.future._settle("overloaded", error=exc)
            with self._cond:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
                self._admission.note_done(req.client)
            obs_events.emit("route_shed", request=req.id, client=client,
                            reason="stopped", admitted=True,
                            trace=req.trace_id)
            raise exc
        return req.future

    # ------------------------------------------------------------------
    # probes / document ingestion
    # ------------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The unified router health document
        (:func:`build_router_document`)."""
        now = time.monotonic()
        with self._cond:
            return build_router_document(
                self._health,
                [b.probe_row() for b in self.backends],
                queue={
                    "depth": len(self._queue),
                    "inflight": len(self._owned),
                    "effective_max_queue":
                        self._admission.effective_max_queue(),
                },
                counters=dict(self._n),
                activity={
                    "age_s": round(max(0.0, now - self._activity_t), 3),
                    "requests": self._n["results"],
                },
            )

    @property
    def state(self) -> str:
        return self._health.state

    @property
    def introspect_url(self) -> Optional[str]:
        return self._introspect.url if self._introspect is not None else None

    def metrics(self) -> Dict[str, Any]:
        return self._registry.snapshot()

    def _fetch_doc(self, backend: Backend) -> Optional[Dict[str, Any]]:
        """One ``/healthz`` round trip (no router lock held).  Returns the
        parsed document (200 OR 503 — a draining backend answering 503 is
        alive and says so), or None when nothing trustworthy answered."""
        url = backend.url + "/healthz"
        try:
            try:
                with urllib.request.urlopen(
                        url, timeout=self.cfg.probe_timeout_s) as r:
                    doc = json.loads(r.read().decode("utf-8"))
            except urllib.error.HTTPError as e:
                if e.code != 503:
                    raise
                doc = json.loads(e.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — any transport/parse failure is
            # the same evidence: nothing trustworthy is answering there
            return None
        # accept BOTH document shapes at their own schema constants: a
        # service answers HEALTH_DOC_SCHEMA, a sub-router (tiers chain)
        # answers ROUTER_DOC_SCHEMA with role="router" — each versioned
        # independently, each refused independently when unknown
        known = isinstance(doc, dict) and (
            (doc.get("role") == "router"
             and doc.get("schema") == ROUTER_DOC_SCHEMA)
            or (doc.get("role") != "router"
                and doc.get("schema") == HEALTH_DOC_SCHEMA))
        if not known:
            # refuse a document this build does not understand — but only
            # log the mismatch once per backend, it is a deploy skew, not
            # a flapping condition
            if not backend.schema_refused:
                backend.schema_refused = True
                log.warning(
                    f"backend {backend.id} ({backend.url}) answered a "
                    f"health document with schema "
                    f"{doc.get('schema') if isinstance(doc, dict) else '?'}"
                    f" (role {doc.get('role') if isinstance(doc, dict) else '?'})"
                    f" this build does not understand; refusing it",
                    kind="io")
            return None
        return doc

    def _probe_backend(self, backend: Backend) -> None:
        """One probe thread's body: fetch the document, fold the verdict
        into routing state under the lock.  Resurrection (DEAD/DRAINING →
        READY) requires BOTH an admitting ``/healthz`` document AND a
        successful wire probe (the data-plane twin of the replica pool's
        tiny probe pair) — a backend whose control plane answers while its
        ``/match`` is broken must stay quarantined, or the pod flaps
        DEAD → READY → DEAD forever.  Demotion to DRAINING is probe-only;
        demotion to DEAD is shared with the data plane's failure streak."""
        doc = self._fetch_doc(backend)
        admitting = doc is not None and doc.get("state") in ADMITTING
        data_ok: Optional[bool] = None
        if admitting and backend.state != BACKEND_READY:
            data_ok = self._wire_probe(backend)
        emit: List[Dict[str, Any]] = []
        with self._cond:
            backend.probing = False
            was = backend.state
            if doc is not None:
                backend.ingest_doc(doc)
                # units can change WITHOUT a backend state change (a READY
                # host losing one of its replicas): re-derive admission
                # capacity from every accepted document
                self._note_capacity_locked()
                if admitting and backend.state != BACKEND_READY:
                    if data_ok:
                        self._revive_locked(backend, emit)
                    # else: control plane up, data plane still broken —
                    # stay quarantined until a probe proves the wire
                elif not admitting and backend.state == BACKEND_READY:
                    # coordinated drain: demoted out of routing before the
                    # backend's own drain completes — NOT a failure
                    backend.state = BACKEND_DRAINING
                    emit.append(dict(event="route_backend",
                                     backend=backend.id,
                                     state=BACKEND_DRAINING,
                                     reason=f"backend_{doc.get('state')}"))
                    self._note_capacity_locked()
            else:
                if backend.state == BACKEND_DRAINING:
                    self._kill_locked(backend, "gone_after_drain", emit)
                elif backend.state == BACKEND_READY:
                    backend.note_failure()
                    self._registry.counter(
                        f"backend_failures_{backend.id}").inc()
                    if backend.consecutive_failures >= \
                            self.cfg.backend_max_failures:
                        self._kill_locked(backend, "probe_unreachable", emit)
            resurrection_attempt = was != BACKEND_READY
            self._cond.notify_all()
        if resurrection_attempt or doc is None:
            # resurrection probes and failures are log-worthy; the periodic
            # doc refresh of a live backend is not (event-spam discipline)
            obs_events.emit("route_backend_probe", backend=backend.id,
                            ok=doc is not None,
                            data_ok=data_ok,
                            state=doc.get("state") if doc else None)
        for e in emit:
            obs_events.emit(**e)

    def _wire_probe(self, backend: Backend) -> bool:
        """One tiny zero pair through the REAL data plane.  Any decoded
        wire answer — a result OR a classified serving outcome — proves
        the path; only a transport failure keeps the backend dead (an
        Overloaded answer to the probe is backpressure, not death)."""
        probe = np.zeros((8, 8, 3), np.uint8)
        client = backend.acquire(self.cfg.probe_timeout_s)
        broken, ok = False, True
        try:
            client.match(probe, probe, client="router_probe",
                         budget_s=self.cfg.probe_timeout_s,
                         request_id=f"{backend.id}-probe",
                         timeout_s=self.cfg.probe_timeout_s)
        except (Overloaded, DeadlineExceeded, RequestQuarantined):
            pass  # a classified answer IS a working data plane
        except Exception:  # noqa: BLE001 — transport/wire failure: dead
            broken, ok = True, False
        backend.release(client, broken=broken)
        return ok

    def _revive_locked(self, backend: Backend,
                       emit: List[Dict[str, Any]]) -> None:
        backend.state = BACKEND_READY
        backend.consecutive_failures = 0
        backend.ewma_wall_s = None  # pre-death walls are stale evidence
        backend.dead_since = None
        emit.append(dict(event="route_backend", backend=backend.id,
                         state=BACKEND_READY, reason="probe_ok"))
        self._note_capacity_locked()

    def _kill_locked(self, backend: Backend, reason: str,
                     emit: List[Dict[str, Any]]) -> None:
        if backend.state == BACKEND_DEAD:
            return
        backend.state = BACKEND_DEAD
        backend.deaths += 1
        backend.dead_since = time.monotonic()
        backend.last_probe_t = None
        emit.append(dict(event="route_backend", backend=backend.id,
                         state=BACKEND_DEAD, reason=reason))
        self._note_capacity_locked()

    def _note_capacity_locked(self) -> None:
        """Membership/probe change → elastic admission.  The units are the
        pod's live drain lanes: the SUM of ready replicas across READY
        backends (the capacity-units contract,
        ``AdmissionController.note_capacity``) — NOT this process's
        devices, which serve nothing here."""
        ready_units = sum(max(1, b.ready_replicas) for b in self.backends
                          if b.state == BACKEND_READY)
        total_units = sum(max(1, b.total_replicas) for b in self.backends)
        self._admission.note_capacity(ready_units, total_units)
        self._registry.gauge("ready_backends").set(
            sum(1 for b in self.backends if b.state == BACKEND_READY))
        ready_b = sum(1 for b in self.backends
                      if b.state == BACKEND_READY)
        if self._health.state in (STARTING, READY) \
                and ready_b < len(self.backends):
            self._health.to(
                DEGRADED,
                "no_ready_backends" if ready_b == 0
                else f"backends_ready:{ready_b}/{len(self.backends)}")
        elif self._health.state == DEGRADED \
                and ready_b == len(self.backends):
            self._health.to(READY, "pod_restored")

    # ------------------------------------------------------------------
    # supervisor (probe scheduling, deadline eviction, drain completion)
    # ------------------------------------------------------------------

    def _run(self) -> None:
        crashed: Optional[BaseException] = None
        # first probe round immediately: real documents beat the
        # optimistic READY default as soon as the pod answers
        try:
            while True:
                if self._drain_requested:
                    self.request_drain("sigterm")
                self._schedule_probes()
                self._evict_expired()
                with self._cond:
                    if self._stop_now:
                        break
                    if self._draining and not self._queue \
                            and not self._owned:
                        break
                    if not self._queue and not self._owned:
                        # a deliberately idle router is alive: the
                        # activity stamp advances exactly like the
                        # service's idle beat
                        self._activity_t = time.monotonic()
                    self._cond.wait(0.05)
        except BaseException as e:  # the supervisor must never die silently
            crashed = e
            log.error(f"router supervisor crashed: {type(e).__name__}: {e}",
                      kind="io")
        finally:
            self._finish(crashed)

    def _schedule_probes(self) -> None:
        now = time.monotonic()
        due: List[Backend] = []
        with self._cond:
            for b in self.backends:
                if b.probing:
                    continue
                period = self.cfg.resurrect_after_s \
                    if b.state == BACKEND_DEAD else self.cfg.probe_period_s
                if b.last_probe_t is None or now - b.last_probe_t >= period:
                    b.last_probe_t = now
                    b.probing = True
                    due.append(b)
        for b in due:
            # probes ride their own daemon threads: a host that hangs
            # instead of erroring must not stall eviction or drain
            threading.Thread(target=self._probe_backend, args=(b,),
                             name=f"route-probe-{b.id}",
                             daemon=True).start()

    def _evict_expired(self) -> None:
        now = time.monotonic()
        expired: List[_RouterRequest] = []
        with self._cond:
            if not any(r.expired(now) for r in self._queue):
                return
            keep: Deque[_RouterRequest] = deque()
            for r in self._queue:
                (expired if r.expired(now) else keep).append(r)
            self._queue = keep
        for r in expired:
            self._resolve_deadline(r, "dequeue")

    # ------------------------------------------------------------------
    # workers (route + wire attempt)
    # ------------------------------------------------------------------

    def _route_locked(self, req: _RouterRequest) -> Optional[Backend]:
        """Lowest-score READY backend with spare depth, preferring ones
        this request has neither failed on nor been shed by; falls back to
        a failed-on backend (retrying beats stranding) but NEVER to a
        shed-by one — that is the backpressure contract."""
        best = fallback = None
        best_s = fb_s = float("inf")
        for b in self.backends:
            if b.state != BACKEND_READY \
                    or b.inflight >= self.cfg.per_backend_depth \
                    or b.id in req.shed_by:
                continue
            s = b.health_score()
            if b.id in req.failed_on:
                if s < fb_s:
                    fallback, fb_s = b, s
            elif s < best_s:
                best, best_s = b, s
        return best if best is not None else fallback

    def _run_worker(self) -> None:
        while True:
            req: Optional[_RouterRequest] = None
            backend: Optional[Backend] = None
            overloaded: Optional[Overloaded] = None
            parked_now = False
            with self._cond:
                while True:
                    if self._workers_stop:
                        return
                    if self._queue:
                        head = self._queue[0]
                        ready = [b for b in self.backends
                                 if b.state == BACKEND_READY]
                        if ready and all(b.id in head.shed_by
                                         for b in ready):
                            # every live backend has shed this request:
                            # propagate the backpressure to the edge now
                            req = self._queue.popleft()
                            overloaded = self._aggregate_overload_locked(req)
                            self._owned.add(req)
                            break
                        backend = self._route_locked(head)
                        if backend is not None:
                            req = self._queue.popleft()
                            backend.inflight += 1
                            backend.requests += 1
                            self._owned.add(req)
                            break
                        if not ready and not head.parked_logged:
                            head.parked_logged = True
                            parked_now = True
                            req = head  # only for the event below
                            break
                    self._cond.wait(0.05)
            if parked_now:
                # the whole pod is dead: admitted work parks off-budget
                # behind the resurrection probes — availability degraded,
                # nothing lost (logged once per request, not per tick)
                obs_events.emit("retry", unit=req.id, kind="connection",
                                on_budget=False, scope="router",
                                via="awaiting_capacity",
                                trace=req.trace_id)
                continue
            if overloaded is not None:
                self._settle_overloaded(req, overloaded)
                continue
            self._attempt(req, backend)

    def _aggregate_overload_locked(self,
                                   req: _RouterRequest) -> Overloaded:
        """The honest aggregate backpressure answer: the soonest ANY
        backend promised capacity (min over their hints), falling back to
        the router's own cadence-derived estimate."""
        hints = [h for h in req.shed_hints if h is not None]
        retry = min(hints) if hints \
            else self._admission.retry_after_s(len(self._queue))
        return Overloaded(
            f"every live backend shed request {req.id} "
            f"({sorted(req.shed_by)})",
            reason="backpressure", retry_after_s=retry)

    def _attempt(self, req: _RouterRequest, backend: Backend) -> None:
        """One wire attempt against one backend, plus the failure ladder."""
        now = time.monotonic()
        if req.expired(now):
            self._release(backend)
            self._resolve_deadline(req, "dequeue")
            return
        budget = req.remaining_s(now)
        # the socket ceiling.  A BUDGETED attempt is bounded by its own
        # budget + the wire's settle margin — strictly above the window in
        # which serve_match answers a classified outcome (budget +
        # WIRE_SETTLE_MARGIN_S), and NEVER capped below it by
        # request_timeout_s: the backend's own deadline classification
        # must always outrun this socket timeout, or an in-budget backend
        # would be charged a failure streak for an edge that merely asked
        # for more time than the transport ceiling (the masquerade the
        # margin exists to prevent).  A hung socket therefore occupies a
        # worker for at most the edge's own budget — the edge asked for
        # that wait.  request_timeout_s bounds budget-LESS attempts only.
        from ncnet_tpu.serving.wire import WIRE_SETTLE_MARGIN_S

        timeout = self.cfg.request_timeout_s if budget is None \
            else budget + WIRE_SETTLE_MARGIN_S + 0.5
        client = backend.acquire(timeout)
        attempt_t0 = time.monotonic()
        try:
            result = client.match(
                req.src, req.tgt, client=req.client, budget_s=budget,
                request_id=req.id, timeout_s=timeout,
                trace=(req.trace.to_header()
                       if req.trace is not None else None))
        except Overloaded as e:
            self._release(backend, client)
            self._on_backpressure(req, backend, e)
            return
        except DeadlineExceeded as e:
            # the backend classified it with the propagated budget — the
            # edge deadline expired AS a deadline, never a silent timeout
            self._release(backend, client)
            self._resolve_deadline(req, f"backend_{e.where}")
            return
        except RequestQuarantined as e:
            self._release(backend, client)
            self._quarantine(req, e.kind, e)
            return
        except _TRANSPORT_ERRORS as e:
            self._release(backend, client, broken=True)
            self._on_attempt_failure(req, backend, e)
            return
        except Exception as e:  # noqa: BLE001 — an unclassified client bug
            # is still a backend-attempt failure, never a lost request
            self._release(backend, client, broken=True)
            self._on_attempt_failure(req, backend, e)
            return
        self._release(backend, client)
        now = time.monotonic()
        if req.expired(now):
            # the result landed after the edge budget (late wire, clock
            # margin): the caller has by contract moved on — classified,
            # not a zombie success
            self._resolve_deadline(req, "fetch")
            return
        self._settle_result(req, backend, result, now,
                            attempt_wall_s=now - attempt_t0)

    def _release(self, backend: Backend,
                 client: Optional[MatchClient] = None, *,
                 broken: bool = False) -> None:
        if client is not None:
            backend.release(client, broken=broken)
        with self._cond:
            backend.inflight = max(0, backend.inflight - 1)
            self._cond.notify_all()

    def _requeue_front(self, req: _RouterRequest) -> None:
        with self._cond:
            self._owned.discard(req)
            self._queue.appendleft(req)
            self._cond.notify_all()

    # -- failure ladder -----------------------------------------------------

    def _on_attempt_failure(self, req: _RouterRequest, backend: Backend,
                            exc: Exception) -> None:
        """Transport failure on one backend — the router-level failover
        ladder, mirroring the pool's: (1) a fresh READY survivor →
        re-route off-budget; (2) no READY backend at all → park off-budget
        behind the resurrection probes; (3) failed on every READY backend
        → the bounded retry budget, then quarantine.  An expired edge
        budget wins over all of it: the hang/refusal is classified as the
        DEADLINE it caused, never a silent timeout."""
        kind = "timeout" if isinstance(exc, socket.timeout) else \
            "wire" if isinstance(exc, WireError) else "connection"
        if req.expired(time.monotonic()):
            # the edge budget is already gone: the "failure" is at least
            # partly our own give-up (the per-attempt socket ceiling
            # tracks the budget), so the DEADLINE is the honest outcome
            # and the backend's streak is NOT charged — sustained
            # short-deadline traffic must not quarantine healthy hosts
            # (a genuinely dead host still dies via its health probes)
            self._resolve_deadline(req, "backend_failure")
            return
        with self._cond:
            backend.note_failure()
            self._registry.counter(f"backend_failures_{backend.id}").inc()
            emit: List[Dict[str, Any]] = []
            if backend.state == BACKEND_READY and \
                    backend.consecutive_failures >= \
                    self.cfg.backend_max_failures:
                log.warning(
                    f"backend {backend.id} ({backend.url}) hit "
                    f"{backend.consecutive_failures} consecutive failures "
                    f"({kind}); quarantined DEAD — /healthz probes every "
                    f"{self.cfg.resurrect_after_s}s", kind=kind)
                self._kill_locked(backend, f"{kind}:{type(exc).__name__}",
                                  emit)
            req.failed_on.add(backend.id)
            survivors = [b for b in self.backends
                         if b.state == BACKEND_READY
                         and b.id not in req.failed_on]
            any_ready = any(b.state == BACKEND_READY
                            for b in self.backends)
        for e in emit:
            obs_events.emit(**e)
        if survivors:
            obs_events.emit("retry", unit=req.id, kind=kind,
                            on_budget=False, scope="router",
                            backend=backend.id, via="reroute",
                            trace=req.trace_id)
            self._requeue_front(req)
            return
        if not any_ready:
            if not req.parked_logged:
                req.parked_logged = True
                obs_events.emit("retry", unit=req.id, kind=kind,
                                on_budget=False, scope="router",
                                backend=backend.id, via="awaiting_capacity",
                                trace=req.trace_id)
            self._requeue_front(req)
            return
        req.attempts += 1
        if req.attempts <= self.cfg.retries:
            obs_events.emit("retry", unit=req.id, kind=kind,
                            attempt=req.attempts, on_budget=True,
                            scope="router", backend=backend.id,
                            trace=req.trace_id)
            self._requeue_front(req)
        else:
            self._quarantine(req, kind, exc)

    def _on_backpressure(self, req: _RouterRequest, backend: Backend,
                         exc: Overloaded) -> None:
        """A backend shed the request: record it (NOT a failure streak —
        an overloaded host is healthy), steer the request to a backend
        that has not shed it, and let the worker loop surface the honest
        aggregate once every live backend has."""
        with self._cond:
            backend.backpressure += 1
            backend.retry_after_s = exc.retry_after_s
            self._registry.counter(
                f"backend_backpressure_{backend.id}").inc()
            req.shed_by.add(backend.id)
            if exc.retry_after_s is not None:
                req.shed_hints.append(float(exc.retry_after_s))
        obs_events.emit("retry", unit=req.id, kind="overloaded",
                        on_budget=False, scope="router",
                        backend=backend.id, via="backpressure",
                        reason=exc.reason,
                        retry_after_s=exc.retry_after_s,
                        trace=req.trace_id)
        if req.expired(time.monotonic()):
            self._resolve_deadline(req, "backpressure")
            return
        self._requeue_front(req)

    # -- settle paths (each ends in _terminal; exactly one wins) ------------

    def _settle_result(self, req: _RouterRequest, backend: Backend,
                       result: MatchResult, now: float, *,
                       attempt_wall_s: float) -> None:
        wall = now - req.submitted_t
        edge = MatchResult(request_id=req.id, table=result.table,
                           quality=result.quality, bucket=result.bucket,
                           wall_s=wall)
        if not req.future._try_settle("result", result=edge):
            self._disown(req)  # force-settled during shutdown: the winner
            return             # did the terminal accounting
        with self._cond:
            # the ATTEMPT wall (wire round trip only) feeds the estimators
            # — the backend's EWMA/score and the retry-after cadence both
            # assume a per-drain wall; the submit-to-settle edge wall
            # includes shared router-queue delay and would double-count
            # the queue in retry_after_s (depth × wall already multiplies
            # by the backlog) and loosen the watchdog's staleness
            # thresholds.  The edge wall still rules the latency
            # histogram, the result, and the events — that IS the
            # end-to-end promise.
            backend.note_success(attempt_wall_s)
            self._activity_t = now
            self._n["results"] += 1
            self._registry.counter("results").inc()
            self._registry.counter(f"backend_results_{backend.id}").inc()
            self._admission.note_batch_wall(attempt_wall_s)
            self._registry.histogram(
                "route_wall_ms", 0.0, self.cfg.latency_hist_ms,
            ).add(wall * 1e3)
        obs_events.emit(
            "route_result", request=req.id, client=req.client,
            backend=backend.id, wall_ms=round(wall * 1e3, 3),
            backend_wall_ms=round(result.wall_s * 1e3, 3),
            attempts=req.attempts, trace=req.trace_id)
        self._terminal(req)

    def _resolve_deadline(self, req: _RouterRequest, where: str) -> None:
        if not req.future._try_settle("deadline", error=DeadlineExceeded(
                f"request {req.id} deadline expired at {where}",
                where=where)):
            self._disown(req)
            return
        with self._cond:
            self._n["deadline"] += 1
            self._registry.counter("deadline_exceeded").inc()
        obs_events.emit("route_deadline", request=req.id,
                        client=req.client, where=where, admitted=True,
                        trace=req.trace_id)
        self._terminal(req)

    def _quarantine(self, req: _RouterRequest, kind: str,
                    exc: Exception) -> None:
        msg = (f"request {req.id} gave up after {req.attempts} budgeted "
               f"attempt(s): {type(exc).__name__}: {exc}")
        if not req.future._try_settle("quarantined",
                                      error=RequestQuarantined(
                                          msg, kind=kind,
                                          attempts=max(1, req.attempts))):
            self._disown(req)
            return
        log.warning(f"{msg} — quarantined; the stream continues",
                    kind="quarantine")
        with self._cond:
            self._n["quarantined"] += 1
            self._registry.counter("quarantined").inc()
        obs_events.emit("route_quarantine", request=req.id,
                        client=req.client, kind=kind,
                        attempts=max(1, req.attempts),
                        error=str(exc)[:300], trace=req.trace_id)
        self._terminal(req)

    def _settle_overloaded(self, req: _RouterRequest,
                           exc: Overloaded) -> None:
        if not req.future._try_settle("overloaded", error=exc):
            self._disown(req)
            return
        with self._cond:
            self._n["shed"] += 1
            self._registry.counter("shed").inc()
        obs_events.emit("route_shed", request=req.id, client=req.client,
                        reason=exc.reason,
                        retry_after_s=exc.retry_after_s, admitted=True,
                        trace=req.trace_id)
        self._terminal(req)

    def _terminal(self, req: _RouterRequest) -> None:
        """Close one admitted request's accounting — called exactly once
        per request, by whichever settle path WON the ``_try_settle``
        race; losers call :meth:`_disown` (ownership bookkeeping only)."""
        with self._cond:
            self._owned.discard(req)
            self._admission.note_done(req.client)
            self._activity_t = time.monotonic()
            self._cond.notify_all()

    def _disown(self, req: _RouterRequest) -> None:
        with self._cond:
            self._owned.discard(req)
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def _finish(self, crashed: Optional[BaseException]) -> None:
        with self._cond:
            self._finishing = True
            self._workers_stop = True
            self._cond.notify_all()
        for t in self._workers:
            # an attempt already on the wire completes (or times out at
            # its socket bound); the join is bounded so a wedged socket
            # cannot wedge shutdown — its request force-settles below and
            # the late completion loses the _try_settle race
            t.join(self.cfg.request_timeout_s + 5.0)
        with self._cond:
            # queued work AND requests a hung worker still owns: both get
            # their classified terminal outcome here, never a silent drop
            leftovers = list(self._queue) + list(self._owned)
            self._queue.clear()
        reason = "crashed" if crashed is not None else "shutdown"
        for req in leftovers:
            if not req.future._try_settle("overloaded", error=Overloaded(
                    f"router stopped before request {req.id} completed",
                    reason=reason)):
                continue
            with self._cond:
                self._n["shed"] += 1
                self._admission.note_done(req.client)
            obs_events.emit("route_shed", request=req.id,
                            client=req.client, reason=reason,
                            admitted=True, trace=req.trace_id)
        obs_events.emit(
            "route_drain", drained=self._draining and crashed is None,
            leftover=len(leftovers),
            **{f"n_{k}": v for k, v in self._n.items()})
        self._registry.flush(scope="router")
        with self._cond:
            if self._health.state != STOPPED:
                self._health.to(
                    STOPPED, "crashed" if crashed is not None else "clean")
            self._cond.notify_all()
        for b in self.backends:
            for c in b._clients:
                c.close()
            b._clients.clear()
        obs_events.emit("route_health_doc", doc=self.health())
        if self._introspect is not None:
            self._introspect.stop()
            self._introspect = None


# ---------------------------------------------------------------------------
# the router's exposition plane (ncnet_route_* families)
# ---------------------------------------------------------------------------


def router_metrics_families(router: MatchRouter) -> List[Family]:
    """The curated ``ncnet_route_*`` family set — the router-tier twin of
    ``serving/introspect.py::metrics_families``, built from one consistent
    health-document cut."""
    doc = router.health()
    with router._cond:
        from ncnet_tpu.observability.metrics import Counter, Histogram

        reg = dict(router._registry._metrics)
        lat = Family("ncnet_route_latency_ms", "histogram",
                     "edge-to-edge request latency through the router")
        for name, h in sorted(reg.items()):
            if isinstance(h, Histogram) and h.count \
                    and name == "route_wall_ms":
                lat.add_histogram(h)
        backend_counters = [
            (name, m.value) for name, m in sorted(reg.items())
            if isinstance(m, Counter) and name.startswith("backend_")
        ]
    fams: List[Family] = []
    up = Family("ncnet_route_up", "gauge",
                "1 while the router admits (STARTING/READY/DEGRADED)")
    up.add(1 if doc["state"] in ADMITTING else 0)
    fams.append(up)
    state = Family("ncnet_route_state", "gauge",
                   "router health state (1 on the active state's series)")
    state.add(1, state=doc["state"])
    fams.append(state)
    outcomes = Family("ncnet_route_requests_total", "counter",
                      "terminal outcomes of admitted requests at the "
                      "router tier (the outcome-total contract)")
    for outcome, n in sorted(doc["counters"].items()):
        outcomes.add(n, outcome=outcome)
    fams.append(outcomes)
    q = doc["queue"]
    fams.append(Family("ncnet_route_queue_depth", "gauge",
                       "requests queued at the router").add(q["depth"]))
    fams.append(Family("ncnet_route_effective_max_queue", "gauge",
                       "the elastic queue bound at live backend capacity")
                .add(q["effective_max_queue"]))
    fams.append(Family("ncnet_route_inflight", "gauge",
                       "requests owned by workers (on the wire or "
                       "settling)").add(q["inflight"]))
    pod = doc["pod"]
    fams.append(Family("ncnet_route_backends", "gauge",
                       "pod capacity by readiness")
                .add(pod["ready"], status="ready")
                .add(pod["total"], status="total"))
    fams.append(Family("ncnet_route_replica_units", "gauge",
                       "pod replica capacity (the admission units)")
                .add(pod["replicas_ready"], status="ready")
                .add(pod["replicas_total"], status="total"))
    b_up = Family("ncnet_route_backend_up", "gauge",
                  "1 = backend READY, 0 = DRAINING or DEAD")
    b_score = Family("ncnet_route_backend_health_score", "gauge",
                     "routing cost (lower = preferred)")
    b_wall = Family("ncnet_route_backend_wall_ewma_ms", "gauge",
                    "request-wall EWMA per backend (wire included)")
    b_inflight = Family("ncnet_route_backend_inflight", "gauge",
                        "wire attempts out per backend")
    for row in pod["backends"]:
        b_up.add(1 if row["state"] == BACKEND_READY else 0,
                 backend=row["id"])
        b_score.add(row["score"], backend=row["id"])
        if row.get("ewma_wall_ms") is not None:
            b_wall.add(row["ewma_wall_ms"], backend=row["id"])
        b_inflight.add(row["inflight"], backend=row["id"])
    fams.extend([b_up, b_score, b_wall, b_inflight])
    b_req = Family("ncnet_route_backend_results_total", "counter",
                   "results served per backend")
    b_fail = Family("ncnet_route_backend_failures_total", "counter",
                    "transport failures per backend")
    b_bp = Family("ncnet_route_backend_backpressure_total", "counter",
                  "Overloaded answers per backend (propagated, never "
                  "retried against the same host)")
    for name, value in backend_counters:
        if name.startswith("backend_results_"):
            b_req.add(value, backend=name[len("backend_results_"):])
        elif name.startswith("backend_failures_"):
            b_fail.add(value, backend=name[len("backend_failures_"):])
        elif name.startswith("backend_backpressure_"):
            b_bp.add(value, backend=name[len("backend_backpressure_"):])
    fams.extend([b_req, b_fail, b_bp, lat])
    fams.append(Family("ncnet_route_activity_age_seconds", "gauge",
                       "seconds since the router last settled a request "
                       "or deliberately idled")
                .add(doc["activity"]["age_s"]))
    return fams


def render_router_statusz(router: MatchRouter) -> str:
    """The router's human page — glanceable, greppable, one document cut."""
    doc = router.health()
    lines: List[str] = []
    add = lines.append
    svc = doc["service"]
    add("ncnet_tpu match router — statusz")
    add(f"state: {doc['state']}  (for {svc['age_s']}s"
        + (f", reason: {svc['reason']}" if svc.get("reason") else "") + ")")
    q = doc["queue"]
    add(f"queue: depth={q['depth']}/{q['effective_max_queue']}  "
        f"inflight={q['inflight']}")
    c = doc["counters"]
    add(f"requests: admitted={c['admitted']}  results={c['results']}  "
        f"deadline={c['deadline']}  quarantined={c['quarantined']}  "
        f"shed={c['shed']}")
    add("")
    pod = doc["pod"]
    add(f"backends ({pod['ready']}/{pod['total']} ready, "
        f"{pod['replicas_ready']}/{pod['replicas_total']} replica units):")
    add(f"  {'id':<6} {'state':<9} {'score':>10} {'ewma_ms':>9} "
        f"{'infl':>4} {'results':>8} {'fail':>5} {'bp':>4} "
        f"{'replicas':>9} {'last_ok':>8}")
    for row in pod["backends"]:
        ewma = row.get("ewma_wall_ms")
        last = row.get("last_result_age_s")
        add(f"  {row['id']:<6} {row['state']:<9} {row['score']:>10.4f} "
            f"{(f'{ewma:.2f}' if ewma is not None else '-'):>9} "
            f"{row['inflight']:>4} {row['results']:>8} "
            f"{row['failures']:>5} {row['backpressure']:>4} "
            f"{row['replicas_ready']}/{row['replicas_total']:>7} "
            f"{(f'{last:.1f}s' if last is not None else '-'):>8}")
    add("")
    add("recent health timeline:")
    for h in svc.get("history", []):
        add(f"  -> {h['state']}"
            + (f"  ({h['reason']})" if h.get("reason") else ""))
    return "\n".join(lines) + "\n"


class _RouterIntrospectionServer(IntrospectionServer):
    """The router's ``/metrics`` + ``/healthz`` + ``/statusz`` +
    ``POST /match`` thread: the base server's handler and lifecycle with
    router-shaped payloads.  ``match_payload`` is inherited unchanged —
    ``MatchRouter.submit`` has the service's submit signature, so a router
    is itself a wire backend and tiers chain."""

    def metrics_text(self) -> str:
        self._scrapes += 1
        fams = router_metrics_families(self._service)
        fams.append(Family("ncnet_route_scrapes_total", "counter",
                           "scrapes answered by this router")
                    .add(self._scrapes))
        return render(fams)

    def statusz_text(self) -> str:
        return render_router_statusz(self._service)
