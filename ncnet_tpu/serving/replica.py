"""Replicated serving: a pool of per-device match engines with health-scored
routing, replica quarantine, and resurrection probes.

PR 8's ``MatchService`` wrapped exactly one :class:`BatchMatchEngine` on one
device — a chip failure forced demote-retrace on the only replica, and the
whole service's capacity was one device's.  The pool turns that into the
robustness shape a pod-scale server needs: **N replicas where losing a
device degrades capacity instead of availability**.

  * **One engine per device.**  :meth:`ReplicaPool.from_model` instantiates
    one :class:`BatchMatchEngine` per visible device (params committed to
    that device, so every jit dispatch lands there) — testable on CPU via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
  * **Health-scored routing.**  The service routes each coalesced batch to
    the READY replica with the lowest :meth:`Replica.health_score` — an
    EWMA of its measured batch walls (the PR 5/6 telemetry signal) scaled
    by its current load, its consecutive-failure streak, and how many tier
    demotions its failures have forced.  A slow or flaky replica is
    de-prioritized *continuously*, not only after it dies.
  * **Replica quarantine, not request quarantine.**  A batch failure
    requeues the batch and re-routes it to a surviving replica off-budget
    (zero lost requests — the failure is the replica's fault, not the
    request's); ``replica_max_failures`` CONSECUTIVE failures move the
    replica itself to DEAD, where the router never sends it traffic.
  * **Resurrection probes.**  Every ``resurrect_after_s`` the service
    dispatches a tiny probe pair at a DEAD replica; success returns it to
    READY (``serve_health`` event, ``replica``-tagged) and its capacity
    flows back into admission control.
  * **Elastic admission.**  Membership changes call back into the service
    (``on_change``) so the queue bound and ``retry_after_s`` hints track
    LIVE capacity: a 4-replica pool running on 2 survivors advertises half
    the queue and double the drain time, and an all-dead pool sheds with
    ``reason="no_capacity"`` instead of queueing work nobody can run.

Replica state is mutated only under the owning service's condition lock
(the pool holds no lock of its own); the chaos seams live in
``utils/faults.py`` (``dead_replica_ids`` / ``slow_replica_ids``), called
from :meth:`Replica.dispatch`/:meth:`Replica.fetch` so injected deaths and
slowdowns exercise the REAL routing and failover paths.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, FrozenSet

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving.request import Bucket

# replica lifecycle states (distinct from the service-level health machine:
# replicas cycle READY <-> DEAD, the service machine is monotone).
# DRAINING is the live-rollout holding state: the router treats it like
# DEAD (no new traffic) but resurrection probes leave it alone — the
# rollout controller owns the replica until it re-admits it via
# ``resurrect``.
REPLICA_READY = "READY"
REPLICA_DEAD = "DEAD"
REPLICA_DRAINING = "DRAINING"

# routing prior for a replica with no measured wall yet (fresh or just
# resurrected): small enough that an idle unknown replica wins against a
# busy known one, large enough that a known-fast idle replica still wins
_PRIOR_WALL_S = 0.05

_EWMA_ALPHA = 0.3  # same ~6-sample memory as the admission batch-wall EWMA


class Replica:
    """One engine in the pool: the engine + its scheduling/health state.

    All mutable fields are owned by the service's condition lock; the only
    methods safe to call without it are :meth:`dispatch`/:meth:`fetch`
    (which touch the device, not the scheduling state).
    """

    def __init__(self, rid: str, engine: Any, device: Any = None):
        self.id = rid
        self.engine = engine
        self.device = device
        self.state = REPLICA_READY
        # scheduling (service-lock owned)
        self.pending: Deque[Any] = deque()   # dispatched, fetch not started
        self.processing: Any = None          # the batch its fetcher holds
        # health signals (the routing score inputs)
        self.ewma_wall_s: Optional[float] = None
        self.consecutive_failures = 0
        self.demotions = 0          # tier demotions this replica's failures forced
        # counters / timeline
        self.batches = 0
        self.failures = 0
        self.deaths = 0
        self.dead_since: Optional[float] = None
        self.last_probe_t: Optional[float] = None
        self.probing = False   # a probe thread is out on this replica
        self.last_bucket: Optional[Bucket] = None
        # which model generation this replica's engine is serving; stamped
        # by the service at construction and advanced by the rollout
        # controller at each drained swap (version-tags results + /metrics)
        self.model_version: str = ""

    # -- device-facing (no service lock; the chaos seams live here) ---------

    def dispatch(self, src_u8, tgt_u8, src_digests=None):
        from ncnet_tpu.utils import faults

        faults.replica_fault_hook(self.id, "dispatch")
        if src_digests is not None:
            # only engines that understand digest memoization get the
            # keyword (injected fakes keep their two-arg signature)
            return self.engine.dispatch(src_u8, tgt_u8,
                                        src_digests=src_digests)
        return self.engine.dispatch(src_u8, tgt_u8)

    def dispatch_tracked(self, src_u8, tgt_u8, prior_ab, prior_ba,
                         src_digests=None):
        """The streaming batch: same fault seam as :meth:`dispatch` (an
        injected replica death kills tracked frames identically), routed
        to the engine's coarse-pass-free tracked program."""
        from ncnet_tpu.utils import faults

        faults.replica_fault_hook(self.id, "dispatch")
        return self.engine.dispatch_tracked(
            src_u8, tgt_u8, prior_ab, prior_ba, src_digests=src_digests)

    @property
    def supports_tracking(self) -> bool:
        return hasattr(self.engine, "dispatch_tracked")

    def fetch(self, handle):
        from ncnet_tpu.utils import faults

        faults.replica_fault_hook(self.id, "fetch")
        return self.engine.fetch(handle)

    # -- scheduling/health state (service-lock owned) -----------------------

    @property
    def load(self) -> int:
        """Batches this replica currently owns (queued for fetch + the one
        its fetcher holds)."""
        return len(self.pending) + (1 if self.processing is not None else 0)

    def health_score(self) -> float:
        """Routing cost, lower = route here.  Base cost is the measured
        batch-wall EWMA (a slow replica is expensive), scaled by current
        load (a busy replica queues the batch behind its backlog), doubled
        per consecutive failure (a flaky replica is probably about to cost
        a full failover round trip), and bumped per tier demotion its
        failures forced (its retraced programs run the slower ladder)."""
        wall = self.ewma_wall_s if self.ewma_wall_s else _PRIOR_WALL_S
        streak = 2.0 ** min(self.consecutive_failures, 4)
        return wall * (1.0 + self.load) * streak * (1.0 + 0.5 * self.demotions)

    def note_success(self, wall_s: float) -> None:
        self.batches += 1
        self.consecutive_failures = 0
        w = float(wall_s)
        self.ewma_wall_s = w if self.ewma_wall_s is None else (
            _EWMA_ALPHA * w + (1.0 - _EWMA_ALPHA) * self.ewma_wall_s)

    def note_failure(self) -> None:
        self.failures += 1
        self.consecutive_failures += 1

    def probe(self) -> Dict[str, Any]:
        """One replica's row in the UNIFIED health document
        (``serving/health.py::build_health_document`` nests these under
        ``pool.replicas`` — the one place this shape is consumed, so the
        row and the service-level probe can no longer drift apart)."""
        return {
            "id": self.id,
            "state": self.state,
            "model_version": self.model_version or None,
            "device": str(self.device) if self.device is not None else None,
            "score": round(self.health_score(), 6),
            "ewma_wall_ms": (round(self.ewma_wall_s * 1e3, 3)
                             if self.ewma_wall_s else None),
            "consecutive_failures": self.consecutive_failures,
            "load": self.load,
            "batches": self.batches,
            "failures": self.failures,
            "deaths": self.deaths,
            "demotions": self.demotions,
            # how long it has been dead (None while READY): the /statusz
            # operator signal for "is resurrection overdue"
            "dead_age_s": (round(time.monotonic() - self.dead_since, 3)
                           if self.dead_since is not None else None),
        }


class ReplicaPool:
    """The replica set + routing.  Owned by one ``MatchService``; every
    method that reads or writes replica state must be called under the
    service's condition lock.  ``on_change(ready, total)`` fires on every
    membership change (death, resurrection) — the service wires it into
    admission control so queue bounds and retry hints track live capacity.
    """

    def __init__(self, replicas: List[Replica],
                 on_change: Optional[Callable[[int, int], None]] = None):
        if not replicas:
            raise ValueError("a replica pool needs at least one replica")
        self.replicas = list(replicas)
        ids = [r.id for r in self.replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.on_change = on_change
        # canary routing (rollout-controller owned, service-lock guarded):
        # while set, ``canary_id`` receives ``canary_fraction`` of routing
        # decisions via a deterministic credit accumulator and is excluded
        # from the general health-scored scan
        self.canary_id: Optional[str] = None
        self.canary_fraction: float = 0.0
        self._canary_credit: float = 0.0

    @classmethod
    def from_model(cls, model_config, params, n_replicas: int = 0,
                   on_change: Optional[Callable[[int, int], None]] = None,
                   **engine_kw) -> "ReplicaPool":
        """One :class:`BatchMatchEngine` per visible device.  ``n_replicas
        == 0`` uses every device; ``n > len(devices)`` assigns devices
        round-robin (useful for CPU smoke tests of the pool mechanics; the
        capacity numbers only mean something at one replica per device)."""
        import jax

        from ncnet_tpu.serving.engine import BatchMatchEngine

        devices = jax.devices()
        n = len(devices) if n_replicas <= 0 else int(n_replicas)
        replicas = []
        for i in range(n):
            dev = devices[i % len(devices)]
            engine = BatchMatchEngine(model_config, params, device=dev,
                                      **engine_kw)
            replicas.append(Replica(f"rep{i}", engine, device=dev))
        return cls(replicas, on_change=on_change)

    # -- membership ---------------------------------------------------------

    def ready(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == REPLICA_READY]

    def dead(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == REPLICA_DEAD]

    def inflight_total(self) -> int:
        return sum(r.load for r in self.replicas)

    def get(self, rid: str) -> Optional[Replica]:
        for r in self.replicas:
            if r.id == rid:
                return r
        return None

    def _notify_change(self) -> None:
        if self.on_change is not None:
            self.on_change(len(self.ready()), len(self.replicas))

    def mark_dead(self, replica: Replica, reason: str) -> None:
        """Quarantine the REPLICA (not any request): the router stops
        sending it traffic until a resurrection probe succeeds.  Emits a
        ``serve_health`` event tagged with the replica id — the service-
        level machine stays wherever it is; replica state is orthogonal."""
        if replica.state == REPLICA_DEAD:
            return
        replica.state = REPLICA_DEAD
        replica.deaths += 1
        replica.dead_since = time.monotonic()
        replica.last_probe_t = None
        obs_events.emit("serve_health", replica=replica.id,
                        state=REPLICA_DEAD, reason=reason)
        self._notify_change()

    def resurrect(self, replica: Replica, reason: str = "probe_ok") -> None:
        """A probe succeeded: back to READY with a clean failure streak and
        a reset wall estimate (the pre-death EWMA is stale evidence)."""
        if replica.state == REPLICA_READY:
            return
        replica.state = REPLICA_READY
        replica.consecutive_failures = 0
        replica.ewma_wall_s = None
        replica.dead_since = None
        obs_events.emit("serve_health", replica=replica.id,
                        state=REPLICA_READY, reason=reason)
        self._notify_change()

    def drain_for_swap(self, replica: Replica, reason: str) -> None:
        """Pull a READY replica out of rotation for a live weight swap:
        DRAINING gets no new traffic (the router only considers READY) but
        — unlike DEAD — resurrection probes skip it, so the rollout
        controller alone decides when it rejoins (via :meth:`resurrect`).
        In-flight batches it already owns finish normally; the caller
        waits for ``load == 0`` before touching the engine."""
        if replica.state != REPLICA_READY:
            return
        replica.state = REPLICA_DRAINING
        obs_events.emit("serve_health", replica=replica.id,
                        state=REPLICA_DRAINING, reason=reason)
        self._notify_change()

    # -- canary routing (rollout controller seam) ---------------------------

    def set_canary(self, replica: Replica, fraction: float) -> None:
        """Route ``fraction`` of decisions to ``replica`` (the freshly
        swapped version) and everything else away from it."""
        self.canary_id = replica.id
        self.canary_fraction = max(0.0, min(1.0, float(fraction)))
        self._canary_credit = 0.0

    def clear_canary(self) -> None:
        self.canary_id = None
        self.canary_fraction = 0.0
        self._canary_credit = 0.0

    def due_probes(self, now: float, period_s: float) -> List[Replica]:
        """DEAD replicas whose next resurrection probe is due (and whose
        backlog has fully failed over — probing a replica that still owns
        batches would race its fetcher).  Stamps ``last_probe_t`` and the
        ``probing`` flag so the caller can probe OFF-thread without
        double-scheduling; a probe that never returns (the chip is wedged,
        not erroring) leaves ``probing`` set and the replica is simply
        never probed again — a wedge cannot be resurrected, and the leaked
        daemon thread is bounded at one per wedged replica."""
        due = []
        for r in self.replicas:
            if r.state != REPLICA_DEAD or r.load or r.probing:
                continue
            since = r.last_probe_t if r.last_probe_t is not None \
                else r.dead_since
            if since is None or now - since >= period_s:
                r.last_probe_t = now
                r.probing = True
                due.append(r)
        return due

    # -- routing ------------------------------------------------------------

    def route(self, max_load: int,
              exclude: FrozenSet[str] = frozenset()) -> Optional[Replica]:
        """The READY replica with the lowest health score and spare depth,
        preferring replicas the batch has NOT already failed on
        (``exclude``); when every candidate is excluded the least-cost
        READY one is returned anyway — retrying a replica beats stranding
        the batch.  None = no READY replica has spare depth.

        While a canary is set, it is carved OUT of the general scan and
        receives exactly ``canary_fraction`` of decisions through a
        deterministic credit accumulator (no RNG: every ``1/fraction``-th
        routable decision goes to the canary) — except when the rest of
        the pool has no spare depth, where the canary takes the batch
        anyway: availability beats holding the fraction exact."""
        canary = self.get(self.canary_id) if self.canary_id else None
        canary_ok = (canary is not None
                     and canary.state == REPLICA_READY
                     and canary.load < max_load)
        if canary_ok and canary.id not in exclude:
            self._canary_credit += self.canary_fraction
            if self._canary_credit >= 1.0:
                self._canary_credit -= 1.0
                return canary
        best = fallback = None
        best_s = fb_s = float("inf")
        for r in self.replicas:
            if r is canary:
                continue
            if r.state != REPLICA_READY or r.load >= max_load:
                continue
            s = r.health_score()
            if r.id in exclude:
                if s < fb_s:
                    fallback, fb_s = r, s
            elif s < best_s:
                best, best_s = r, s
        chosen = best if best is not None else fallback
        if chosen is None and canary_ok:
            return canary
        return chosen
