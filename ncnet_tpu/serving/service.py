"""The resident match service: continuous batching + replicated fault-
tolerant serving.

This is the serving twin of PR 1 (fault-tolerant training) and PR 3
(resilient batch eval): a resident process around the warm matcher that
keeps answering — correctly, within deadlines, at a degraded tier or on a
surviving replica if it must — while devices fail, queues overflow, and
clients misbehave.  The r05 bench motivates the shape: bs1 bf16 device time
is 5.5 ms but a serial caller waits ~681 ms of wall; the win is structural
(queueing, batching, pipelining, replication), not a kernel.

Pieces, and where each discipline comes from:

  * **Continuous batching** — an async request queue coalesces
    variable-resolution queries into padded shape buckets
    (``serving/buckets.py``, bounded jit cache) and dispatches the next
    batch while previous batches' fetches are still in flight; the
    per-replica in-flight depth follows the PR 2
    ``PipelineDepthController`` (the drain unit is one batch, exactly the
    PF-Pascal regime).
  * **Replicated serving** — ``serving/replica.py``: a :class:`ReplicaPool`
    of one ``BatchMatchEngine`` per visible device.  A dedicated fetcher
    thread per replica blocks on that replica's fetches, so a wedged chip
    stalls only its own lane; the dispatcher routes each coalesced batch to
    the least-loaded READY replica by a health score fed by the measured
    batch-wall EWMA, consecutive-failure streak, and tier-demotion state.
  * **Replica failover** — a replica failing mid-batch requeues that batch
    at the FRONT and re-routes it to a surviving replica OFF-budget (the
    failure is the replica's fault, not the request's — zero lost
    requests); ``replica_max_failures`` consecutive failures quarantine the
    REPLICA into a DEAD state with periodic resurrection probes.  Pool
    membership changes flow into admission control: the queue bound and
    ``retry_after_s`` hints track live capacity elastically.
  * **Admission control + backpressure** — ``serving/admission.py``:
    elastic queue depth, per-client in-flight caps, classified
    ``Overloaded`` rejections with aggregate-pool-cadence retry-after
    hints, ``no_capacity`` shedding when every replica is dead.
  * **Per-request deadlines** — the budget is checked at admission (an
    already-expired request is refused), at dequeue (expired requests are
    EVICTED from the batch before dispatch — they never waste device time),
    and at fetch (a result that lands after its caller's budget resolves
    deadline-exceeded, not as a zombie success).  Each fetch rides
    ``pipeline.call_with_watchdog`` so a hung tunnel surfaces as a
    retryable timeout, not an eternal stall.
  * **Degraded-mode survival** — when no surviving replica can take a
    failed batch (a single-replica pool, or a request that failed
    everywhere), the PR 3 ``recover_from_device_failure`` demote-retrace
    path runs and grants a free retry; repeated failures quarantine
    individual requests into a journaled ``RunManifest``; SIGTERM (PR 1's
    ``PreemptionHandler`` pattern) stops admission and drains admitted work
    to completion; the STARTING/READY/DEGRADED/DRAINING/STOPPED health
    machine (``serving/health.py``) is exported for probes, with the
    replica-pool recovery owning the one DEGRADED → READY edge.
  * **Telemetry** — every lifecycle edge is an event (``serve_admit`` /
    ``serve_shed`` / ``serve_batch`` / ``serve_result`` / ``serve_deadline``
    / ``serve_quarantine`` / ``serve_health`` / ``serve_drain``), with
    ``serve_batch``/``serve_result``/``retry``/``quality`` and replica
    deaths/resurrections tagged by replica id; latency aggregates through
    per-bucket AND per-replica ``Histogram`` digests, and the PR 5
    ``Heartbeat`` is bumped per dispatched batch pool-wide (the
    ``tools/stall_watchdog.py`` liveness contract — one wedged replica
    cannot stop the beats while survivors dispatch).

The outcome-total contract (serving/request.py): every admitted request
terminates in exactly one of {result, deadline, overloaded, quarantined} —
proven by event-log accounting in ``tools/run_report.py --serving`` and
executed under fault injection by tests/test_serving.py (single engine) and
tests/test_serving_pool.py (the replica pool's chaos chain).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ncnet_tpu.observability import MetricsRegistry, events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability import memory as obs_memory
from ncnet_tpu.observability.device import DeviceMonitor
from ncnet_tpu.serving.admission import AdmissionController
from ncnet_tpu.serving.buckets import ShapeBucketer, pad_to_bucket
from ncnet_tpu.serving.health import (
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    HealthMachine,
    build_health_document,
)
from ncnet_tpu.serving.slo import SLOTracker
from ncnet_tpu.serving.replica import (
    REPLICA_READY,
    Replica,
    ReplicaPool,
)
from ncnet_tpu.serving.request import (
    Bucket,
    DeadlineExceeded,
    MatchFuture,
    MatchRequest,
    MatchResult,
    Overloaded,
    RequestQuarantined,
    ServeError,
    as_pair_image,
    bucket_label,
)

log = get_logger("serving")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs of the resident match service (README "Serving")."""

    # admission / backpressure
    max_queue: int = 64                 # total queued requests before shedding
    max_in_flight_per_client: int = 16  # outstanding per client id
    # batching
    max_batch: int = 8                  # requests coalesced per dispatch
    pipeline_depth: int = 0             # 0 = adaptive (2-4); >0 pins it
    # deadlines / hangs
    default_deadline_s: Optional[float] = None  # None = no implicit deadline
    fetch_timeout_s: float = 0.0        # >0: watchdog per batch fetch
    # failure policy
    retries: int = 1                    # budgeted retries per request
    quarantine_dir: Optional[str] = None  # RunManifest home (None = events only)
    # replication (serving/replica.py)
    replicas: int = 1                   # engines in the pool; 0 = one per device
    replica_max_failures: int = 3       # consecutive failures -> replica DEAD
    resurrect_after_s: float = 5.0      # probe period for DEAD replicas
    elastic_admission: bool = True      # queue bound tracks ready/total
    # shape buckets (bounded jit cache)
    bucket_multiple: int = 64
    max_image_side: int = 1024
    max_buckets: int = 4
    buckets: Optional[Tuple[Tuple[int, int], ...]] = None  # fixed ladder
    warm_buckets: Tuple[Tuple[int, int], ...] = ()  # square pairs compiled at start
    # liveness / telemetry
    heartbeat_path: Optional[str] = None
    latency_hist_ms: float = 2000.0     # per-bucket latency digest range
    install_sigterm: bool = False       # SIGTERM -> drain (PreemptionHandler style)
    # SLO / error budget (serving/slo.py)
    slo_ms: Optional[float] = None      # default per-request latency objective
    slo_ms_by_bucket: Tuple[Tuple[str, float], ...] = ()  # bucket-label overrides
    slo_budget_pct: float = 1.0         # allowed SLO-bad fraction of admitted (%)
    slo_window: int = 256               # sliding window for the live burn signal
    slo_emit_every: int = 32            # `slo` event cadence (terminal outcomes)
    # live introspection plane (serving/introspect.py): /metrics + /healthz
    # + /statusz.  None = off; 0 = ephemeral port (read back via
    # MatchService.introspect_url)
    introspect_port: Optional[int] = None
    introspect_host: str = "127.0.0.1"
    # persistent database-side feature store (ncnet_tpu/store/; README
    # "Feature store"): source-row backbone features cached on disk,
    # verified on read, shared by every replica's engine.  None = off.
    feature_store_dir: Optional[str] = None
    feature_store_budget_mb: int = 0    # LRU-evict above this (0 = unbounded)
    # streaming tracked mode (serving/stream.py; README "Streaming
    # matching"): per-stream sessions whose steady frames skip the coarse
    # pass by seeding candidates from the previous frame's match table.
    stream_tracking: bool = True        # False = every frame runs the full
                                        # pipeline (sessions still track
                                        # ordering/digests)
    stream_cut_recall: float = 0.35     # tracked frame whose candidate-
                                        # containment proxy falls below this
                                        # → scene cut → exact fallback
    stream_cut_quality_frac: float = 0.5  # ...or whose score/coherence
                                        # falls below this fraction of the
                                        # stream's EMA baseline
    stream_idle_evict_s: float = 30.0   # session GC age (worker tick)
    stream_max_sessions: int = 64       # live-session cap; admission sheds
                                        # `stream_cap` beyond it
    # match extraction
    do_softmax: bool = True
    scale: str = "centered"
    # live rollout (serving/rollout.py): the version label stamped on every
    # replica at construction — serve_result/quality events and /metrics
    # families carry it, and the rollout controller advances it per
    # drained swap
    model_version: str = "v0"


@dataclasses.dataclass
class _InFlight:
    handle: Any
    batch: List[MatchRequest]
    bucket: Bucket
    replica: Replica
    t0: float
    seq: int  # stamped at dispatch: fetchers complete out of order


class MatchService:
    """Resident, fault-tolerant match service around the warm matcher.

    Usage::

        service = MatchService(config, params, ServingConfig(...))
        service.start()
        fut = service.submit(src_u8, tgt_u8, deadline_s=0.5, client="cam0")
        result = fut.result(timeout=5.0)   # MatchResult, or a classified error
        ...
        service.stop()                      # drains admitted work, then stops

    ``engine`` may be injected (anything with ``dispatch``/``fetch``/
    ``retrace``) — the chaos suite drives the full lifecycle against a fake
    device without paying jit compiles; a SEQUENCE of engines builds a
    multi-replica pool over them (one replica per engine, ids ``rep0..``).
    Without injection, ``serving.replicas`` controls the pool: 1 (default)
    is the PR 8 single-engine service on the default device, N builds one
    ``BatchMatchEngine`` per visible device (0 = all of them).
    """

    def __init__(self, model_config=None, params=None,
                 serving: ServingConfig = ServingConfig(), *,
                 engine=None, registry: Optional[MetricsRegistry] = None,
                 store=None):
        self.cfg = serving
        # one persistent feature store SHARED across the pool (the store is
        # thread-safe; entries are device-independent f32 bytes).  Built
        # from the config when a model is given, or injected (chaos tests
        # attach one beside fake engines to exercise the health section).
        if store is None and serving.feature_store_dir \
                and model_config is not None and params is not None:
            from ncnet_tpu.store import FeatureStore, backbone_fingerprint

            fp = backbone_fingerprint(
                params, image_size="serve",
                k_size=max(model_config.relocalization_k_size, 1),
                dtype="bf16" if model_config.half_precision else "f32")
            store = FeatureStore(
                serving.feature_store_dir, fp,
                budget_bytes=serving.feature_store_budget_mb * 2 ** 20,
                scope="serving")
            store.gc_superseded()
        self._store = store
        # live-rollout state (serving/rollout.py): the model identity the
        # pod currently serves, the resident params a rollback swaps back
        # to, and the attached controller (None = no rollout in progress)
        self._model_config = model_config
        self._model_params = params
        self._model_version = serving.model_version
        self._rollout = None
        self._rollout_thread: Optional[threading.Thread] = None
        # test seam: replaces the controller's default checkpoint loader
        self.rollout_loader = None
        if engine is not None:
            engines = list(engine) if isinstance(engine, (list, tuple)) \
                else [engine]
            self._pool = ReplicaPool(
                [Replica(f"rep{i}", e) for i, e in enumerate(engines)],
                on_change=self._on_pool_change,
            )
        else:
            self._pool = ReplicaPool.from_model(
                model_config, params, serving.replicas,
                on_change=self._on_pool_change,
                do_softmax=serving.do_softmax, scale=serving.scale,
                store=self._store,
            )
        for rep in self._pool.replicas:
            rep.model_version = self._model_version
        self._registry = registry or MetricsRegistry(scope="serving")
        self._bucketer = ShapeBucketer(
            multiple=serving.bucket_multiple,
            max_side=serving.max_image_side,
            max_buckets=serving.max_buckets,
            fixed=serving.buckets,
        )
        self._admission = AdmissionController(
            max_queue=serving.max_queue,
            max_in_flight_per_client=serving.max_in_flight_per_client,
            max_batch=serving.max_batch,
            elastic=serving.elastic_admission,
            dead_retry_after_s=serving.resurrect_after_s,
        )
        self._admission.note_capacity(len(self._pool.ready()),
                                      len(self._pool.replicas))
        from ncnet_tpu.evaluation.pipeline import PipelineDepthController

        self._controller = PipelineDepthController(fixed=serving.pipeline_depth)
        self._health = HealthMachine()
        self._heartbeat = None
        if serving.heartbeat_path:
            from ncnet_tpu.observability import Heartbeat

            self._heartbeat = Heartbeat(serving.heartbeat_path)
        self._manifest = None
        if serving.quarantine_dir:
            from ncnet_tpu.evaluation.resilience import RunManifest

            os.makedirs(serving.quarantine_dir, exist_ok=True)
            self._manifest = RunManifest(
                os.path.join(serving.quarantine_dir, "manifest.json"),
                meta={"scope": "serving"},
            )

        self._cond = threading.Condition()
        self._queues: Dict[Bucket, Deque[MatchRequest]] = {}
        self._worker: Optional[threading.Thread] = None
        self._fetchers: List[threading.Thread] = []
        self._fetchers_stop = False
        self._draining = False
        self._drain_requested = False   # set from the signal handler: no lock
        self._stop_now = False
        self._finishing = False         # _finish has begun: admission closed
        self._processing: Optional[List[MatchRequest]] = None
        self._last_idle_beat = 0.0
        self._drain_resolved = 0
        self._req_seq = 0
        self._batch_seq = 0
        self._old_sigterm = None
        # tier-recovery single-flight: concurrent fetcher failures must not
        # each burn a ladder rung for ONE fault (generation bumps on every
        # successful demotion; a failure observed before someone else's
        # recovery rides that recovery instead of demoting again)
        self._recovery_lock = threading.Lock()
        self._recovery_gen = 0
        self._last_recovery_tier: Optional[str] = None
        # terminal-outcome accounting (the event log is the durable copy;
        # these back the health probe and the drain summary)
        self._n = {"admitted": 0, "results": 0, "deadline": 0,
                   "quarantined": 0, "shed": 0}
        # SLO error-budget tracker: fed under the service lock at every
        # terminal outcome, surfaced on /metrics + /healthz + `slo` events
        self._slo = SLOTracker(
            default_ms=serving.slo_ms,
            by_bucket=serving.slo_ms_by_bucket,
            budget_pct=serving.slo_budget_pct,
            window=serving.slo_window,
            emit_every=serving.slo_emit_every,
            registry=self._registry,
        )
        # monotonic stamp of the pool's last dispatch (or deliberate idle
        # tick): the HTTP-reachable liveness signal /healthz exports for
        # stall_watchdog --url — same semantics as the heartbeat beats
        # (a wedged fetch with nothing else dispatching stops advancing it)
        self._activity_t = time.monotonic()
        self._introspect = None
        # memory observability (observability/memory.py): per-replica HBM
        # watermarks sampled at every dispatched batch (CPU backends expose
        # none — the plane stays silent), a rate-limited device_snapshot
        # emitter on the worker tick, and the live-array leak sentinel fed
        # at batch boundaries
        self._hbm: Dict[str, Dict[str, Any]] = {}
        self._dev_monitor = DeviceMonitor(every_s=30.0)
        self._leak = obs_memory.LeakSentinel(
            window=4, min_interval_s=1.0, scope="serving")
        # streaming sessions (serving/stream.py): per-stream FIFO + prior
        # tables, idle-evicted from the worker tick, drained with the
        # service.  Tracked dispatch engages only when EVERY replica's
        # engine exposes the tracked program — a mixed pool would make a
        # stream's path depend on routing
        from ncnet_tpu.serving.stream import StreamTable

        self._streams = StreamTable(
            max_sessions=serving.stream_max_sessions,
            idle_evict_s=serving.stream_idle_evict_s)
        self._tracking_capable = all(
            r.supports_tracking for r in self._pool.replicas)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "MatchService":
        if self._worker is not None:
            raise RuntimeError("service already started")
        if self.cfg.introspect_port is not None:
            # fail-open: a port clash (or any bind failure) costs the
            # introspection plane, never the serving plane
            from ncnet_tpu.serving.introspect import IntrospectionServer

            try:
                self._introspect = IntrospectionServer(
                    self, host=self.cfg.introspect_host,
                    port=self.cfg.introspect_port).start()
            except Exception as e:  # noqa: BLE001 — telemetry never
                # kills the service it observes
                self._introspect = None
                log.warning(
                    f"introspection endpoint failed to bind "
                    f"{self.cfg.introspect_host}:{self.cfg.introspect_port}"
                    f" ({type(e).__name__}: {e}); serving without "
                    "/metrics + /healthz", kind="io")
        obs_events.emit(
            "serve_start",
            max_queue=self.cfg.max_queue, max_batch=self.cfg.max_batch,
            retries=self.cfg.retries,
            default_deadline_s=self.cfg.default_deadline_s,
            fetch_timeout_s=self.cfg.fetch_timeout_s,
            replicas=[r.id for r in self._pool.replicas],
            # the SLO objectives ride in the log so run_report --slo can
            # replay a dead service with the exact live thresholds
            slo=self._slo.config(),
            introspect_port=(self._introspect.port
                             if self._introspect is not None else None),
        )
        if self.cfg.install_sigterm and \
                threading.current_thread() is threading.main_thread():
            self._old_sigterm = signal.signal(signal.SIGTERM, self._on_sigterm)
        for rep in self._pool.replicas:
            t = threading.Thread(target=self._fetch_loop, args=(rep,),
                                 name=f"match-fetch-{rep.id}", daemon=True)
            t.start()
            self._fetchers.append(t)
        self._worker = threading.Thread(
            target=self._run, name="match-serve", daemon=True)
        self._worker.start()
        # safety net for a process that exits without stop() (an unhandled
        # exception in the caller): settle the outstanding futures and join
        # the worker before interpreter teardown — a daemon thread killed
        # mid-XLA-dispatch can otherwise segfault the exit
        import atexit

        atexit.register(self._atexit_stop)
        return self

    def _atexit_stop(self) -> None:
        w = self._worker
        if w is not None and w.is_alive():
            self.stop(drain=False, timeout=10.0)

    def _on_sigterm(self, signum, frame):
        # handler discipline (PR 1 PreemptionHandler): flip a flag, write
        # via os.write (print from a handler can deadlock on the stream
        # lock), let the worker act at its next loop edge.  No lock here —
        # the main thread may hold self._cond inside submit() when the
        # signal lands.
        self._drain_requested = True
        os.write(2, b"[serving] received SIGTERM; draining in-flight "
                    b"requests, admission closed\n")

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop the service.  ``drain=True`` (default) completes every
        admitted request first; ``drain=False`` aborts — queued and
        in-flight requests settle ``Overloaded(reason="shutdown")`` (still a
        classified terminal outcome, never a silent drop).  One caveat on
        the abort: a batch whose blocking device fetch has ALREADY begun
        completes normally first (a blocking fetch cannot be interrupted;
        configure ``fetch_timeout_s`` to bound that wait)."""
        with self._cond:
            if drain:
                self._begin_drain_locked("stop")
            else:
                # NOT _draining: an abort force-settles admitted work, and
                # the serve_drain event's `drained` flag must be able to
                # tell the two apart; admission closes via _stop_now
                self._stop_now = True
            self._cond.notify_all()
        w = self._worker
        if w is not None and w is not threading.current_thread():
            w.join(timeout)
        if self._old_sigterm is not None:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None
        import atexit

        try:
            # the safety net registered by start() would otherwise hold a
            # strong reference (service + engine jit cache + staged params)
            # for the life of the process
            atexit.unregister(self._atexit_stop)
        except Exception:  # noqa: BLE001 — interpreter teardown ordering
            pass

    def request_drain(self, reason: str = "drain") -> None:
        """Close admission and finish admitted work (the SIGTERM path,
        callable programmatically); returns immediately — join via
        :meth:`stop` or poll :meth:`health`."""
        with self._cond:
            self._begin_drain_locked(reason)
            self._cond.notify_all()

    def _begin_drain_locked(self, reason: str) -> None:
        if self._draining:
            return
        self._draining = True
        if self._health.state != STOPPED:
            self._health.to(DRAINING, reason)

    def __enter__(self) -> "MatchService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, src, tgt, *, deadline_s: Optional[float] = None,
               client: str = "default", trace: Optional[str] = None,
               _stream_fields: Optional[Dict[str, Any]] = None
               ) -> MatchFuture:
        """Admit one match query (raw uint8 pair).  Returns a
        :class:`MatchFuture`; raises :class:`Overloaded` (shed) or
        :class:`DeadlineExceeded` (budget already gone) synchronously —
        rejections are classified at the door, not discovered by timeout.
        ``trace`` adopts a pod-wide trace (a traceparent header or bare
        trace id — typically the wire's propagated context): every event
        this request touches then carries the trace id.  ``_stream_fields``
        is the private streaming seam (:meth:`stream_submit` passes the
        request's session/prior payload); external callers leave it None.
        """
        from ncnet_tpu.observability.tracing import normalize_trace

        src = as_pair_image(src, "src")
        tgt = as_pair_image(tgt, "tgt")
        trace = normalize_trace(trace)
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        shed: Optional[Overloaded] = None
        expired = False
        req: Optional[MatchRequest] = None
        with self._cond:
            if self._worker is None or self._finishing or self._stop_now \
                    or self._health.state == STOPPED:
                # _finishing closes the submit/_finish race: once _finish
                # has collected the leftover queues, a late submit must
                # shed rather than enqueue work nobody will ever settle
                shed = Overloaded("service is not running", reason="stopped")
            elif self._draining or self._drain_requested:
                shed = Overloaded("service is draining", reason="draining")
            elif deadline_s is not None and deadline_s <= 0:
                expired = True
            else:
                depth = self._queued_locked()
                try:
                    # peek first, COMMIT only after admission passes: a
                    # shed request must not burn a compiled-program slot
                    bucket = self._bucketer.peek(
                        src.shape[:2], tgt.shape[:2])
                    self._admission.admit(client, depth)
                    self._bucketer.commit(bucket)
                except Overloaded as e:
                    shed = e
                else:
                    # RESERVE only — the request is not visible to the
                    # worker until phase 2 enqueues it, so its serve_admit
                    # event always reaches the log before any terminal
                    # event (negative unresolved counts would otherwise be
                    # possible after a crash in the emit window)
                    self._req_seq += 1
                    req = MatchRequest(
                        id=f"r{self._req_seq}", client=client, src=src,
                        tgt=tgt, bucket=bucket,
                        future=MatchFuture(f"r{self._req_seq}"),
                        submitted_t=now,
                        deadline_t=(now + deadline_s) if deadline_s
                        else None,
                        trace=trace,
                        **(_stream_fields or {}),
                    )
                    self._admission.note_admit(client)
                    self._n["admitted"] += 1
                    self._registry.counter("admitted").inc()
                    self._registry.gauge("queue_depth").set(depth + 1)
            if shed is not None:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
        # event emission OUTSIDE the lock: EventLog appends flush+fsync,
        # and an fsync held under the service lock would serialize every
        # client's admission (and the worker's queue operations) behind
        # the disk
        if expired:
            obs_events.emit("serve_deadline", request=None, client=client,
                            where="admission", admitted=False)
            raise DeadlineExceeded(
                f"deadline budget {deadline_s}s already expired at "
                "admission", where="admission")
        if shed is not None:
            obs_events.emit(
                "serve_shed", client=client, reason=shed.reason,
                retry_after_s=shed.retry_after_s, admitted=False,
            )
            raise shed
        obs_events.emit(
            "serve_admit", request=req.id, client=client,
            bucket=bucket_label(req.bucket),
            deadline_s=round(deadline_s, 6) if deadline_s else None,
            **({"trace": trace} if trace else {}),
        )
        # phase 2: make the admitted request visible to the worker.  If
        # the service died between the phases, the admitted request still
        # gets its terminal outcome here (nobody else can see it).
        with self._cond:
            dead = self._finishing or self._stop_now \
                or self._health.state == STOPPED
            if not dead:
                self._queues.setdefault(req.bucket, deque()).append(req)
                self._cond.notify_all()
        if dead:
            exc = Overloaded(
                f"service stopped before request {req.id} was queued",
                reason="stopped")
            req.future._settle("overloaded", error=exc)
            with self._cond:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
            obs_events.emit("serve_shed", request=req.id, client=client,
                            reason="stopped", admitted=True,
                            **({"trace": trace} if trace else {}))
            self._observe_slo(req, "shed")
            self._emit_timeline(req, "overloaded")
            self._terminal(req)
            raise exc
        return req.future

    # ------------------------------------------------------------------
    # streaming (serving/stream.py; README "Streaming matching")
    # ------------------------------------------------------------------

    def stream_submit(self, stream: str, src, tgt, *,
                      deadline_s: Optional[float] = None,
                      client: Optional[str] = None,
                      trace: Optional[str] = None):
        """Serve one frame of a video stream — BLOCKING (unlike
        :meth:`submit`): frame ``t+1``'s candidates are seeded from this
        frame's match table, so the data dependence forces one frame in
        flight per stream; concurrent streams overlap freely, and the
        session's FIFO lock extends the ordering guarantee to
        multi-threaded callers of one stream id.

        The fast path dispatches the engine's TRACKED program — zero
        coarse passes — when the session has a prior, the bucket is
        unchanged, and the shape class is eligible.  Cut/drift detection
        runs on the result (candidate-containment proxy + quality-EMA
        collapse); a detected cut re-runs the SAME frame through the full
        pipeline (``submit`` — the identical executable a cold query uses,
        so the fallback output is bitwise a cold query's), re-seeding the
        tracker.  Returns a :class:`~ncnet_tpu.serving.stream.
        StreamFrameResult`; raises the same classified errors as
        :meth:`submit`."""
        from ncnet_tpu.serving.stream import StreamFrameResult

        client = client or f"stream:{stream}"
        sess = self._streams.acquire(stream)
        with sess.lock:
            try:
                out = self._stream_frame(sess, src, tgt, deadline_s, client,
                                         trace=trace)
            except ServeError:
                with self._cond:
                    sess.errors += 1
                raise
            finally:
                sess.last_activity = time.monotonic()
        self._registry.gauge("active_streams").set(
            self._streams.doc()["active"])
        assert isinstance(out, StreamFrameResult)
        return out

    def _stream_geom(self, bucket: Bucket):
        """(grid_a, grid_b, factor, radius) on the PADDED bucket, or None
        when no model config is attached (injected fake engines): the
        recall proxy and prior inversion are then skipped and the cut
        detector rides quality collapse alone."""
        mc = self._model_config
        if mc is None:
            return None
        from ncnet_tpu.ops.temporal import FEATURE_STRIDE

        ga = tuple(d // FEATURE_STRIDE for d in bucket[0])
        gb = tuple(d // FEATURE_STRIDE for d in bucket[1])
        if min(*ga, *gb) <= 0:
            return None
        return ga, gb, mc.sparse_factor, mc.track_radius

    def _tracking_eligible(self, bucket: Bucket) -> bool:
        if not (self.cfg.stream_tracking and self._tracking_capable):
            return False
        eng = self._pool.replicas[0].engine
        feasible = getattr(eng, "tracking_feasible", None)
        if feasible is None:
            return True  # injected fakes: capability implies eligibility
        return bool(feasible(bucket[0], bucket[1]))

    def _stream_frame(self, sess, src, tgt, deadline_s, client,
                      trace: Optional[str] = None):
        from ncnet_tpu.serving.stream import StreamFrameResult

        src = as_pair_image(src, "src")
        tgt = as_pair_image(tgt, "tgt")
        seq = sess.seq
        sess.seq += 1
        bucket = self._bucketer.peek(src.shape[:2], tgt.shape[:2])
        if sess.bucket is not None and bucket != sess.bucket:
            # resolution change: the prior's grids no longer describe the
            # frames — cold restart for this stream, never a stale gather
            sess.reset_tracking()
        sess.bucket = bucket
        geom = self._stream_geom(bucket)
        digest = None
        if self._tracking_capable:
            digest = sess.src_digest(
                src, bucket,
                lambda: pad_to_bucket([src], bucket[0])[0])
        tracked = (sess.prior_ab is not None
                   and self._tracking_eligible(bucket))
        fallback = False
        recall = None
        if tracked:
            fut = self.submit(
                src, tgt, deadline_s=deadline_s, client=client, trace=trace,
                _stream_fields=dict(
                    stream=sess.id, stream_seq=seq, tracked=True,
                    prior_ab=sess.prior_ab, prior_ba=sess.prior_ba,
                    src_digest=digest))
            res = fut.result()
            if geom is not None:
                from ncnet_tpu.ops.temporal import tracking_recall_proxy

                ga, gb, factor, radius = geom
                recall = tracking_recall_proxy(
                    sess.prior_ab, res.table, ga, gb, factor, radius,
                    scale=self.cfg.scale)
                sess.last_recall = recall
            cut = (recall is not None
                   and recall < self.cfg.stream_cut_recall) \
                or sess.quality_collapsed(
                    res.quality, self.cfg.stream_cut_quality_frac)
            if cut:
                obs_events.emit(
                    "stream_cut", stream=sess.id, seq=seq,
                    recall=(round(recall, 4) if recall is not None
                            else None),
                    quality=res.quality,
                    bucket=bucket_label(bucket))
                self._registry.counter("stream_cuts").inc()
                # exact fallback: the SAME frame through the full
                # pipeline — the identical program a cold query runs, so
                # this output is bitwise a cold coarse-to-fine query's —
                # and the tracker re-seeds from its table below
                sess.reset_tracking()
                fut = self.submit(src, tgt, deadline_s=deadline_s,
                                  client=client, trace=trace,
                                  _stream_fields=dict(
                                      stream=sess.id, stream_seq=seq,
                                      src_digest=digest))
                res = fut.result()
                tracked, fallback = False, True
        else:
            fut = self.submit(src, tgt, deadline_s=deadline_s,
                              client=client, trace=trace,
                              _stream_fields=dict(
                                  stream=sess.id, stream_seq=seq,
                                  src_digest=digest))
            res = fut.result()
        # re-seed / roll the prior from the served table, warm the quality
        # baseline, and account the frame
        if geom is not None:
            from ncnet_tpu.ops.temporal import prior_from_table

            ga, gb, factor, _radius = geom
            try:
                sess.prior_ab, sess.prior_ba = prior_from_table(
                    res.table, ga, gb, factor, scale=self.cfg.scale)
            except ValueError:
                # a table that doesn't invert (foreign engine shape) just
                # means the next frame runs the full pipeline
                sess.reset_tracking()
        sess.note_quality(res.quality)
        kind = "tracked" if tracked else (
            "fallback" if fallback else "cold")
        sess.frames += 1
        if tracked:
            sess.tracked_frames += 1
        elif fallback:
            sess.fallback_frames += 1
        else:
            sess.cold_frames += 1
        self._streams.note_frame(kind)
        self._registry.counter("stream_frames").inc()
        self._registry.counter(f"stream_frames_{kind}").inc()
        if recall is not None:
            self._registry.gauge("stream_recall").set(round(recall, 4))
        from ncnet_tpu.observability.tracing import normalize_trace

        obs_events.emit(
            "stream_frame", stream=sess.id, seq=seq, kind=kind,
            tracked=tracked, fallback=fallback,
            recall=(round(recall, 4) if recall is not None else None),
            wall_ms=round(res.wall_s * 1e3, 3),
            bucket=bucket_label(bucket), client=client,
            **({"trace": normalize_trace(trace)} if trace else {}))
        return StreamFrameResult(result=res, stream=sess.id, seq=seq,
                                 tracked=tracked, fallback=fallback,
                                 recall=recall)

    def _evict_idle_streams(self) -> None:
        for sess in self._streams.evict_idle():
            obs_events.emit("stream_evict", stream=sess.id,
                            frames=sess.frames,
                            tracked=sess.tracked_frames,
                            fallback=sess.fallback_frames, reason="idle")

    # ------------------------------------------------------------------
    # probes
    # ------------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The unified, schema-versioned health document
        (``serving/health.py::build_health_document``): service state +
        transition history, pool capacity + per-replica rows, queue/
        in-flight depth + bucket ladder, outcome counters, the SLO
        error-budget snapshot, and the activity age.  The same dict serves
        ``/healthz``, the chaos tests, and the final ``serve_health_doc``
        event ``run_report --serving`` renders."""
        now = time.monotonic()
        with self._cond:
            return build_health_document(
                self._health,
                [r.probe() for r in self._pool.replicas],
                queue={
                    "depth": self._queued_locked(),
                    "inflight_batches": self._pool.inflight_total(),
                    "pipeline_depth": self._controller.depth,
                    "effective_max_queue":
                        self._admission.effective_max_queue(),
                    "buckets": [bucket_label(b)
                                for b in self._bucketer.buckets],
                },
                counters=dict(self._n),
                slo=self._slo.snapshot(),
                activity={
                    "age_s": round(max(0.0, now - self._activity_t), 3),
                    "batches": self._batch_seq,
                },
                memory=self._memory_doc_locked(),
                store=(self._store.health()
                       if self._store is not None else None),
                model_version=self._model_version,
                rollout=(self._rollout.status()
                         if self._rollout is not None else None),
                streams=self._streams.doc(now),
            )

    def _memory_doc_locked(self) -> Dict[str, Any]:
        """The health document's memory section: the bucket ladder's
        PREDICTED aggregate footprint (sum of ledger temp+output bytes over
        this process's warmed serve programs) set against the live
        ``bytes_limit``, plus the latest per-replica HBM watermarks — the
        headroom an operator reads BEFORE admitting a new bucket."""
        predicted = obs_memory.predicted_footprint_bytes(
            program=obs_memory.SERVE_PROGRAM)
        doc: Dict[str, Any] = {
            "predicted_ladder_bytes": predicted,
            "ledger_programs": len(obs_memory.ledger_rows(
                program=obs_memory.SERVE_PROGRAM)),
            "hbm": {rid: dict(s) for rid, s in sorted(self._hbm.items())},
        }
        limits = [s.get("bytes_limit") for s in self._hbm.values()
                  if isinstance(s.get("bytes_limit"), int)]
        if limits and predicted is not None:
            doc["headroom_bytes"] = min(limits) - predicted
        return doc

    @property
    def state(self) -> str:
        return self._health.state

    @property
    def introspect_url(self) -> Optional[str]:
        """Base URL of the live introspection plane (None when disabled or
        bind failed) — ``<url>/metrics`` etc."""
        return self._introspect.url if self._introspect is not None else None

    def metrics(self) -> Dict[str, Any]:
        return self._registry.snapshot()

    # ------------------------------------------------------------------
    # pool membership -> admission (the elastic-capacity seam)
    # ------------------------------------------------------------------

    def _on_pool_change(self, ready: int, total: int) -> None:
        """ReplicaPool membership callback (service lock already held —
        mark_dead/resurrect are only called under it).  Queue bounds and
        retry hints re-derive from live capacity; the health machine
        reflects pool strength: below full → DEGRADED, fully restored with
        no standing tier demotion → back to READY (the pool owns that one
        recovery edge)."""
        self._admission.note_capacity(ready, total)
        self._registry.gauge("ready_replicas").set(ready)
        if self._health.state in (STARTING, READY) and ready < total:
            self._health.to(
                DEGRADED,
                "no_ready_replicas" if ready == 0
                else f"replicas_ready:{ready}/{total}")
        elif self._health.state == DEGRADED and ready == total:
            from ncnet_tpu import ops

            if not ops.demoted_fused_tiers():
                self._health.to(READY, "pool_restored")

    # ------------------------------------------------------------------
    # worker (dispatcher)
    # ------------------------------------------------------------------

    def _queued_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _run(self) -> None:
        crashed: Optional[BaseException] = None
        try:
            self._warmup()
            with self._cond:
                if self._health.state == STARTING:
                    self._health.to(READY, "warm")
            while True:
                if self._drain_requested:
                    self.request_drain("sigterm")
                # rate-limited device_snapshot on the worker tick: HBM
                # pressure is visible in the event log even while the
                # service idles (before this, only `fit` ever emitted one)
                self._dev_monitor.maybe_emit(step=self._batch_seq)
                self._maybe_resurrect()
                self._evict_expired()
                self._evict_idle_streams()
                self._fill_pipeline()
                with self._cond:
                    if self._stop_now:
                        # an ABORT does not drain in-flight fetches: the
                        # replica backlogs settle Overloaded("shutdown") in
                        # _finish, as stop(drain=False) documents
                        break
                    busy = self._pool.inflight_total() > 0
                    if self._draining and not self._queued_locked() \
                            and not busy:
                        break
                    if not self._queued_locked() and not busy:
                        self._controller.note_gap()
                        # a deliberately idle pool is alive: advance the
                        # /healthz activity stamp exactly where the idle
                        # heartbeat fires (and even when no heartbeat file
                        # is configured), so a wedged fetch — with nothing
                        # else dispatching — stops BOTH liveness signals
                        self._activity_t = time.monotonic()
                        self._idle_beat()
                    # fetcher completions, submits, and stop/drain all
                    # notify; the timeout bounds resurrection-probe and
                    # deadline-eviction latency while idle
                    self._cond.wait(0.05)
        except BaseException as e:  # the worker must never die silently
            crashed = e
            log.error(f"serving worker crashed: {type(e).__name__}: {e}",
                      kind="device")
        finally:
            self._finish(crashed)

    def _idle_beat(self) -> None:
        """Keep the heartbeat fresh while IDLE (rate-limited to ~1/s): a
        quiet service must stay distinguishable from a wedged one — these
        beats fire only when no batch is queued or in flight anywhere in
        the pool, so a wedged fetch (with nothing else dispatching) stops
        the beats exactly when the stall watchdog should fire."""
        if self._heartbeat is None:
            return
        now = time.monotonic()
        if now - self._last_idle_beat >= 1.0:
            self._last_idle_beat = now
            self._heartbeat.beat(step=self._batch_seq,
                                 state=self._health.state, idle=True)

    def _batch_ladder(self) -> List[int]:
        """The padded batch sizes _dispatch can produce: powers of two up
        to (and always including) max_batch."""
        sizes, b = [], 1
        while b < self.cfg.max_batch:
            sizes.append(b)
            b *= 2
        sizes.append(self.cfg.max_batch)
        return sizes

    def _warmup(self) -> None:
        """Compile the configured warm buckets (square pairs) at EVERY
        ladder batch size on EVERY replica before admitting traffic counts
        them as latency — each replica compiles its own programs on its own
        device, so a bucket warmed only on rep0 would still stall the live
        stream the first time the router sends that shape to rep1.
        Fail-open: a failed warm compile logs and moves on — the first real
        request in that shape pays the compile instead."""
        for hw in self.cfg.warm_buckets:
            try:
                bucket = self._bucketer.register(tuple(hw), tuple(hw))
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                log.warning(f"warm bucket {hw} not registrable "
                            f"({type(e).__name__}: {e})", kind="device")
                continue
            warmed = []
            for rep in self._pool.replicas:
                try:
                    for b in self._batch_ladder():
                        zeros = np.zeros((b, *bucket[0], 3), np.uint8)
                        zt = np.zeros((b, *bucket[1], 3), np.uint8)
                        rep.fetch(rep.dispatch(zeros, zt))
                    warmed.append(rep.id)
                except Exception as e:  # noqa: BLE001 — one replica's
                    # failed warm compile must not cold-start the others
                    log.warning(f"warmup of bucket {hw} on {rep.id} failed "
                                f"({type(e).__name__}: {e}); its first "
                                "request pays the compile", kind="device")
            obs_events.emit("serve_warm", bucket=bucket_label(bucket),
                            batch_sizes=self._batch_ladder(),
                            replicas=warmed)
        # drain the warm programs' background ledger analyses (bounded) so
        # the predicted-footprint gauge is complete by the time the
        # service reports READY — their compile cost overlaps the ladder's
        # own warm compiles above instead of riding a live request
        obs_memory.flush_pending(timeout=120.0)

    def _evict_expired(self) -> None:
        """Evict deadline-expired QUEUED requests even when no replica can
        take a batch — _fill_pipeline's dequeue check never runs while the
        pool is unroutable (all replicas dead or at depth), and a parked
        request whose budget is gone must still settle the classified
        ``DeadlineExceeded(where="dequeue")``, not hang until resurrection
        or shutdown.  Cheap: a scan per worker tick, a rebuild only when
        something actually expired."""
        now = time.monotonic()
        expired: List[MatchRequest] = []
        with self._cond:
            if not any(req.expired(now)
                       for q in self._queues.values() for req in q):
                return
            for bucket in list(self._queues):
                keep: Deque[MatchRequest] = deque()
                for req in self._queues[bucket]:
                    (expired if req.expired(now) else keep).append(req)
                if keep:
                    self._queues[bucket] = keep
                else:
                    del self._queues[bucket]
        for req in expired:
            self._resolve_deadline(req, "dequeue")

    def _fill_pipeline(self) -> None:
        """Dispatch batches until every READY replica's pipeline is full or
        the queue is empty — dispatching the NEXT batch while previous
        fetches are in flight is the continuous-batching overlap itself,
        and routing picks the least-loaded healthy replica per batch."""
        while True:
            expired: List[MatchRequest] = []
            batch: List[MatchRequest] = []
            bucket: Optional[Bucket] = None
            replica: Optional[Replica] = None
            with self._cond:
                if self._stop_now:
                    return
                bucket = self._pick_bucket_locked()
                if bucket is None:
                    return
                q = self._queues[bucket]
                # route BEFORE popping: an unroutable batch (every replica
                # busy or dead) stays queued instead of bouncing.  The
                # head request's failed-on set is the exclusion hint — a
                # requeued failed batch sits contiguously at the front, so
                # the head's history speaks for the batch.
                replica = self._pool.route(
                    max_load=self._controller.depth,
                    exclude=frozenset(q[0].failed_on))
                if replica is None:
                    return
                now = time.monotonic()
                while q and len(batch) < self.cfg.max_batch:
                    # tracked-homogeneous coalescing: a tracked and a plain
                    # request cannot share a program, so peek BEFORE
                    # popping and stop at the first flag flip — the
                    # minority flavor leads the next batch instead of
                    # bouncing
                    if batch and q[0].tracked != batch[0].tracked:
                        break
                    req = q.popleft()
                    # deadline check at DEQUEUE: an expired request is
                    # evicted before it can waste a device slot
                    (expired if req.expired(now) else batch).append(req)
                if not q:
                    del self._queues[bucket]
            for req in expired:
                self._resolve_deadline(req, "dequeue")
            if not batch:
                if expired:
                    continue  # the queue may hold more work behind evictions
                return
            with self._cond:
                self._processing = batch  # crash accounting (see _finish)
            self._dispatch(batch, bucket, replica)
            with self._cond:
                self._processing = None

    def _pick_bucket_locked(self) -> Optional[Bucket]:
        """Oldest-head-first across buckets: global FIFO fairness at batch
        granularity (a hot bucket cannot starve a cold one)."""
        best = None
        for bucket, q in self._queues.items():
            if q and (best is None
                      or q[0].submitted_t < self._queues[best][0].submitted_t):
                best = bucket
        return best

    def _dispatch(self, batch: List[MatchRequest], bucket: Bucket,
                  replica: Replica) -> None:
        # the BATCH dimension is bucketed too (next power of two, capped at
        # max_batch): without it every distinct coalesced size 1..max_batch
        # compiles its own program per shape bucket, and the first
        # occurrence of each size stalls the whole stream for a compile —
        # the very spike the bounded-jit-cache design exists to prevent.
        # Rows beyond len(batch) are zero padding; _drain_batch indexes
        # results by request position and never reads them.
        b = 1
        while b < len(batch):
            b *= 2
        b = min(b, self.cfg.max_batch)
        npad = b - len(batch)
        tracked = batch[0].tracked
        if tracked:
            # padding REPLICATES row 0 (not zeros): a tracked pad row must
            # carry a valid prior, and repeating the head's image keeps
            # the digest-memoized feature resolve a pure cache hit instead
            # of hashing + extracting a zero image per dispatch
            src = pad_to_bucket(
                [r.src for r in batch] + [batch[0].src] * npad, bucket[0])
            tgt = pad_to_bucket(
                [r.tgt for r in batch] + [batch[0].tgt] * npad, bucket[1])
            prior_ab = np.stack(
                [r.prior_ab for r in batch]
                + [batch[0].prior_ab] * npad).astype(np.int32)
            prior_ba = np.stack(
                [r.prior_ba for r in batch]
                + [batch[0].prior_ba] * npad).astype(np.int32)
            digests = ([r.src_digest for r in batch]
                       + [batch[0].src_digest] * npad)
        else:
            pad = [None] * npad
            src = pad_to_bucket(
                [r.src for r in batch] + pad, bucket[0])
            tgt = pad_to_bucket(
                [r.tgt for r in batch] + pad, bucket[1])
            digests = [r.src_digest for r in batch] + [None] * npad
        try:
            if tracked:
                handle = replica.dispatch_tracked(
                    src, tgt, prior_ab, prior_ba, src_digests=digests)
            elif any(d is not None for d in digests):
                handle = replica.dispatch(src, tgt, src_digests=digests)
            else:
                handle = replica.dispatch(src, tgt)
        except Exception as e:
            self._on_batch_failure(batch, e, phase="dispatch",
                                   replica=replica)
            return
        # trace-timeline stamps: queue phase ends here; a failover
        # re-dispatch re-stamps (the attribution covers the terminating
        # attempt, the queue segment absorbs earlier failed round trips)
        now_dispatch = time.monotonic()
        for req in batch:
            req.dispatched_t = now_dispatch
            req.fetch_begin_t = None
        self._batch_seq += 1
        if self._heartbeat is not None:
            # the liveness contract (tools/stall_watchdog.py): one beat per
            # dispatched batch, POOL-wide — a wedged replica stops the
            # beats only when no survivor is dispatching either
            self._heartbeat.beat(step=self._batch_seq,
                                 state=self._health.state)
        # live HBM watermark, sampled per dispatched batch (a cheap host
        # call; None on backends without memory_stats — the plane stays
        # silent, never errors).  A replica without a pinned device
        # (engine-injection pools) is NOT sampled: defaulting to device 0
        # would attribute one chip's watermarks to every lane
        hbm = (obs_memory.hbm_stats(replica.device)
               if replica.device is not None else None)
        with self._cond:
            self._activity_t = now_dispatch  # /healthz liveness signal
            if hbm is not None:
                self._hbm[replica.id] = hbm
                self._registry.gauge(
                    f"hbm_bytes_in_use_{replica.id}").set(
                        hbm.get("bytes_in_use"))
            replica.last_bucket = bucket
            replica.pending.append(
                _InFlight(handle, batch, bucket, replica, time.monotonic(),
                          self._batch_seq))
            self._registry.gauge("queue_depth").set(self._queued_locked())
            self._cond.notify_all()  # wake the replica's fetcher

    # ------------------------------------------------------------------
    # fetchers (one thread per replica)
    # ------------------------------------------------------------------

    def _fetch_loop(self, replica: Replica) -> None:
        """One replica's fetch lane: blocks on that replica's oldest
        in-flight batch, settles its requests, hands failures to the
        shared failover path.  A wedged chip therefore stalls only its own
        lane — survivors keep draining theirs."""
        while True:
            inf: Optional[_InFlight] = None
            with self._cond:
                while not replica.pending and not self._fetchers_stop:
                    self._cond.wait(0.2)
                if self._fetchers_stop:
                    # batches still pending here are dispatched-but-never-
                    # fetched: _finish settles them as classified sheds
                    # (the stop(drain=False) contract)
                    return
                inf = replica.pending.popleft()
                replica.processing = inf.batch
            try:
                self._drain_batch(inf)
            finally:
                with self._cond:
                    replica.processing = None
                    self._cond.notify_all()  # capacity freed: wake dispatcher

    def _drain_batch(self, inf: _InFlight) -> None:
        from ncnet_tpu.evaluation.pipeline import call_with_watchdog

        fetch_begin = time.monotonic()
        for req in inf.batch:
            req.fetch_begin_t = fetch_begin  # device phase ends here
        try:
            table = call_with_watchdog(
                inf.replica.fetch, (inf.handle,),
                timeout=self.cfg.fetch_timeout_s, label="serve_fetch",
            )
        except Exception as e:
            self._on_batch_failure(inf.batch, e, phase="fetch",
                                   replica=inf.replica)
            return
        now = time.monotonic()
        wall = now - inf.t0
        rid = inf.replica.id
        with self._cond:
            self._controller.note_drain()
            self._admission.note_batch_wall(wall)
            inf.replica.note_success(wall)
            qd = self._queued_locked()
            inflight = self._pool.inflight_total()
            self._registry.counter("batches").inc()
            self._registry.counter(f"replica_batches_{rid}").inc()
            self._registry.timer("batch_wall_s").observe(wall)
            self._registry.histogram(
                f"replica_wall_ms_{rid}", 0.0, self.cfg.latency_hist_ms,
            ).add(wall * 1e3)
        obs_events.emit(
            "serve_batch", bucket=bucket_label(inf.bucket),
            size=len(inf.batch), wall_s=round(wall, 6), queue_depth=qd,
            inflight=inflight, seq=inf.seq, replica=rid,
        )
        # leak sentinel census at the batch boundary (rate-limited inside;
        # a growing shape class emits memory_leak_suspect)
        self._leak.observe(step=inf.seq)
        tables, quality = self._split_table(inf.replica, table)
        tier = self._active_tier(inf.replica)
        # which model generation produced this batch — stamped on every
        # result/quality event and per-version metric so the canary judge
        # (and run_report --rollout) can split old from new
        ver = inf.replica.model_version
        if quality:
            from ncnet_tpu.utils import faults

            # chaos seam: shift the NEW version's quality signals so the
            # canary judge's PSI gate has a real regression to catch
            quality = faults.canary_quality_shift_hook(ver, quality)
        for i, req in enumerate(inf.batch):
            if req.expired(now):
                # deadline check at FETCH: the caller's budget is gone —
                # the computed result is discarded, the outcome classified
                self._resolve_deadline(req, "fetch")
                continue
            req_wall = now - req.submitted_t
            result = MatchResult(
                request_id=req.id, table=np.array(tables[i]),
                quality=quality[i] if quality else None,
                bucket=inf.bucket, wall_s=req_wall,
            )
            if not req.future._try_settle("result", result=result):
                continue  # settled elsewhere (abandoned-fetch abort path)
            with self._cond:
                self._n["results"] += 1
                self._registry.counter("results").inc()
                self._registry.counter(f"version_results_{ver}").inc()
                self._registry.histogram(
                    f"serve_wall_ms_{bucket_label(inf.bucket)}",
                    0.0, self.cfg.latency_hist_ms,
                ).add(req_wall * 1e3)
                self._registry.histogram(
                    f"version_wall_ms_{ver}",
                    0.0, self.cfg.latency_hist_ms,
                ).add(req_wall * 1e3)
            wall_ms = round(req_wall * 1e3, 3)
            obs_events.emit(
                "serve_result", request=req.id, client=req.client,
                bucket=bucket_label(inf.bucket),
                wall_ms=wall_ms, batch_size=len(inf.batch),
                replica=rid, model_version=ver,
                **({"trace": req.trace} if req.trace else {}),
            )
            # SLO judged on the SAME rounded wall the event records, so
            # run_report --slo replaying the log reclassifies identically
            self._observe_slo(req, "result", wall_ms=wall_ms)
            self._emit_timeline(req, "result", replica=rid)
            if quality:
                from ncnet_tpu.observability.quality import emit_quality

                emit_quality("serving", quality[i], tier=tier,
                             registry=self._registry, request=req.id,
                             replica=rid, model_version=ver)
            rollout = self._rollout
            if rollout is not None:
                # feed the canary judge (controller takes its OWN lock;
                # never called under self._cond — see rollout.py)
                rollout.observe_result(
                    ver, wall_ms, quality[i] if quality else None)
            self._terminal(req)

    @staticmethod
    def _split_table(replica: Replica, table) -> Tuple[Any, Any]:
        split = getattr(replica.engine, "split", None)
        if split is not None:
            return split(np.asarray(table))
        from ncnet_tpu.serving.engine import BatchMatchEngine

        return BatchMatchEngine.split(np.asarray(table))

    def _active_tier(self, replica: Replica) -> str:
        from ncnet_tpu.observability.quality import active_tier

        return active_tier(getattr(replica.engine, "half_precision", False))

    # ------------------------------------------------------------------
    # failure handling (failover ladder)
    # ------------------------------------------------------------------

    def _on_batch_failure(self, batch: List[MatchRequest],
                          exc: Exception, phase: str,
                          replica: Replica) -> None:
        """One failed batch on one replica (dispatch raised, fetch raised,
        or the fetch watchdog fired).  The failover ladder, per request:

          1. a surviving READY replica this request has NOT failed on →
             requeue at the FRONT, re-routed OFF-budget (the failure is the
             replica's fault; zero lost requests);
          2. no READY replica at all (the pool is dead) → requeue
             off-budget and WAIT — resurrection probes are the recovery,
             and new admissions shed ``no_capacity`` meanwhile;
          3. otherwise (single-replica pool, or failed everywhere) the PR 8
             ladder: a program-changing recovery (tier demotion + retrace
             of every replica) grants a FREE retry; else the request's
             bounded budget is charged and exhausted requests quarantine.

        Repeated failures quarantine the REPLICA: ``replica_max_failures``
        consecutive failures move it to DEAD (router stops sending traffic,
        admission capacity shrinks, resurrection probes begin)."""
        from ncnet_tpu.evaluation.resilience import classify_failure

        kind = classify_failure(exc)
        # a RESOURCE_EXHAUSTED batch failure is a MEMORY failure: bundle
        # the HBM snapshot, the failed program's ledger rows, and the
        # live-array census into ONE memory_postmortem (idempotent — the
        # demote-retrace path below may see the same exception again)
        obs_memory.report_oom(
            exc, program=obs_memory.SERVE_PROGRAM, scope="serving",
            replica=replica.id, phase=phase,
            bucket=bucket_label(batch[0].bucket) if batch else None)
        with self._cond:
            self._controller.note_failure()
            replica.note_failure()
            self._registry.counter(f"replica_failures_{replica.id}").inc()
            self._registry.counter(
                f"version_failures_{replica.model_version}").inc()
            if replica.state == REPLICA_READY and \
                    replica.consecutive_failures >= \
                    self.cfg.replica_max_failures:
                log.warning(
                    f"replica {replica.id} hit "
                    f"{replica.consecutive_failures} consecutive failures "
                    f"({kind}); quarantined DEAD — resurrection probes "
                    f"every {self.cfg.resurrect_after_s}s", kind=kind)
                self._pool.mark_dead(replica, f"{kind}:{type(exc).__name__}")
            pending = [r for r in batch if not r.future.done()]
            for req in pending:
                req.failed_on.add(replica.id)
            survivors = [r for r in self._pool.ready() if r is not replica]
            any_ready = bool(self._pool.ready())
            recovery_gen = self._recovery_gen
        rollout = self._rollout
        if rollout is not None:
            rollout.observe_failure(replica.model_version)
        requeue: List[MatchRequest] = []
        quarantine: List[MatchRequest] = []
        tier: Optional[str] = None
        tier_attempted = False
        for req in pending:
            fresh = any(r.id not in req.failed_on for r in survivors)
            if fresh:
                obs_events.emit("retry", unit=req.id, kind=kind,
                                on_budget=False, scope="serving",
                                replica=replica.id, via="reroute")
                requeue.append(req)
                continue
            if not any_ready:
                # the whole pool is dead: park the work off-budget behind
                # the resurrection probes — availability degraded, nothing
                # lost
                obs_events.emit("retry", unit=req.id, kind=kind,
                                on_budget=False, scope="serving",
                                replica=replica.id,
                                via="awaiting_capacity")
                requeue.append(req)
                continue
            if not tier_attempted:
                tier_attempted = True
                tier = self._try_tier_recovery(exc, replica, recovery_gen)
            if tier is not None:
                # a new program: every replica is fresh evidence again
                req.failed_on.clear()
                obs_events.emit("retry", unit=req.id, kind=kind,
                                recovered=tier, on_budget=False,
                                scope="serving", replica=replica.id)
                requeue.append(req)
                continue
            req.attempts += 1
            if req.attempts <= self.cfg.retries:
                obs_events.emit("retry", unit=req.id, kind=kind,
                                attempt=req.attempts, on_budget=True,
                                scope="serving", replica=replica.id)
                requeue.append(req)
            else:
                quarantine.append(req)
        if requeue:
            for req in requeue:
                # the failed attempt's timeline stamps are dead evidence: a
                # requeued request is QUEUED again (re-stamped at its next
                # dispatch), and one that terminates while parked — e.g. a
                # deadline eviction behind an all-dead pool — must
                # attribute the wait to the queue phase, not to a fetch
                # that never completed
                req.dispatched_t = None
                req.fetch_begin_t = None
            routes = {r.id for r in survivors} or {"(awaiting capacity)"}
            log.warning(
                f"serving batch {phase} failed on {replica.id} ({kind}: "
                f"{type(exc).__name__}: {exc}); {len(requeue)} request(s) "
                f"requeued at the front (candidates: {sorted(routes)})",
                kind=kind)
            with self._cond:
                q = self._queues.setdefault(requeue[0].bucket, deque())
                q.extendleft(reversed(requeue))
                self._cond.notify_all()
        for req in quarantine:
            self._quarantine(req, kind, exc)

    def _try_tier_recovery(self, exc: Exception, replica: Replica,
                           gen: int) -> Optional[str]:
        """The PR 8 demote-retrace path (last resort once no surviving
        replica can take the batch): demote the Pallas tier registry and
        retrace EVERY replica's engine — the registry is process-global, so
        a poisoned tier must be rebuilt out of all of them.  Single-flight
        across fetcher threads: ``gen`` is the recovery generation observed
        WHEN this failure was classified; if another thread's recovery
        landed since, this failure rides that program change instead of
        burning a second ladder rung for the same fault.  On success the
        service degrades (unless already draining) and the failing
        replica's demotion count feeds its routing penalty; a recovery that
        itself crashes falls back to the plain retry budget rather than
        taking the worker (and every queued request) down with it."""
        from ncnet_tpu.models.ncnet import recover_from_device_failure

        with self._recovery_lock:
            if self._recovery_gen != gen:
                return self._last_recovery_tier
            try:
                tier = recover_from_device_failure(
                    exc, *[r.engine for r in self._pool.replicas])
            except Exception as rec_exc:  # noqa: BLE001 — recovery must
                # not take the stream down with it
                log.error(f"tier recovery itself failed "
                          f"({type(rec_exc).__name__}: {rec_exc}); falling "
                          "back to the plain retry budget", kind="device")
                return None
            if tier is None:
                return None
            self._recovery_gen += 1
            self._last_recovery_tier = tier
        with self._cond:
            replica.demotions += 1  # its failures forced this: route-penalized
            # a demotion during DRAINING/STOPPED must not fight the
            # lifecycle states — the drain keeps completing admitted
            # work on the demoted tier either way
            if self._health.state in (STARTING, READY):
                self._health.to(DEGRADED, f"tier_demoted:{tier}")
        log.warning(
            f"demoted tier '{tier}' and re-traced every replica — "
            "the failed batch retries off-budget", kind="device")
        return tier

    def _quarantine(self, req: MatchRequest, kind: str,
                    exc: Exception) -> None:
        msg = (f"request {req.id} gave up after {req.attempts} attempt(s): "
               f"{type(exc).__name__}: {exc}")
        if not req.future._try_settle("quarantined", error=RequestQuarantined(
                msg, kind=kind, attempts=req.attempts)):
            return
        log.warning(f"{msg} — quarantined; the stream continues",
                    kind="quarantine")
        with self._cond:
            self._n["quarantined"] += 1
            self._registry.counter("quarantined").inc()
        obs_events.emit("serve_quarantine", request=req.id,
                        client=req.client, kind=kind,
                        attempts=req.attempts, error=str(exc)[:300],
                        **({"trace": req.trace} if req.trace else {}))
        self._observe_slo(req, "quarantined")
        self._emit_timeline(req, "quarantined")
        if self._manifest is not None:
            self._manifest.quarantine(req.id, kind, str(exc), req.attempts)
        self._terminal(req)

    def _resolve_deadline(self, req: MatchRequest, where: str) -> None:
        if not req.future._try_settle("deadline", error=DeadlineExceeded(
                f"request {req.id} deadline expired at {where}",
                where=where)):
            return
        with self._cond:
            self._n["deadline"] += 1
            self._registry.counter("deadline_exceeded").inc()
        obs_events.emit("serve_deadline", request=req.id, client=req.client,
                        where=where, admitted=True,
                        **({"trace": req.trace} if req.trace else {}))
        self._observe_slo(req, "deadline")
        self._emit_timeline(req, "deadline", where=where)
        self._terminal(req)

    # ------------------------------------------------------------------
    # SLO accounting + per-request trace timelines (every settle path
    # passes through these right after its terminal event)
    # ------------------------------------------------------------------

    def _observe_slo(self, req: MatchRequest, outcome: str,
                     wall_ms: Optional[float] = None) -> None:
        """Feed one admitted terminal outcome to the error-budget tracker
        (under the service lock, like every counter) and emit the periodic
        ``slo`` event OUTSIDE it — the fsync must not serialize admission."""
        with self._cond:
            due = self._slo.observe(
                outcome, bucket=bucket_label(req.bucket), wall_ms=wall_ms)
            snap = self._slo.snapshot() if due else None
        if snap is not None:
            obs_events.emit("slo", **snap)

    def _emit_timeline(self, req: MatchRequest, outcome: str, *,
                       replica: Optional[str] = None,
                       where: Optional[str] = None) -> None:
        """One ``request_timeline`` event per terminal outcome: the
        queue/device/fetch attribution (``MatchRequest.timeline_ms`` — the
        segments SUM to ``total_ms`` by construction) plus the wall-clock
        submission instant ``t0``, so ``tools/trace_export.py`` can lay the
        request out as Perfetto async slices keyed by its id."""
        now_m = time.monotonic()
        # t0 reconstructs the wall-clock submission instant from the
        # monotonic age — through wall_now(), so an injected clock skew
        # shifts the timeline exactly like every other stamp this process
        # publishes (the federation's skew correction must see ONE clock)
        fields: Dict[str, Any] = dict(
            request=req.id, client=req.client,
            bucket=bucket_label(req.bucket), outcome=outcome,
            attempts=req.attempts,
            t0=round(obs_events.wall_now() - (now_m - req.submitted_t), 6),
        )
        if req.trace:
            fields["trace"] = req.trace
        if replica is not None:
            fields["replica"] = replica
        if where is not None:
            fields["where"] = where
        fields.update(req.timeline_ms(now_m))
        obs_events.emit("request_timeline", **fields)

    def _terminal(self, req: MatchRequest) -> None:
        """Close one admitted request's accounting (every settle path ends
        here — the exactly-one-outcome bar)."""
        with self._cond:
            self._admission.note_done(req.client)
        if self._draining:
            with self._cond:
                self._drain_resolved += 1
                n = self._drain_resolved
            from ncnet_tpu.utils import faults

            # chaos seam: SIGKILL after the Nth terminal outcome of the
            # drain phase (tests prove the event log still accounts for
            # everything that had no outcome yet)
            faults.serve_drain_kill_hook(n)

    # ------------------------------------------------------------------
    # live rollout seam (serving/rollout.py drives these; each method
    # takes the service lock itself — the controller NEVER holds its own
    # lock while calling in, and the service never calls controller
    # methods under self._cond except status()/observe_* which take only
    # the controller's lock: one consistent lock order, no deadlock)
    # ------------------------------------------------------------------

    def attach_rollout(self, controller) -> None:
        self._rollout = controller

    def detach_rollout(self) -> None:
        self._rollout = None

    def start_rollout(self, candidate: str, config=None):
        """Kick a rollout to ``candidate`` (a checkpoint dir or versioned
        root) on a background thread — the POST /rollout entry point.
        Returns the attached controller; raises if one is already live."""
        from ncnet_tpu.serving.rollout import RolloutConfig, RolloutController

        with self._cond:
            if self._rollout_thread is not None \
                    and self._rollout_thread.is_alive():
                raise RuntimeError("a rollout is already in progress")
        ctl = RolloutController(self, config or RolloutConfig(),
                                loader=self.rollout_loader)
        t = threading.Thread(target=ctl.run, args=(candidate,),
                             name="match-rollout", daemon=True)
        self._rollout_thread = t
        t.start()
        return ctl

    @property
    def model_version(self) -> str:
        return self._model_version

    def rollout_pick_canary(self) -> Replica:
        """The replica staging borrows: READY with the lowest load.  A
        pool with fewer than two READY replicas refuses — draining the
        sole survivor would trade a model update for an outage."""
        with self._cond:
            ready = self._pool.ready()
            if len(ready) < 2:
                raise RuntimeError(
                    f"rollout needs >= 2 READY replicas to keep serving "
                    f"during the swap (have {len(ready)})")
            return min(ready, key=lambda r: r.load)

    def rollout_drain(self, rep: Replica, timeout_s: float) -> bool:
        """DRAINING + wait for the replica's in-flight batches to finish.
        Returns False on timeout (the replica is left DRAINING for the
        caller to re-admit or roll back)."""
        with self._cond:
            self._pool.drain_for_swap(rep, "rollout_swap")
            self._cond.notify_all()
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while rep.load > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(0.2, remaining))
        return True

    def rollout_swap(self, rep: Replica, params, version: str, *,
                     detach_store: bool = False) -> None:
        """Swap one DRAINED replica's weights and warm the new programs
        off the dispatch path: re-stage params (engine.swap_params drops
        the old executables only for a structurally different tree — a
        same-shape swap keeps them and the ladder replay below is pure
        cache hits), then run the registered bucket ladder at every batch
        size — memory-ledger rows re-record through the engine's
        ResilientJit exactly like startup warmup.  The
        ``kill_at_weight_swap`` chaos seam fires between the re-stage and
        the version stamp: a SIGKILL there leaves the pod restartable on
        the OLD version (the state file's pointer only advances at
        COMPLETE)."""
        from ncnet_tpu.utils import faults

        engine = rep.engine
        swap = getattr(engine, "swap_params", None)
        if swap is None:
            raise RuntimeError(
                f"replica {rep.id} engine cannot swap params")
        fast0 = getattr(engine, "swap_fastpath_hits", 0)
        swap(params)
        # same-structure swap (engine.swap_params fast path): the ladder
        # warmup below replays cached executables + their tier decisions
        # instead of re-probing and recompiling
        fastpath = getattr(engine, "swap_fastpath_hits", 0) > fast0
        faults.weight_swap_kill_hook()
        if detach_store and hasattr(engine, "attach_store"):
            # new weights must not commit features into the old
            # fingerprint's generation (cache poisoning); recompute-only
            # until the pod converges and the store generation advances
            engine.attach_store(None)
        with self._cond:
            rep.model_version = version
            buckets = list(self._bucketer.buckets)
        warmed = []
        try:
            for bucket in buckets:
                for b in self._batch_ladder():
                    zeros = np.zeros((b, *bucket[0], 3), np.uint8)
                    zt = np.zeros((b, *bucket[1], 3), np.uint8)
                    rep.fetch(rep.dispatch(zeros, zt))
                warmed.append(bucket_label(bucket))
            obs_memory.flush_pending(timeout=120.0)
        except Exception:
            obs_events.emit("rollout_swap", replica=rep.id, version=version,
                            warmed=warmed, fastpath=fastpath, ok=False)
            raise
        obs_events.emit("rollout_swap", replica=rep.id, version=version,
                        warmed=warmed, fastpath=fastpath, ok=True)

    def rollout_readmit(self, rep: Replica, reason: str) -> None:
        with self._cond:
            self._pool.resurrect(rep, reason=reason)
            self._cond.notify_all()

    def rollout_set_canary(self, rep: Replica, fraction: float) -> None:
        with self._cond:
            self._pool.set_canary(rep, fraction)
            self._cond.notify_all()

    def rollout_clear_canary(self) -> None:
        with self._cond:
            self._pool.clear_canary()
            self._cond.notify_all()

    def rollout_replicas(self) -> List[Replica]:
        with self._cond:
            return list(self._pool.replicas)

    def rollout_set_version(self, version: str, params) -> None:
        """The pod's converged identity: health docs and future replicas
        report ``version``; ``params`` become what a later rollback (or
        the next rollout's old side) swaps back to."""
        with self._cond:
            self._model_version = version
            self._model_params = params

    def rollout_switch_store(self, params) -> None:
        """Advance the shared feature store to the new weights' fingerprint
        generation and re-attach it to every engine (promotion committed),
        GC'ing superseded generations with the configured grace so the
        rollback target's cache survives.  No store configured = no-op."""
        old = self._store
        if old is None:
            return
        from ncnet_tpu.store import FeatureStore, backbone_fingerprint

        mc = self._model_config
        fp = backbone_fingerprint(
            params, image_size="serve",
            k_size=max(mc.relocalization_k_size, 1) if mc is not None else 1,
            dtype="bf16" if mc is not None and mc.half_precision else "f32")
        if fp == old.fingerprint:
            # same backbone (an NC-filter-only fine-tune): the generation
            # is still valid everywhere — just re-attach where detached
            new = old
        else:
            new = FeatureStore(old.root, fp, budget_bytes=old.budget_bytes,
                               scope="serving")
        with self._cond:
            self._store = new
            for rep in self._pool.replicas:
                if hasattr(rep.engine, "attach_store"):
                    rep.engine.attach_store(new)
        if new is not old:
            old.flush_stats()
            old.close()

    def rollout_reattach_store(self) -> None:
        """Rollback path: the store generation never advanced — re-attach
        the existing store to any engine the canary swap detached."""
        if self._store is None:
            return
        with self._cond:
            for rep in self._pool.replicas:
                if hasattr(rep.engine, "attach_store"):
                    rep.engine.attach_store(self._store)

    def rollout_gc_store(self, keep_generations: int) -> None:
        if self._store is not None:
            self._store.gc_superseded(keep_generations=keep_generations)

    # ------------------------------------------------------------------
    # resurrection probes
    # ------------------------------------------------------------------

    def _maybe_resurrect(self) -> None:
        """Schedule resurrection probes for DEAD replicas whose period has
        elapsed.  Each probe (a tiny zero pair at the replica's last, or
        smallest known, bucket) runs on its OWN daemon thread — a probe at
        a replica that hangs instead of erroring must not stall the
        dispatcher, which would wedge every healthy lane behind a dead
        chip's silence.  Success returns the replica to READY and its
        capacity to admission; failure leaves it DEAD until the next
        period.  Probes run during DRAINING too — a drain stuck behind a
        dead pool NEEDS the resurrection to finish its admitted work."""
        if self._stop_now:
            return
        now = time.monotonic()
        with self._cond:
            due = self._pool.due_probes(now, self.cfg.resurrect_after_s)
            buckets = self._bucketer.buckets
        for rep in due:
            bucket = rep.last_bucket or (buckets[0] if buckets else None)
            if bucket is None:
                m = self.cfg.bucket_multiple
                bucket = ((m, m), (m, m))
            threading.Thread(
                target=self._probe_replica, args=(rep, bucket),
                name=f"match-probe-{rep.id}", daemon=True,
            ).start()

    def _probe_replica(self, rep: Replica, bucket: Bucket) -> None:
        ok, err = True, None
        try:
            from ncnet_tpu.evaluation.pipeline import call_with_watchdog

            src = np.zeros((1, *bucket[0], 3), np.uint8)
            tgt = np.zeros((1, *bucket[1], 3), np.uint8)
            handle = rep.dispatch(src, tgt)
            call_with_watchdog(rep.fetch, (handle,),
                               timeout=self.cfg.fetch_timeout_s,
                               label="resurrect_probe")
        except Exception as e:  # noqa: BLE001 — a failed probe only
            # means the replica stays dead until the next period
            ok, err = False, f"{type(e).__name__}: {e}"
        obs_events.emit("serve_replica_probe", replica=rep.id, ok=ok,
                        error=err and err[:200],
                        bucket=bucket_label(bucket))
        with self._cond:
            rep.probing = False
            if ok:
                self._pool.resurrect(rep)
            self._cond.notify_all()
        if ok:
            log.info(f"replica {rep.id} resurrected (probe ok); "
                     "rejoining the pool", kind="device")

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def _finish(self, crashed: Optional[BaseException]) -> None:
        with self._cond:
            self._finishing = True  # admission closed before collection
            self._fetchers_stop = True
            self._cond.notify_all()
        for t in self._fetchers:
            # a fetch that already began completes normally (a blocking
            # fetch cannot be interrupted); the join is bounded so a hung
            # fetch without a watchdog cannot wedge shutdown — its batch is
            # then force-settled below and the late fetch result discarded
            # (the done() guards in _drain_batch)
            t.join(10.0)
        with self._cond:
            leftovers: List[MatchRequest] = []
            for q in self._queues.values():
                leftovers.extend(q)
            self._queues.clear()
            for rep in self._pool.replicas:
                for inf in rep.pending:
                    leftovers.extend(inf.batch)
                rep.pending.clear()
                if rep.processing:
                    # the batch a hung (or crashed) fetcher still holds
                    leftovers.extend(rep.processing)
                    rep.processing = None
            if self._processing:
                # the batch the worker held when it crashed — in no queue
                # and no replica's backlog
                leftovers.extend(self._processing)
                self._processing = None
        reason = "crashed" if crashed is not None else "shutdown"
        for req in leftovers:
            # an aborted shutdown (or a worker crash) still settles every
            # admitted request with a classified outcome; _try_settle keeps
            # this atomic against a hung fetcher that outlived the bounded
            # join and is only now landing its results
            if not req.future._try_settle("overloaded", error=Overloaded(
                    f"service stopped before request {req.id} completed",
                    reason=reason)):
                continue  # settled before the crash interrupted its batch
            self._n["shed"] += 1
            obs_events.emit("serve_shed", request=req.id, client=req.client,
                            reason=reason, admitted=True,
                            **({"trace": req.trace} if req.trace else {}))
            self._observe_slo(req, "shed")
            self._emit_timeline(req, "overloaded")
            self._terminal(req)
        for sess in self._streams.evict_all():
            obs_events.emit("stream_evict", stream=sess.id,
                            frames=sess.frames,
                            tracked=sess.tracked_frames,
                            fallback=sess.fallback_frames, reason="drain")
        obs_events.emit(
            "serve_drain", drained=self._draining and crashed is None,
            leftover=len(leftovers), **{f"n_{k}": v
                                        for k, v in self._n.items()},
        )
        # the FINAL slo event: the cumulative budget counters every replay
        # consumer (run_report --slo) must reproduce exactly from the
        # terminal events above it in this same log
        obs_events.emit("slo", final=True, **self._slo.snapshot())
        if self._store is not None:
            # the durable per-run store stats (run_report --store replays
            # them); the journal handle closes with the service
            self._store.flush_stats()
            self._store.close()
        self._registry.flush(scope="serving")
        with self._cond:
            if self._health.state != STOPPED:
                self._health.to(
                    STOPPED, "crashed" if crashed is not None else "clean")
            self._cond.notify_all()
        # last act of the worker: durably record the unified health
        # document (run_report --serving renders it), then take the
        # introspection plane down with the service it describes
        obs_events.emit("serve_health_doc", doc=self.health())
        if self._introspect is not None:
            self._introspect.stop()
            self._introspect = None
