"""The resident match service: continuous batching + fault-tolerant serving.

This is the serving twin of PR 1 (fault-tolerant training) and PR 3
(resilient batch eval): a resident process around the warm matcher that
keeps answering — correctly, within deadlines, at a degraded tier if it
must — while devices fail, queues overflow, and clients misbehave.  The r05
bench motivates the shape: bs1 bf16 device time is 5.5 ms but a serial
caller waits ~681 ms of wall; the win is structural (queueing, batching,
pipelining), not a kernel.

Pieces, and where each discipline comes from:

  * **Continuous batching** — an async request queue coalesces
    variable-resolution queries into padded shape buckets
    (``serving/buckets.py``, bounded jit cache) and dispatches the next
    batch while the previous batch's fetch is still in flight; the
    in-flight depth follows the PR 2 ``PipelineDepthController`` (the drain
    unit is one batch, exactly the PF-Pascal regime).
  * **Admission control + backpressure** — ``serving/admission.py``:
    bounded queue depth, per-client in-flight caps, classified
    ``Overloaded`` rejections with throughput-derived retry-after hints.
  * **Per-request deadlines** — the budget is checked at admission (an
    already-expired request is refused), at dequeue (expired requests are
    EVICTED from the batch before dispatch — they never waste device time),
    and at fetch (a result that lands after its caller's budget resolves
    deadline-exceeded, not as a zombie success).  The fetch itself rides
    ``pipeline.call_with_watchdog`` so a hung tunnel surfaces as a
    retryable timeout, not an eternal stall.
  * **Degraded-mode survival** — a runtime device failure mid-stream runs
    the PR 3 ``recover_from_device_failure`` demote-retrace path and
    REQUEUES the failed batch at the front (zero lost requests, retried
    off-budget because the program changed); repeated failures quarantine
    individual requests into a journaled ``RunManifest``; SIGTERM (PR 1's
    ``PreemptionHandler`` pattern) stops admission and drains admitted work
    to completion; the STARTING/READY/DEGRADED/DRAINING/STOPPED health
    machine (``serving/health.py``) is exported for probes.
  * **Telemetry** — every lifecycle edge is an event (``serve_admit`` /
    ``serve_shed`` / ``serve_batch`` / ``serve_result`` / ``serve_deadline``
    / ``serve_quarantine`` / ``serve_health`` / ``serve_drain``), latency
    aggregates through per-bucket ``Histogram`` digests, per-pair quality
    signals stream tier-tagged through ``emit_quality``, and the PR 5
    ``Heartbeat`` is bumped per dispatched batch (the
    ``tools/stall_watchdog.py`` liveness contract).

The outcome-total contract (serving/request.py): every admitted request
terminates in exactly one of {result, deadline, overloaded, quarantined} —
proven by event-log accounting in ``tools/run_report.py --serving`` and
executed under fault injection by tests/test_serving.py.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ncnet_tpu.observability import MetricsRegistry, events as obs_events
from ncnet_tpu.observability import get_logger
from ncnet_tpu.serving.admission import AdmissionController
from ncnet_tpu.serving.buckets import ShapeBucketer, pad_to_bucket
from ncnet_tpu.serving.health import (
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    HealthMachine,
)
from ncnet_tpu.serving.request import (
    Bucket,
    DeadlineExceeded,
    MatchFuture,
    MatchRequest,
    MatchResult,
    Overloaded,
    RequestQuarantined,
    as_pair_image,
    bucket_label,
)

log = get_logger("serving")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs of the resident match service (README "Serving")."""

    # admission / backpressure
    max_queue: int = 64                 # total queued requests before shedding
    max_in_flight_per_client: int = 16  # outstanding per client id
    # batching
    max_batch: int = 8                  # requests coalesced per dispatch
    pipeline_depth: int = 0             # 0 = adaptive (2-4); >0 pins it
    # deadlines / hangs
    default_deadline_s: Optional[float] = None  # None = no implicit deadline
    fetch_timeout_s: float = 0.0        # >0: watchdog per batch fetch
    # failure policy
    retries: int = 1                    # budgeted retries per request
    quarantine_dir: Optional[str] = None  # RunManifest home (None = events only)
    # shape buckets (bounded jit cache)
    bucket_multiple: int = 64
    max_image_side: int = 1024
    max_buckets: int = 4
    buckets: Optional[Tuple[Tuple[int, int], ...]] = None  # fixed ladder
    warm_buckets: Tuple[Tuple[int, int], ...] = ()  # square pairs compiled at start
    # liveness / telemetry
    heartbeat_path: Optional[str] = None
    latency_hist_ms: float = 2000.0     # per-bucket latency digest range
    install_sigterm: bool = False       # SIGTERM -> drain (PreemptionHandler style)
    # match extraction
    do_softmax: bool = True
    scale: str = "centered"


@dataclasses.dataclass
class _InFlight:
    handle: Any
    batch: List[MatchRequest]
    bucket: Bucket
    t0: float


class MatchService:
    """Resident, fault-tolerant match service around the warm matcher.

    Usage::

        service = MatchService(config, params, ServingConfig(...))
        service.start()
        fut = service.submit(src_u8, tgt_u8, deadline_s=0.5, client="cam0")
        result = fut.result(timeout=5.0)   # MatchResult, or a classified error
        ...
        service.stop()                      # drains admitted work, then stops

    ``engine`` may be injected (anything with ``dispatch``/``fetch``/
    ``retrace``) — the chaos suite drives the full lifecycle against a fake
    device without paying jit compiles.
    """

    def __init__(self, model_config=None, params=None,
                 serving: ServingConfig = ServingConfig(), *,
                 engine=None, registry: Optional[MetricsRegistry] = None):
        if engine is None:
            from ncnet_tpu.serving.engine import BatchMatchEngine

            engine = BatchMatchEngine(
                model_config, params, do_softmax=serving.do_softmax,
                scale=serving.scale,
            )
        self.cfg = serving
        self._engine = engine
        self._registry = registry or MetricsRegistry(scope="serving")
        self._bucketer = ShapeBucketer(
            multiple=serving.bucket_multiple,
            max_side=serving.max_image_side,
            max_buckets=serving.max_buckets,
            fixed=serving.buckets,
        )
        self._admission = AdmissionController(
            max_queue=serving.max_queue,
            max_in_flight_per_client=serving.max_in_flight_per_client,
            max_batch=serving.max_batch,
        )
        from ncnet_tpu.evaluation.pipeline import PipelineDepthController

        self._controller = PipelineDepthController(fixed=serving.pipeline_depth)
        self._health = HealthMachine()
        self._heartbeat = None
        if serving.heartbeat_path:
            from ncnet_tpu.observability import Heartbeat

            self._heartbeat = Heartbeat(serving.heartbeat_path)
        self._manifest = None
        if serving.quarantine_dir:
            from ncnet_tpu.evaluation.resilience import RunManifest

            os.makedirs(serving.quarantine_dir, exist_ok=True)
            self._manifest = RunManifest(
                os.path.join(serving.quarantine_dir, "manifest.json"),
                meta={"scope": "serving"},
            )

        self._cond = threading.Condition()
        self._queues: Dict[Bucket, Deque[MatchRequest]] = {}
        self._inflight: Deque[_InFlight] = deque()
        self._worker: Optional[threading.Thread] = None
        self._draining = False
        self._drain_requested = False   # set from the signal handler: no lock
        self._stop_now = False
        self._finishing = False         # _finish has begun: admission closed
        self._processing: Optional[List[MatchRequest]] = None
        self._last_idle_beat = 0.0
        self._drain_resolved = 0
        self._req_seq = 0
        self._batch_seq = 0
        self._old_sigterm = None
        # terminal-outcome accounting (the event log is the durable copy;
        # these back the health probe and the drain summary)
        self._n = {"admitted": 0, "results": 0, "deadline": 0,
                   "quarantined": 0, "shed": 0}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "MatchService":
        if self._worker is not None:
            raise RuntimeError("service already started")
        obs_events.emit(
            "serve_start",
            max_queue=self.cfg.max_queue, max_batch=self.cfg.max_batch,
            retries=self.cfg.retries,
            default_deadline_s=self.cfg.default_deadline_s,
            fetch_timeout_s=self.cfg.fetch_timeout_s,
        )
        if self.cfg.install_sigterm and \
                threading.current_thread() is threading.main_thread():
            self._old_sigterm = signal.signal(signal.SIGTERM, self._on_sigterm)
        self._worker = threading.Thread(
            target=self._run, name="match-serve", daemon=True)
        self._worker.start()
        # safety net for a process that exits without stop() (an unhandled
        # exception in the caller): settle the outstanding futures and join
        # the worker before interpreter teardown — a daemon thread killed
        # mid-XLA-dispatch can otherwise segfault the exit
        import atexit

        atexit.register(self._atexit_stop)
        return self

    def _atexit_stop(self) -> None:
        w = self._worker
        if w is not None and w.is_alive():
            self.stop(drain=False, timeout=10.0)

    def _on_sigterm(self, signum, frame):
        # handler discipline (PR 1 PreemptionHandler): flip a flag, write
        # via os.write (print from a handler can deadlock on the stream
        # lock), let the worker act at its next loop edge.  No lock here —
        # the main thread may hold self._cond inside submit() when the
        # signal lands.
        self._drain_requested = True
        os.write(2, b"[serving] received SIGTERM; draining in-flight "
                    b"requests, admission closed\n")

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop the service.  ``drain=True`` (default) completes every
        admitted request first; ``drain=False`` aborts — queued and
        in-flight requests settle ``Overloaded(reason="shutdown")`` (still a
        classified terminal outcome, never a silent drop).  One caveat on
        the abort: a batch whose blocking device fetch has ALREADY begun
        completes normally first (a blocking fetch cannot be interrupted;
        configure ``fetch_timeout_s`` to bound that wait)."""
        with self._cond:
            if drain:
                self._begin_drain_locked("stop")
            else:
                # NOT _draining: an abort force-settles admitted work, and
                # the serve_drain event's `drained` flag must be able to
                # tell the two apart; admission closes via _stop_now
                self._stop_now = True
            self._cond.notify_all()
        w = self._worker
        if w is not None and w is not threading.current_thread():
            w.join(timeout)
        if self._old_sigterm is not None:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None
        import atexit

        try:
            # the safety net registered by start() would otherwise hold a
            # strong reference (service + engine jit cache + staged params)
            # for the life of the process
            atexit.unregister(self._atexit_stop)
        except Exception:  # noqa: BLE001 — interpreter teardown ordering
            pass

    def request_drain(self, reason: str = "drain") -> None:
        """Close admission and finish admitted work (the SIGTERM path,
        callable programmatically); returns immediately — join via
        :meth:`stop` or poll :meth:`health`."""
        with self._cond:
            self._begin_drain_locked(reason)
            self._cond.notify_all()

    def _begin_drain_locked(self, reason: str) -> None:
        if self._draining:
            return
        self._draining = True
        if self._health.state != STOPPED:
            self._health.to(DRAINING, reason)

    def __enter__(self) -> "MatchService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, src, tgt, *, deadline_s: Optional[float] = None,
               client: str = "default") -> MatchFuture:
        """Admit one match query (raw uint8 pair).  Returns a
        :class:`MatchFuture`; raises :class:`Overloaded` (shed) or
        :class:`DeadlineExceeded` (budget already gone) synchronously —
        rejections are classified at the door, not discovered by timeout.
        """
        src = as_pair_image(src, "src")
        tgt = as_pair_image(tgt, "tgt")
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        shed: Optional[Overloaded] = None
        expired = False
        req: Optional[MatchRequest] = None
        with self._cond:
            if self._worker is None or self._finishing or self._stop_now \
                    or self._health.state == STOPPED:
                # _finishing closes the submit/_finish race: once _finish
                # has collected the leftover queues, a late submit must
                # shed rather than enqueue work nobody will ever settle
                shed = Overloaded("service is not running", reason="stopped")
            elif self._draining or self._drain_requested:
                shed = Overloaded("service is draining", reason="draining")
            elif deadline_s is not None and deadline_s <= 0:
                expired = True
            else:
                depth = self._queued_locked()
                try:
                    # peek first, COMMIT only after admission passes: a
                    # shed request must not burn a compiled-program slot
                    bucket = self._bucketer.peek(
                        src.shape[:2], tgt.shape[:2])
                    self._admission.admit(client, depth)
                    self._bucketer.commit(bucket)
                except Overloaded as e:
                    shed = e
                else:
                    # RESERVE only — the request is not visible to the
                    # worker until phase 2 enqueues it, so its serve_admit
                    # event always reaches the log before any terminal
                    # event (negative unresolved counts would otherwise be
                    # possible after a crash in the emit window)
                    self._req_seq += 1
                    req = MatchRequest(
                        id=f"r{self._req_seq}", client=client, src=src,
                        tgt=tgt, bucket=bucket,
                        future=MatchFuture(f"r{self._req_seq}"),
                        submitted_t=now,
                        deadline_t=(now + deadline_s) if deadline_s
                        else None,
                    )
                    self._admission.note_admit(client)
                    self._n["admitted"] += 1
                    self._registry.counter("admitted").inc()
                    self._registry.gauge("queue_depth").set(depth + 1)
            if shed is not None:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
        # event emission OUTSIDE the lock: EventLog appends flush+fsync,
        # and an fsync held under the service lock would serialize every
        # client's admission (and the worker's queue operations) behind
        # the disk
        if expired:
            obs_events.emit("serve_deadline", request=None, client=client,
                            where="admission", admitted=False)
            raise DeadlineExceeded(
                f"deadline budget {deadline_s}s already expired at "
                "admission", where="admission")
        if shed is not None:
            obs_events.emit(
                "serve_shed", client=client, reason=shed.reason,
                retry_after_s=shed.retry_after_s, admitted=False,
            )
            raise shed
        obs_events.emit(
            "serve_admit", request=req.id, client=client,
            bucket=bucket_label(req.bucket),
            deadline_s=round(deadline_s, 6) if deadline_s else None,
        )
        # phase 2: make the admitted request visible to the worker.  If
        # the service died between the phases, the admitted request still
        # gets its terminal outcome here (nobody else can see it).
        with self._cond:
            dead = self._finishing or self._stop_now \
                or self._health.state == STOPPED
            if not dead:
                self._queues.setdefault(req.bucket, deque()).append(req)
                self._cond.notify_all()
        if dead:
            exc = Overloaded(
                f"service stopped before request {req.id} was queued",
                reason="stopped")
            req.future._settle("overloaded", error=exc)
            with self._cond:
                self._n["shed"] += 1
                self._registry.counter("shed").inc()
            obs_events.emit("serve_shed", request=req.id, client=client,
                            reason="stopped", admitted=True)
            self._terminal(req)
            raise exc
        return req.future

    # ------------------------------------------------------------------
    # probes
    # ------------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The probe payload: health state + queue/in-flight depth +
        outcome counters + active buckets."""
        with self._cond:
            return {
                **self._health.probe(),
                "queue_depth": self._queued_locked(),
                "inflight_batches": len(self._inflight),
                "buckets": [bucket_label(b) for b in self._bucketer.buckets],
                "counters": dict(self._n),
                "pipeline_depth": self._controller.depth,
            }

    @property
    def state(self) -> str:
        return self._health.state

    def metrics(self) -> Dict[str, Any]:
        return self._registry.snapshot()

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------

    def _queued_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _run(self) -> None:
        crashed: Optional[BaseException] = None
        try:
            self._warmup()
            with self._cond:
                if self._health.state == STARTING:
                    self._health.to(READY, "warm")
            while True:
                if self._drain_requested:
                    self.request_drain("sigterm")
                self._fill_pipeline()
                inf = None
                with self._cond:
                    if self._stop_now:
                        # an ABORT does not drain in-flight fetches: the
                        # deque's batches settle Overloaded("shutdown") in
                        # _finish, as stop(drain=False) documents
                        break
                    if self._inflight:
                        inf = self._inflight.popleft()
                        # crash accounting: a batch popped from the
                        # in-flight deque is otherwise invisible to
                        # _finish — track it until its outcome lands
                        self._processing = inf.batch
                    else:
                        if self._stop_now or (
                                self._draining and not self._queued_locked()):
                            break
                        if not self._queued_locked():
                            self._controller.note_gap()
                            self._idle_beat()
                            self._cond.wait(0.05)
                if inf is not None:
                    # no finally: if _drain_batch raises (a worker crash),
                    # _processing stays set so _finish settles the batch
                    self._drain_batch(inf)
                    with self._cond:
                        self._processing = None
        except BaseException as e:  # the worker must never die silently
            crashed = e
            log.error(f"serving worker crashed: {type(e).__name__}: {e}",
                      kind="device")
        finally:
            self._finish(crashed)

    def _idle_beat(self) -> None:
        """Keep the heartbeat fresh while IDLE (rate-limited to ~1/s): a
        quiet service must stay distinguishable from a wedged one — a
        genuinely wedged fetch blocks the worker loop itself, so these
        beats stop exactly when the stall watchdog should fire."""
        if self._heartbeat is None:
            return
        now = time.monotonic()
        if now - self._last_idle_beat >= 1.0:
            self._last_idle_beat = now
            self._heartbeat.beat(step=self._batch_seq,
                                 state=self._health.state, idle=True)

    def _batch_ladder(self) -> List[int]:
        """The padded batch sizes _dispatch can produce: powers of two up
        to (and always including) max_batch."""
        sizes, b = [], 1
        while b < self.cfg.max_batch:
            sizes.append(b)
            b *= 2
        sizes.append(self.cfg.max_batch)
        return sizes

    def _warmup(self) -> None:
        """Compile the configured warm buckets (square pairs) at EVERY
        ladder batch size before admitting traffic counts them as latency
        — _dispatch pads batches onto the power-of-two ladder, so a
        bucket warmed only at B=1 would still stall the live stream the
        first time a coalesced batch arrives.  Fail-open: a failed warm
        compile logs and moves on — the first real request in that shape
        pays the compile instead."""
        for hw in self.cfg.warm_buckets:
            try:
                bucket = self._bucketer.register(tuple(hw), tuple(hw))
                for b in self._batch_ladder():
                    zeros = np.zeros((b, *bucket[0], 3), np.uint8)
                    zt = np.zeros((b, *bucket[1], 3), np.uint8)
                    self._engine.fetch(self._engine.dispatch(zeros, zt))
                obs_events.emit("serve_warm", bucket=bucket_label(bucket),
                                batch_sizes=self._batch_ladder())
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                log.warning(f"warmup of bucket {hw} failed "
                            f"({type(e).__name__}: {e}); first request "
                            "pays the compile", kind="device")

    def _fill_pipeline(self) -> None:
        """Dispatch batches until the pipeline is full or the queue is
        empty — dispatching the NEXT batch while the previous fetch is in
        flight is the continuous-batching overlap itself."""
        while True:
            expired: List[MatchRequest] = []
            batch: List[MatchRequest] = []
            bucket: Optional[Bucket] = None
            with self._cond:
                if self._stop_now:
                    return
                if len(self._inflight) >= self._controller.depth:
                    return
                bucket = self._pick_bucket_locked()
                if bucket is not None:
                    q = self._queues[bucket]
                    now = time.monotonic()
                    while q and len(batch) < self.cfg.max_batch:
                        req = q.popleft()
                        # deadline check at DEQUEUE: an expired request is
                        # evicted before it can waste a device slot
                        (expired if req.expired(now) else batch).append(req)
                    if not q:
                        del self._queues[bucket]
            for req in expired:
                self._resolve_deadline(req, "dequeue")
            if not batch:
                if expired:
                    continue  # the queue may hold more work behind evictions
                return
            with self._cond:
                self._processing = batch  # crash accounting (see _run)
            self._dispatch(batch, bucket)
            with self._cond:
                self._processing = None

    def _pick_bucket_locked(self) -> Optional[Bucket]:
        """Oldest-head-first across buckets: global FIFO fairness at batch
        granularity (a hot bucket cannot starve a cold one)."""
        best = None
        for bucket, q in self._queues.items():
            if q and (best is None
                      or q[0].submitted_t < self._queues[best][0].submitted_t):
                best = bucket
        return best

    def _dispatch(self, batch: List[MatchRequest], bucket: Bucket) -> None:
        # the BATCH dimension is bucketed too (next power of two, capped at
        # max_batch): without it every distinct coalesced size 1..max_batch
        # compiles its own program per shape bucket, and the first
        # occurrence of each size stalls the whole stream for a compile —
        # the very spike the bounded-jit-cache design exists to prevent.
        # Rows beyond len(batch) are zero padding; _drain_batch indexes
        # results by request position and never reads them.
        b = 1
        while b < len(batch):
            b *= 2
        b = min(b, self.cfg.max_batch)
        pad = [None] * (b - len(batch))
        src = pad_to_bucket(
            [r.src for r in batch] + pad, bucket[0])
        tgt = pad_to_bucket(
            [r.tgt for r in batch] + pad, bucket[1])
        try:
            handle = self._engine.dispatch(src, tgt)
        except Exception as e:
            self._on_batch_failure(batch, e, phase="dispatch")
            return
        self._batch_seq += 1
        if self._heartbeat is not None:
            # the liveness contract (tools/stall_watchdog.py): one beat per
            # dispatched batch — a wedged fetch stops the beats
            self._heartbeat.beat(step=self._batch_seq,
                                 state=self._health.state)
        with self._cond:
            self._inflight.append(
                _InFlight(handle, batch, bucket, time.monotonic()))
            self._registry.gauge("queue_depth").set(self._queued_locked())

    def _drain_batch(self, inf: _InFlight) -> None:
        from ncnet_tpu.evaluation.pipeline import call_with_watchdog

        try:
            table = call_with_watchdog(
                self._engine.fetch, (inf.handle,),
                timeout=self.cfg.fetch_timeout_s, label="serve_fetch",
            )
        except Exception as e:
            self._on_batch_failure(inf.batch, e, phase="fetch")
            return
        now = time.monotonic()
        wall = now - inf.t0
        self._controller.note_drain()
        self._admission.note_batch_wall(wall)
        self._registry.counter("batches").inc()
        self._registry.timer("batch_wall_s").observe(wall)
        with self._cond:
            qd = self._queued_locked()
        obs_events.emit(
            "serve_batch", bucket=bucket_label(inf.bucket),
            size=len(inf.batch), wall_s=round(wall, 6), queue_depth=qd,
            inflight=len(self._inflight), seq=self._batch_seq,
        )
        tables, quality = self._engine.split(np.asarray(table))
        tier = self._active_tier()
        for i, req in enumerate(inf.batch):
            if req.expired(now):
                # deadline check at FETCH: the caller's budget is gone —
                # the computed result is discarded, the outcome classified
                self._resolve_deadline(req, "fetch")
                continue
            req_wall = now - req.submitted_t
            result = MatchResult(
                request_id=req.id, table=np.array(tables[i]),
                quality=quality[i] if quality else None,
                bucket=inf.bucket, wall_s=req_wall,
            )
            req.future._settle("result", result=result)
            self._n["results"] += 1
            self._registry.counter("results").inc()
            self._registry.histogram(
                f"serve_wall_ms_{bucket_label(inf.bucket)}",
                0.0, self.cfg.latency_hist_ms,
            ).add(req_wall * 1e3)
            obs_events.emit(
                "serve_result", request=req.id, client=req.client,
                bucket=bucket_label(inf.bucket),
                wall_ms=round(req_wall * 1e3, 3), batch_size=len(inf.batch),
            )
            if quality:
                from ncnet_tpu.observability.quality import emit_quality

                emit_quality("serving", quality[i], tier=tier,
                             registry=self._registry, request=req.id)
            self._terminal(req)

    def _active_tier(self) -> str:
        from ncnet_tpu.observability.quality import active_tier

        return active_tier(getattr(self._engine, "half_precision", False))

    # ------------------------------------------------------------------
    # failure handling
    # ------------------------------------------------------------------

    def _on_batch_failure(self, batch: List[MatchRequest],
                          exc: Exception, phase: str) -> None:
        """One failed batch (dispatch raised, fetch raised, or the fetch
        watchdog fired).  Recovery order mirrors ``run_isolated``: a
        program-changing recovery (tier demotion + retrace) grants a FREE
        retry of the whole batch; otherwise each request's bounded budget
        is charged and exhausted requests quarantine.  Requeued requests go
        to the FRONT of their bucket queue — queued work behind a failure
        is delayed, never lost or reordered past the failure."""
        from ncnet_tpu.evaluation.resilience import classify_failure
        from ncnet_tpu.models.ncnet import recover_from_device_failure

        self._controller.note_failure()
        kind = classify_failure(exc)
        try:
            tier = recover_from_device_failure(exc, self._engine)
        except Exception as rec_exc:  # noqa: BLE001 — recovery must not
            # take the worker (and every queued request) down with it;
            # a failed recovery just means the plain retry budget applies
            log.error(f"tier recovery itself failed "
                      f"({type(rec_exc).__name__}: {rec_exc}); falling "
                      "back to the plain retry budget", kind="device")
            tier = None
        requeue: List[MatchRequest] = []
        quarantine: List[MatchRequest] = []
        if tier is not None:
            with self._cond:
                # a demotion during DRAINING/STOPPED must not fight the
                # lifecycle states — the drain keeps completing admitted
                # work on the demoted tier either way
                if self._health.state in (STARTING, READY):
                    self._health.to(DEGRADED, f"tier_demoted:{tier}")
            log.warning(
                f"serving batch {phase} failed ({kind}); demoted tier "
                f"'{tier}' and re-tracing — {len(batch)} request(s) "
                "requeued off-budget", kind=kind)
            for req in batch:
                obs_events.emit("retry", unit=req.id, kind=kind,
                                recovered=tier, on_budget=False,
                                scope="serving")
                requeue.append(req)
        else:
            for req in batch:
                req.attempts += 1
                if req.attempts <= self.cfg.retries:
                    obs_events.emit("retry", unit=req.id, kind=kind,
                                    attempt=req.attempts, on_budget=True,
                                    scope="serving")
                    requeue.append(req)
                else:
                    quarantine.append(req)
            if requeue:
                log.warning(
                    f"serving batch {phase} failed ({kind}: "
                    f"{type(exc).__name__}: {exc}); {len(requeue)} "
                    "request(s) requeued on-budget", kind=kind)
        if requeue:
            with self._cond:
                q = self._queues.setdefault(requeue[0].bucket, deque())
                q.extendleft(reversed(requeue))
                self._cond.notify_all()
        for req in quarantine:
            self._quarantine(req, kind, exc)

    def _quarantine(self, req: MatchRequest, kind: str,
                    exc: Exception) -> None:
        msg = (f"request {req.id} gave up after {req.attempts} attempt(s): "
               f"{type(exc).__name__}: {exc}")
        log.warning(f"{msg} — quarantined; the stream continues",
                    kind="quarantine")
        req.future._settle("quarantined", error=RequestQuarantined(
            msg, kind=kind, attempts=req.attempts))
        self._n["quarantined"] += 1
        self._registry.counter("quarantined").inc()
        obs_events.emit("serve_quarantine", request=req.id,
                        client=req.client, kind=kind,
                        attempts=req.attempts, error=str(exc)[:300])
        if self._manifest is not None:
            self._manifest.quarantine(req.id, kind, str(exc), req.attempts)
        self._terminal(req)

    def _resolve_deadline(self, req: MatchRequest, where: str) -> None:
        req.future._settle("deadline", error=DeadlineExceeded(
            f"request {req.id} deadline expired at {where}", where=where))
        self._n["deadline"] += 1
        self._registry.counter("deadline_exceeded").inc()
        obs_events.emit("serve_deadline", request=req.id, client=req.client,
                        where=where, admitted=True)
        self._terminal(req)

    def _terminal(self, req: MatchRequest) -> None:
        """Close one admitted request's accounting (every settle path ends
        here — the exactly-one-outcome bar)."""
        with self._cond:
            self._admission.note_done(req.client)
        if self._draining:
            self._drain_resolved += 1
            from ncnet_tpu.utils import faults

            # chaos seam: SIGKILL after the Nth terminal outcome of the
            # drain phase (tests prove the event log still accounts for
            # everything that had no outcome yet)
            faults.serve_drain_kill_hook(self._drain_resolved)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def _finish(self, crashed: Optional[BaseException]) -> None:
        with self._cond:
            self._finishing = True  # admission closed before collection
            leftovers: List[MatchRequest] = []
            for q in self._queues.values():
                leftovers.extend(q)
            self._queues.clear()
            for inf in self._inflight:
                leftovers.extend(inf.batch)
            self._inflight.clear()
            if self._processing:
                # the batch the worker held when it crashed — in no queue
                # and no longer in the in-flight deque
                leftovers.extend(self._processing)
                self._processing = None
        reason = "crashed" if crashed is not None else "shutdown"
        for req in leftovers:
            if req.future.done():
                continue  # settled before the crash interrupted its batch
            # an aborted shutdown (or a worker crash) still settles every
            # admitted request with a classified outcome
            req.future._settle("overloaded", error=Overloaded(
                f"service stopped before request {req.id} completed",
                reason=reason))
            self._n["shed"] += 1
            obs_events.emit("serve_shed", request=req.id, client=req.client,
                            reason=reason, admitted=True)
            self._terminal(req)
        obs_events.emit(
            "serve_drain", drained=self._draining and crashed is None,
            leftover=len(leftovers), **{f"n_{k}": v
                                        for k, v in self._n.items()},
        )
        self._registry.flush(scope="serving")
        with self._cond:
            if self._health.state != STOPPED:
                self._health.to(
                    STOPPED, "crashed" if crashed is not None else "clean")
            self._cond.notify_all()
