"""Resident match serving: the fault-tolerant service around the warm matcher.

ROADMAP item 1, built on the PR 1-7 layers: continuous batching into padded
shape buckets (bounded jit cache), a replica pool (one engine per visible
device) with health-scored routing, replica failover/quarantine and
resurrection probes, elastic admission control with classified
``Overloaded`` shedding + aggregate-pool-cadence retry-after hints,
per-request deadlines checked at admission/dequeue/fetch, demote-retrace
survival of device failures with zero lost requests, SIGTERM drain, a
STARTING/READY/DEGRADED/DRAINING/STOPPED health machine for probes, and
full replica-tagged event/metric/quality telemetry.  On top of the
single-process service sits the multi-host tier: a versioned wire data
plane (``wire.py`` + ``POST /match`` on the introspection server) and a
fronting ``MatchRouter`` (``router.py``) that scores per-host backends
from their ``/healthz`` documents, fails over across process/network
boundaries off-budget, propagates backend backpressure, and drains in
coordination with its backends.  See README "Serving" / "Replicated
serving" / "Multi-host serving" for the API, overload semantics and chaos
knobs; tests/test_serving.py, tests/test_serving_pool.py and
tests/test_router.py are the fault-injected proof of the invariants.
"""

from ncnet_tpu.serving.admission import AdmissionController  # noqa: F401
from ncnet_tpu.serving.buckets import ShapeBucketer, pad_to_bucket  # noqa: F401
from ncnet_tpu.serving.engine import BatchMatchEngine  # noqa: F401
from ncnet_tpu.serving.health import (  # noqa: F401
    ADMITTING,
    DEGRADED,
    DRAINING,
    HEALTH_DOC_SCHEMA,
    READY,
    STARTING,
    STOPPED,
    HealthMachine,
    build_health_document,
)
from ncnet_tpu.serving.introspect import IntrospectionServer  # noqa: F401
from ncnet_tpu.serving.replica import (  # noqa: F401
    REPLICA_DEAD,
    REPLICA_DRAINING,
    REPLICA_READY,
    Replica,
    ReplicaPool,
)
from ncnet_tpu.serving.rollout import (  # noqa: F401
    ROLLOUT_CANARY,
    ROLLOUT_COMPLETE,
    ROLLOUT_IDLE,
    ROLLOUT_PROMOTING,
    ROLLOUT_ROLLED_BACK,
    ROLLOUT_ROLLING_BACK,
    ROLLOUT_STAGING,
    RolloutConfig,
    RolloutController,
    RolloutRefused,
    read_rollout_state,
    resolve_serving_checkpoint,
    write_rollout_state,
)
from ncnet_tpu.serving.router import (  # noqa: F401
    BACKEND_DEAD,
    BACKEND_DRAINING,
    BACKEND_READY,
    ROUTER_DOC_SCHEMA,
    Backend,
    MatchRouter,
    RouterConfig,
    build_router_document,
)
from ncnet_tpu.serving.wire import (  # noqa: F401
    WIRE_SCHEMA,
    MatchClient,
    WireError,
)
from ncnet_tpu.serving.request import (  # noqa: F401
    TERMINAL_OUTCOMES,
    DeadlineExceeded,
    MatchFuture,
    MatchRequest,
    MatchResult,
    Overloaded,
    RequestQuarantined,
    bucket_label,
)
from ncnet_tpu.serving.service import MatchService, ServingConfig  # noqa: F401
from ncnet_tpu.serving.slo import SLOTracker  # noqa: F401
from ncnet_tpu.serving.stream import (  # noqa: F401
    StreamFrameResult,
    StreamSession,
    StreamTable,
    run_stream_load,
    stream_schedule,
)

__all__ = [
    "ADMITTING",
    "AdmissionController",
    "BACKEND_DEAD",
    "BACKEND_DRAINING",
    "BACKEND_READY",
    "Backend",
    "BatchMatchEngine",
    "DEGRADED",
    "DRAINING",
    "DeadlineExceeded",
    "HEALTH_DOC_SCHEMA",
    "HealthMachine",
    "IntrospectionServer",
    "MatchClient",
    "MatchFuture",
    "MatchRequest",
    "MatchResult",
    "MatchRouter",
    "MatchService",
    "Overloaded",
    "READY",
    "REPLICA_DEAD",
    "REPLICA_DRAINING",
    "REPLICA_READY",
    "ROLLOUT_CANARY",
    "ROLLOUT_COMPLETE",
    "ROLLOUT_IDLE",
    "ROLLOUT_PROMOTING",
    "ROLLOUT_ROLLED_BACK",
    "ROLLOUT_ROLLING_BACK",
    "ROLLOUT_STAGING",
    "ROUTER_DOC_SCHEMA",
    "Replica",
    "ReplicaPool",
    "RequestQuarantined",
    "RolloutConfig",
    "RolloutController",
    "RolloutRefused",
    "RouterConfig",
    "SLOTracker",
    "STARTING",
    "STOPPED",
    "ServingConfig",
    "ShapeBucketer",
    "StreamFrameResult",
    "StreamSession",
    "StreamTable",
    "TERMINAL_OUTCOMES",
    "WIRE_SCHEMA",
    "WireError",
    "bucket_label",
    "build_health_document",
    "build_router_document",
    "pad_to_bucket",
    "read_rollout_state",
    "resolve_serving_checkpoint",
    "run_stream_load",
    "stream_schedule",
    "write_rollout_state",
]
