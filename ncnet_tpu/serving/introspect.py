"""Live introspection plane for the resident match service.

Every observability layer before this one is write-then-replay: the event
log, the perf store, ``run_report`` all explain a run *after* the fact.  A
resident service fronting real traffic needs the READ side live — a
supervisor probes readiness, a router reads per-host capacity, an operator
watches queue depth NOW, not at the postmortem.  This module is that
surface: a stdlib ``http.server`` thread bolted onto a running
:class:`~ncnet_tpu.serving.service.MatchService`, serving three endpoints:

  * ``GET /metrics``  — Prometheus exposition (``observability/export.py``)
    of the serving plane: queue depth, per-bucket and per-replica latency
    histograms (cumulative ``_bucket``/``_sum``/``_count``), the
    outcome-total counters, replica health scores, quality-signal digests,
    and the SLO error-budget counters.  Metric names follow the
    ``ncnet_serve_*`` scheme (README "Live observability"); bucket/replica
    identities ride as labels, never name fragments.
  * ``GET /healthz``  — the unified, schema-versioned health document
    (``serving/health.py::build_health_document``) as JSON: HTTP 200 while
    the service admits (STARTING/READY/DEGRADED), 503 once it drains or
    stops.  This is the exact dict the future multi-host fan-out router
    consumes to route on per-host health/capacity/latency.
  * ``GET /statusz``  — the human page: replica table, bucket ladder,
    queue/active-request counts, SLO burn, recent health timeline.
  * ``POST /match``   — the wire DATA plane (``serving/wire.py``): one
    framed uint8 pair in, the classified terminal outcome (match+quality
    table, or overloaded/deadline/quarantined) out, with the edge's
    deadline budget and client identity propagated into this service's
    admission control.  This is the endpoint the multi-host
    ``serving/router.py::MatchRouter`` fans out to.

Fail-open like every telemetry layer: the server runs on daemon threads, a
handler exception answers 500 instead of propagating, ``start()`` failures
are the caller's to swallow (``MatchService.start`` logs and serves
without the plane), and killing this thread mid-scrape leaves serving
untouched — proven by the tier-1 kill-mid-scrape test.  The endpoints only
READ service state under its condition lock (an RLock, so the nested
``health()`` call is safe) and never mutate scheduling state.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ncnet_tpu.observability.export import Family, render
from ncnet_tpu.observability.metrics import Counter, Histogram

# registry-key prefixes whose identity suffix becomes a label (the curated
# families below); everything else in the registry is either mirrored by a
# curated family or internal
_BUCKET_HIST_PREFIX = "serve_wall_ms_"
_REPLICA_HIST_PREFIX = "replica_wall_ms_"
_QUALITY_HIST_PREFIX = "q_"
_VERSION_HIST_PREFIX = "version_wall_ms_"


def metrics_families(service) -> List[Family]:
    """The curated ``ncnet_serve_*`` family set for one service, built
    under the service lock so counters/histograms and the health document
    are one consistent cut."""
    lat = Family("ncnet_serve_latency_ms", "histogram",
                 "end-to-end request latency per shape bucket")
    rep_hist = Family("ncnet_serve_replica_batch_wall_ms", "histogram",
                      "batch wall per replica")
    quality = Family("ncnet_serve_quality", "histogram",
                     "per-pair match-quality signal digests "
                     "(observability/quality.py)")
    ver_lat = Family("ncnet_serve_version_latency_ms", "histogram",
                     "end-to-end request latency per model version "
                     "(live rollout: canary vs baseline)")
    with service._cond:
        doc = service.health()
        reg_items = dict(service._registry._metrics)
        replica_counters = [
            (name, m.value) for name, m in sorted(reg_items.items())
            if isinstance(m, Counter) and name.startswith("replica_")
        ]
        version_counters = [
            (name, m.value) for name, m in sorted(reg_items.items())
            if isinstance(m, Counter) and name.startswith("version_")
        ]
        stream_cuts = next(
            (m.value for name, m in reg_items.items()
             if name == "stream_cuts" and isinstance(m, Counter)), 0)
        stream_tier = (
            "tracked"
            if getattr(service, "_tracking_capable", False)
            and getattr(getattr(service, "cfg", None),
                        "stream_tracking", False)
            else "full")
        # histogram families render INSIDE the lock: counts and sum must
        # be one cut, or a fetcher landing mid-scrape could put a value in
        # _sum that _count does not yet count — exactly the consistency
        # the scrape tests pin
        for name, h in sorted(reg_items.items()):
            if not isinstance(h, Histogram) or not h.count:
                continue
            if name.startswith(_BUCKET_HIST_PREFIX):
                lat.add_histogram(h, bucket=name[len(_BUCKET_HIST_PREFIX):])
            elif name.startswith(_REPLICA_HIST_PREFIX):
                rep_hist.add_histogram(
                    h, replica=name[len(_REPLICA_HIST_PREFIX):])
            elif name.startswith(_QUALITY_HIST_PREFIX):
                quality.add_histogram(
                    h, signal=name[len(_QUALITY_HIST_PREFIX):])
            elif name.startswith(_VERSION_HIST_PREFIX):
                ver_lat.add_histogram(
                    h, model_version=name[len(_VERSION_HIST_PREFIX):])
    fams: List[Family] = []

    up = Family("ncnet_serve_up", "gauge",
                "1 while the service admits (STARTING/READY/DEGRADED)")
    up.add(1 if doc["state"] in ("STARTING", "READY", "DEGRADED") else 0)
    fams.append(up)
    state = Family("ncnet_serve_state", "gauge",
                   "service health state (1 on the active state's series)")
    state.add(1, state=doc["state"])
    fams.append(state)

    outcomes = Family(
        "ncnet_serve_requests_total", "counter",
        "terminal outcomes of admitted requests (the outcome-total "
        "contract), plus admissions under outcome=\"admitted\"")
    for outcome, n in sorted(doc["counters"].items()):
        outcomes.add(n, outcome=outcome)
    fams.append(outcomes)

    q = doc["queue"]
    fams.append(Family("ncnet_serve_queue_depth", "gauge",
                       "requests queued across shape buckets")
                .add(q["depth"]))
    fams.append(Family("ncnet_serve_effective_max_queue", "gauge",
                       "the elastic queue bound at live capacity")
                .add(q["effective_max_queue"]))
    fams.append(Family("ncnet_serve_inflight_batches", "gauge",
                       "dispatched batches not yet fetched")
                .add(q["inflight_batches"]))
    fams.append(Family("ncnet_serve_pipeline_depth", "gauge",
                       "per-replica in-flight depth target")
                .add(q["pipeline_depth"]))

    pool = doc["pool"]
    fams.append(Family("ncnet_serve_replicas", "gauge",
                       "pool capacity by readiness")
                .add(pool["ready"], status="ready")
                .add(pool["total"], status="total"))
    rep_up = Family("ncnet_serve_replica_up", "gauge",
                    "1 = replica READY, 0 = DEAD awaiting resurrection")
    rep_score = Family("ncnet_serve_replica_health_score", "gauge",
                       "routing cost (lower = preferred)")
    rep_wall = Family("ncnet_serve_replica_wall_ewma_ms", "gauge",
                      "batch-wall EWMA per replica")
    rep_load = Family("ncnet_serve_replica_load", "gauge",
                      "batches owned (queued for fetch + fetching)")
    for r in pool["replicas"]:
        rep_up.add(1 if r["state"] == "READY" else 0, replica=r["id"])
        rep_score.add(r["score"], replica=r["id"])
        if r.get("ewma_wall_ms") is not None:
            rep_wall.add(r["ewma_wall_ms"], replica=r["id"])
        rep_load.add(r["load"], replica=r["id"])
    fams.extend([rep_up, rep_score, rep_wall, rep_load])

    rep_batches = Family("ncnet_serve_replica_batches_total", "counter",
                         "batches completed per replica")
    rep_failures = Family("ncnet_serve_replica_failures_total", "counter",
                          "batch failures per replica")
    for name, value in replica_counters:
        if name.startswith("replica_batches_"):
            rep_batches.add(value,
                            replica=name[len("replica_batches_"):])
        elif name.startswith("replica_failures_"):
            rep_failures.add(value,
                             replica=name[len("replica_failures_"):])
    fams.extend([rep_batches, rep_failures])

    fams.extend([lat, rep_hist, quality])

    # live-rollout version families (serving/rollout.py): the pod's
    # converged identity as an info-style gauge, plus per-version terminal
    # counts and latency digests — the canary judge's evidence, scrapable
    if doc.get("model_version"):
        fams.append(Family(
            "ncnet_serve_model_version", "gauge",
            "1 on the pod's converged model version's series")
            .add(1, model_version=doc["model_version"]))
    ver_req = Family(
        "ncnet_serve_version_requests_total", "counter",
        "terminal outcomes per model version (live rollout)")
    for name, value in version_counters:
        if name.startswith("version_results_"):
            ver_req.add(value, outcome="result",
                        model_version=name[len("version_results_"):])
        elif name.startswith("version_failures_"):
            ver_req.add(value, outcome="failure",
                        model_version=name[len("version_failures_"):])
    if ver_req.samples:
        fams.append(ver_req)
    if ver_lat.samples:
        fams.append(ver_lat)

    slo = doc.get("slo")
    if slo is not None:
        slo_fam = Family(
            "ncnet_serve_slo_requests_total", "counter",
            "SLO classification of admitted terminal outcomes")
        slo_fam.add(slo["ok"], slo_class="ok")
        for cls, n in sorted(slo["bad"].items()):
            slo_fam.add(n, slo_class=cls)
        fams.append(slo_fam)
        fams.append(Family("ncnet_serve_slo_admitted_total", "counter",
                           "admitted requests judged against the SLO")
                    .add(slo["admitted"]))
        fams.append(Family(
            "ncnet_serve_slo_budget_burn_pct", "gauge",
            "cumulative error-budget burn (100 = budget spent)")
            .add(slo["budget_burn_pct"]))
        fams.append(Family(
            "ncnet_serve_slo_window_burn_pct", "gauge",
            "error-budget burn over the sliding window")
            .add(slo["window"]["burn_pct"]))
        obj = Family("ncnet_serve_slo_objective_ms", "gauge",
                     "latency objective per bucket (default under "
                     "bucket=\"default\")")
        if slo["objectives"]["default_ms"] is not None:
            obj.add(slo["objectives"]["default_ms"], bucket="default")
        for b, ms in sorted(slo["objectives"]["by_bucket"].items()):
            obj.add(ms, bucket=b)
        fams.append(obj)

    act = doc.get("activity")
    if act is not None:
        fams.append(Family("ncnet_serve_activity_age_seconds", "gauge",
                           "seconds since the pool last dispatched or "
                           "deliberately idled").add(act["age_s"]))
        fams.append(Family("ncnet_serve_batches_dispatched_total",
                           "counter", "batches dispatched pool-wide")
                    .add(act["batches"]))

    # memory observability (observability/memory.py): the warmed ladder's
    # PREDICTED footprint from the compiled-program ledger, and the live
    # per-replica HBM watermarks sampled at every dispatched batch.  A
    # backend without memory_stats (CPU) simply has no hbm_bytes series —
    # the predicted gauge still renders from the ledger alone.
    mem = doc.get("memory")
    if mem is not None:
        if mem.get("predicted_ladder_bytes") is not None:
            fams.append(Family(
                "ncnet_serve_hbm_predicted_ladder_bytes", "gauge",
                "predicted aggregate footprint of the warmed bucket "
                "ladder (sum of ledger temp+output bytes)")
                .add(mem["predicted_ladder_bytes"]))
        if mem.get("headroom_bytes") is not None:
            fams.append(Family(
                "ncnet_serve_hbm_headroom_bytes", "gauge",
                "bytes_limit minus the predicted ladder footprint "
                "(negative = the ladder cannot all be resident)")
                .add(mem["headroom_bytes"]))
        hbm_bytes = Family("ncnet_serve_hbm_bytes", "gauge",
                           "per-replica HBM watermarks (memory_stats)")
        hbm_fill = Family("ncnet_serve_hbm_fill_pct", "gauge",
                          "bytes_in_use / bytes_limit per replica")
        for rid, s in sorted((mem.get("hbm") or {}).items()):
            for stat in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "bytes_reserved",
                         "largest_free_block_bytes"):
                if s.get(stat) is not None:
                    hbm_bytes.add(s[stat], replica=rid, stat=stat)
            if s.get("fill_pct") is not None:
                hbm_fill.add(s["fill_pct"], replica=rid)
        if hbm_bytes.samples:
            fams.append(hbm_bytes)
        if hbm_fill.samples:
            fams.append(hbm_fill)

    # persistent feature store (ncnet_tpu/store/): the ncnet_store_*
    # families — OK/DEGRADED, the hit/miss/corrupt/evict/degraded counters
    # (monotone within one store lifetime), and the footprint gauges.  A
    # DEGRADED store serves on via recompute (fail-open), so ncnet_store_up
    # going 0 is an operator page about the DISK, not about availability.
    # streaming tracked mode (serving/stream.py): monotone frame totals by
    # kind (tracked = coarse pass SKIPPED, fallback = cut-triggered exact
    # re-seed, cold = first/unseeded frame), cut detections, live session
    # gauge, the candidate-recall proxy, and the pipeline tier streaming
    # frames currently dispatch through
    sm = doc.get("streams")
    if sm is not None:
        frames = Family(
            "ncnet_serve_stream_frames_total", "counter",
            "stream frames served by kind (tracked = coarse pass skipped, "
            "fallback = cut re-seed, cold = unseeded)")
        frames.add(sm["tracked_frames"], kind="tracked")
        frames.add(sm["fallback_frames"], kind="fallback")
        frames.add(sm["cold_frames"], kind="cold")
        fams.append(frames)
        fams.append(Family("ncnet_serve_stream_cuts_total", "counter",
                           "detected scene cuts / tracking drifts "
                           "(recall collapse or quality collapse)")
                    .add(stream_cuts))
        fams.append(Family("ncnet_serve_stream_sessions", "gauge",
                           "live stream sessions (bound under "
                           "label=\"max\")")
                    .add(sm["active"], bound="active")
                    .add(sm["max_sessions"], bound="max"))
        fams.append(Family("ncnet_serve_stream_evicted_total", "counter",
                           "stream sessions evicted (idle/cap/drain)")
                    .add(sm["evicted"]))
        if sm.get("recall_mean") is not None:
            fams.append(Family(
                "ncnet_serve_stream_recall", "gauge",
                "mean candidate-recall proxy over live sessions "
                "(fraction of served matches inside the seeded windows)")
                .add(sm["recall_mean"]))
        fams.append(Family(
            "ncnet_serve_stream_pipeline", "gauge",
            "1 on the pipeline tier streaming frames dispatch through "
            "(tracked = temporal-candidate fine pass, full = per-frame "
            "coarse-to-fine)").add(1, tier=stream_tier))

    st = doc.get("store")
    if st is not None:
        fams.append(Family(
            "ncnet_store_up", "gauge",
            "1 = feature store OK, 0 = DEGRADED (failing open to "
            "recompute)").add(1 if st.get("state") == "OK" else 0))
        c = st.get("counters") or {}
        for metric, key, help_text in (
                ("ncnet_store_hits_total", "hits",
                 "verified feature-store read hits"),
                ("ncnet_store_misses_total", "misses",
                 "feature-store misses (recomputed + committed)"),
                ("ncnet_store_corrupt_total", "corrupt",
                 "entries that failed verification and were quarantined"),
                ("ncnet_store_evictions_total", "evictions",
                 "LRU evictions under the size budget"),
                ("ncnet_store_degraded_ops_total", "degraded_ops",
                 "store operations that failed open (I/O errors)")):
            if key in c:
                fams.append(Family(metric, "counter", help_text)
                            .add(c[key]))
        fams.append(Family("ncnet_store_entries", "gauge",
                           "live entries in the current generation")
                    .add(st.get("entries", 0)))
        fams.append(Family("ncnet_store_bytes", "gauge",
                           "bytes used by the current generation")
                    .add(st.get("bytes", 0)))
        if st.get("budget_bytes"):
            fams.append(Family("ncnet_store_budget_bytes", "gauge",
                               "LRU eviction budget (0 = unbounded)")
                        .add(st["budget_bytes"]))
        if st.get("hit_pct") is not None:
            fams.append(Family("ncnet_store_hit_pct", "gauge",
                               "verified-hit percentage over all lookups")
                        .add(st["hit_pct"]))
    return fams


def render_statusz(service) -> str:
    """The human page: one consistent cut of the health document rendered
    as fixed-width text (``/statusz`` convention — glanceable, greppable,
    no JSON tooling needed)."""
    doc = service.health()
    lines: List[str] = []
    add = lines.append
    svc = doc["service"]
    add("ncnet_tpu match service — statusz")
    add(f"state: {doc['state']}  (for {svc['age_s']}s"
        + (f", reason: {svc['reason']}" if svc.get("reason") else "") + ")")
    if doc.get("model_version"):
        line = f"model version: {doc['model_version']}"
        ro = doc.get("rollout")
        if ro is not None and ro.get("phase") not in (None, "IDLE"):
            line += (f"  rollout: {ro['phase']}"
                     + (f" -> {ro['new_version']}"
                        if ro.get("new_version") else ""))
        add(line)
    q = doc["queue"]
    add(f"queue: depth={q['depth']}/{q['effective_max_queue']}  "
        f"inflight_batches={q['inflight_batches']}  "
        f"pipeline_depth={q['pipeline_depth']}")
    c = doc["counters"]
    active = c["admitted"] - (c["results"] + c["deadline"]
                              + c["quarantined"] + c["shed"])
    add(f"requests: admitted={c['admitted']}  results={c['results']}  "
        f"deadline={c['deadline']}  quarantined={c['quarantined']}  "
        f"shed={c['shed']}  active={max(0, active)}")
    add("")
    add(f"bucket ladder: {', '.join(q['buckets']) or '(none registered)'}")
    add("")
    pool = doc["pool"]
    hbm = (doc.get("memory") or {}).get("hbm") or {}
    add(f"replicas ({pool['ready']}/{pool['total']} ready):")
    add(f"  {'id':<8} {'state':<8} {'version':<10} {'score':>10} "
        f"{'ewma_ms':>9} {'load':>4} {'batches':>8} {'failures':>8} "
        f"{'deaths':>6} {'hbm%':>6}")
    for r in pool["replicas"]:
        ewma = r.get("ewma_wall_ms")
        fill = (hbm.get(r["id"]) or {}).get("fill_pct")
        add(f"  {r['id']:<8} {r['state']:<8} "
            f"{(r.get('model_version') or '-'):<10} {r['score']:>10.4f} "
            f"{(f'{ewma:.2f}' if ewma is not None else '-'):>9} "
            f"{r['load']:>4} {r['batches']:>8} {r['failures']:>8} "
            f"{r['deaths']:>6} "
            f"{(f'{fill:.1f}' if fill is not None else '-'):>6}")
    mem = doc.get("memory")
    if mem is not None and (mem.get("predicted_ladder_bytes") is not None
                            or hbm):
        add("")
        pred = mem.get("predicted_ladder_bytes")
        line = (f"memory: predicted ladder "
                f"{pred / 2 ** 20:.1f} MiB over "
                f"{mem.get('ledger_programs')} warmed program(s)"
                if pred is not None else
                "memory: no warmed programs in the ledger")
        head = mem.get("headroom_bytes")
        if head is not None:
            line += f"  headroom vs bytes_limit {head / 2 ** 20:.1f} MiB"
        add(line)
    st = doc.get("store")
    if st is not None:
        add("")
        c = st.get("counters") or {}
        hp = st.get("hit_pct")
        add(f"feature store: {st.get('state')}"
            + (f" ({st.get('reason')})" if st.get("reason") else "")
            + f"  entries={st.get('entries')}"
            f"  bytes={(st.get('bytes') or 0) / 2 ** 20:.1f} MiB"
            + (f"  hit%={hp:.1f}" if hp is not None else "")
            + f"  corrupt={c.get('corrupt', 0)}"
            f"  evictions={c.get('evictions', 0)}")
    sm = doc.get("streams")
    if sm is not None and (sm["active"] or sm["frames"]):
        add("")
        rc = sm.get("recall_mean")
        add(f"streams: active={sm['active']}/{sm['max_sessions']}  "
            f"frames={sm['frames']}  tracked={sm['tracked_frames']}  "
            f"fallback={sm['fallback_frames']}  cold={sm['cold_frames']}  "
            f"evicted={sm['evicted']}"
            + (f"  recall={rc:.3f}" if rc is not None else ""))
    slo = doc.get("slo")
    if slo is not None and slo["admitted"]:
        add("")
        w = slo["window"]
        add(f"SLO: burn={slo['budget_burn_pct']}% of budget "
            f"({slo['bad_total']}/{slo['admitted']} bad, budget "
            f"{slo['objectives']['budget_pct']}%)  window: "
            f"{w['bad']}/{w['n']} bad = {w['burn_pct']}%")
    add("")
    add("recent health timeline:")
    for h in svc.get("history", []):
        add(f"  -> {h['state']}"
            + (f"  ({h['reason']})" if h.get("reason") else ""))
    return "\n".join(lines) + "\n"


def scrape_wall_ms(base_url: str, n: int = 5, timeout: float = 30.0) -> float:
    """Median wall of ``n`` ``/metrics`` scrapes over real HTTP, in ms —
    THE scrape-cost methodology, shared by bench.py's 1%-of-cadence gate
    and serve_probe's real-device measurement so the two can never
    silently measure different things."""
    import statistics
    import time as _time
    import urllib.request

    url = base_url.rstrip("/") + "/metrics"
    walls = []
    for _ in range(int(n)):
        t0 = _time.perf_counter()
        with urllib.request.urlopen(url, timeout=timeout) as r:
            r.read()
        walls.append(_time.perf_counter() - t0)
    return float(statistics.median(walls)) * 1e3


class _Handler(BaseHTTPRequestHandler):
    server_version = "ncnet-introspect/1"
    protocol_version = "HTTP/1.1"

    # the library logger is the one console sink; per-request access lines
    # are noise there and a bare print would break the tier-1 pin
    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        pass

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        intro = getattr(self.server, "introspect", None)
        try:
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if intro is None:
                code, ctype, body = 503, "text/plain; charset=utf-8", \
                    "introspection detached\n"
            elif path == "/metrics":
                code = 200
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                body = intro.metrics_text()
            elif path == "/healthz":
                doc = intro.health_doc()
                code = 200 if doc.get("state") in (
                    "STARTING", "READY", "DEGRADED") else 503
                ctype = "application/json; charset=utf-8"
                body = json.dumps(doc, sort_keys=True) + "\n"
            elif path == "/statusz":
                code, ctype = 200, "text/plain; charset=utf-8"
                body = intro.statusz_text()
            elif path == "/rollout":
                code, ctype = 200, "application/json; charset=utf-8"
                body = json.dumps(intro.rollout_doc(),
                                  sort_keys=True) + "\n"
            elif path == "/":
                code, ctype = 200, "text/plain; charset=utf-8"
                body = "endpoints: /metrics /healthz /statusz /rollout " \
                    "(+ POST /match, POST /retrieve, POST /rollout)\n"
            else:
                code, ctype, body = 404, "text/plain; charset=utf-8", \
                    f"no such endpoint {path}; try /metrics /healthz " \
                    "/statusz\n"
        except Exception as e:  # noqa: BLE001 — the plane fails open: a
            # renderer bug answers 500, it never propagates into serving
            code, ctype = 500, "text/plain; charset=utf-8"
            body = f"introspection error: {type(e).__name__}: {e}\n"
        self._respond(code, ctype, body.encode("utf-8"))

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        """The wire data plane: ``POST /match`` (serving/wire.py) admits
        one framed request against the fronted service/router and blocks
        this connection's thread until its terminal outcome — the
        multi-host twin of a local ``submit(...).result()``.  ``POST
        /retrieve`` (retrieval/wire.py) is the same contract for the
        scatter-gather shortlist plane; a host that fronts no retrieval
        service answers 404 there, not 500."""
        intro = getattr(self.server, "introspect", None)
        path = self.path.split("?", 1)[0].rstrip("/")
        if intro is None or path not in ("/match", "/retrieve", "/rollout"):
            self._respond(503 if intro is None else 404,
                          "text/plain; charset=utf-8",
                          b"POST accepts only /match, /retrieve and "
                          b"/rollout\n")
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n) if n > 0 else b""
            if path == "/retrieve":
                code, ctype, payload = intro.retrieve_payload(body)
            elif path == "/rollout":
                code, ctype, payload = intro.rollout_payload(body)
            else:
                code, ctype, payload = intro.match_payload(body)
        except Exception as e:  # noqa: BLE001 — same fail-open contract
            # as do_GET: a data-plane handler bug answers 500
            code, ctype = 500, "text/plain; charset=utf-8"
            payload = f"match error: {type(e).__name__}: {e}\n" \
                .encode("utf-8")
        self._respond(code, ctype, payload)

    def _respond(self, code: int, ctype: str, payload: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except OSError:
            pass  # client went away mid-write: its problem, not serving's


class IntrospectionServer:
    """The ``/metrics`` + ``/healthz`` + ``/statusz`` thread for one
    service.  ``port=0`` binds an ephemeral port (tests, multi-service
    hosts); read it back via :attr:`port` / :attr:`url` after
    :meth:`start`.  Binds loopback by default — exposing the plane beyond
    the host is a deployment decision (``ServingConfig.introspect_host``),
    not a default."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self._service = service
        self._host = host
        self._want_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._scrapes = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "IntrospectionServer":
        if self._httpd is not None:
            raise RuntimeError("introspection server already started")
        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True
        httpd.introspect = self
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="match-introspect", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self._host}:{self.port}" if self._httpd else None

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:  # noqa: BLE001 — shutdown of a dead socket is
            pass           # not worth more than the attempt
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    # -- endpoint payloads (also the in-process API the tests drive) --------

    def metrics_text(self) -> str:
        self._scrapes += 1
        fams = metrics_families(self._service)
        fams.append(Family("ncnet_serve_scrapes_total", "counter",
                           "scrapes answered by this introspection server")
                    .add(self._scrapes))
        return render(fams)

    def health_doc(self) -> Dict[str, Any]:
        return self._service.health()

    def statusz_text(self) -> str:
        return render_statusz(self._service)

    def match_payload(self, body: bytes):
        """``POST /match`` body → ``(status, content_type, payload)`` —
        one wire request submitted to the fronted service with the
        propagated deadline budget + client identity
        (``serving/wire.py::serve_match``).  The router's introspection
        plane inherits this unchanged, so a router is itself a valid wire
        backend (tiers chain)."""
        from ncnet_tpu.serving.wire import serve_match

        return serve_match(
            self._service.submit, body,
            stream_submit=getattr(self._service, "stream_submit", None))

    def rollout_doc(self) -> Dict[str, Any]:
        """``GET /rollout``: the live rollout status (phase, versions,
        verdict inputs) — IDLE with the pod's version when no controller
        was ever attached."""
        ctl = getattr(self._service, "_rollout", None)
        if ctl is not None:
            return ctl.status()
        return {"phase": "IDLE",
                "model_version": getattr(self._service, "model_version",
                                         None)}

    def rollout_payload(self, body: bytes):
        """``POST /rollout`` (control plane, ``tools/rollout.py``): JSON
        ``{"checkpoint": ..., knobs...}`` kicks a background rollout on
        the fronted service.  A host that fronts no rollout-capable
        service (a router) answers 404 — same pattern as /retrieve."""
        start = getattr(self._service, "start_rollout", None)
        if not callable(start):
            return (404, "text/plain; charset=utf-8",
                    b"this host serves no rollout control plane\n")
        from ncnet_tpu.serving.rollout import RolloutConfig

        try:
            req = json.loads(body.decode("utf-8") or "{}")
            candidate = req["checkpoint"]
        except (ValueError, KeyError) as e:
            return (400, "text/plain; charset=utf-8",
                    f"bad rollout request: {type(e).__name__}: {e}\n"
                    .encode("utf-8"))
        knobs = {k: req[k] for k in (
            "canary_fraction", "canary_min_results", "canary_timeout_s",
            "drain_timeout_s", "psi_threshold", "error_rate_margin",
            "latency_factor", "min_latency_samples", "state_path",
            "gc_keep_generations") if k in req}
        # additive trace field (pod observability): the control plane's
        # rollout order joins the federated trace like any data request
        from ncnet_tpu.observability import events as obs_events
        from ncnet_tpu.observability.tracing import normalize_trace

        trace = normalize_trace(req.get("trace"))
        obs_events.emit(
            "rollout_control", checkpoint=str(candidate)[:200],
            knobs=sorted(knobs),
            **({"trace": trace} if trace else {}))
        try:
            ctl = start(candidate, RolloutConfig(**knobs))
        except RuntimeError as e:  # a rollout is already in progress
            return (409, "text/plain; charset=utf-8",
                    f"{e}\n".encode("utf-8"))
        payload = json.dumps(ctl.status(), sort_keys=True) + "\n"
        return (202, "application/json; charset=utf-8",
                payload.encode("utf-8"))

    def retrieve_payload(self, body: bytes):
        """``POST /retrieve`` body → ``(status, content_type, payload)``
        — one framed retrieval request against the fronted service
        (``retrieval/wire.py::serve_retrieve``).  Any service exposing a
        ``retrieve(desc, ...)`` data plane (a shard host, the coordinator)
        joins the wire automatically; everything else answers 404."""
        retrieve = getattr(self._service, "retrieve", None)
        if not callable(retrieve):
            return (404, "text/plain; charset=utf-8",
                    b"this host serves no /retrieve\n")
        from ncnet_tpu.retrieval.wire import serve_retrieve

        return serve_retrieve(retrieve, body)
