"""Wire data plane for multi-host match serving.

The replica pool (PR 10) stops at a process boundary: every engine it can
route to hangs off this process's ``jax.devices()``.  A pod has hosts
beyond that, so the fronting router (``serving/router.py``) needs a way to
hand a request to ANOTHER host's ``MatchService`` and get the classified
outcome back — with the same deadline budget and client identity the edge
promised, so the backend's admission control and SLO accounting judge the
request exactly as a local submit would.  This module is that wire:

  * **Framing.**  One binary layout for both directions: ``NCMW`` magic +
    a one-byte schema version + a length-prefixed JSON header + the raw
    array payload.  Requests carry two uint8 ``(H, W, 3)`` images (shapes
    in the header, bytes concatenated); responses carry the ``(5|6, N)``
    float32 match+quality table.  The version byte is checked BEFORE the
    header is parsed — a peer speaking a different wire schema is refused
    with :class:`WireError` (which the router classifies as a backend
    failure), never silently misread.
  * **Deadline propagation.**  The header carries ``budget_s`` — the
    REMAINING deadline budget at send time, not an absolute instant
    (wall clocks on two hosts need not agree; monotonic clocks never do).
    The serving side submits with ``deadline_s=budget_s``, so an edge
    deadline expires as a classified ``DeadlineExceeded`` at whichever
    checkpoint catches it (backend admission, dequeue, fetch, or the
    router's own post-flight check) — never as a silent backend timeout.
  * **Client identity propagation.**  ``client`` rides the header so the
    backend's per-client in-flight caps and SLO attribution see the edge
    client, not an anonymous router.
  * **Outcome totality over HTTP.**  Every response is one of the four
    terminal outcomes: ``result`` (HTTP 200 + table payload),
    ``overloaded`` (429, with machine-readable ``reason`` +
    ``retry_after_s``), ``deadline`` (504, with ``where``), ``quarantined``
    (500, with ``kind`` + ``attempts``).  :func:`decode_response` maps the
    error outcomes back onto the SAME exception classes
    (``serving/request.py``) a local submit raises, so router code cannot
    tell — and need not care — whether a service is in-process or across
    the pod.

Endpoint: ``POST /match`` on the serving introspection server
(``serving/introspect.py``), one request per call, blocking until the
request's terminal outcome.  The server threads per connection
(``ThreadingHTTPServer``), so concurrent in-flight wire requests cost one
parked thread each — the router bounds that with its per-backend depth.
"""

from __future__ import annotations

import http.client
import json
import socket
import struct
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from ncnet_tpu.observability import events as obs_events
from ncnet_tpu.serving.request import (
    DeadlineExceeded,
    MatchResult,
    Overloaded,
    RequestQuarantined,
)

# wire schema version: the magic+version prefix is validated before any
# payload is trusted; bump on any framing or header-semantics change so a
# mixed-version pod fails loudly instead of corrupting tables
WIRE_SCHEMA = 1
_MAGIC = b"NCMW"
_HLEN = struct.Struct("<I")

# HTTP status per terminal outcome (the body is authoritative — the status
# exists for generic infrastructure between the tiers: LBs, access logs)
_OUTCOME_STATUS = {"result": 200, "overloaded": 429, "deadline": 504,
                   "quarantined": 500}

# how long past a propagated budget the serving side waits for the settle
# before answering a classified wire-wait timeout.  The ROUTER's per-attempt
# socket ceiling must exceed budget + THIS margin (router.py adds its own
# headroom on top), or the backend's classified 504 — produced between
# budget and budget+margin — could never reach the router by construction
# and every expiring deadline would masquerade as a backend failure.
WIRE_SETTLE_MARGIN_S = 2.0

WIRE_CONTENT_TYPE = "application/x-ncnet-match"

# clock-sync sampling cadence per client connection: one NTP-style offset
# sample (half-RTT from the request/response wall stamps already on the
# wire) per this many seconds.  Every response CARRIES the stamps; the
# throttle only bounds the event-log fsync traffic.
CLOCK_SYNC_INTERVAL_S = 1.0


def sync_stamps(recv_t: float) -> Dict[str, Any]:
    """The ADDITIVE response-header stamps the clock-sync plane rides on:
    the server's wall clock at request receipt (``recv_t``) and response
    encode (``resp_t``), plus the server's event-log run id (``peer_run``,
    None when no sink is bound) — the node identity the federation's skew
    graph keys corrections by (hostnames collide when a test pod runs
    every process on one machine; run ids never do)."""
    sink = obs_events.get_global_sink()
    return {
        "recv_t": round(recv_t, 6),
        "resp_t": round(obs_events.wall_now(), 6),
        "peer_run": sink.run_id if sink is not None else None,
    }


def emit_clock_sync(peer: str, header: Dict[str, Any],
                    t_send: float, t_recv: float) -> None:
    """One NTP-style offset sample from a completed round trip:
    ``offset_s`` estimates peer_wall − local_wall (positive = the peer's
    clock is ahead), ``rtt_s`` is the wire time with the peer's serve time
    subtracted out.  A response without stamps (an old peer) is a no-op —
    the sync plane is additive end to end."""
    t1, t2 = header.get("recv_t"), header.get("resp_t")
    if not isinstance(t1, (int, float)) or not isinstance(t2, (int, float)):
        return
    offset = ((float(t1) - t_send) + (float(t2) - t_recv)) / 2.0
    rtt = (t_recv - t_send) - (float(t2) - float(t1))
    obs_events.emit(
        "clock_sync", peer=peer, peer_run=header.get("peer_run"),
        offset_s=round(offset, 6), rtt_s=round(max(0.0, rtt), 6))


class WireError(ValueError):
    """Malformed or wrong-schema wire payload.  The router treats this as
    a backend failure (re-route + failure streak) — a peer we cannot
    understand is as unusable as one that is down."""


def _frame(header: Dict[str, Any], payload: bytes = b"") -> bytes:
    header = dict(header)
    header["schema"] = WIRE_SCHEMA
    hj = json.dumps(header, sort_keys=True).encode("utf-8")
    return _MAGIC + bytes([WIRE_SCHEMA]) + _HLEN.pack(len(hj)) + hj + payload


def _unframe(data: bytes) -> Tuple[Dict[str, Any], bytes]:
    if len(data) < len(_MAGIC) + 1 + _HLEN.size:
        raise WireError(f"wire frame truncated ({len(data)} bytes)")
    if data[:4] != _MAGIC:
        raise WireError(f"bad wire magic {data[:4]!r}")
    version = data[4]
    if version != WIRE_SCHEMA:
        raise WireError(
            f"wire schema {version} != {WIRE_SCHEMA} — refusing a frame "
            "this build does not understand")
    (hlen,) = _HLEN.unpack_from(data, 5)
    start = 5 + _HLEN.size
    if len(data) < start + hlen:
        raise WireError("wire header truncated")
    try:
        header = json.loads(data[start:start + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparseable wire header: {e}") from e
    if not isinstance(header, dict):
        raise WireError("wire header is not an object")
    return header, data[start + hlen:]


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------


def encode_request(src: np.ndarray, tgt: np.ndarray, *,
                   client: str = "wire",
                   budget_s: Optional[float] = None,
                   request_id: str = "",
                   stream: Optional[str] = None,
                   trace: Optional[str] = None) -> bytes:
    """One match query as wire bytes.  ``budget_s`` is the REMAINING
    deadline budget (None = no deadline); the receiving service admits
    with exactly this budget, so edge and backend judge the same promise.
    ``stream`` (optional, ADDITIVE — schema-1 peers that predate it never
    read the key) tags the request as one frame of a video stream: the
    backend routes it through its per-stream FIFO session
    (``MatchService.stream_submit``) so consecutive frames reuse temporal
    candidate priors and skip the coarse pass on steady frames.
    ``trace`` (optional, ADDITIVE like ``stream``) is the traceparent
    header (``observability/tracing.py::TraceContext.to_header``) that
    makes the backend's events part of the caller's pod-wide trace; the
    always-present ``sent_t`` wall stamp pairs with the response's
    ``recv_t``/``resp_t`` for NTP-style clock-offset sampling."""
    src = np.ascontiguousarray(src)
    tgt = np.ascontiguousarray(tgt)
    for name, a in (("src", src), ("tgt", tgt)):
        if a.ndim != 3 or a.shape[-1] != 3 or a.dtype != np.uint8:
            raise ValueError(f"{name} must be (H, W, 3) uint8 for the "
                             f"wire, got {a.shape} {a.dtype}")
    header = {
        "src_shape": list(src.shape),
        "tgt_shape": list(tgt.shape),
        "dtype": "uint8",
        "client": str(client),
        "budget_s": (round(float(budget_s), 6)
                     if budget_s is not None else None),
        "request": str(request_id),
        "sent_t": round(obs_events.wall_now(), 6),
    }
    if stream is not None:
        header["stream"] = str(stream)
    if trace is not None:
        header["trace"] = str(trace)
    return _frame(header, src.tobytes() + tgt.tobytes())


def decode_request(data: bytes
                   ) -> Tuple[np.ndarray, np.ndarray, Dict[str, Any]]:
    """Wire bytes → ``(src, tgt, meta)``; raises :class:`WireError` on a
    frame this build must refuse."""
    header, payload = _unframe(data)
    if header.get("dtype") != "uint8":
        raise WireError(f"request dtype {header.get('dtype')!r} != uint8")
    try:
        ss = tuple(int(x) for x in header["src_shape"])
        ts = tuple(int(x) for x in header["tgt_shape"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"bad request shapes: {e}") from e
    if len(ss) != 3 or len(ts) != 3 or ss[-1] != 3 or ts[-1] != 3:
        raise WireError(f"bad request shapes {ss}/{ts}")
    n_src = int(np.prod(ss))
    if len(payload) != n_src + int(np.prod(ts)):
        raise WireError(
            f"request payload {len(payload)} bytes != declared "
            f"{n_src + int(np.prod(ts))}")
    src = np.frombuffer(payload, np.uint8, count=n_src).reshape(ss)
    tgt = np.frombuffer(payload, np.uint8, offset=n_src).reshape(ts)
    meta = {
        "client": str(header.get("client", "wire")),
        "budget_s": (float(header["budget_s"])
                     if isinstance(header.get("budget_s"), (int, float))
                     else None),
        "request": str(header.get("request", "")),
        "stream": (str(header["stream"])
                   if header.get("stream") else None),
        "trace": (str(header["trace"])
                  if header.get("trace") else None),
        "sent_t": (float(header["sent_t"])
                   if isinstance(header.get("sent_t"), (int, float))
                   else None),
    }
    return src, tgt, meta


# ---------------------------------------------------------------------------
# responses
# ---------------------------------------------------------------------------


def encode_result(result: MatchResult,
                  extra: Optional[Dict[str, Any]] = None) -> Tuple[int, bytes]:
    """``(http_status, wire bytes)`` for a served table.  ``extra`` merges
    additive header fields (the clock-sync stamps) — old readers ignore
    keys they do not know."""
    table = np.ascontiguousarray(result.table, dtype=np.float32)
    header = {
        "outcome": "result",
        "table_shape": list(table.shape),
        "dtype": "float32",
        "request": result.request_id,
        "bucket": [list(result.bucket[0]), list(result.bucket[1])],
        "wall_ms": round(result.wall_s * 1e3, 3),
        "quality": result.quality,
    }
    if extra:
        header.update(extra)
    return _OUTCOME_STATUS["result"], _frame(header, table.tobytes())


def encode_error(exc: Exception,
                 extra: Optional[Dict[str, Any]] = None) -> Tuple[int, bytes]:
    """``(http_status, wire bytes)`` for a classified terminal rejection.
    Anything that is not one of the serving outcome classes encodes as a
    quarantine-shaped 500 — the wire stays outcome-total even when the
    backend hits an unexpected bug."""
    header: Dict[str, Any] = {"message": str(exc)[:500]}
    if isinstance(exc, Overloaded):
        header.update(outcome="overloaded", reason=exc.reason,
                      retry_after_s=exc.retry_after_s)
    elif isinstance(exc, DeadlineExceeded):
        header.update(outcome="deadline", where=exc.where)
    elif isinstance(exc, RequestQuarantined):
        header.update(outcome="quarantined", kind=exc.kind,
                      attempts=exc.attempts)
    else:
        header.update(outcome="quarantined", kind="internal", attempts=1)
    if extra:
        header.update(extra)
    return _OUTCOME_STATUS[header["outcome"]], _frame(header)


def decode_response(data: bytes) -> MatchResult:
    """Wire response → :class:`MatchResult`, or RAISES the classified
    terminal error exactly as a local ``MatchFuture.result()`` would."""
    header, payload = _unframe(data)
    return _response_from(header, payload)


def _response_from(header: Dict[str, Any], payload: bytes) -> MatchResult:
    outcome = header.get("outcome")
    msg = str(header.get("message", ""))
    if outcome == "overloaded":
        ra = header.get("retry_after_s")
        raise Overloaded(msg or "backend overloaded",
                         reason=str(header.get("reason", "unknown")),
                         retry_after_s=float(ra) if isinstance(
                             ra, (int, float)) else None)
    if outcome == "deadline":
        raise DeadlineExceeded(msg or "deadline expired at the backend",
                               where=str(header.get("where", "backend")))
    if outcome == "quarantined":
        raise RequestQuarantined(
            msg or "backend quarantined the request",
            kind=str(header.get("kind", "unknown")),
            attempts=int(header.get("attempts", 1) or 1))
    if outcome != "result":
        raise WireError(f"unknown wire outcome {outcome!r}")
    if header.get("dtype") != "float32":
        raise WireError(f"result dtype {header.get('dtype')!r} != float32")
    try:
        shape = tuple(int(x) for x in header["table_shape"])
        (sh, sw), (th, tw) = header["bucket"]
        bucket = ((int(sh), int(sw)), (int(th), int(tw)))
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"bad result header: {e}") from e
    n = int(np.prod(shape)) if shape else 0
    if len(payload) != n * 4:
        raise WireError(
            f"result payload {len(payload)} bytes != declared {n * 4}")
    table = np.frombuffer(payload, np.float32).reshape(shape)
    quality = header.get("quality")
    return MatchResult(
        request_id=str(header.get("request", "")),
        table=table,
        quality={str(k): float(v) for k, v in quality.items()}
        if isinstance(quality, dict) else None,
        bucket=bucket,
        wall_s=float(header.get("wall_ms", 0.0)) / 1e3,
    )


# ---------------------------------------------------------------------------
# server side: the /match handler body
# ---------------------------------------------------------------------------


def serve_match(submit: Callable[..., Any], body: bytes, *,
                max_wait_s: float = 600.0,
                stream_submit: Optional[Callable[..., Any]] = None
                ) -> Tuple[int, str, bytes]:
    """Handle one wire request against ``submit`` (a ``MatchService.submit``
    or ``MatchRouter.submit`` — the wire cannot tell tiers apart): decode,
    admit with the propagated budget + client, BLOCK until the terminal
    outcome, encode it.  Returns ``(status, content_type, payload)`` for
    the HTTP handler.  ``max_wait_s`` bounds the wait for budget-less
    requests only — a budgeted request settles by its own deadline (plus a
    small margin for the settle itself).

    A ``stream``-tagged request routes through ``stream_submit``
    (``MatchService.stream_submit``) when the fronted service provides one
    — the per-stream FIFO session that carries temporal priors across
    frames.  A host without a streaming plane (a router) serves the frame
    as an ordinary request: correct, just never coarse-skipped.

    Every response — result or classified rejection — carries the
    clock-sync stamps (:func:`sync_stamps`): ``recv_t`` is taken HERE,
    before the decode, so the stamped serve interval covers everything the
    peer's half-RTT estimate must exclude.
    """
    recv_t = obs_events.wall_now()
    try:
        src, tgt, meta = decode_request(body)
    except WireError as e:
        # deliberate 400 override of the quarantine-shaped body's 500:
        # the frame itself was unserviceable, a caller error
        _, payload = encode_error(RequestQuarantined(
            f"unserviceable wire request: {e}", kind="wire", attempts=1),
            extra=sync_stamps(recv_t))
        return 400, WIRE_CONTENT_TYPE, payload
    budget = meta["budget_s"]
    # the trace rides into the fronted tier as a keyword only when the
    # peer sent one: an untraced request reaches `submit` with the exact
    # pre-trace signature, so wrapped/legacy submits keep working
    tr = {"trace": meta["trace"]} if meta.get("trace") else {}
    try:
        if meta.get("stream") and stream_submit is not None:
            result = stream_submit(
                meta["stream"], src, tgt, deadline_s=budget,
                client=meta["client"], **tr).result
        else:
            fut = submit(src, tgt, deadline_s=budget,
                         client=meta["client"], **tr)
            result = fut.result(
                timeout=(budget + WIRE_SETTLE_MARGIN_S)
                if budget is not None else max_wait_s)
    except TimeoutError:
        # only reachable when the serving side failed to settle within its
        # own budget (or the budget-less cap): answer a classified timeout,
        # never hold the connection forever
        status, payload = encode_error(DeadlineExceeded(
            "request did not settle within the wire wait bound",
            where="wire_wait"), extra=sync_stamps(recv_t))
        return status, WIRE_CONTENT_TYPE, payload
    except (Overloaded, DeadlineExceeded, RequestQuarantined) as e:
        status, payload = encode_error(e, extra=sync_stamps(recv_t))
        return status, WIRE_CONTENT_TYPE, payload
    except Exception as e:  # noqa: BLE001 — the wire stays outcome-total
        status, payload = encode_error(e, extra=sync_stamps(recv_t))
        return status, WIRE_CONTENT_TYPE, payload
    status, payload = encode_result(result, extra=sync_stamps(recv_t))
    return status, WIRE_CONTENT_TYPE, payload


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class MatchClient:
    """One persistent HTTP/1.1 connection to a backend's ``/match``.

    NOT thread-safe — the router pools one client per concurrent attempt
    per backend.  Transport failures (refused, reset, hung socket past the
    timeout) raise their native ``OSError``/``http.client`` exceptions with
    the connection closed, so the next :meth:`match` reconnects; classified
    serving outcomes raise the ``serving/request.py`` exception classes via
    :func:`decode_response`.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        parts = urlsplit(base_url if "//" in base_url
                         else f"http://{base_url}")
        if not parts.hostname or not parts.port:
            raise ValueError(f"backend url needs host:port, got {base_url!r}")
        self.base_url = f"http://{parts.hostname}:{parts.port}"
        self._host = parts.hostname
        self._port = int(parts.port)
        self.timeout_s = float(timeout_s)
        self._conn: Optional[http.client.HTTPConnection] = None
        self._last_sync_t = 0.0  # monotonic; throttles clock_sync events

    def _connection(self, timeout: float) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self._host, self._port, timeout=timeout)
        elif self._conn.sock is not None:
            self._conn.sock.settimeout(timeout)
        else:
            self._conn.timeout = timeout
        return self._conn

    def match(self, src: np.ndarray, tgt: np.ndarray, *,
              client: str = "wire", budget_s: Optional[float] = None,
              request_id: str = "", stream: Optional[str] = None,
              trace: Optional[str] = None,
              timeout_s: Optional[float] = None) -> MatchResult:
        """One wire round trip.  ``timeout_s`` bounds the WHOLE attempt at
        the socket level (send + the backend's serve + the response read) —
        the hung-socket backstop the router relies on to keep a wedged host
        from absorbing its workers.  ``trace`` propagates the caller's
        traceparent header; each round trip also yields one NTP-style
        clock-offset sample against this peer, emitted as a throttled
        ``clock_sync`` event."""
        import time as _time

        from ncnet_tpu.utils import faults

        # the multi-host chaos seam: injected backend death / socket hang
        # without needing a real process to kill (the chaos suite also
        # kills real processes; this hook covers the in-process tests)
        faults.backend_fault_hook(self.base_url, "send")
        body = encode_request(src, tgt, client=client, budget_s=budget_s,
                              request_id=request_id, stream=stream,
                              trace=trace)
        conn = self._connection(timeout_s if timeout_s is not None
                                else self.timeout_s)
        t_send = obs_events.wall_now()
        try:
            conn.request("POST", "/match", body=body,
                         headers={"Content-Type": WIRE_CONTENT_TYPE})
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException, socket.timeout):
            self.close()  # the connection state is unknowable: reconnect
            raise
        t_recv = obs_events.wall_now()
        header, payload = _unframe(data)
        now_m = _time.monotonic()
        if now_m - self._last_sync_t >= CLOCK_SYNC_INTERVAL_S:
            self._last_sync_t = now_m
            emit_clock_sync(self.base_url, header, t_send, t_recv)
        return _response_from(header, payload)

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — closing a dead socket
                pass

    def __enter__(self) -> "MatchClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
