"""Serving health state machine — the probe surface external supervisors see.

States and their meaning for a load balancer / readiness probe:

  * ``STARTING``  — the worker is up but warming (compiling warm buckets).
    Admission IS open (requests queue behind the warmup) but probes should
    not route fresh traffic yet.
  * ``READY``     — serving normally on the preferred tier ladder.
  * ``DEGRADED``  — a runtime device failure demoted a Pallas tier
    (``recover_from_device_failure``), or the replica pool is below full
    strength (a replica is DEAD awaiting resurrection — capacity degraded,
    availability intact); the service is still serving — with zero lost
    requests — but an operator should look.  A tier demotion is sticky
    until the registry is reset; a pure capacity degradation recovers to
    READY once every replica is resurrected (the one DEGRADED → READY
    edge).
  * ``DRAINING``  — SIGTERM (or ``stop()``): admission is closed, admitted
    work is completing.  Probes must stop routing here.
  * ``STOPPED``   — terminal; the worker has exited.

Transitions are monotone along STARTING → READY → DEGRADED and any
non-terminal state may enter DRAINING → STOPPED; anything else is a service
bug and raises.  Every transition is emitted as a ``serve_health`` event so
``tools/run_report.py --serving`` can reconstruct the health timeline of a
dead service from its event log alone.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ncnet_tpu.observability import events as obs_events

# schema version of the UNIFIED health document (build_health_document):
# bump when the nesting or field meanings change, so the multi-host router
# / watchdog / chaos tests scraping /healthz can refuse documents they do
# not understand instead of silently misreading them
HEALTH_DOC_SCHEMA = 1

STARTING = "STARTING"
READY = "READY"
DEGRADED = "DEGRADED"
DRAINING = "DRAINING"
STOPPED = "STOPPED"

_ALLOWED = {
    STARTING: (READY, DEGRADED, DRAINING, STOPPED),
    READY: (DEGRADED, DRAINING, STOPPED),
    # DEGRADED -> READY is the replica-pool recovery edge ONLY: every dead
    # replica resurrected AND no Pallas tier demoted (the service checks
    # both before requesting it).  A tier-demotion DEGRADED stays sticky
    # exactly as before — nothing requests READY while a demotion holds.
    DEGRADED: (READY, DRAINING, STOPPED),
    DRAINING: (STOPPED,),
    STOPPED: (),
}

# states whose admission door is open
ADMITTING = (STARTING, READY, DEGRADED)


class HealthMachine:
    """The service's state cell; mutated only under the service lock.
    ``event`` names the transition event emitted into the log —
    ``serve_health`` for a ``MatchService``, ``route_health`` for the
    multi-host ``MatchRouter`` (same machine, same transition rules, so
    ``run_report`` reconstructs either tier's timeline the same way)."""

    def __init__(self, event: str = "serve_health"):
        self.event = event
        self.state = STARTING
        self.since = time.time()
        self.reason: Optional[str] = None
        self.history: List[Dict[str, Any]] = [
            {"state": STARTING, "t": self.since, "reason": "init"}
        ]

    def to(self, state: str, reason: str = "") -> bool:
        """Transition (emitting the machine's transition event); returns
        False when the machine is already there (idempotent re-entry is not
        an error — DEGRADED may be requested per failed batch)."""
        if state == self.state:
            return False
        if state not in _ALLOWED[self.state]:
            raise RuntimeError(
                f"illegal health transition {self.state} -> {state}"
            )
        self.state = state
        self.since = time.time()
        self.reason = reason or None
        self.history.append(
            {"state": state, "t": self.since, "reason": reason or None})
        obs_events.emit(self.event, state=state, reason=reason or None)
        return True

    @property
    def admitting(self) -> bool:
        return self.state in ADMITTING

    def probe(self, history: int = 8) -> Dict[str, Any]:
        """This machine's section of the unified health document: current
        state + how long it has held + why + the recent transition
        timeline (newest last, bounded so the document stays a probe, not
        a log)."""
        return {
            "state": self.state,
            "since": self.since,
            "age_s": round(max(0.0, time.time() - self.since), 3),
            "reason": self.reason,
            "history": [dict(h) for h in self.history[-history:]],
        }


def build_health_document(machine: HealthMachine,
                          replicas: List[Dict[str, Any]], *,
                          queue: Dict[str, Any],
                          counters: Dict[str, Any],
                          slo: Optional[Dict[str, Any]] = None,
                          activity: Optional[Dict[str, Any]] = None,
                          memory: Optional[Dict[str, Any]] = None,
                          store: Optional[Dict[str, Any]] = None,
                          model_version: Optional[str] = None,
                          rollout: Optional[Dict[str, Any]] = None,
                          streams: Optional[Dict[str, Any]] = None,
                          ) -> Dict[str, Any]:
    """THE one health document (``HEALTH_DOC_SCHEMA``-versioned) — the
    ``/healthz`` body, ``MatchService.health()`` return value, the final
    ``serve_health_doc`` event payload that ``run_report --serving``
    renders, and the dict the future multi-host router will route on.

    Before this builder the service-level probe (``HealthMachine.probe``)
    and the per-replica rows (``Replica.probe``) were merged ad hoc at each
    consumer and drifted independently; now every consumer reads the same
    nesting:

      * ``state`` — the service state, mirrored top-level (the one field a
        load balancer needs without parsing the rest);
      * ``service`` — the health machine's probe (state/age/reason/recent
        transition history);
      * ``pool`` — ``ready``/``total`` capacity + every replica's row
        (``Replica.probe()``: id, state, score, EWMA wall, load, counters);
      * ``queue`` — depth, in-flight batches, pipeline depth, the elastic
        queue bound, and the registered bucket ladder;
      * ``counters`` — the terminal-outcome accounting;
      * ``slo`` — the error-budget tracker's snapshot (when configured);
      * ``activity`` — seconds since the pool last dispatched (or idled
        deliberately): the HTTP-reachable liveness signal
        ``stall_watchdog --url`` judges instead of a heartbeat mtime.
      * ``memory`` — the memory observability section (when the service
        tracks one): the warmed ladder's predicted footprint from the
        compiled-program ledger, per-replica HBM watermarks, and the
        headroom against ``bytes_limit``.
      * ``store`` — the persistent feature store's health
        (``FeatureStore.health()``, when one is attached): OK/DEGRADED
        state + hit/miss/corrupt/evict counters + footprint.  A DEGRADED
        store is an operator signal, NOT a serving outage — the store
        fails open to recompute, so ``stall_watchdog --url`` must (and
        does) treat store-DEGRADED as degraded-but-serving, never stalled.
      * ``model_version`` — the pod's converged model identity (live
        rollout, serving/rollout.py); per-replica versions live in the
        pool rows, so a mid-rollout mixed pod is visible to the router.
      * ``rollout`` — the rollout controller's status while one is
        attached (phase, versions, canary verdict inputs).
      * ``streams`` — the streaming session table (``StreamTable.doc()``,
        serving/stream.py): active/tracked/fallback/cold frame totals,
        mean candidate recall, and per-session rows — the tracked-mode
        counterpart of the request counters.

    ``model_version``/``rollout``/``streams`` are ADDITIVE optional
    fields — schema 1 consumers that predate them simply never read the
    keys.
    """
    ready = sum(1 for r in replicas if r.get("state") == "READY")
    doc: Dict[str, Any] = {
        "schema": HEALTH_DOC_SCHEMA,
        "state": machine.state,
        "service": machine.probe(),
        "pool": {"ready": ready, "total": len(replicas),
                 "replicas": list(replicas)},
        "queue": dict(queue),
        "counters": dict(counters),
    }
    if slo is not None:
        doc["slo"] = slo
    if activity is not None:
        doc["activity"] = activity
    if memory is not None:
        doc["memory"] = memory
    if store is not None:
        doc["store"] = store
    if model_version is not None:
        doc["model_version"] = model_version
    if rollout is not None:
        doc["rollout"] = rollout
    if streams is not None:
        doc["streams"] = streams
    return doc
