"""Stream sessions for tracked (coarse-pass-skipping) video matching.

One :class:`StreamSession` per live video stream: the session owns the
temporal prior pair frame ``t`` seeds its candidates from (inverted from
frame ``t-1``'s served match table, ``ops/temporal.prior_from_table``), the
memoized content digest of the stream's reference image, the quality-EMA
baseline the cut detector compares against, and the per-stream FIFO lock
that serializes the stream's frames through admission and batching (frame
``t`` cannot be built before frame ``t-1``'s table exists — the data
dependence IS the ordering guarantee, and the lock extends it to
multi-threaded callers of one stream id).

:class:`StreamTable` is the service-side registry: bounded, idle-evicted
from the worker tick, drained with the service, and summarized into the
health document's ``streams`` section (which /metrics and /statusz render).

:func:`run_stream_load` is the shared open-loop driver (bench scenario,
``tools/stream_probe.py``, chaos tests): per-stream arrival schedules with
jitter + bursts, frames submitted at their scheduled instants regardless of
completion (open-loop — backpressure shows up as lateness, not as a politely
slowed client), per-frame outcome records for SLO accounting.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ncnet_tpu.serving.request import Bucket, MatchResult

# EMA memory of the per-stream quality baseline (~6 frames, the admission
# batch-wall constant): long enough to ride out one noisy frame, short
# enough that a re-seeded tracker re-baselines within a burst
_QUALITY_EWMA_ALPHA = 0.3


@dataclasses.dataclass
class StreamFrameResult:
    """What :meth:`MatchService.stream_submit` returns for one frame: the
    ordinary :class:`MatchResult` plus the streaming-plane facts the
    open-loop driver and the tests assert on."""

    result: MatchResult
    stream: str
    seq: int
    tracked: bool      # served by the coarse-pass-free tracked program
    fallback: bool     # a cut/drift fallback re-ran the full pipeline
    recall: Optional[float]  # candidate-containment proxy (tracked frames)

    @property
    def table(self) -> np.ndarray:
        return self.result.table


class StreamSession:
    """Per-stream state (see module docstring).  ``lock`` is the stream's
    FIFO: the service holds it for the whole frame round trip, so one
    stream's frames admit, batch, and settle strictly in ``seq`` order
    while other streams proceed concurrently."""

    def __init__(self, stream_id: str):
        self.id = stream_id
        self.lock = threading.Lock()
        self.created_t = time.monotonic()
        self.last_activity = self.created_t
        self.seq = 0
        # temporal prior pair over the session's bucket's coarse grids;
        # None until the first full-pipeline frame seeds the tracker
        self.bucket: Optional[Bucket] = None
        self.prior_ab: Optional[np.ndarray] = None
        self.prior_ba: Optional[np.ndarray] = None
        # memoized reference-image digest (of the PADDED bucket row — the
        # exact bytes the engine's store path would hash), keyed by object
        # identity: a steady stream passes the same reference array every
        # frame, so identity is the zero-cost "unchanged" witness.  A new
        # array object re-hashes (mutating an array in place between
        # frames is a caller error the identity check cannot see).
        self._digest: Optional[str] = None
        self._digest_src_id: Optional[int] = None
        self._digest_bucket: Optional[Bucket] = None
        # quality-EMA baseline for the cut detector
        self.score_ema: Optional[float] = None
        self.coherence_ema: Optional[float] = None
        self.last_recall: Optional[float] = None
        # counters (health/metrics rows)
        self.frames = 0
        self.tracked_frames = 0
        self.fallback_frames = 0
        self.cold_frames = 0
        self.errors = 0

    def src_digest(self, src: np.ndarray, bucket: Bucket,
                   padded_row: Callable[[], np.ndarray]) -> str:
        """The reference image's content digest, hashed at most once per
        (array object, bucket) — the satellite fix for the per-request
        sha256 the store-backed pair path used to pay."""
        if (self._digest is not None and self._digest_src_id == id(src)
                and self._digest_bucket == bucket):
            return self._digest
        from ncnet_tpu.store import content_digest

        self._digest = content_digest(np.ascontiguousarray(padded_row()))
        self._digest_src_id = id(src)
        self._digest_bucket = bucket
        return self._digest

    def note_quality(self, quality: Optional[Dict[str, float]]) -> None:
        if not quality:
            return
        a = _QUALITY_EWMA_ALPHA
        s = quality.get("score")
        if s is not None:
            self.score_ema = s if self.score_ema is None \
                else a * s + (1 - a) * self.score_ema
        c = quality.get("coherence")
        if c is not None:
            self.coherence_ema = c if self.coherence_ema is None \
                else a * c + (1 - a) * self.coherence_ema

    def quality_collapsed(self, quality: Optional[Dict[str, float]],
                          frac: float) -> bool:
        """The PR 7 quality-collapse half of the cut detector: a tracked
        frame whose score OR coherence fell below ``frac`` of the stream's
        EMA baseline stopped matching the scene the tracker believes in.
        No baseline yet (first frames) → never collapsed by this test."""
        if not quality:
            return False
        s, c = quality.get("score"), quality.get("coherence")
        if s is not None and self.score_ema is not None \
                and s < frac * self.score_ema:
            return True
        if c is not None and self.coherence_ema is not None \
                and c < frac * self.coherence_ema:
            return True
        return False

    def reset_tracking(self) -> None:
        """Drop the prior pair (bucket change, eviction re-entry): the next
        frame runs the full pipeline and re-seeds."""
        self.prior_ab = None
        self.prior_ba = None
        self.score_ema = None
        self.coherence_ema = None
        self.last_recall = None

    def row(self, now: float) -> Dict[str, Any]:
        """This session's row in the health document."""
        return {
            "stream": self.id,
            "frames": self.frames,
            "tracked": self.tracked_frames,
            "fallback": self.fallback_frames,
            "cold": self.cold_frames,
            "errors": self.errors,
            "seeded": self.prior_ab is not None,
            "recall": (round(self.last_recall, 4)
                       if self.last_recall is not None else None),
            "idle_s": round(max(0.0, now - self.last_activity), 3),
        }


class StreamTable:
    """Bounded registry of live stream sessions.  Thread-safe; the service
    worker evicts idle sessions on its tick and drains the table at stop.
    Aggregate counters survive their sessions — the Prometheus families
    (``ncnet_serve_stream_*``) are monotone across evictions."""

    def __init__(self, *, max_sessions: int = 64,
                 idle_evict_s: float = 30.0):
        self.max_sessions = max_sessions
        self.idle_evict_s = idle_evict_s
        self._lock = threading.Lock()
        self._sessions: Dict[str, StreamSession] = {}
        # monotone totals (evicted sessions fold in here)
        self.total_frames = 0
        self.total_tracked = 0
        self.total_fallback = 0
        self.total_cold = 0
        self.total_evicted = 0

    def acquire(self, stream_id: str) -> StreamSession:
        """Get-or-create; raises ``Overloaded(reason="stream_cap")`` when
        the table is full and no idle session can make room (an ACTIVE
        session is never evicted to admit a new stream)."""
        from ncnet_tpu.serving.request import Overloaded

        now = time.monotonic()
        with self._lock:
            sess = self._sessions.get(stream_id)
            if sess is not None:
                sess.last_activity = now
                return sess
            if len(self._sessions) >= self.max_sessions:
                victim = self._evict_lru_locked(now)
                if victim is None:
                    raise Overloaded(
                        f"stream table full ({self.max_sessions} live "
                        f"sessions, none idle)", reason="stream_cap")
            sess = StreamSession(stream_id)
            self._sessions[stream_id] = sess
            return sess

    def _fold_locked(self, sess: StreamSession) -> None:
        self.total_evicted += 1

    def _evict_lru_locked(self, now: float) -> Optional[StreamSession]:
        idle = [s for s in self._sessions.values() if not s.lock.locked()]
        if not idle:
            return None
        victim = min(idle, key=lambda s: s.last_activity)
        del self._sessions[victim.id]
        self._fold_locked(victim)
        return victim

    def note_frame(self, kind: str) -> None:
        """Aggregate a terminal frame outcome (``tracked`` / ``fallback`` /
        ``cold``) into the monotone totals."""
        with self._lock:
            self.total_frames += 1
            if kind == "tracked":
                self.total_tracked += 1
            elif kind == "fallback":
                self.total_fallback += 1
            else:
                self.total_cold += 1

    def evict_idle(self, now: Optional[float] = None
                   ) -> List[StreamSession]:
        """Evict sessions idle past the threshold (skipping any whose FIFO
        lock is held — a frame in flight is activity the stamp just hasn't
        recorded yet).  Returns the evicted sessions for event emission."""
        now = time.monotonic() if now is None else now
        out: List[StreamSession] = []
        with self._lock:
            for sid in list(self._sessions):
                s = self._sessions[sid]
                if s.lock.locked():
                    continue
                if now - s.last_activity >= self.idle_evict_s:
                    del self._sessions[sid]
                    self._fold_locked(s)
                    out.append(s)
        return out

    def evict_all(self) -> List[StreamSession]:
        with self._lock:
            out = list(self._sessions.values())
            for s in out:
                self._fold_locked(s)
            self._sessions.clear()
        return out

    def doc(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The health document's ``streams`` section."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rows = [s.row(now) for s in self._sessions.values()]
            recalls = [r["recall"] for r in rows if r["recall"] is not None]
            return {
                "active": len(rows),
                "max_sessions": self.max_sessions,
                "idle_evict_s": self.idle_evict_s,
                "frames": self.total_frames,
                "tracked_frames": self.total_tracked,
                "fallback_frames": self.total_fallback,
                "cold_frames": self.total_cold,
                "evicted": self.total_evicted,
                "recall_mean": (round(float(np.mean(recalls)), 4)
                                if recalls else None),
                "sessions": sorted(rows, key=lambda r: r["stream"]),
            }


# ---------------------------------------------------------------------------
# the shared open-loop streaming driver (bench scenario, stream_probe, tests)
# ---------------------------------------------------------------------------


def stream_schedule(frames: int, rate_hz: float, *, jitter: float = 0.3,
                    burst_every: int = 4, seed: int = 0) -> List[float]:
    """Open-loop arrival offsets (seconds from stream start): a jittered
    base period with every ``burst_every``-th gap collapsed to zero —
    bursty arrivals that stress admission and coalescing the way a real
    camera's frame pacing (vsync drift + transport hiccups) does."""
    rng = np.random.RandomState(seed)
    period = 1.0 / max(rate_hz, 1e-6)
    t, out = 0.0, []
    for i in range(frames):
        out.append(t)
        gap = period * (1.0 + jitter * float(rng.uniform(-1.0, 1.0)))
        if burst_every > 0 and (i + 1) % burst_every == 0:
            gap = 0.0
        t += max(0.0, gap)
    return out


def run_stream_load(
    service, frame_fn: Callable[[int, int], Tuple[np.ndarray, np.ndarray]],
    *, streams: int = 2, frames: int = 8, rate_hz: float = 20.0,
    jitter: float = 0.3, burst_every: int = 4,
    deadline_s: Optional[float] = None, seed: int = 0,
    stream_prefix: str = "cam",
) -> List[Dict[str, Any]]:
    """Drive ``streams`` concurrent open-loop streams of ``frames`` frames
    each through ``service.stream_submit``.

    ``frame_fn(stream_idx, frame_idx)`` supplies each frame's (reference,
    frame) uint8 pair — cut injection is the caller's choice of content.
    Per-stream ordering is structural (each stream thread blocks on its
    frame before the next), and the OPEN loop is preserved across frames
    by scheduling: a frame whose arrival instant has passed while the
    previous frame was in flight submits immediately, and its lateness is
    recorded (``late_ms``) instead of silently re-pacing the client.

    Returns one record per frame: stream, seq, outcome ("result" or the
    classified error name), tracked/fallback flags, recall, wall_ms,
    late_ms — everything the bench extras and the SLO replay assert on.
    """
    from ncnet_tpu.serving.request import ServeError

    records: List[List[Dict[str, Any]]] = [[] for _ in range(streams)]

    def one_stream(si: int) -> None:
        from ncnet_tpu.observability.tracing import new_trace

        sched = stream_schedule(frames, rate_hz, jitter=jitter,
                                burst_every=burst_every, seed=seed + si)
        sid = f"{stream_prefix}{si}"
        # one pod trace per stream: every frame of a session shares the
        # trace id, so the federated view groups a camera's whole life
        trace = new_trace().trace_id
        t0 = time.monotonic()
        for fi in range(frames):
            due = t0 + sched[fi]
            now = time.monotonic()
            if due > now:
                time.sleep(due - now)
            late_ms = round(max(0.0, time.monotonic() - due) * 1e3, 3)
            src, tgt = frame_fn(si, fi)
            t1 = time.monotonic()
            rec: Dict[str, Any] = {"stream": sid, "seq": fi,
                                   "late_ms": late_ms}
            try:
                fr = service.stream_submit(
                    sid, src, tgt, deadline_s=deadline_s,
                    client=f"{stream_prefix}{si}", trace=trace)
                rec.update(outcome="result", tracked=fr.tracked,
                           fallback=fr.fallback, recall=fr.recall,
                           wall_ms=round((time.monotonic() - t1) * 1e3, 3))
            except ServeError as e:
                rec.update(outcome=e.outcome, tracked=False, fallback=False,
                           recall=None,
                           wall_ms=round((time.monotonic() - t1) * 1e3, 3))
            records[si].append(rec)

    threads = [threading.Thread(target=one_stream, args=(i,),
                                name=f"stream-load-{i}", daemon=True)
               for i in range(streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    flat = [r for per in records for r in per]
    # the per-stream ordering invariant, asserted where the records are
    # born: each stream's results appended strictly in seq order
    for per in records:
        seqs = [r["seq"] for r in per]
        assert seqs == sorted(seqs), f"stream records out of order: {seqs}"
    return flat
