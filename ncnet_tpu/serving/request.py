"""Request lifecycle types for the resident match service.

The serving contract (ncnet_tpu/serving/service.py) is outcome-total: every
request presented to :meth:`MatchService.submit` terminates in EXACTLY ONE of
four classified outcomes —

  * ``result``      — the match table (plus per-pair quality signals) came
    back within budget; the future resolves with a :class:`MatchResult`.
  * ``deadline``    — the request's deadline expired (at admission, at
    dequeue before dispatch, or by the time its batch's fetch landed); the
    future raises :class:`DeadlineExceeded` naming where the budget died.
  * ``overloaded``  — admission shed the request (queue full, per-client
    cap, bucket capacity, draining) with a ``retry_after_s`` hint, or an
    aborted shutdown rejected admitted-but-unfinished work; the caller gets
    :class:`Overloaded` with a machine-readable ``reason``.
  * ``quarantined`` — the request failed repeatedly after every recovery
    (tier demotion, retries) was exhausted; :class:`RequestQuarantined`
    carries the classified failure kind, and the request lands in the
    service's quarantine manifest (the PR 3 ``RunManifest`` discipline).

Nothing is ever silently dropped: the chaos suite (tests/test_serving.py)
proves the accounting identity ``admitted == results + deadlines +
quarantines + admitted_sheds`` over the event log, and ``tools/run_report.py
--serving`` recomputes it for any run.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

# the four terminal outcomes; `outcome` on a settled MatchFuture is one of
# these, and the event-log accounting in run_report --serving sums them
TERMINAL_OUTCOMES = ("result", "deadline", "overloaded", "quarantined")

# bucket key: ((src_h, src_w), (tgt_h, tgt_w)) padded shapes — one compiled
# program per key (the bounded jit cache unit)
Bucket = Tuple[Tuple[int, int], Tuple[int, int]]


def bucket_label(bucket: Bucket) -> str:
    """Stable human/metric label for a shape bucket: ``64x64-96x64``."""
    (sh, sw), (th, tw) = bucket
    return f"{sh}x{sw}-{th}x{tw}"


class ServeError(RuntimeError):
    """Base of the classified terminal rejections."""

    outcome: str = "overloaded"


class Overloaded(ServeError):
    """Admission shed the request (or an aborted shutdown rejected it).

    ``reason`` is machine-readable: ``queue_full`` / ``client_cap`` /
    ``bucket_capacity`` / ``unservable_shape`` / ``no_capacity`` (every
    pool replica is DEAD; retry after the resurrection-probe period) /
    ``draining`` / ``stopped`` / ``shutdown`` / ``crashed``.  ``retry_after_s`` (when not None) is the
    service's estimate of when a retry could be admitted — derived from the
    current queue depth and the recent batch wall, so a well-behaved client
    backs off proportionally to actual load instead of hammering.
    """

    outcome = "overloaded"

    def __init__(self, message: str, *, reason: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(ServeError):
    """The request's deadline budget expired.  ``where`` names the check
    that caught it: ``admission`` (already expired when submitted — never
    admitted), ``dequeue`` (evicted from its batch before dispatch), or
    ``fetch`` (the result landed after the caller's budget — discarded, the
    caller has by contract moved on)."""

    outcome = "deadline"

    def __init__(self, message: str, *, where: str):
        super().__init__(message)
        self.where = where


class RequestQuarantined(ServeError):
    """Retries and program-changing recoveries were exhausted for this
    request; the service gave up on it (and recorded it in the quarantine
    manifest) rather than let it wedge the stream."""

    outcome = "quarantined"

    def __init__(self, message: str, *, kind: str, attempts: int):
        super().__init__(message)
        self.kind = kind
        self.attempts = attempts


@dataclasses.dataclass
class MatchResult:
    """One served pair: the match table rows plus the in-graph quality
    signals that rode back in the same device→host pull.

    ``matches`` is the raw ``(5, N)`` float32 table (xA, yA, xB, yB, score —
    the :class:`~ncnet_tpu.ops.matching.Matches` row order; coordinates are
    normalized over the PADDED bucket grid, see the README "Serving"
    section).  ``quality`` maps each
    :data:`~ncnet_tpu.observability.quality.QUALITY_SIGNALS` name to its
    per-pair value (None when the table was too narrow to carry the row).
    """

    request_id: str
    table: np.ndarray
    quality: Optional[Dict[str, float]]
    bucket: Bucket
    wall_s: float

    @property
    def matches(self):
        from ncnet_tpu.ops import Matches

        return Matches(*(self.table[i] for i in range(5)))


class MatchFuture:
    """Thread-safe one-shot result slot for a submitted request.

    ``result(timeout)`` blocks until the request reaches its terminal
    outcome, then returns the :class:`MatchResult` or raises the classified
    terminal error.  ``outcome`` is None until settled, then one of
    :data:`TERMINAL_OUTCOMES`.  Settling twice is a programming error in
    the service and raises — the outcome-total contract means exactly one.
    """

    def __init__(self, request_id: str):
        self.request_id = request_id
        self.outcome: Optional[str] = None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[MatchResult] = None
        self._error: Optional[BaseException] = None

    def _settle(self, outcome: str, *, result: Optional[MatchResult] = None,
                error: Optional[BaseException] = None) -> None:
        if not self._try_settle(outcome, result=result, error=error):
            raise RuntimeError(
                f"request {self.request_id} settled twice "
                f"({self.outcome} then {outcome})"
            )

    def _try_settle(self, outcome: str, *,
                    result: Optional[MatchResult] = None,
                    error: Optional[BaseException] = None) -> bool:
        """Atomically settle if still pending; False when another path won
        the race.  The check-and-set is one critical section because the
        pool's settle paths genuinely race: a bounded-join shutdown
        force-settles a hung fetcher's batch while the late fetch may be
        landing its results — exactly one side must win, and the loser must
        skip its accounting rather than crash."""
        assert outcome in TERMINAL_OUTCOMES
        with self._lock:
            if self.outcome is not None:
                return False
            self._result, self._error = result, error
            self.outcome = outcome
        self._event.set()
        return True

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> MatchResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not settled within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


@dataclasses.dataclass
class MatchRequest:
    """One admitted request moving through the queue/batch/fetch pipeline.
    ``deadline_t`` is an absolute ``time.monotonic`` instant (None = no
    deadline); ``attempts`` counts BUDGETED failures only — recoveries that
    change the program (tier demotion + retrace) retry free, exactly the
    :func:`~ncnet_tpu.evaluation.resilience.run_isolated` discipline."""

    id: str
    client: str
    src: np.ndarray
    tgt: np.ndarray
    bucket: Bucket
    future: MatchFuture
    submitted_t: float
    deadline_t: Optional[float] = None
    attempts: int = 0
    # replica ids this request's batch has already failed on: the router
    # prefers replicas NOT in this set, and a re-route to a fresh replica
    # is off-budget (the failure was the replica's, not the request's);
    # once no fresh READY replica remains, failures charge the budget
    failed_on: set = dataclasses.field(default_factory=set)
    # trace-timeline stamps (time.monotonic, like submitted_t/deadline_t):
    # the service stamps dispatch and fetch-begin so every terminal outcome
    # can attribute its end-to-end wall to queue vs device vs fetch time
    # (a request re-dispatched by failover keeps its LAST stamps — the
    # attribution covers the attempt that terminated it, and the queue
    # segment absorbs the earlier failed round trips)
    dispatched_t: Optional[float] = None
    fetch_begin_t: Optional[float] = None
    # streaming tracked mode (serving/stream.py): requests carrying a
    # session's temporal priors dispatch through the engine's coarse-pass-
    # free tracked program.  The dispatcher keeps batches tracked-
    # homogeneous (a tracked and a plain request cannot share a program),
    # and ``src_digest`` lets the engine skip re-hashing a stream's
    # unchanged reference image.  All None/False for ordinary requests —
    # the plain path is untouched.
    stream: Optional[str] = None
    stream_seq: int = 0
    tracked: bool = False
    prior_ab: Optional[np.ndarray] = None
    prior_ba: Optional[np.ndarray] = None
    src_digest: Optional[str] = None
    # pod-wide trace id (observability/tracing.py::TraceContext) adopted
    # from the wire or the submitting caller: every event this request
    # touches carries it, so the federated pod trace and the pod identity
    # report can join this process's slice of the request to the rest.
    # None for an untraced request — the plain path is untouched.
    trace: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def remaining_s(self, now: float) -> Optional[float]:
        if self.deadline_t is None:
            return None
        return self.deadline_t - now

    def timeline_ms(self, settled_t: float) -> Dict[str, float]:
        """Phase attribution of this request's life, in milliseconds:
        ``queue_ms`` (admission → dispatch: queueing + bucket coalescing),
        ``device_ms`` (dispatch → fetch-begin: in flight on the replica,
        the async device execution overlapping the fetch lane's backlog),
        ``fetch_ms`` (fetch-begin → settle: the blocking device→host pull
        and settlement).  Phases the request never reached are absent
        (a dequeue-evicted deadline has only ``queue_ms``), and
        ``total_ms`` is the SUM of the rendered segments — the identity
        the Perfetto timeline export and the tier-1 chain rely on."""
        segs: Dict[str, float] = {}
        queue_end = self.dispatched_t if self.dispatched_t is not None \
            else settled_t
        segs["queue_ms"] = round(
            max(0.0, queue_end - self.submitted_t) * 1e3, 3)
        if self.dispatched_t is not None:
            dev_end = self.fetch_begin_t \
                if self.fetch_begin_t is not None else settled_t
            segs["device_ms"] = round(
                max(0.0, dev_end - self.dispatched_t) * 1e3, 3)
            if self.fetch_begin_t is not None:
                segs["fetch_ms"] = round(
                    max(0.0, settled_t - self.fetch_begin_t) * 1e3, 3)
        segs["total_ms"] = round(sum(segs.values()), 3)
        return segs


def as_pair_image(x: Any, name: str) -> np.ndarray:
    """Validate/normalize one side of a pair to ``(H, W, 3)`` uint8 — the
    serving wire shape.  A leading batch-1 axis (the demo/matcher shape
    ``(1, H, W, 3)``) is squeezed; anything else is a caller error, rejected
    synchronously at submit rather than poisoning a batch."""
    arr = np.asarray(x)
    if arr.ndim == 4 and arr.shape[0] == 1:
        arr = arr[0]
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise ValueError(
            f"{name} must be (H, W, 3) or (1, H, W, 3) uint8, got "
            f"{arr.shape}"
        )
    if arr.dtype != np.uint8:
        raise ValueError(f"{name} must be uint8 (raw image bytes; the "
                         f"service normalizes on device), got {arr.dtype}")
    return arr
