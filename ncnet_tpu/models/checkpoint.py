"""Checkpoint I/O: native orbax checkpoints + a one-way torch importer.

Native format: a directory containing ``config.json`` (the full
:class:`ModelConfig` — the TPU-native analog of the reference smuggling its
argparse Namespace inside the pickle, /root/reference/lib/model.py:215-220)
and an orbax pytree of params (plus opt_state/step for training state, see
``ncnet_tpu.training``).

Versioned training roots: ``fit`` writes *versioned* checkpoints — a root
directory holding ``step_<N>`` subdirectories, each a complete native
checkpoint as above.  A version is written to ``step_<N>.tmp`` and committed
by a single atomic rename, so a directory matching ``step_<N>`` (no ``.tmp``
suffix) with a ``config.json`` inside IS the completeness marker; anything
still carrying ``.tmp`` is a crashed save and is ignored (and reclaimed by
the next writer).  :func:`resolve_checkpoint_dir` maps either layout — a
version root, a single version, a ``best_`` copy, or a legacy flat
checkpoint — onto the concrete directory to read, so every loader
(:func:`load_params`, ``training.load_train_checkpoint``, eval/finetune
``--checkpoint``) accepts any of them interchangeably.

Torch importer: reads the reference's ``.pth.tar`` pickles
(``{epoch, args, state_dict, ...}``, /root/reference/train.py:197-205) and
converts weights into our pytrees — needed to reproduce paper numbers from
the released ``ncnet_pfpascal.pth.tar`` / ``ncnet_ivd.pth.tar`` without
retraining.  Mirrors the reference's own load-time quirks: the ``'vgg'→
'model'`` key rename and arch-hyperparam override from stored args
(model.py:211-220); ``num_batches_tracked`` buffers are ignored
(model.py:244-248).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models import backbone as bb

# reference FeatureExtraction wraps the trunk in nn.Sequential, so resnet
# children are addressed by index (model.py:38-44): 0=conv1 1=bn1 2=relu
# 3=maxpool 4=layer1 5=layer2 6=layer3.
_RESNET_SEQ_TO_NAME = {
    "0": "conv1", "1": "bn1", "4": "layer1", "5": "layer2", "6": "layer3",
    "7": "layer4",  # checkpoints trained with feature_extraction_last_layer='layer4'
}

# fields that describe the trained network (restored from checkpoints); all
# other ModelConfig fields are runtime flags owned by the caller.
_ARCH_FIELDS = (
    "backbone",
    "backbone_last_layer",
    "ncons_kernel_sizes",
    "ncons_channels",
    "symmetric_mode",
    "normalize_features",
)


def _to_np(v) -> np.ndarray:
    return v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)


def split_reference_state_dict(state_dict, config: ModelConfig):
    """Rekey + split a reference state_dict into framework-layout views.

    Applies the load-time quirks the reference itself applies — the legacy
    ``'vgg'→'model'`` key rename (model.py:225-232) and the
    ``num_batches_tracked`` filter (model.py:244-248) — then splits into:

      * ``fe_sd``: trunk weights keyed by torchvision names (numpy), and
      * ``nc_raw``: per-NC-layer ``(weight, bias)`` numpy pairs in the
        STORED Conv4d layout ``(kA, C_out, C_in, kWA, kB, kWB)``
        (/root/reference/lib/conv4d.py:72-77).

    The one parsing used BOTH by the production importer and by the
    torch-twin activation check (tools/parity_kit.py) — a loader quirk
    added here is automatically exercised by the parity runbook.
    """
    sd = {k.replace("vgg", "model"): _to_np(v) for k, v in state_dict.items()}
    fe_sd = {}
    for k, v in sd.items():
        if not k.startswith("FeatureExtraction.model."):
            continue
        rest = k[len("FeatureExtraction.model."):]
        if "num_batches_tracked" in rest:
            continue
        if config.backbone == "resnet101":
            idx, _, tail = rest.partition(".")
            name = _RESNET_SEQ_TO_NAME.get(idx)
            if name is None:
                raise KeyError(f"unexpected trunk child index {idx} in {k}")
            fe_sd[f"{name}.{tail}"] = v
        else:
            fe_sd[rest] = v
    # Sequential [Conv4d, ReLU]×N → conv layers at indices 0, 2, 4, ...
    nc_raw = [
        (sd[f"NeighConsensus.conv.{2 * j}.weight"],
         sd[f"NeighConsensus.conv.{2 * j}.bias"])
        for j in range(len(config.ncons_kernel_sizes))
    ]
    return fe_sd, nc_raw


def import_torch_checkpoint(
    ckpt: Any, base_config: ModelConfig = ModelConfig()
) -> Tuple[ModelConfig, Dict[str, Any]]:
    """Convert a reference ``.pth.tar`` checkpoint (path or loaded dict).

    Returns ``(config, params)`` with arch hyperparams overridden from the
    checkpoint's stored args, like the reference does.
    """
    if isinstance(ckpt, (str, os.PathLike)):
        import torch

        ckpt = torch.load(ckpt, map_location="cpu", weights_only=False)

    config = base_config
    args = ckpt.get("args")
    if args is not None:
        config = config.replace(
            ncons_kernel_sizes=tuple(getattr(args, "ncons_kernel_sizes", config.ncons_kernel_sizes)),
            ncons_channels=tuple(getattr(args, "ncons_channels", config.ncons_channels)),
        )
        fe = getattr(args, "feature_extraction_cnn", None)
        if fe:
            config = config.replace(backbone=fe)
        fe_last = getattr(args, "feature_extraction_last_layer", None)
        if fe_last:
            config = config.replace(backbone_last_layer=fe_last)

    fe_sd, nc_raw = split_reference_state_dict(ckpt["state_dict"], config)
    backbone_params = bb.import_torch_backbone(
        fe_sd, config.backbone, last_layer=config.backbone_last_layer
    )
    # stored Conv4d layout (kA, C_out, C_in, kWA, kB, kWB) → ours
    # (kA, kWA, kB, kWB, C_in, C_out)
    nc = [
        {"w": jnp.asarray(np.transpose(w, (0, 3, 4, 5, 2, 1))),
         "b": jnp.asarray(b)}
        for w, b in nc_raw
    ]

    return config, {"backbone": backbone_params, "nc": nc}


# ---------------------------------------------------------------------------
# versioned checkpoint roots (atomic step_<N> layout; see module docstring)
# ---------------------------------------------------------------------------

_VERSION_RE = re.compile(r"^step_(\d+)$")


def checkpoint_version_name(step: int) -> str:
    """``step_<N>`` zero-padded so lexicographic order == numeric order."""
    return f"step_{step:08d}"


def list_checkpoint_versions(root: str) -> List[Tuple[int, str]]:
    """Complete ``step_<N>`` versions under ``root``, ascending by step.

    Complete = the directory name carries no ``.tmp`` suffix (the atomic
    rename IS the commit) *and* ``config.json`` exists inside (belt and
    braces against hand-made empty directories).  A ``step_<N>.old``
    directory — the displaced original of a same-step re-save — stands in
    for version N when the replacement's commit never happened (a crash
    between the two renames): it IS a previously committed version, and
    refusing it would strand the run.  Returns ``[]`` when ``root`` is not
    a directory or holds no versions.
    """
    if not os.path.isdir(root):
        return []
    out, displaced = {}, {}
    for name in os.listdir(root):
        base, old = (name[:-4], True) if name.endswith(".old") else (name, False)
        m = _VERSION_RE.match(base)
        path = os.path.join(root, name)
        if not (m and os.path.isdir(path)
                and os.path.isfile(os.path.join(path, "config.json"))):
            continue
        (displaced if old else out)[int(m.group(1))] = path
    for n, path in displaced.items():
        out.setdefault(n, path)  # recovered only when step_<N> is absent
    return sorted(out.items())


def resolve_checkpoint_dir(path: str) -> str:
    """Map any checkpoint-directory spelling onto the directory to read.

    A versioned root resolves to its newest *complete* version; anything
    else (a single version dir, a ``best_`` copy, a legacy flat checkpoint)
    resolves to itself.  Raises if ``path`` holds only ``.tmp`` carcasses —
    every save crashed and there is nothing safe to load.
    """
    path = os.path.abspath(path)
    versions = list_checkpoint_versions(path)
    if versions:
        return versions[-1][1]
    if os.path.isdir(path) and not os.path.isfile(os.path.join(path, "config.json")):
        if any(n.endswith(".tmp") and _VERSION_RE.match(n[:-4])
               for n in os.listdir(path)):
            raise FileNotFoundError(
                f"checkpoint root {path!r} holds only incomplete .tmp "
                "versions (every save crashed mid-write); nothing to load"
            )
    return path


def owning_checkpoint_root(path: str) -> str | None:
    """The versioned root that owns ``path``, or None.

    ``fit`` uses this to continue writing versions *in place* when resumed
    from its own output (a root, or a version directory inside one) rather
    than forking a fresh timestamped root per restart.
    """
    path = os.path.abspath(path)
    if list_checkpoint_versions(path):
        return path
    base = os.path.basename(path)
    if base.endswith(".old"):  # a crash-recovered displaced version
        base = base[:-4]
    if _VERSION_RE.match(base):
        parent = os.path.dirname(path)
        if list_checkpoint_versions(parent):
            return parent
    return None


def with_io_retries(
    fn: Callable[[], Any],
    attempts: int = 3,
    backoff: float = 0.5,
    what: str = "checkpoint I/O",
) -> Any:
    """Run ``fn`` with bounded retry + exponential backoff.

    For transient filesystem/orbax failures (GCS hiccups, NFS timeouts).
    Multi-process: retries are forced OFF (one attempt) — a single host
    re-entering a *collective* orbax save while the others have moved on
    deadlocks the job, so distributed saves fail fast and the job-level
    restart (which re-enters collectively) is the retry.
    """
    import jax

    if jax.process_count() > 1:
        attempts = 1
    last: Exception | None = None
    for i in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — orbax raises heterogeneous types
            last = e
            if i + 1 < max(attempts, 1):
                delay = backoff * (2 ** i)
                from ncnet_tpu.observability import events as obs_events
                from ncnet_tpu.observability import get_logger

                get_logger("checkpoint").warning(
                    f"[fault-tolerance] {what} failed "
                    f"(attempt {i + 1}/{attempts}): {e}; retrying in "
                    f"{delay:.1f}s", kind="io")
                obs_events.emit("io_retry", what=what, attempt=i + 1,
                                attempts=attempts, error=str(e)[:300])
                time.sleep(delay)
    raise last  # type: ignore[misc]


# ---------------------------------------------------------------------------
# payload integrity (the commit-metadata sha256 the live rollout trusts)
# ---------------------------------------------------------------------------

# config.json key carrying the params-payload digest.  Underscore-prefixed
# like the _train/_epoch metadata keys: load_params picks _ARCH_FIELDS only,
# so every existing reader skips it.
PAYLOAD_SHA_KEY = "_payload_sha256"


class CheckpointPayloadError(RuntimeError):
    """Loaded checkpoint params do not match the payload sha256 recorded in
    the commit metadata — bit rot, a torn copy, or tampering.  Raised by
    :func:`verify_checkpoint_payload` so consumers (the live rollout's
    staging gate, ``fit --resume``) can refuse the checkpoint instead of
    serving or training on silently-wrong weights."""


def params_payload_sha256(params) -> str:
    """Full sha256 over every param leaf's dtype/shape/bytes in pytree
    order — the payload identity recorded at commit and re-derived at load.
    Same hashing discipline as the feature store's ``weights_digest`` but
    over the WHOLE tree (NC filter included: a rollout candidate is the
    complete model) and untruncated (this digest gates trust, not cache
    addressing)."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype.str).encode())
        h.update(str(tuple(a.shape)).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def verify_checkpoint_payload(path: str, params) -> Optional[str]:
    """Check loaded ``params`` against the sha256 the checkpoint's commit
    metadata recorded.  Returns the verified digest; ``None`` when the
    checkpoint predates payload metadata (legacy: nothing to verify
    against, the caller decides whether that is acceptable).  Raises
    :class:`CheckpointPayloadError` on mismatch — deserialization that
    *succeeds* on rotten bytes is exactly the failure this closes."""
    cfg_path = os.path.join(resolve_checkpoint_dir(path), "config.json")
    try:
        with open(cfg_path) as f:
            expect = json.load(f).get(PAYLOAD_SHA_KEY)
    except (OSError, ValueError):
        return None
    if not expect:
        return None
    got = params_payload_sha256(params)
    if got != expect:
        raise CheckpointPayloadError(
            f"checkpoint {path!r} payload sha256 mismatch: config.json "
            f"records {expect[:16]}..., loaded params hash to "
            f"{got[:16]}... — refusing the corrupt/torn payload")
    return got


# ---------------------------------------------------------------------------
# native (orbax) checkpoints
# ---------------------------------------------------------------------------


def save_params(path: str, config: ModelConfig, params) -> None:
    """Save ``{config.json, params/}`` under ``path`` (orbax pytree).  The
    commit metadata records the payload sha256 so later loaders
    (:func:`verify_checkpoint_payload` — the rollout staging gate) can
    refuse a bit-rotted directory instead of trusting whatever orbax
    happens to deserialize."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    doc = dataclasses.asdict(config)
    doc[PAYLOAD_SHA_KEY] = params_payload_sha256(params)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(doc, f, indent=2, default=list)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)
    ckptr.wait_until_finished()


def load_params(path: str, base_config: ModelConfig = ModelConfig()):
    """Load a checkpoint from either format.

    ``path`` may be a torch ``.pth.tar`` file (reference format), a native
    orbax directory written by :func:`save_params`, or a versioned training
    root / ``step_<N>`` version written by ``training.fit`` (resolved to the
    newest complete version via :func:`resolve_checkpoint_dir`).
    Returns ``(config, params)``.
    """
    if os.path.isfile(path):
        return import_torch_checkpoint(path, base_config)
    import orbax.checkpoint as ocp

    path = resolve_checkpoint_dir(path)
    with open(os.path.join(path, "config.json")) as f:
        cfg_dict = json.load(f)
    for key in ("ncons_kernel_sizes", "ncons_channels"):
        cfg_dict[key] = tuple(cfg_dict[key])
    # same policy as the torch path (and the reference, model.py:215-220):
    # architecture comes from the checkpoint, runtime flags (half_precision,
    # relocalization_k_size, backbone_bf16, ...) from the caller's config.
    config = base_config.replace(
        **{k: cfg_dict[k] for k in _ARCH_FIELDS if k in cfg_dict}
    )
    ckptr = ocp.StandardCheckpointer()
    params = with_io_retries(
        lambda: ckptr.restore(os.path.join(path, "params")),
        what=f"restore of {path}",
    )
    return config, params
