"""Feature-extraction backbones (functional, NHWC, frozen-BN).

Reference: ``FeatureExtraction`` (/root/reference/lib/model.py:19-87) wraps a
*pretrained, frozen* torchvision trunk — ResNet-101 cut after ``layer3``
(model.py:38-44, the default) or VGG-16 cut after ``pool4`` (model.py:24-35) —
always run in eval mode (model.py:251), optionally with the last few blocks
unfrozen for finetuning (train.py:60-63).  The ``resnet101fpn`` variant is dead
code upstream (undefined ``fpn_body``, model.py:61) and is not carried forward.

TPU-first design decisions:
  * plain pytree params + pure apply functions — no framework Module needed for
    a frozen trunk, and ``jax.grad`` flows through the pytree when finetuning;
  * NHWC layout end-to-end (MXU-native), vs. the reference's NCHW;
  * BatchNorm is *inference-only by construction*: stored as raw
    ``(scale, bias, mean, var)`` for checkpoint fidelity, applied as a folded
    affine — matching eval-mode semantics of the always-frozen reference BN;
  * a ``tiny`` backbone (2 strided convs) for fast tests and dry-runs.

``import_torch_backbone`` converts a torchvision-style ``state_dict`` (as
numpy arrays) into these pytrees, for golden parity with released checkpoints.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BN_EPS = 1e-5

# torchvision resnet101: blocks per stage.  The reference default cut is
# layer3 / stride 16 (model.py:38-44) but its FeatureExtraction accepts any
# stage up to layer4, so all four are constructible here.
RESNET101_STAGES = {"layer1": 3, "layer2": 4, "layer3": 23, "layer4": 3}
RESNET101_PLANES = {"layer1": 64, "layer2": 128, "layer3": 256, "layer4": 512}


def _resnet_stages(last_layer: str):
    """Stages up to the cut point; '' means the reference default 'layer3'."""
    last = last_layer or "layer3"
    if last not in RESNET101_STAGES:
        raise ValueError(
            f"unsupported resnet101 cut {last!r}; have {list(RESNET101_STAGES)}"
        )
    names = list(RESNET101_STAGES)
    return names[: names.index(last) + 1]


def _vgg_units(last_layer: str):
    """Unit ops (('conv', i) | ('relu',) | ('pool',)) up to the cut, inclusive.

    Names follow the reference's vgg_feature_layers (model.py:26-31):
    'convN_M' / 'reluN_M' / 'poolN', default cut 'pool4'.  A cut at a conv
    name ends on the RAW conv output (no trailing ReLU), exactly like the
    reference's Sequential slice.
    """
    last = last_layer or "pool4"
    units, names = [], []
    block, c, ci = 1, 0, 0
    for cout in VGG16_PLAN:
        if cout == -1:
            units.append(("pool",))
            names.append(f"pool{block}")
            block += 1
            c = 0
        else:
            c += 1
            units.append(("conv", ci))
            names.append(f"conv{block}_{c}")
            units.append(("relu",))
            names.append(f"relu{block}_{c}")
            ci += 1
    if last not in names:
        raise ValueError(f"unsupported vgg cut {last!r}; have {names}")
    return units[: names.index(last) + 1]


def _vgg_num_convs(last_layer: str) -> int:
    return sum(1 for u in _vgg_units(last_layer) if u[0] == "conv")

# VGG-16 `features` sequence through pool5: channel plan per conv layer,
# '-1' marks a maxpool.  The reference default cut is pool4 (model.py:24-35).
VGG16_PLAN = (
    64, 64, -1, 128, 128, -1, 256, 256, 256, -1,
    512, 512, 512, -1, 512, 512, 512, -1,
)

# DenseNet-201 (reference cut: features[:-4] ⇒ conv0..transition2 inclusive,
# /root/reference/lib/model.py:69-74): growth 32, bn_size 4; only the first
# two dense blocks fall inside the cut.
DENSENET201_BLOCKS = {"denseblock1": 6, "denseblock2": 12}
DENSENET_GROWTH = 32
DENSENET_BN_SIZE = 4

OUTPUT_CHANNELS = {"resnet101": 1024, "vgg": 512, "tiny": 32, "densenet201": 256}
OUTPUT_STRIDE = {"resnet101": 16, "vgg": 16, "tiny": 16, "densenet201": 16}


# ---------------------------------------------------------------------------
# primitive appliers
# ---------------------------------------------------------------------------


def _conv(x, w, stride=1, padding=0):
    """NHWC conv with HWIO weights, torch-style explicit symmetric padding."""
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p):
    """Eval-mode batch norm from stored running stats (torch eps=1e-5)."""
    inv = p["scale"] * lax.rsqrt(p["var"] + BN_EPS)
    return x * inv + (p["bias"] - p["mean"] * inv)


def _maxpool(x, window=3, stride=2, padding=1):
    """torch MaxPool2d semantics (pads with -inf)."""
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (padding, padding), (padding, padding), (0, 0)),
    )


def _avgpool2(x):
    """torch AvgPool2d(2, 2) (the DenseNet transition pool)."""
    return lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="VALID",
    ) / 4.0


# ---------------------------------------------------------------------------
# initializers
# ---------------------------------------------------------------------------


def _he_conv(key, kh, kw, cin, cout, dtype):
    fan_out = kh * kw * cout
    std = math.sqrt(2.0 / fan_out)
    return std * jax.random.normal(key, (kh, kw, cin, cout), dtype)


def _bn_init(c, dtype):
    return {
        "scale": jnp.ones((c,), dtype),
        "bias": jnp.zeros((c,), dtype),
        "mean": jnp.zeros((c,), dtype),
        "var": jnp.ones((c,), dtype),
    }


def init_resnet101(key: jax.Array, dtype=jnp.float32, last_layer: str = "") -> Dict[str, Any]:
    """Random-init ResNet-101 trunk (conv1..``last_layer``), torchvision layout."""
    keys = iter(jax.random.split(key, 256))
    params: Dict[str, Any] = {
        "conv1": {"w": _he_conv(next(keys), 7, 7, 3, 64, dtype)},
        "bn1": _bn_init(64, dtype),
    }
    inplanes = 64
    for stage in _resnet_stages(last_layer):
        nblocks = RESNET101_STAGES[stage]
        planes = RESNET101_PLANES[stage]
        stride = 1 if stage == "layer1" else 2
        blocks = []
        for i in range(nblocks):
            s = stride if i == 0 else 1
            blk = {
                "conv1": {"w": _he_conv(next(keys), 1, 1, inplanes, planes, dtype)},
                "bn1": _bn_init(planes, dtype),
                "conv2": {"w": _he_conv(next(keys), 3, 3, planes, planes, dtype)},
                "bn2": _bn_init(planes, dtype),
                "conv3": {"w": _he_conv(next(keys), 1, 1, planes, planes * 4, dtype)},
                "bn3": _bn_init(planes * 4, dtype),
            }
            if i == 0:
                blk["downsample"] = {
                    "conv": {"w": _he_conv(next(keys), 1, 1, inplanes, planes * 4, dtype)},
                    "bn": _bn_init(planes * 4, dtype),
                }
                inplanes = planes * 4
            blocks.append(blk)
        params[stage] = blocks
    return params


def init_vgg16(key: jax.Array, dtype=jnp.float32, last_layer: str = "") -> Dict[str, Any]:
    """Random-init VGG-16 features up to ``last_layer`` (convs carry biases)."""
    keys = iter(jax.random.split(key, 32))
    convs = []
    cin = 3
    plan = [c for c in VGG16_PLAN if c != -1][: _vgg_num_convs(last_layer)]
    for cout in plan:
        convs.append(
            {
                "w": _he_conv(next(keys), 3, 3, cin, cout, dtype),
                "b": jnp.zeros((cout,), dtype),
            }
        )
        cin = cout
    return {"convs": convs}


def _densenet_channel_plan():
    """Yields (block_name, n_layers, c_in_of_block) under the reference cut;
    transitions halve channels."""
    c = 64
    plan = []
    for name, n in DENSENET201_BLOCKS.items():
        plan.append((name, n, c))
        c = (c + n * DENSENET_GROWTH) // 2  # transition conv halves
    return plan, c


def init_densenet201(
    key: jax.Array, dtype=jnp.float32, last_layer: str = ""
) -> Dict[str, Any]:
    """Random-init DenseNet-201 trunk (conv0..transition2, torchvision
    layout).  ``last_layer`` must be '' or 'transition2' — the reference
    offers no other cut (model.py:69-74)."""
    if last_layer not in ("", "transition2"):
        raise ValueError(
            f"unsupported densenet201 cut {last_layer!r}; only 'transition2'"
        )
    keys = iter(jax.random.split(key, 64))
    params: Dict[str, Any] = {
        "conv0": {"w": _he_conv(next(keys), 7, 7, 3, 64, dtype)},
        "norm0": _bn_init(64, dtype),
    }
    plan, _ = _densenet_channel_plan()
    for bi, (name, n_layers, c) in enumerate(plan, start=1):
        layers = []
        for _i in range(n_layers):
            mid = DENSENET_BN_SIZE * DENSENET_GROWTH
            layers.append({
                "norm1": _bn_init(c, dtype),
                "conv1": {"w": _he_conv(next(keys), 1, 1, c, mid, dtype)},
                "norm2": _bn_init(mid, dtype),
                "conv2": {"w": _he_conv(next(keys), 3, 3, mid, DENSENET_GROWTH, dtype)},
            })
            c += DENSENET_GROWTH
        params[name] = layers
        params[f"transition{bi}"] = {
            "norm": _bn_init(c, dtype),
            "conv": {"w": _he_conv(next(keys), 1, 1, c, c // 2, dtype)},
        }
    return params


def init_tiny(key: jax.Array, dtype=jnp.float32, last_layer: str = "") -> Dict[str, Any]:
    """Tiny 2-conv stride-16 trunk for tests/dry-runs (no reference analog)."""
    k1, k2 = jax.random.split(key)
    return {
        "conv1": {"w": _he_conv(k1, 5, 5, 3, 16, dtype), "b": jnp.zeros((16,), dtype)},
        "conv2": {"w": _he_conv(k2, 5, 5, 16, 32, dtype), "b": jnp.zeros((32,), dtype)},
    }


# ---------------------------------------------------------------------------
# forward passes
# ---------------------------------------------------------------------------


def _bottleneck(x, blk, stride):
    """torchvision Bottleneck (stride on the 3x3 conv)."""
    out = jax.nn.relu(_bn(_conv(x, blk["conv1"]["w"]), blk["bn1"]))
    out = jax.nn.relu(_bn(_conv(out, blk["conv2"]["w"], stride=stride, padding=1), blk["bn2"]))
    out = _bn(_conv(out, blk["conv3"]["w"]), blk["bn3"])
    if "downsample" in blk:
        x = _bn(_conv(x, blk["downsample"]["conv"]["w"], stride=stride), blk["downsample"]["bn"])
    return jax.nn.relu(out + x)


def resnet101_features(
    params: Dict[str, Any], images: jnp.ndarray, last_layer: str = ""
) -> jnp.ndarray:
    """``(B, H, W, 3)`` → ``(B, H/16, W/16, 1024)`` at the default layer3 cut."""
    x = jax.nn.relu(_bn(_conv(images, params["conv1"]["w"], stride=2, padding=3), params["bn1"]))
    x = _maxpool(x)
    for stage in _resnet_stages(last_layer):
        stride = 1 if stage == "layer1" else 2
        for i, blk in enumerate(params[stage]):
            x = _bottleneck(x, blk, stride if i == 0 else 1)
    return x


def vgg16_features(
    params: Dict[str, Any], images: jnp.ndarray, last_layer: str = ""
) -> jnp.ndarray:
    """``(B, H, W, 3)`` → ``(B, H/16, W/16, 512)`` at the default pool4 cut."""
    x = images
    for unit in _vgg_units(last_layer):
        if unit[0] == "pool":
            x = _maxpool(x, window=2, stride=2, padding=0)
        elif unit[0] == "conv":
            c = params["convs"][unit[1]]
            x = _conv(x, c["w"], padding=1) + c["b"]
        else:
            x = jax.nn.relu(x)
    return x


def densenet201_features(
    params: Dict[str, Any], images: jnp.ndarray, last_layer: str = ""
) -> jnp.ndarray:
    """``(B, H, W, 3)`` → ``(B, H/16, W/16, 256)`` at the reference's
    transition2 cut (torchvision DenseNet: each dense layer concatenates its
    32 new features onto the running stack)."""
    x = jax.nn.relu(
        _bn(_conv(images, params["conv0"]["w"], stride=2, padding=3), params["norm0"])
    )
    x = _maxpool(x)
    for bi, name in enumerate(DENSENET201_BLOCKS, start=1):
        for layer in params[name]:
            y = jax.nn.relu(_bn(x, layer["norm1"]))
            y = _conv(y, layer["conv1"]["w"])
            y = jax.nn.relu(_bn(y, layer["norm2"]))
            y = _conv(y, layer["conv2"]["w"], padding=1)
            x = jnp.concatenate([x, y], axis=-1)
        tr = params[f"transition{bi}"]
        x = _avgpool2(_conv(jax.nn.relu(_bn(x, tr["norm"])), tr["conv"]["w"]))
    return x


def tiny_features(
    params: Dict[str, Any], images: jnp.ndarray, last_layer: str = ""
) -> jnp.ndarray:
    x = jax.nn.relu(_conv(images, params["conv1"]["w"], stride=4, padding=2) + params["conv1"]["b"])
    return jax.nn.relu(_conv(x, params["conv2"]["w"], stride=4, padding=2) + params["conv2"]["b"])


_INITS = {
    "resnet101": init_resnet101,
    "vgg": init_vgg16,
    "tiny": init_tiny,
    "densenet201": init_densenet201,
}
_APPLYS = {
    "resnet101": resnet101_features,
    "vgg": vgg16_features,
    "tiny": tiny_features,
    "densenet201": densenet201_features,
}


def backbone_init(name: str, key: jax.Array, dtype=jnp.float32, last_layer: str = ""):
    if name not in _INITS:
        raise ValueError(f"unknown backbone {name!r}; have {sorted(_INITS)}")
    return _INITS[name](key, dtype, last_layer)


def backbone_apply(name: str, params, images: jnp.ndarray, last_layer: str = "") -> jnp.ndarray:
    if name not in _APPLYS:
        raise ValueError(f"unknown backbone {name!r}; have {sorted(_APPLYS)}")
    return _APPLYS[name](params, images, last_layer)


# ---------------------------------------------------------------------------
# finetune partitioning (reference train.py:60-63 semantics)
# ---------------------------------------------------------------------------


def finetune_labels(name: str, params, n_finetune_blocks: int):
    """Pytree of {'frozen','trainable'} labels for optax.multi_transform.

    The reference unfreezes the *last* ``fe_finetune_params`` child modules of
    the trunk (train.py:60-63 iterates reversed ``model.FeatureExtraction``
    children) — but only ``.parameters()``: BatchNorm running stats are
    buffers and stay frozen even in finetuned blocks.  Here the unit is a
    residual block (resnet) / conv layer (vgg).
    """

    def _unfreeze(subtree):
        from ncnet_tpu.utils.compat import tree_map_with_path

        # conv weights + BN affine train; BN running stats never do.
        return tree_map_with_path(
            lambda path, _: "frozen"
            if any(getattr(k, "key", None) in ("mean", "var") for k in path)
            else "trainable",
            subtree,
        )

    if name not in _APPLYS:
        raise ValueError(f"unknown backbone {name!r}; have {sorted(_APPLYS)}")
    labels = jax.tree.map(lambda _: "frozen", params)
    if n_finetune_blocks <= 0:
        return labels
    if name == "resnet101":
        flat_blocks = [
            (s, i) for s in RESNET101_STAGES if s in params for i in range(len(params[s]))
        ]
        for s, i in flat_blocks[-n_finetune_blocks:]:
            labels[s][i] = _unfreeze(labels[s][i])
    elif name == "vgg":
        for i in range(len(params["convs"]))[-n_finetune_blocks:]:
            labels["convs"][i] = _unfreeze(labels["convs"][i])
    elif name == "densenet201":
        # deepest-last unit order: transition2, then denseblock2's layers
        # (the reference's model[-1][-(i+1)] indexes sub-children of the last
        # Sequential child; the dense-layer granularity is the useful analog)
        units = [("transition2", None)] + [
            ("denseblock2", i)
            for i in reversed(range(len(params["denseblock2"])))
        ]
        for name_, i in units[:n_finetune_blocks]:
            if i is None:
                labels[name_] = _unfreeze(labels[name_])
            else:
                labels[name_][i] = _unfreeze(labels[name_][i])
    else:  # tiny: the whole (non-pretrained) trunk trains
        labels = _unfreeze(params)
    return labels


# ---------------------------------------------------------------------------
# torch state_dict import
# ---------------------------------------------------------------------------


def _t2j_conv(w: np.ndarray) -> jnp.ndarray:
    """torch conv weight (O, I, kH, kW) → HWIO."""
    return jnp.asarray(np.transpose(w, (2, 3, 1, 0)))


def _t2j_bn(sd, prefix) -> Dict[str, jnp.ndarray]:
    return {
        "scale": jnp.asarray(sd[prefix + ".weight"]),
        "bias": jnp.asarray(sd[prefix + ".bias"]),
        "mean": jnp.asarray(sd[prefix + ".running_mean"]),
        "var": jnp.asarray(sd[prefix + ".running_var"]),
    }


def import_torch_backbone(
    state_dict, name: str = "resnet101", prefix: str = "", last_layer: str = ""
):
    """Convert a torchvision-style ``state_dict`` into a backbone pytree.

    Accepts the key naming of torchvision ``resnet101`` / ``vgg16.features``;
    ``prefix`` strips a leading path (e.g. the reference checkpoint nests the
    trunk under ``FeatureExtraction.model.<idx>.`` — see
    /root/reference/lib/model.py:242-249 and models/checkpoint.py).
    Values may be torch tensors or numpy arrays.
    """
    sd = {}
    for k, v in state_dict.items():
        if prefix and not k.startswith(prefix):
            continue
        k = k[len(prefix):]
        sd[k] = v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)

    if name == "resnet101":
        params: Dict[str, Any] = {
            "conv1": {"w": _t2j_conv(sd["conv1.weight"])},
            "bn1": _t2j_bn(sd, "bn1"),
        }
        for stage in _resnet_stages(last_layer):
            blocks = []
            for i in range(RESNET101_STAGES[stage]):
                p = f"{stage}.{i}"
                blk = {
                    "conv1": {"w": _t2j_conv(sd[f"{p}.conv1.weight"])},
                    "bn1": _t2j_bn(sd, f"{p}.bn1"),
                    "conv2": {"w": _t2j_conv(sd[f"{p}.conv2.weight"])},
                    "bn2": _t2j_bn(sd, f"{p}.bn2"),
                    "conv3": {"w": _t2j_conv(sd[f"{p}.conv3.weight"])},
                    "bn3": _t2j_bn(sd, f"{p}.bn3"),
                }
                if f"{p}.downsample.0.weight" in sd:
                    blk["downsample"] = {
                        "conv": {"w": _t2j_conv(sd[f"{p}.downsample.0.weight"])},
                        "bn": _t2j_bn(sd, f"{p}.downsample.1"),
                    }
                blocks.append(blk)
            params[stage] = blocks
        return params

    if name == "vgg":
        # torchvision vgg16.features is an nn.Sequential; conv layers sit at
        # indices 0,2,5,7,10,12,14,17,19,21 (pre-pool4 slice).
        conv_idx = []
        idx = 0
        n_convs = _vgg_num_convs(last_layer)
        for cout in VGG16_PLAN:
            if len(conv_idx) == n_convs:
                break
            if cout == -1:
                idx += 1  # the pool layer
            else:
                conv_idx.append(idx)
                idx += 2  # conv + relu
        convs = []
        for i in conv_idx:
            convs.append(
                {
                    "w": _t2j_conv(sd[f"{i}.weight"]),
                    "b": jnp.asarray(sd[f"{i}.bias"]),
                }
            )
        return {"convs": convs}

    if name == "densenet201":
        params = {
            "conv0": {"w": _t2j_conv(sd["conv0.weight"])},
            "norm0": _t2j_bn(sd, "norm0"),
        }
        for bi, (bname, n_layers) in enumerate(DENSENET201_BLOCKS.items(), start=1):
            layers = []
            for i in range(1, n_layers + 1):
                p = f"{bname}.denselayer{i}"
                layers.append({
                    "norm1": _t2j_bn(sd, f"{p}.norm1"),
                    "conv1": {"w": _t2j_conv(sd[f"{p}.conv1.weight"])},
                    "norm2": _t2j_bn(sd, f"{p}.norm2"),
                    "conv2": {"w": _t2j_conv(sd[f"{p}.conv2.weight"])},
                })
            params[bname] = layers
            params[f"transition{bi}"] = {
                "norm": _t2j_bn(sd, f"transition{bi}.norm"),
                "conv": {"w": _t2j_conv(sd[f"transition{bi}.conv.weight"])},
            }
        return params

    raise ValueError(f"no torch importer for backbone {name!r}")
