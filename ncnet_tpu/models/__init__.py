"""Model assembly: backbones, NCNet composition, checkpoint I/O."""

from ncnet_tpu.models.backbone import (
    backbone_apply,
    backbone_init,
    finetune_labels,
    import_torch_backbone,
)
from ncnet_tpu.models.ncnet import (
    NCNet,
    NCNetOutput,
    coarse2fine_filter,
    coarse2fine_tracked_filter,
    extract_features,
    init_ncnet,
    make_point_matcher,
    ncnet_filter,
    ncnet_forward,
    ncnet_forward_from_features,
    ncnet_forward_tracked,
    ncnet_match_volume,
    neigh_consensus,
)
from ncnet_tpu.models.checkpoint import (
    import_torch_checkpoint,
    load_params,
    save_params,
)

__all__ = [
    "NCNet",
    "NCNetOutput",
    "backbone_apply",
    "backbone_init",
    "extract_features",
    "finetune_labels",
    "import_torch_backbone",
    "import_torch_checkpoint",
    "init_ncnet",
    "load_params",
    "make_point_matcher",
    "coarse2fine_filter",
    "coarse2fine_tracked_filter",
    "ncnet_filter",
    "ncnet_forward",
    "ncnet_forward_from_features",
    "ncnet_forward_tracked",
    "ncnet_match_volume",
    "neigh_consensus",
    "save_params",
]
