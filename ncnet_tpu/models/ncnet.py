"""NCNet model assembly (the reference's ImMatchNet).

Composes: backbone feature extraction → L2 norm → 4D correlation →
[maxpool4d relocalization] → mutual matching → neighbourhood-consensus conv4d
stack → mutual matching.  Reference: ``ImMatchNet``
(/root/reference/lib/model.py:193-282) and ``NeighConsensus``
(model.py:122-153).

Functional design: parameters are a plain pytree
``{"backbone": ..., "nc": [{"w", "b"}, ...]}``; the forward is a pure function
of ``(config, params, images)`` — jit/grad/shard-friendly.  ``half_precision``
maps to bfloat16 (TPU-native) rather than the reference's fp16
(model.py:253-258, 265-267), with f32 MXU accumulation in the correlation.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models import backbone as bb
from ncnet_tpu.ops import (
    Matches,
    choose_conv4d_variant,
    conv4d,
    conv4d_init,
    conv4d_same,
    corr_to_matches,
    correlation_4d,
    feature_l2_norm,
    maxpool4d_with_argmax,
    mutual_matching,
)
from ncnet_tpu.observability import get_logger
from ncnet_tpu.utils import faults

log = get_logger("models")


def _runtime_device_error_types() -> Tuple[type, ...]:
    """Exception types that mean 'the compiled program / device runtime
    failed', as opposed to a bug in host code: jax's runtime error (OOM,
    Mosaic faults, tunnel resets surface as XlaRuntimeError subclasses of
    it) plus the deterministic test stand-in."""
    errs = [faults.InjectedDeviceError]
    try:
        errs.append(jax.errors.JaxRuntimeError)
    except AttributeError:  # pragma: no cover - older jax
        pass
    try:  # pragma: no cover - defensive: not all jaxlibs alias it under errors
        from jax._src.lib import xla_client

        errs.append(xla_client.XlaRuntimeError)
    except Exception:
        pass
    return tuple(errs)


RUNTIME_DEVICE_ERRORS = _runtime_device_error_types()


class ResilientJit:
    """``jax.jit`` whose compiled-program cache can be dropped mid-run.

    The eval paths' tier-degradation recovery needs two things a bare
    ``jax.jit`` cannot give: (1) a host-side dispatch seam where an injected
    runtime device error can be raised deterministically
    (``faults.device_error_hook`` — one ``is None`` check when unarmed), and
    (2) :meth:`retrace`, which discards every cached executable so that after
    ``ops.demote_fused_tier`` disabled a Pallas tier the next call re-traces
    through ``choose_fused_stack`` and lands on the surviving tier —
    without it, jit's per-shape cache would keep replaying the poisoned
    executable for every shape bucket already seen."""

    def __init__(self, fn, *, label: str = "", hook: bool = True,
                 ledger_program: Optional[str] = None,
                 ledger_key_fn=None, ledger_tier=None, **jit_kwargs):
        self._fn = fn
        self._label = label
        self._hook = hook
        self._jit_kwargs = jit_kwargs
        self._jitted = jax.jit(fn, **jit_kwargs)
        # compiled-program memory ledger (observability/memory.py): when
        # ``ledger_program`` is set, the first successful dispatch of each
        # shape class records lowered.compile().memory_analysis() — an AOT
        # analysis compile, paid once per (program, shape, device kind) per
        # MACHINE (the persisted ledger replays it for warm processes) and
        # skipped entirely when NCNET_TPU_MEMORY_LEDGER=off
        self._ledger_program = ledger_program
        self._ledger_key_fn = ledger_key_fn
        self._ledger_tier = ledger_tier
        self._ledger_seen: set = set()

    def __call__(self, *args, **kwargs):
        if self._hook:
            faults.device_error_hook(self._label)
        out = self._jitted(*args, **kwargs)
        if self._ledger_program is not None:
            self._maybe_record_ledger(args, kwargs)
        return out

    def _maybe_record_ledger(self, args, kwargs) -> None:
        """One ledger row per shape class actually dispatched (fail-open:
        the ledger must never be the reason a dispatch fails)."""
        try:
            from ncnet_tpu.observability import memory as obs_memory

            if obs_memory.ledger_path() is None:
                return  # the plane is off: skip the analysis compile too
            key = (self._ledger_key_fn(*args, **kwargs)
                   if self._ledger_key_fn is not None
                   else obs_memory.shape_class((args, kwargs)))
            if key in self._ledger_seen:
                return
            self._ledger_seen.add(key)
            tier = self._ledger_tier() if self._ledger_tier else None
            # capture only ShapeDtypeStructs (not the live arrays — the
            # async closure must not extend the dispatched buffers' lives);
            # the AOT analysis compile itself runs on a background thread
            # (ensure_program_async), never blocking this dispatch
            jitted = self._jitted
            sds = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape") and hasattr(a, "dtype") else a,
                (args, kwargs))

            obs_memory.ensure_program_async(
                self._ledger_program, key, tier=tier,
                analyze=lambda: jitted.lower(*sds[0], **sds[1]).compile())
        except Exception:  # noqa: BLE001 — telemetry never kills dispatch
            pass

    def retrace(self) -> None:
        """Drop all cached executables; the next call re-traces (and
        re-consults the fused-stack tier chooser).

        ``jax.jit(self._fn)`` again would NOT do this: jax's tracing cache
        is keyed on the callable's identity, so re-jitting the same function
        object replays the cached jaxpr — the poisoned tier included —
        without ever re-running the Python trace (verified on jax 0.4.37).
        A fresh ``functools.wraps``-ed closure changes the cache key while
        preserving the signature that ``static_argnames`` resolves against.
        """
        import functools

        from ncnet_tpu.observability.tracing import span

        with span("retrace", label=self._label):
            fn = self._fn
            wrapper = functools.wraps(fn)(lambda *a, **kw: fn(*a, **kw))
            self._jitted = jax.jit(wrapper, **self._jit_kwargs)
            # the retraced programs run a different tier ladder: their
            # memory footprints are fresh evidence, re-record per shape
            self._ledger_seen.clear()


def recover_from_device_failure(exc: BaseException, *retraceables,
                                prefer_tier: Optional[str] = None) -> Optional[str]:
    """The runtime tier-degradation policy, in one place.

    If ``exc`` is a runtime device error (``RUNTIME_DEVICE_ERRORS``): demote
    the highest still-enabled fused-stack Pallas tier
    (``ops.demote_fused_tier``), call ``.retrace()`` on every given object so
    their cached executables are rebuilt on the surviving tier, and return
    the demoted tier's name — the caller should retry the failed query
    WITHOUT consuming its bounded retry budget (the retry runs a genuinely
    different program).  Returns None when there is nothing left to demote
    (already on plain XLA — the failure is real) or the error is not
    device-shaped; the caller falls back to its plain retry/quarantine
    policy.

    ``prefer_tier`` names a tier to demote FIRST if it is still enabled —
    the training loop passes ``"resident_vjp"`` so a device failure inside a
    train step disables the Pallas backward (the tier only training runs)
    before it starts eating into the forward ladder; eval callers leave it
    None and walk the forward ladder exactly as before.

    Policy note: the tier actually executing is chosen per SHAPE inside the
    traced program, so this recovery cannot know it — it demotes the ladder
    top-down instead.  When the failing shape was already below the demoted
    tier the free retry re-runs the same program once per remaining rung (at
    most two retrace cycles, after which every failure counts against the
    plain budget); that bounded over-demotion is the price of keeping the
    chooser the single authority on tier selection."""
    if not isinstance(exc, RUNTIME_DEVICE_ERRORS):
        return None
    # a RESOURCE_EXHAUSTED surfacing through this path is a MEMORY failure:
    # one memory_postmortem event per failure (idempotent across seams —
    # a serving failure handler may have already reported this exception)
    from ncnet_tpu.observability import memory as obs_memory

    obs_memory.report_oom(exc, scope="demote_retrace")
    if not isinstance(exc, faults.InjectedDeviceError):
        # a REAL device error on a backend with no Pallas at all cannot be
        # tier-related: demoting would only grant pointless off-budget
        # retries of a bit-identical program.  (Injected errors bypass the
        # gate — they exist to simulate a Pallas-capable rig's failure on
        # the CPU test backend.)  Exception: when the coarse2fine sparse
        # PIPELINE is routing traffic, demoting it to dense is a genuinely
        # different program on any backend (the sparse path can OOM or
        # fail where dense would not), so the gate lets it through.
        from ncnet_tpu.ops import last_selected_tier
        from ncnet_tpu.ops.conv4d import _pallas_available

        if not _pallas_available() \
                and last_selected_tier("pipeline") != "coarse2fine":
            return None
    from ncnet_tpu.ops import demote_fused_tier

    tier = demote_fused_tier(prefer_tier) if prefer_tier is not None else None
    if tier is None:
        tier = demote_fused_tier()
    if tier is None:
        return None
    log.warning(
        f"runtime device failure ({type(exc).__name__}: {exc}); "
        f"demoting fused NC tier '{tier}' and re-tracing the eval programs "
        "— the run continues on the next tier", kind="device",
    )
    from ncnet_tpu.observability.tracing import span

    # the demotion span bounds the recovery's host-side cost (N retraces);
    # the retry's recompile lands inside the next dispatch, where the trace
    # shows it as that span's inflated wall
    with span("tier_recovery", tier=tier, error=type(exc).__name__):
        for r in retraceables:
            r.retrace()
    return tier


class NCNetOutput(NamedTuple):
    """Filtered correlation volume (+ relocalization offsets when k>1)."""

    corr: jnp.ndarray                      # (B, hA, wA, hB, wB)
    delta4d: Optional[Tuple[jnp.ndarray, ...]] = None


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_ncnet(config: ModelConfig, key: jax.Array) -> Dict[str, Any]:
    """Init parameters for the full model: random NC stack + a trunk from
    ``config.backbone_weights`` (torchvision state_dict) when given, else
    random.

    The reference *always* starts its trunk from ImageNet-pretrained
    torchvision weights (model.py:25,39); a randomly-initialized frozen trunk
    trains but cannot approach reference quality, so that case warns loudly.
    """
    if len(config.ncons_kernel_sizes) != len(config.ncons_channels):
        raise ValueError(
            "ncons_kernel_sizes and ncons_channels must have equal length, got "
            f"{config.ncons_kernel_sizes} vs {config.ncons_channels}"
        )
    k_bb, k_nc = jax.random.split(key)
    if config.backbone_weights:
        trunk = bb.import_torch_backbone(
            _load_torch_state_dict(config.backbone_weights, config.backbone),
            config.backbone,
            last_layer=config.backbone_last_layer,
        )
    else:
        if config.backbone in ("resnet101", "vgg", "densenet201"):
            import warnings

            warnings.warn(
                f"initializing a '{config.backbone}' trunk with RANDOM weights "
                "— the reference always uses ImageNet-pretrained weights; pass "
                "backbone_weights=<torchvision .pth> (or a checkpoint) for "
                "meaningful features",
                stacklevel=2,
            )
        trunk = bb.backbone_init(
            config.backbone, k_bb, last_layer=config.backbone_last_layer
        )
    params: Dict[str, Any] = {"backbone": trunk}
    nc: List[Dict[str, jnp.ndarray]] = []
    c_in = 1
    for k_size, c_out in zip(config.ncons_kernel_sizes, config.ncons_channels):
        k_nc, sub = jax.random.split(k_nc)
        w, b = conv4d_init(sub, k_size, c_in, c_out)
        nc.append({"w": w, "b": b})
        c_in = c_out
    params["nc"] = nc
    return params


def _load_torch_state_dict(path: str, backbone: str):
    """Load a torchvision ``.pth`` state_dict for the trunk importer; full
    vgg16/densenet201 checkpoints nest convs under ``features.``, which the
    importer expects stripped."""
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if backbone in ("vgg", "densenet201") and any(
        k.startswith("features.") for k in sd
    ):
        sd = {k[len("features."):]: v for k, v in sd.items()
              if k.startswith("features.")}
    return sd


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def swap_ab_taps(layer: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """The layer whose plain application equals ``transpose ∘ layer ∘
    transpose`` (A↔B volume transposition): kernel tap groups (kA,kWA) and
    (kB,kWB) swapped, channels untouched.  Requires a cubic kernel."""
    return {"w": jnp.transpose(layer["w"], (2, 3, 0, 1, 4, 5)),
            "b": layer["b"]}


def tap_swap_fusable(nc_params) -> bool:
    """Whether the symmetric pass may run as tap-swapped stacks with a fused
    first layer — the shape class the optimization was MEASURED on (see
    neigh_consensus): cubic kernels, exactly two layers, 1-channel input."""
    return (
        len(nc_params) == 2
        and nc_params[0]["w"].shape[4] == 1
        and all(
            layer["w"].shape[0:2] == layer["w"].shape[2:4]
            for layer in nc_params
        )
    )


def tap_swap_fused_layers(nc_params):
    """``(fused_l1, l2, l2_swapped)`` for the tap-swapped symmetric fast
    path.  The ONE construction of the fusion arithmetic — the unsharded
    (:func:`neigh_consensus`) and hB-sharded (parallel/spatial.py) branches
    both build from it so they agree to float-level numerical parity (the
    InLoc eval shares per-query .mat files across ``spatial_shards``
    settings; the sharded path's halo-padded conv shapes can still round
    differently through the variant chooser, so the agreement is
    within-tolerance, NOT bit-exact — see tests/test_spatial.py)."""
    sw = [swap_ab_taps(layer) for layer in nc_params]
    fused_l1 = {
        "w": jnp.concatenate([nc_params[0]["w"], sw[0]["w"]], axis=-1),
        "b": jnp.concatenate([nc_params[0]["b"], sw[0]["b"]]),
    }
    return fused_l1, nc_params[1], sw[1]


def tap_swap_chain(nc_params):
    """The tap-swapped symmetric pass as ONE 2-layer chain for the resident
    fused stack: ``[fused L1 (1 → 2C), block-diagonal L2 (2C → 2)]``.

    The block-diagonal final layer applies the plain L2 to channels ``:C``
    (→ output channel 0) and the tap-swapped L2 to channels ``C:`` (→ output
    channel 1) with per-stack biases, so the kernel's bias+ReLU epilogue
    applies to each stack SEPARATELY — summing the two output channels
    afterwards reproduces ``relu(L2(y_a)) + relu(L2ᵀ(y_b))`` exactly
    (a single 2C → 1 conv would wrongly ReLU the sum).  Built from
    :func:`tap_swap_fused_layers` so the fusion arithmetic has one home."""
    fused_l1, l2, l2s = tap_swap_fused_layers(nc_params)
    zero = jnp.zeros_like(l2["w"])
    w_bd = jnp.concatenate(
        [jnp.concatenate([l2["w"], zero], axis=4),
         jnp.concatenate([zero, l2s["w"]], axis=4)],
        axis=5,
    )  # (k, k, k, k, 2C, 2)
    b_bd = jnp.concatenate([l2["b"], l2s["b"]])
    return [fused_l1, {"w": w_bd, "b": b_bd}]


def neigh_consensus(
    nc_params: List[Dict[str, jnp.ndarray]],
    corr: jnp.ndarray,
    *,
    symmetric: bool = True,
    remat_layers: bool = False,
    custom_grad: "bool | Sequence[Dict[str, str]]" = False,
    allow_pallas: bool = True,
    require_vjp: bool = False,
    force_tier: Optional[str] = None,
) -> jnp.ndarray:
    """Neighbourhood-consensus filtering of the 4D volume.

    ``corr``: ``(B, hA, wA, hB, wB)`` scalar volume.  The conv stack runs
    channels-last; symmetric mode applies the *whole* stack to the volume and
    to its A↔B transpose, transposing back and summing — exactly the
    reference's stack-level symmetry (model.py:144-150), which is NOT the same
    as symmetrizing each layer because of the interleaved ReLUs.

    ``remat_layers``: rematerialize each conv+ReLU separately under autodiff,
    so the backward pass holds one layer's folded-conv intermediates at a
    time instead of the whole stack's (training memory knob; a forward-only
    jit is unaffected).

    ``custom_grad``: route each layer through :func:`conv4d_same`, whose
    custom VJP picks its own formulation per gradient.  Measured on v5e
    (tools/vjp_probe.py, 25⁴ symmetric stack, fp32): ~18% SLOWER than XLA's
    plain transpose (56.9 vs 48.4 ms/pair at bs4) but ~45% less XLA temp
    memory (7.2 vs 12.7 GB) — a memory knob, cheaper per saved byte than
    ``remat_layers``' ~30% step-time cost, not a speed default.  Instead of
    ``True`` a per-layer routing may be given: a sequence (one entry per NC
    layer) of ``{"dx": <variant>, "dw": <variant>}`` dicts passed to
    :func:`ncnet_tpu.ops.conv4d.make_conv4d_same` (tools/vjp_sweep_probe.py
    measures the combos composed).

    ``allow_pallas``: permit routing the whole stack through the fused-lane
    Pallas kernels (ops/nc_fused_lane.py) when the shape class fits —
    bfloat16, cubic uniform odd kernels, VMEM-feasible volume, Mosaic
    compile-probe green.  ``choose_fused_stack`` picks the tier per shape:
    the RESIDENT whole-stack kernel (one pallas_call, intermediates in VMEM
    rings — round 6), else the r5 per-layer chain (measured 2.0 vs 3.95
    ms/volume against the XLA stack, tools/nc_fused_lane_probe), else XLA.
    The tap-swapped symmetric pass routes through the resident kernel as a
    2-layer block-diagonal chain (:func:`tap_swap_chain`) when it compiles.

    ``require_vjp``: the TRAINING gate (round 7).  Route to the fused stack
    only when ``choose_fused_vjp`` (ops/nc_fused_lane_vjp.py) confirms the
    resident Pallas BACKWARD engages for every shape this call will run —
    under ``value_and_grad`` a fused forward whose VJP replays the XLA
    stack is a net loss (the pre-r7 reason training pinned
    ``nc_pallas=False``), so the forward must not outrun its backward.
    Where the VJP tier is unavailable the call keeps the plain XLA stack,
    exactly the pre-r7 training path.

    ``force_tier``: route the stack through a named ARITHMETIC tier
    unconditionally — ``'cp'`` (rank-R separable chain; every layer must
    carry factors, see tools/cp_decompose.py) or ``'fft'`` (spectral
    conv) — bypassing the chooser's gates.  The explicit seam for
    ``ModelConfig.nc_tier`` and the CP fine-tune path, which must train
    the factors even where the arithmetic gate would keep the dense
    tiers; the forced tier is still announced to the tier machinery
    (``note_forced_tier``) so quality events carry the honest label.
    Round 17: without a force, ``choose_fused_stack`` considers the
    arithmetic tiers wherever the layer structure permits — any backend,
    any dtype — and they outrank the Pallas ladder when their FLOP gates
    clear (training's ``require_vjp`` path never auto-selects them).
    """
    if custom_grad is True:
        convs = [conv4d_same] * len(nc_params)
    elif isinstance(custom_grad, (list, tuple)):
        # an (accidentally) empty routing list must hit the length check
        # below, not silently mean "plain AD"
        from ncnet_tpu.ops.conv4d import make_conv4d_same

        if len(custom_grad) != len(nc_params):
            raise ValueError(
                f"custom_grad routing has {len(custom_grad)} entries for "
                f"{len(nc_params)} NC layers"
            )
        convs = [
            conv4d if spec is None else
            make_conv4d_same(spec.get("dx", "auto"), spec.get("dw", "coutfold"))
            for spec in custom_grad
        ]
    else:
        convs = [conv4d] * len(nc_params)

    def make_layer(i):
        def one_layer(w, b, x):
            return jax.nn.relu(convs[i](x, w, b))

        return jax.checkpoint(one_layer) if remat_layers else one_layer

    layers = [make_layer(i) for i in range(len(nc_params))]

    x = corr[..., None]  # (B, hA, wA, hB, wB, 1)

    # params must already be bf16 (ncnet_filter casts them) for the Pallas
    # tiers: mixed fp32-params/bf16-volume calls keep them off, where XLA's
    # own promotion rules apply, instead of a silent bf16 downcast.  The
    # ARITHMETIC tiers (cp/fft, round 17) are plain XLA with no dtype or
    # backend requirement, so eligibility splits: the chooser is consulted
    # whenever the layer STRUCTURE permits, and ``pallas_ok`` tells it
    # whether the Pallas ladder is additionally on the table.
    bf16_ok = (
        x.dtype == jnp.bfloat16
        and all(layer["w"].dtype == jnp.bfloat16 for layer in nc_params)
    )
    tier_eligible = (
        allow_pallas and not remat_layers and custom_grad is False
    )
    use_fused = False
    fused_tap_swap = False
    arith_tier = None
    b, ha, wa, hb, wb = corr.shape
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    channels = tuple(layer["w"].shape[5] for layer in nc_params)
    if force_tier:
        from ncnet_tpu.ops import cp_stack_ranks, note_forced_tier

        if force_tier not in ("cp", "fft"):
            raise ValueError(
                f"force_tier must be 'cp' or 'fft', got {force_tier!r}")
        if force_tier == "cp" and cp_stack_ranks(nc_params) is None:
            raise ValueError(
                "force_tier='cp' needs CP factors on every NC layer "
                "(tools/cp_decompose.py attaches them)")
        arith_tier = force_tier
        note_forced_tier(ha, wa, hb, wb, kernels, channels, force_tier)
    elif tier_eligible and require_vjp:
        # the require_vjp (TRAINING) gate fuses only where the resident
        # BACKWARD engages — a fused forward whose VJP replays XLA is a net
        # loss under value_and_grad; its forward side needs no extra check
        # (nc_stack_fused's impl dispatcher falls back per shape anyway).
        # The arithmetic tiers are never auto-selected here: training
        # defaults keep the proven resident-VJP path, and the CP fine-tune
        # path opts in explicitly via ``force_tier``.
        if bf16_ok:
            from ncnet_tpu.ops import choose_fused_stack, choose_fused_vjp

            if symmetric and (ha, wa) != (hb, wb) \
                    and tap_swap_fusable(nc_params):
                # the tap-swapped symmetric pass is itself a 2-layer chain
                # (see below); training on this class additionally needs
                # the Pallas backward of the block-diagonal chain
                c = nc_params[0]["w"].shape[5]
                fused_tap_swap = choose_fused_stack(
                    ha, wa, hb, wb, kernels, (2 * c, 2)
                ) == "resident" and choose_fused_vjp(
                    ha, wa, hb, wb, kernels, (2 * c, 2)
                ) is not None
            shapes = {(ha, wa, hb, wb)}
            if symmetric and (ha, wa) != (hb, wb) \
                    and not tap_swap_fusable(nc_params):
                shapes.add((hb, wb, ha, wa))
            use_fused = all(
                choose_fused_vjp(*s, kernels, channels) is not None
                for s in shapes
            )
    elif tier_eligible:
        from ncnet_tpu.ops import choose_fused_stack, cp_stack_ranks

        cp_ranks = cp_stack_ranks(nc_params)
        shapes = [(ha, wa, hb, wb)]
        if symmetric and (ha, wa) != (hb, wb) \
                and not tap_swap_fusable(nc_params):
            # only the rectangular two-pass fallback runs stack() on the
            # A<->B transposed volume — gate that orientation only when it
            # will actually execute (a square volume batch-folds and the
            # tap-swap class never transposes)
            shapes.append((hb, wb, ha, wa))
        decisions = [
            choose_fused_stack(*s, kernels, channels,
                               cp_ranks=cp_ranks, pallas_ok=bf16_ok)
            for s in shapes
        ]
        if decisions[0] in ("cp", "fft") \
                and all(d == decisions[0] for d in decisions):
            # an arithmetic tier won every orientation: route stack()
            # straight through its differentiable XLA body (both gates are
            # symmetric under the A<->B swap, so a split can only mean a
            # demotion landed mid-consult — then the generic dispatch below
            # re-asks per shape)
            arith_tier = decisions[0]
        else:
            use_fused = bf16_ok and all(d is not None for d in decisions)
        if bf16_ok and arith_tier is None and symmetric \
                and (ha, wa) != (hb, wb) and tap_swap_fusable(nc_params):
            # the tap-swapped symmetric pass is itself a 2-layer chain
            # (1 → 2C fused first layer, then a BLOCK-DIAGONAL 2C → 2 final
            # layer whose two output channels are the two stacks' outputs,
            # summed after the kernel's per-stack ReLUs) — the resident
            # whole-stack kernel runs it when the shape class compiles
            c = nc_params[0]["w"].shape[5]
            fused_tap_swap = choose_fused_stack(
                ha, wa, hb, wb, kernels, (2 * c, 2)
            ) == "resident"

    def stack(x: jnp.ndarray) -> jnp.ndarray:
        # every layer takes and emits the plain channels-last volume.  An
        # arithmetic tier (chosen or forced) replaces the whole stack with
        # its differentiable XLA chain; the fused-lane Pallas chain does so
        # when the shape class fits (see ``allow_pallas`` above); otherwise
        # conv4d's 'auto' chooser (ops/conv4d.py) remains the single
        # authority for the per-layer MXU formulation
        if arith_tier == "cp":
            from ncnet_tpu.ops import nc_stack_cp

            return nc_stack_cp(nc_params, x)
        if arith_tier == "fft":
            from ncnet_tpu.ops import nc_stack_fft

            return nc_stack_fft(nc_params, x)
        if use_fused:
            from ncnet_tpu.ops.nc_fused_lane import nc_stack_fused

            return nc_stack_fused(nc_params, x)
        for one_layer, layer in zip(layers, nc_params):
            x = one_layer(layer["w"], layer["b"], x)
        return x
    if symmetric:
        # folding the two passes into the batch dim doubles every NC
        # intermediate's live footprint — an OOM at the InLoc volume, and a
        # formulation downgrade (conv4d's auto gate demotes the folded batch
        # to 'unroll') at large training batches — so ask the one authority,
        # the variant chooser itself, whether every layer keeps a channel-
        # folding formulation at the doubled batch; otherwise run the two
        # passes sequentially (their buffer lifetimes then barely overlap)
        # the fused Pallas tiers stream one row at a time (per-volume VMEM
        # working set, batch only widens the grid), so the XLA chooser's
        # fold-memory demotion does not apply to them; the arithmetic tiers
        # never materialize a k⁴-folded patch matrix at all
        fold_ok = use_fused or arith_tier is not None or all(
            choose_conv4d_variant(
                layer["w"].shape[4], layer["w"].shape[5], hb, wb,
                shape_a=(ha, wa), kernel=tuple(layer["w"].shape[:4]),
                dtype=x.dtype, batch=2 * b,
            ) != "unroll"
            for layer in nc_params
        )
        if x.shape[1:3] == x.shape[3:5] and fold_ok:
            # square volume (hA,wA)==(hB,wB): fold the two passes into the
            # batch dim — one stack over 2B volumes fills the MXU better than
            # two B-sized passes (~12% at the PF-Pascal workload on v5e) and
            # is numerically identical (batching does not reassociate the
            # per-volume convs).  Rectangular volumes (InLoc) keep two passes.
            b = x.shape[0]
            xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))  # swap (hA,wA)↔(hB,wB)
            y = stack(jnp.concatenate([x, xt], axis=0))
            out = y[:b] + jnp.transpose(y[b:], (0, 3, 4, 1, 2, 5))
        elif tap_swap_fusable(nc_params) and arith_tier is None:
            # rectangular volumes cannot batch-fold, but the transpose pass
            # is avoidable algebraically: transposition commutes with ReLU
            # and swaps a cubic kernel's A/B tap groups, so
            # NC(xᵀ)ᵀ ≡ NC_tap-swapped(x) — and with a 1-channel first layer
            # the two stacks' L1s fuse into ONE double-width conv over x.
            # Measured COMPOSED on the 56M-cell InLoc volume (IVD arch,
            # bf16, v5e): filter stage 109 → 46 ms/pair in production (the
            # hand-built probe estimated 76; XLA fuses the production
            # composition further); the unfused tap-swap alone is SLOWER
            # (123), so only the measured 2-layer shape class takes this
            # path (deeper stacks keep the transpose form).
            if fused_tap_swap:
                from ncnet_tpu.ops import nc_stack_fused

                y2 = nc_stack_fused(tap_swap_chain(nc_params), x)
                out = y2[..., :1] + y2[..., 1:]
            else:
                fused_l1, l2, l2s = tap_swap_fused_layers(nc_params)
                y = layers[0](fused_l1["w"], fused_l1["b"], x)  # 1→2C, one pass
                c = nc_params[0]["w"].shape[5]
                out = (
                    layers[1](l2["w"], l2["b"], y[..., :c])
                    + layers[1](l2s["w"], l2s["b"], y[..., c:])
                )
        else:
            xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
            out = stack(x) + jnp.transpose(stack(xt), (0, 3, 4, 1, 2, 5))
    else:
        out = stack(x)
    return out[..., 0]


def extract_features(config: ModelConfig, params, images: jnp.ndarray) -> jnp.ndarray:
    """Backbone features, optionally L2-normalized per location
    (reference FeatureExtraction.forward, model.py:83-87).

    ``config.backbone_bf16`` runs the (frozen) trunk in bfloat16 — a
    TPU-native fast path with no reference analog; the L2 norm is taken in
    f32 either way, and the output dtype follows the input images unless
    ``half_precision`` later narrows it."""
    bb_params = params["backbone"]
    if config.backbone_bf16:
        bb_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), bb_params)
        images = images.astype(jnp.bfloat16)
    feats = bb.backbone_apply(
        config.backbone, bb_params, images,
        last_layer=config.backbone_last_layer,
    )
    if config.backbone_bf16:
        feats = feats.astype(jnp.float32)
    if config.normalize_features:
        feats = feature_l2_norm(feats)
    return feats


def ncnet_forward(
    config: ModelConfig,
    params,
    source_images: jnp.ndarray,
    target_images: jnp.ndarray,
) -> NCNetOutput:
    """Full forward pass on an image-pair batch.

    Args:
      source_images, target_images: ``(B, H, W, 3)`` normalized images.

    Returns:
      :class:`NCNetOutput` with the filtered volume ``(B, hA, wA, hB, wB)``
      and, when ``config.relocalization_k_size > 1``, the ``delta4d`` offsets
      for fine-grid match recovery (reference model.py:261-282).
    """
    fa = extract_features(config, params, source_images)
    return ncnet_forward_from_features(config, params, fa, target_images)


def ncnet_forward_from_features(
    config: ModelConfig,
    params,
    source_features: jnp.ndarray,
    target_images: jnp.ndarray,
) -> NCNetOutput:
    """Forward with the SOURCE side's backbone features precomputed.

    The InLoc eval matches one query against ~10 panos; recomputing the
    query's trunk per pair (as the reference does, eval_inloc.py:124-132)
    wastes ~30 ms/pair of device time at 3200 px.  ``source_features`` must
    be exactly ``extract_features(config, params, src)``.  Identity caveat
    (ADVICE r3): when the features come from a SEPARATELY-jitted
    ``extract_features`` program, on-TPU fusion may round them differently
    than the trunk embedded in a fused forward — so outputs are bit-stable
    within one input path, and match :func:`ncnet_forward` to float-level
    tolerance (demonstrated bit-exact on CPU only).  The InLoc eval loop
    uses the cached-features path consistently for every pair, which is
    what its resume artifacts rely on."""
    fa = source_features
    fb = extract_features(config, params, target_images)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    return ncnet_match_volume(config, params, fa, fb)


def ncnet_forward_from_feature_pair(
    config: ModelConfig,
    params,
    source_features: jnp.ndarray,
    target_features: jnp.ndarray,
) -> NCNetOutput:
    """Forward with BOTH sides' backbone features precomputed — the
    feature-store serving shape (ncnet_tpu/store/): the query's features
    come from ``matcher.preprocess`` (computed once per query) and the
    database side's from the persistent store, so a warm-store pair runs
    ZERO backbone extractions.  Both feature tensors must be exactly
    ``extract_features(config, params, img)`` outputs (f32, pre-bf16-cast
    — the cast happens here so stored bytes are precision-independent).
    The :func:`ncnet_forward_from_features` identity caveat applies
    doubly: bit-stability holds within one input path, which is why the
    store-backed eval uses this path for EVERY pair (hit and miss alike)
    — a hit's bytes are checksum-identical to the miss's compute, so the
    two are bitwise-interchangeable by construction."""
    fa, fb = source_features, target_features
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    return ncnet_match_volume(config, params, fa, fb)


def ncnet_match_volume(config: ModelConfig, params, fa: jnp.ndarray,
                       fb: jnp.ndarray) -> NCNetOutput:
    """Correlation + filtering of a feature pair, behind the match-pipeline
    tier dispatch: the DENSE path (full 4D correlation → :func:`ncnet_filter`)
    or the COARSE-TO-FINE sparse path (:func:`coarse2fine_filter`) when
    ``config.sparse_topk`` > 0, the shape class is eligible, and the
    "coarse2fine" tier is not demoted (``ops/sparse_corr.py::
    choose_match_pipeline`` is the one authority; the decision happens at
    trace time, so a post-demotion ``ResilientJit.retrace`` lands the next
    dispatch on the dense fallback exactly like the fused-stack ladder).
    Every feature-pair forward converges here, which is what wires the
    sparse tier through ``make_point_matcher``, the serving engine, and
    both eval entry points without touching their downstream wire shapes."""
    from ncnet_tpu.ops.sparse_corr import choose_match_pipeline
    from ncnet_tpu.ops.sparse_topk import resolve_halo

    tier = choose_match_pipeline(
        fa.shape[1], fa.shape[2], fb.shape[1], fb.shape[2],
        sparse_topk=config.sparse_topk,
        factor=config.sparse_factor,
        halo=resolve_halo(config.sparse_halo, config.sparse_factor),
        reloc_k=config.relocalization_k_size,
    )
    if tier == "coarse2fine":
        return coarse2fine_filter(config, params, fa, fb)
    corr = correlation_4d(fa, fb)
    return ncnet_filter(config, params, corr)


def coarse2fine_filter(config: ModelConfig, params, fa: jnp.ndarray,
                       fb: jnp.ndarray) -> NCNetOutput:
    """The coarse-to-fine sparse match pipeline (ROADMAP item 2; README
    "Coarse-to-fine matching"):

      1. **coarse pass** — pool both feature grids by ``config.sparse_factor``
         (stride-32 at the default 2), build the coarse 4D volume
         (``1/factor⁴`` of the dense cells), and run the UNCHANGED dense
         filter on it (:func:`ncnet_filter` — mutual matching + the full NC
         consensus stack, same weights: conv4d is resolution-agnostic);
      2. **candidate selection** — per-row top-k over the filtered coarse
         volume (``ops/sparse_topk.topk_candidates``, static-shape coverage
         contract);
      3. **sparse fine pass** — gather the candidates' fine feature patches,
         correlate, mutual-match with cross-tile scatter-max vectors, run
         the NC stack on the folded tiles (``neigh_consensus`` — its own
         tier chooser routes the tile batch through the resident Pallas
         kernels where the shape class compiles), gate again, and scatter
         the filtered scores back onto the dense volume shape
         (``ops/sparse_corr.sparse_refine``).

    The returned :class:`NCNetOutput` carries a bitwise wire-compatible
    dense-shaped volume (zeros off the candidate support), so match
    extraction, quality signals, serving and the InLoc writers all run
    unchanged.  Callers must gate eligibility through
    ``choose_match_pipeline`` (:func:`ncnet_match_volume` does)."""
    from ncnet_tpu.ops.sparse_corr import sparse_refine
    from ncnet_tpu.ops.sparse_topk import (
        pool_features,
        resolve_halo,
        topk_candidates,
    )

    nc_params = params["nc"]
    if config.half_precision:
        nc_params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), nc_params)
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    factor = config.sparse_factor
    halo = resolve_halo(config.sparse_halo, factor)
    # coarse pass: the dense machinery at 1/factor² resolution (ncnet_filter
    # re-casts under half_precision — idempotent)
    fac = pool_features(fa, factor, renormalize=config.normalize_features)
    fbc = pool_features(fb, factor, renormalize=config.normalize_features)
    coarse = ncnet_filter(config, params, correlation_4d(fac, fbc))
    # SYMMETRIC candidate selection: per coarse source cell over targets AND
    # per coarse target cell over sources.  Selection in one direction only
    # leaves the OTHER direction's extraction uncovered (a target cell no
    # source cell selected has an all-zero column → a garbage argmax row in
    # the B→A match table), and both eval paths read both directions —
    # corr_to_matches' default is per-target-cell, InLoc extracts both.
    cand_ab = topk_candidates(coarse.corr, config.sparse_topk)
    cand_ba = topk_candidates(
        jnp.transpose(coarse.corr, (0, 3, 4, 1, 2)), config.sparse_topk)
    return _sparse_dual_refine(config, nc_params, fa, fb, cand_ab, cand_ba,
                               factor=factor, halo=halo)


def _sparse_dual_refine(config: ModelConfig, nc_params, fa: jnp.ndarray,
                        fb: jnp.ndarray, cand_ab: jnp.ndarray,
                        cand_ba: jnp.ndarray, *, factor: int,
                        halo: int) -> NCNetOutput:
    """The candidate-agnostic fine pass shared by :func:`coarse2fine_filter`
    and :func:`coarse2fine_tracked_filter`: refine BOTH candidate families
    through the gathered-tile NC stack and merge on the dense frame.  One
    code path for both tiers is what makes the tracked mode's full-coverage
    / fallback equalities structural — each tile's filtered value depends
    only on its (source cell, candidate cell) pair and the cross-tile
    scatter-max gates, all order-independent, so any two candidate sets
    with equal coverage scatter the identical dense volume.  Inputs are
    already precision-cast by the caller."""
    from ncnet_tpu.ops.sparse_corr import sparse_refine

    def stack_fn(vol: jnp.ndarray) -> jnp.ndarray:
        # the folded-tile batch consults the SAME tier chooser as the dense
        # volume — the arithmetic tiers (cp/fft) and the Pallas ladder all
        # apply per tile shape, so a CP win compounds on the coarse pass
        # and again on every fine tile (ISSUE 17); config.nc_tier forces
        # the arithmetic tier here exactly like the dense path
        return neigh_consensus(nc_params, vol,
                               symmetric=config.symmetric_mode,
                               force_tier=config.nc_tier or None)

    def stack_fn_t(vol: jnp.ndarray) -> jnp.ndarray:
        # the role-swapped tile family's stack: the symmetric stack commutes
        # with A↔B volume transposition, so it applies as-is; an asymmetric
        # stack must be conjugated by the transpose to filter the swapped
        # tiles identically to their dense orientation
        if config.symmetric_mode:
            return stack_fn(vol)
        vt = jnp.transpose(vol, (0, 3, 4, 1, 2))
        return jnp.transpose(stack_fn(vt), (0, 3, 4, 1, 2))

    vol_ab = sparse_refine(fa, fb, cand_ab, factor=factor, halo=halo,
                           stack_fn=stack_fn)
    vol_ba = sparse_refine(fb, fa, cand_ba, factor=factor, halo=halo,
                           stack_fn=stack_fn_t)
    # merge the two families on the dense frame by max — duplicates (a tile
    # selected in both directions) carry the same filtered value, and at
    # full coverage each family alone already equals the dense volume
    corr = jnp.maximum(vol_ab, jnp.transpose(vol_ba, (0, 3, 4, 1, 2)))
    return NCNetOutput(corr, None)


def coarse2fine_tracked_filter(config: ModelConfig, params, fa: jnp.ndarray,
                               fb: jnp.ndarray, prior_ab: jnp.ndarray,
                               prior_ba: jnp.ndarray) -> NCNetOutput:
    """The TRACKED match pipeline (README "Streaming matching"): the
    coarse-to-fine fine pass with the coarse pass REPLACED by temporal
    candidate seeding — frame ``t-1``'s match table, inverted to a
    per-coarse-cell prior pair (``ops/temporal.prior_from_table``) and
    dilated in-graph by the static ``(2·track_radius+1)²`` search window
    (``ops/temporal.temporal_candidates``).  No coarse correlation, no
    coarse NC filter: on a steady frame the only dense-resolution work is
    the gathered tiles.  Both candidate families are seeded (A→B from
    ``prior_ab``, B→A from ``prior_ba``) so both readout directions stay
    covered exactly like the symmetric top-k selection.  The output is the
    same dense-shaped wire volume; at full window coverage (radius ≥
    coarse grid − 1) it is bitwise the sparse tier's at full k (shared
    :func:`_sparse_dual_refine`).  Callers gate eligibility through
    ``choose_tracked_pipeline`` and own cut/drift fallback — this function
    trusts its prior."""
    from ncnet_tpu.ops.sparse_topk import resolve_halo
    from ncnet_tpu.ops.temporal import temporal_candidates

    nc_params = params["nc"]
    if config.half_precision:
        nc_params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), nc_params)
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    factor = config.sparse_factor
    halo = resolve_halo(config.sparse_halo, factor)
    hac, wac = fa.shape[1] // factor, fa.shape[2] // factor
    hbc, wbc = fb.shape[1] // factor, fb.shape[2] // factor
    cand_ab = temporal_candidates(prior_ab, hbc, wbc, config.track_radius)
    cand_ba = temporal_candidates(prior_ba, hac, wac, config.track_radius)
    return _sparse_dual_refine(config, nc_params, fa, fb, cand_ab, cand_ba,
                               factor=factor, halo=halo)


def ncnet_forward_tracked(
    config: ModelConfig,
    params,
    source_features: jnp.ndarray,
    target_images: jnp.ndarray,
    prior_ab: jnp.ndarray,
    prior_ba: jnp.ndarray,
) -> NCNetOutput:
    """Streaming forward: source (reference) features precomputed — resolved
    once per stream from the feature store — target frame extracted
    in-program, and the match volume built by the tracked pipeline.  The
    tier consult happens at trace time like every other dispatch, so a
    demoted sparse tier retraces onto the ordinary
    :func:`ncnet_match_volume` fallback instead of re-entering the crashed
    fine pass through the streaming door."""
    from ncnet_tpu.ops.sparse_corr import choose_tracked_pipeline
    from ncnet_tpu.ops.sparse_topk import resolve_halo

    fa = source_features
    fb = extract_features(config, params, target_images)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    tier = choose_tracked_pipeline(
        fa.shape[1], fa.shape[2], fb.shape[1], fb.shape[2],
        factor=config.sparse_factor,
        halo=resolve_halo(config.sparse_halo, config.sparse_factor),
        radius=config.track_radius,
        reloc_k=config.relocalization_k_size,
    )
    if tier == "tracked":
        return coarse2fine_tracked_filter(config, params, fa, fb,
                                          prior_ab, prior_ba)
    return ncnet_match_volume(config, params, fa, fb)


def ncnet_filter(config: ModelConfig, params, corr: jnp.ndarray,
                 remat_nc_layers: bool = False,
                 nc_custom_grad: bool = False,
                 nc_pallas: bool = True,
                 nc_pallas_vjp: bool = False) -> NCNetOutput:
    """The post-correlation half of the forward pass: [maxpool4d] →
    MutualMatching → NeighConsensus → MutualMatching.  Split out so the
    high-res/sharded paths can feed their own correlation volume.
    ``remat_nc_layers`` / ``nc_custom_grad``: see :func:`neigh_consensus`
    (training memory knobs).  ``nc_pallas``: permit the fused-lane Pallas
    stack on the forward.  ``nc_pallas_vjp``: the TRAINING form of that
    permission — fuse only where the resident Pallas BACKWARD also engages
    (``require_vjp`` in :func:`neigh_consensus`); training/loss.py passes
    both True since round 7.  ``config.nc_tier`` (round 17) forces the
    named arithmetic tier ('cp'/'fft') through :func:`neigh_consensus`'s
    ``force_tier`` seam — the CP fine-tune path sets it so factor
    gradients flow regardless of the chooser's FLOP gate."""
    nc_params = params["nc"]
    if config.half_precision:
        nc_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), nc_params)
        corr = corr.astype(jnp.bfloat16)
    delta4d = None
    if config.relocalization_k_size > 1:
        corr, delta4d = maxpool4d_with_argmax(corr, config.relocalization_k_size)
    corr = mutual_matching(corr)
    corr = neigh_consensus(nc_params, corr, symmetric=config.symmetric_mode,
                           remat_layers=remat_nc_layers,
                           custom_grad=nc_custom_grad,
                           allow_pallas=nc_pallas,
                           require_vjp=nc_pallas_vjp,
                           force_tier=config.nc_tier or None)
    corr = mutual_matching(corr)
    return NCNetOutput(corr, delta4d)


def make_point_matcher(config: ModelConfig, params, *, do_softmax: bool = True,
                       scale: str = "centered"):
    """Persistent warm single-pair matcher — the demo / batch-1 serving path.

    The bench measured the naive bs1 wall at ~44× device time (VERDICT r5
    #4): a serial caller uploads two fp32 400² images (~3.8 MB) and pulls
    the fp32 25⁴ volume (~1.6 MB) through the tunnel per pair.  This wraps
    the same forward the demo runs into the InLoc pipeline shape: ONE jitted
    program (weights staged on device at build, program cached after the
    first call) taking raw uint8 ``(1, H, W, 3)`` pairs, normalizing on
    device, and returning the compact ``corr_to_matches`` table instead of
    the volume — ~4× fewer upload bytes and ~100× fewer download bytes.
    ``dispatch``/``fetch`` expose the async split so a caller with several
    pairs can pipeline them exactly like the InLoc eval loop.

    Returns ``matcher(src_u8, tgt_u8) ->``
    :class:`~ncnet_tpu.ops.matching.Matches` of numpy arrays.
    """
    from ncnet_tpu.ops.image import normalize_imagenet

    params = jax.device_put(params)  # pre-staged once, reused every pair

    from ncnet_tpu.observability.quality import (
        active_tier,
        append_quality_row,
        emit_quality,
        split_quality_row,
    )

    def run(p, src, tgt):
        src = normalize_imagenet(src.astype(jnp.float32))
        tgt = normalize_imagenet(tgt.astype(jnp.float32))
        out = ncnet_forward(config, p, src, tgt)
        # relocalization configs pool the volume and carry delta4d — apply
        # it so matches land on the fine grid (as extract_match_table does)
        m = corr_to_matches(
            out.corr, delta4d=out.delta4d,
            k_size=max(config.relocalization_k_size, 1),
            do_softmax=do_softmax, scale=scale,
        )
        # one stacked result: a single device→host pull instead of five.
        # An extra row carries the pair's quality signals (the
        # append_quality_row wire protocol) — the serving path's per-query
        # accuracy monitor, computed in-graph at no extra round trip.
        # ravel() flattens the batch-1 fields to the (5, N) wire shape the
        # protocol expects (round-10 stacked them as (5, 1, N), which
        # silently failed append_quality_row's width guard — the quality
        # row never actually rode along; fetch restores the (1, N) field
        # shape on host)
        table = jnp.stack([v.astype(jnp.float32).ravel() for v in m])
        return append_quality_row(table, out.corr)

    jitted = ResilientJit(
        run, label="point_matcher",
        # compiled-program memory ledger: one row per pair-shape class the
        # warm matcher actually serves (observability/memory.py)
        ledger_program="point_matcher",
        ledger_key_fn=lambda p, s, t: (
            f"{s.shape[1]}x{s.shape[2]}-{t.shape[1]}x{t.shape[2]}xb1"),
        ledger_tier=lambda: active_tier(config.half_precision),
    )

    def dispatch(src, tgt):
        """Enqueue upload + forward + match extraction without blocking."""
        return jitted(params, jnp.asarray(src), jnp.asarray(tgt))

    def fetch_with_quality(handle):
        """``(Matches, {signal: float} | None)`` for one fetched handle —
        the quality travels WITH the result it describes, so concurrent
        callers (the serving layer pipelines several pairs) can never read
        another request's signals.  The per-call return is the fix for the
        round-10 attribute-on-closure pattern: ``matcher.last_quality`` is
        kept as a demo/notebook convenience but is last-write-wins across
        callers by construction — anything concurrent must use this."""
        table, quality = split_quality_row(
            np.asarray(handle, dtype=np.float32))
        if quality is not None:
            # streamed as a tier-tagged `quality` event when a telemetry
            # sink is bound (no-op otherwise)
            matcher.last_quality = quality
            emit_quality("serving", quality,
                         tier=active_tier(config.half_precision))
        return Matches(*(table[i][None] for i in range(5))), quality

    def fetch(handle) -> "Matches":
        return fetch_with_quality(handle)[0]

    def match_with_quality(src, tgt):
        """One blocking call returning ``(Matches, quality | None)``."""
        return fetch_with_quality(dispatch(src, tgt))

    def matcher(src, tgt) -> "Matches":
        return fetch(dispatch(src, tgt))

    matcher.dispatch = dispatch
    matcher.fetch = fetch
    matcher.fetch_with_quality = fetch_with_quality
    matcher.match_with_quality = match_with_quality
    # single-caller convenience only (see fetch_with_quality): the signals
    # of the most recent fetch ANY caller made
    matcher.last_quality = None
    # tier-degradation seam: recover_from_device_failure(exc, matcher)
    matcher.retrace = jitted.retrace
    return matcher


class NCNet:
    """Thin convenience wrapper bundling config + params with a jitted call.

    The functional API (``init_ncnet`` / ``ncnet_forward``) is the real
    surface; this mirrors the reference's ``model = ImMatchNet(...);
    model(batch)`` usage for scripts and notebooks.
    """

    def __init__(self, config: ModelConfig = ModelConfig(), params=None, seed: int = 1):
        from ncnet_tpu.models.checkpoint import load_params  # lazy, avoids cycle

        self.config = config
        if params is None and config.checkpoint:
            self.config, params = load_params(config.checkpoint, config)
        self.params = params if params is not None else init_ncnet(
            self.config, jax.random.key(seed)
        )
        self._jitted = jax.jit(
            lambda p, s, t: ncnet_forward(self.config, p, s, t)
        )

    def __call__(self, source_images, target_images) -> NCNetOutput:
        return self._jitted(self.params, source_images, target_images)

    def forward_fn(self, params, source_images, target_images) -> NCNetOutput:
        """Unjitted functional forward with explicit params — compose this
        inside larger jitted programs (eval steps, train steps)."""
        return ncnet_forward(self.config, params, source_images, target_images)
