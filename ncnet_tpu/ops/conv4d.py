"""4D convolution for neighbourhood-consensus filtering.

The reference implements conv4d as a *Python loop* over the first spatial dim,
each iteration dispatching an F.conv3d (/root/reference/lib/conv4d.py:39-48) —
the single hottest anti-pattern to avoid on TPU.  Here the k_A-tap
decomposition is a statically-unrolled sum of ``lax.conv_general_dilated`` 3D
convolutions over the *whole* volume: under ``jit`` the unroll is traced once,
XLA fuses the shifted reads, and each conv runs batched over ``B·hA`` on the
MXU.

Semantics: cross-correlation (like torch convNd), "same" zero padding of
``k//2`` per spatial dim, stride/dilation/groups fixed at 1 — exactly the
envelope the reference supports (conv4d.py:59-62).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def conv4d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    precision=None,
    pad_ha: bool = True,
    pad_hb: bool = True,
) -> jnp.ndarray:
    """4D convolution over the correlation volume ("same" by default).

    Args:
      x:      ``(B, hA, wA, hB, wB, C_in)`` channels-last volume.
      weight: ``(kA, kWA, kB, kWB, C_in, C_out)``.
      bias:   ``(C_out,)`` or None.
      pad_ha / pad_hb: when False, the hA / hB dim is treated as *valid* —
        the caller already padded it (the spatially-sharded path pre-pads
        with halo slabs exchanged between shards, parallel/spatial.py) and
        the output is ``k//2`` smaller on each side of that dim.

    Returns:
      ``(B, hA', wA, hB', wB, C_out)`` (primed dims shrink iff unpadded).
    """
    b, ha, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, wc_in, c_out = weight.shape
    assert wc_in == c_in, f"channel mismatch: {wc_in} vs {c_in}"

    if pad_ha:
        # Zero-pad the leading spatial dim once; the other three dims are
        # padded inside the 3D conv below.
        x = jnp.pad(x, ((0, 0), (ka // 2, ka // 2), (0, 0), (0, 0), (0, 0), (0, 0)))
    xp = x
    ha = xp.shape[1] - (ka - 1)  # output length of the tap loop

    pads3 = [
        (kwa // 2, kwa // 2),
        (kb // 2, kb // 2) if pad_hb else (0, 0),
        (kwb // 2, kwb // 2),
    ]
    hb_out = hb if pad_hb else hb - (kb - 1)
    dn = lax.conv_dimension_numbers(
        (b * ha, wa, hb, wb, c_in), (kwa, kb, kwb, c_in, c_out), ("NDHWC", "DHWIO", "NDHWC")
    )

    out = None
    for p in range(ka):  # static unroll: ka ≤ 5, traced once under jit
        # shifted slice s.t. out[i] = Σ_p x[i + p - k//2] * w[p]  (the same
        # tap alignment as the reference loop, conv4d.py:39-48)
        sl = lax.slice_in_dim(xp, p, p + ha, axis=1)
        o = lax.conv_general_dilated(
            sl.reshape(b * ha, wa, hb, wb, c_in),
            weight[p],
            window_strides=(1, 1, 1),
            padding=pads3,
            dimension_numbers=dn,
            precision=precision,
        )
        out = o if out is None else out + o
    out = out.reshape(b, ha, wa, hb_out, wb, c_out)
    if bias is not None:
        out = out + bias
    return out


def conv4d_init(
    key: jax.Array, kernel_size: int, c_in: int, c_out: int, dtype=jnp.float32
):
    """torch-_ConvNd-style uniform init ±1/√(C_in·k⁴), the distribution the
    reference's Conv4d inherits (conv4d.py:53-82 via _ConvNd defaults), so
    training dynamics start from a comparable point.

    Returns ``(weight, bias)`` with weight ``(k,k,k,k,C_in,C_out)``.
    """
    k_w, k_b = jax.random.split(key)
    fan_in = c_in * kernel_size**4
    bound = 1.0 / math.sqrt(fan_in)
    weight = jax.random.uniform(
        k_w,
        (kernel_size,) * 4 + (c_in, c_out),
        minval=-bound,
        maxval=bound,
        dtype=dtype,
    )
    bias = jax.random.uniform(k_b, (c_out,), minval=-bound, maxval=bound, dtype=dtype)
    return weight, bias
