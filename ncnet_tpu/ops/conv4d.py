"""4D convolution for neighbourhood-consensus filtering.

The reference implements conv4d as a *Python loop* over the first spatial dim,
each iteration dispatching an F.conv3d (/root/reference/lib/conv4d.py:39-48) —
the single hottest anti-pattern to avoid on TPU.  Here the k_A-tap
decomposition becomes whole-volume ``lax.conv_general_dilated`` programs, with
five MXU-aware formulations of which ``auto`` selects per layer by
measurement (TPU v5e at the PF-Pascal 25⁴ workload):

  * ``unroll``   — statically-unrolled sum of kA 3D convs over shifted views.
  * ``tapfold``  — folds the kA taps into *input* channels (one 3D conv with
                   kA·C_in inputs); wins when C_in is tiny (the 1-channel
                   first NC layer), where the plain conv's reduction dim
                   underfills the MXU.
  * ``coutfold`` — folds the kA taps into *output* channels (one 3D conv
                   producing kA·C_out channels + a cheap shifted sum); the
                   best conv formulation for the fat 16→16 middle layer,
                   where plain convs leave 112 of 128 MXU output lanes idle.
  * ``afold``    — folds the FULL A-side stencil (kA·kWA taps) into output
                   channels (one 2D conv over (hB,wB) + shifted sums over
                   both A dims); maximizes MXU output-lane fill.  Wins
                   STANDALONE for small C_out (0.84 vs coutfold 1.69 ms/pair
                   at 16→1) but loses composed into the stack and breaks
                   under AD on this toolchain — not selected by ``auto``
                   (measurement history in choose_conv4d_variant).
  * ``toeplitz_b`` — expresses the whole B-side (kB,kWB) stencil as a dense
                   banded matrix over the flattened hB·wB lane dim, turning
                   the layer into kA·kWA big matmuls of shape
                   (B·hA·wA, C_in·hB·wB) × (C_in·hB·wB, hB·wB·C_out) — near-
                   peak MXU utilization bought with kB·kWB× the true FLOPs
                   and an O((hB·wB)²) mask.  NOT selected by ``auto``:
                   honest scan-differenced timing shows ``coutfold`` beats it
                   ~8× standalone forward and ~4× under autodiff (its XLA
                   transpose materializes the full dense weight-grad tensor);
                   it stays available as an explicitly-selectable formulation
                   and as a structurally-independent test oracle.

``variant='auto'`` picks per-layer by channel shape (see
``choose_conv4d_variant`` for the measurements).  All variants share the
reference's semantics: cross-correlation (like torch convNd), "same" zero
padding of ``k//2`` per spatial dim, stride/dilation/groups fixed at 1 —
exactly the envelope the reference supports (conv4d.py:59-62).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _dn3(x_shape, w_shape):
    return lax.conv_dimension_numbers(x_shape, w_shape, ("NDHWC", "DHWIO", "NDHWC"))


def _pads3(kwa: int, kb: int, kwb: int, pad_hb: bool,
           pad_wa: bool = True, pad_wb: bool = True):
    return [
        (kwa // 2, kwa // 2) if pad_wa else (0, 0),
        (kb // 2, kb // 2) if pad_hb else (0, 0),
        (kwb // 2, kwb // 2) if pad_wb else (0, 0),
    ]


def _conv4d_unroll(x, weight, *, precision, pad_ha, pad_hb, pad_wa, pad_wb):
    """Sum over kA taps of a 3D conv on shifted whole-volume views."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    if pad_ha:
        x = jnp.pad(x, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    ha = x.shape[1] - (ka - 1)
    wa_out = wa if pad_wa else wa - (kwa - 1)
    hb_out = hb if pad_hb else hb - (kb - 1)
    wb_out = wb if pad_wb else wb - (kwb - 1)
    dn = _dn3((b * ha, wa, hb, wb, c_in), (kwa, kb, kwb, c_in, c_out))
    out = None
    for p in range(ka):  # static unroll: ka ≤ 5, traced once under jit
        sl = lax.slice_in_dim(x, p, p + ha, axis=1)
        o = lax.conv_general_dilated(
            sl.reshape(b * ha, wa, hb, wb, c_in),
            weight[p],
            window_strides=(1, 1, 1),
            padding=_pads3(kwa, kb, kwb, pad_hb, pad_wa, pad_wb),
            dimension_numbers=dn,
            precision=precision,
        )
        out = o if out is None else out + o
    return out.reshape(b, ha, wa_out, hb_out, wb_out, c_out)


def _conv4d_tapfold(x, weight, *, precision, pad_ha, pad_hb, pad_wa, pad_wb):
    """One 3D conv with the kA taps folded into input channels."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    if pad_ha:
        x = jnp.pad(x, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    ha = x.shape[1] - (ka - 1)
    wa_out = wa if pad_wa else wa - (kwa - 1)
    hb_out = hb if pad_hb else hb - (kb - 1)
    wb_out = wb if pad_wb else wb - (kwb - 1)
    shifts = jnp.concatenate(
        [lax.slice_in_dim(x, p, p + ha, axis=1) for p in range(ka)], axis=-1
    )
    wf = jnp.transpose(weight, (1, 2, 3, 0, 4, 5)).reshape(
        kwa, kb, kwb, ka * c_in, c_out
    )
    dn = _dn3((b * ha, wa, hb, wb, ka * c_in), wf.shape)
    o = lax.conv_general_dilated(
        shifts.reshape(b * ha, wa, hb, wb, ka * c_in),
        wf,
        window_strides=(1, 1, 1),
        padding=_pads3(kwa, kb, kwb, pad_hb, pad_wa, pad_wb),
        dimension_numbers=dn,
        precision=precision,
    )
    return o.reshape(b, ha, wa_out, hb_out, wb_out, c_out)


def _conv4d_coutfold(x, weight, *, precision, pad_ha, pad_hb, pad_wa, pad_wb):
    """One 3D conv producing kA·C_out channels + shifted sum over hA."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    wa_out = wa if pad_wa else wa - (kwa - 1)
    hb_out = hb if pad_hb else hb - (kb - 1)
    wb_out = wb if pad_wb else wb - (kwb - 1)
    wf = jnp.transpose(weight, (1, 2, 3, 4, 0, 5)).reshape(
        kwa, kb, kwb, c_in, ka * c_out
    )
    dn = _dn3((b * ha_in, wa, hb, wb, c_in), wf.shape)
    y = lax.conv_general_dilated(
        x.reshape(b * ha_in, wa, hb, wb, c_in),
        wf,
        window_strides=(1, 1, 1),
        padding=_pads3(kwa, kb, kwb, pad_hb, pad_wa, pad_wb),
        dimension_numbers=dn,
        precision=precision,
    )
    # out[i] = Σ_p y[i + p − (pad: ka//2 / valid: 0), …, tap-p channel block].
    # The tap is selected by slicing the fused (ka·C_out) channel dim —
    # splitting it into a (…, ka, C_out) axis pair makes XLA materialize a
    # relayout of the whole volume (~30ms at the PF-Pascal workload).
    y = y.reshape(b, ha_in, wa_out, hb_out, wb_out, ka * c_out)
    if pad_ha:
        y = jnp.pad(y, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    ha = y.shape[1] - (ka - 1)
    out = None
    for p in range(ka):
        o = lax.slice_in_dim(y, p, p + ha, axis=1)[..., p * c_out:(p + 1) * c_out]
        out = o if out is None else out + o
    return out


def _conv4d_afold(x, weight, *, precision, pad_ha, pad_hb,
                  pad_wa=True, pad_wb=True):
    """One 2D conv over (hB,wB) producing kA·kWA·C_out channels + a shifted
    sum over BOTH A dims.

    Folding the whole A-side stencil into output channels lifts the matmul's
    output dim to kA·kWA·C_out (400 for the 5⁴ 16→16 layer) — full 128-lane
    MXU tiles where ``coutfold``'s kA·C_out=80 underfills — at the cost of a
    kA·kWA·C_out-channel intermediate and kA·kWA shifted adds.  The
    intermediate's traffic decides the contest (v5e, 25⁴ volume, bf16 bs4,
    scan-differenced, tools/xla_layer_probe.py): at 16→16 the 25×
    intermediate swamps the fill gain (7.1 vs coutfold 2.7 ms/pair), while
    at 16→1 the intermediate is only ~1.6× the input volume and afold wins
    STANDALONE (0.84 vs 1.69) — but loses composed into the NC stack and
    its transpose breaks under AD on this toolchain, so ``auto`` still
    avoids it (see choose_conv4d_variant).
    """
    if not (pad_wa and pad_wb):
        raise ValueError(
            "afold does not support valid (unpadded) wA/wB; use "
            "unroll/tapfold/coutfold for the 2D-sharded shapes"
        )
    b, ha, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    hb_out = hb if pad_hb else hb - (kb - 1)
    wf = jnp.transpose(weight, (2, 3, 4, 0, 1, 5)).reshape(
        kb, kwb, c_in, ka * kwa * c_out
    )
    dn = lax.conv_dimension_numbers(
        (b * ha * wa, hb, wb, c_in), wf.shape, ("NHWC", "HWIO", "NHWC")
    )
    y = lax.conv_general_dilated(
        x.reshape(b * ha * wa, hb, wb, c_in),
        wf,
        window_strides=(1, 1),
        padding=[
            (kb // 2, kb // 2) if pad_hb else (0, 0),
            (kwb // 2, kwb // 2),
        ],
        dimension_numbers=dn,
        precision=precision,
    )
    # out[i,j] = Σ_{p,q} y[i+p−padA, j+q−kwa//2, …, tap-(p,q) channel block]
    # (the same tap-selection-by-channel-slice trick as coutfold: splitting
    # the fused channel axis would relayout the whole volume)
    y = y.reshape(b, ha, wa, hb_out, wb, ka * kwa * c_out)
    pads = ((0, 0), (ka // 2, ka // 2) if pad_ha else (0, 0),
            (kwa // 2, kwa // 2)) + ((0, 0),) * 3
    y = jnp.pad(y, pads)
    ha_out = y.shape[1] - (ka - 1)
    out = None
    for p in range(ka):
        yp = lax.slice_in_dim(y, p, p + ha_out, axis=1)
        for q in range(kwa):
            t = (p * kwa + q) * c_out
            o = lax.slice_in_dim(yp, q, q + wa, axis=2)[..., t:t + c_out]
            out = o if out is None else out + o
    return out


@functools.lru_cache(maxsize=32)
def _shift_masks(hb_in: int, wb_in: int, hb_out: int, wb_out: int,
                 kb: int, kwb: int, pad_hb: bool):
    """One-hot banded shift masks ``(kB·kWB, hb_in·wb_in, hb_out·wb_out)``:
    ``M[(r,s), n_src, n_out] = 1`` iff source cell ``n_src`` sits at stencil
    offset ``(r,s)`` of output cell ``n_out`` (zero padding ⇒ missing rows)."""
    ms = []
    for r in range(kb):
        for s in range(kwb):
            sh = np.eye(hb_in, hb_out, k=(kb // 2 if pad_hb else 0) - r)
            sw = np.eye(wb_in, wb_out, k=kwb // 2 - s)
            ms.append(np.kron(sh, sw))
    return np.stack(ms).astype(np.float32)


def _conv4d_toeplitz_b(x, weight, *, precision, pad_ha, pad_hb,
                       pad_wa=True, pad_wb=True):
    """kA·kWA shifted matmuls against a dense banded B-stencil matrix."""
    if not (pad_wa and pad_wb):
        raise ValueError(
            "toeplitz_b does not support valid (unpadded) wA/wB; use "
            "unroll/tapfold/coutfold for the 2D-sharded shapes"
        )
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    hb_out = hb if pad_hb else hb - (kb - 1)
    n_in, n_out = hb * wb, hb_out * wb
    masks = jnp.asarray(
        _shift_masks(hb, wb, hb_out, wb, kb, kwb, pad_hb), dtype=weight.dtype
    )
    wv = weight.reshape(ka, kwa, kb * kwb, c_in, c_out)
    # T[p, q, K, (n_out, c_out)] — K ordered (n_src, c_in) to match the
    # input flattening (a pure minor-dims reshape of the 6D volume), which
    # avoids a ~10ms whole-volume transpose.
    t = jnp.einsum("pquio,unm->pqnimo", wv, masks, precision=precision)
    t = t.reshape(ka, kwa, n_in * c_in, n_out * c_out)
    xf = x.reshape(b, ha_in, wa, n_in * c_in)
    if pad_ha:
        xf = jnp.pad(xf, ((0, 0), (ka // 2,) * 2, (0, 0), (0, 0)))
    xf = jnp.pad(xf, ((0, 0), (0, 0), (kwa // 2,) * 2, (0, 0)))
    ha = xf.shape[1] - (ka - 1)
    out = None
    for p in range(ka):
        for q in range(kwa):
            xs = xf[:, p:p + ha, q:q + wa, :]
            o = jnp.einsum("bijk,kn->bijn", xs, t[p, q], precision=precision)
            out = o if out is None else out + o
    return out.reshape(b, ha, wa, hb_out, wb, c_out)


_VARIANTS = {
    "unroll": _conv4d_unroll,
    "tapfold": _conv4d_tapfold,
    "coutfold": _conv4d_coutfold,
    "afold": _conv4d_afold,
    "toeplitz_b": _conv4d_toeplitz_b,
}


# The channel-folding formulations materialize a kA·C-channel copy of the
# whole volume (coutfold: kA·C_out; tapfold: kA·C_in).  At the PF-Pascal
# training workload that copy is ~2GB and is the price of the fastest
# formulation; at InLoc resolution (56M cells) it is tens of GB and a
# guaranteed OOM on a 16GB chip.  Above this bound 'auto' falls back to the
# tap-unrolled formulation, whose intermediates stay at 1× the volume.
_FOLD_BYTES_LIMIT = 4 * 2**30


def conv4d_fold_fits(
    batch: int, ha: int, wa: int, hb: int, wb: int, k: int, ch: int, dtype
) -> bool:
    """True when the channel-folding formulations' kA·ch whole-volume copy
    stays under ``_FOLD_BYTES_LIMIT`` — the same bound ``auto`` uses to
    demote to ``unroll``.  Exposed so callers planning batch layouts (the
    symmetric fold in models/ncnet.py) can consult the one authority instead
    of duplicating the threshold."""
    cells = batch * ha * wa * hb * wb
    return cells * k * ch * jnp.dtype(dtype).itemsize <= _FOLD_BYTES_LIMIT


def choose_conv4d_variant(
    c_in: int,
    c_out: int,
    hb: int,
    wb: int,
    *,
    shape_a: tuple | None = None,
    kernel: tuple | None = None,
    same_pad: bool = True,
    dtype=None,
    batch: int | None = None,
) -> str:
    """Per-layer formulation choice, measured on v5e at the PF-Pascal 25⁴
    volume (batch 8, fp32, device-side scan-differenced timing — the honest
    harness; earlier numbers from the cached-execution loop were wrong):

      forward-only:  1→16 tapfold 3.3ms;  16→16 coutfold 24ms;
                     16→1 coutfold 1.9ms (toeplitz_b 15.4ms standalone,
                     ~equal inside the stack behind a CN seam)
      fwd+bwd (AD):  1→16 tapfold 12.5ms; 16→16 coutfold 69ms;
                     16→1 coutfold 13.5ms vs toeplitz_b 54ms — the
                     XLA transpose of the dense-mask einsums materializes a
                     (kA·kWA, hB·wB·C_in, hB·wB·C_out) weight-gradient tensor

    ``auto`` never picks ``toeplitz_b`` or ``afold`` (both remain selectable
    explicitly; afold's standalone small-C_out win did not survive
    composition — see the in-body comment).  With the full shape context
    (``shape_a=(ha, wa)``, ``kernel``, ``dtype``) the small-C_out case first
    tries the Pallas tap-folding kernel where Mosaic accepts it — true FLOPs
    at full MXU lanes (see ops/conv4d_pallas.py for its current status) —
    and the channel-folding formulations are gated on their
    ``_FOLD_BYTES_LIMIT`` memory blowup (InLoc-scale volumes use
    ``unroll``)."""

    def fold_fits(ch: int) -> bool:
        if batch is None or shape_a is None or kernel is None or dtype is None:
            return True  # shape context unknown: legacy small-volume callers
        return conv4d_fold_fits(
            batch, shape_a[0], shape_a[1], hb, wb, kernel[0], ch, dtype
        )

    if c_in <= 4:
        return "tapfold" if fold_fits(c_in) else "unroll"
    if c_out <= 4:
        if (
            same_pad
            and shape_a is not None
            and kernel is not None
            and dtype is not None
            and len(set(kernel)) == 1
            and kernel[0] % 2 == 1
            and _pallas_available()
        ):
            from ncnet_tpu.ops.conv4d_pallas import (
                pallas_compiles,
                pallas_feasible,
            )

            itemsize = jnp.dtype(dtype).itemsize
            if pallas_feasible(
                shape_a[0], shape_a[1], hb, wb, c_in, c_out, kernel[0],
                itemsize=itemsize,
            ) and pallas_compiles(
                shape_a[0], shape_a[1], hb, wb, c_in, c_out, kernel[0],
                dtype_name=jnp.dtype(dtype).name,
            ):
                return "pallas"
        # afold measured FASTER standalone for small C_out (0.84 vs coutfold
        # 1.69 ms/pair, 16→1 bf16 bs4 25⁴, tools/xla_layer_probe.py) — its
        # kA·kWA·C_out-channel intermediate is tiny there — but the win did
        # NOT survive composition: with afold auto-selected the full-model
        # bench REGRESSED (fp32 11.5→13.0, bf16 9.2→9.9 ms/pair; layout seam
        # between afold's (b·hA·wA, hB, wB, C) 2D-conv form and its
        # neighbours' (b·hA, wA, hB, wB, C) 3D form), and differentiating
        # through afold's XLA transpose hit repeated compile failures on this
        # toolchain (tools/vjp_probe.py dw_afold, bench train bs8).  So auto
        # stays on coutfold; afold remains explicitly selectable.
        #
        # The same standalone-vs-composed inversion reproduced independently
        # at the InLoc scale for the c_in≤4 rule: 1→16 k3 on the 56M-cell
        # volume measures coutfold 3.6 vs tapfold 10.2 ms standalone
        # (tools/inloc_filter_probe.py), yet swapping it inside the composed
        # ncnet_filter made the whole filter SLOWER (88.3 → 99.0 ms).  Treat
        # any future standalone variant probe as a hypothesis only — the
        # composed program is the unit of measurement.
    return "coutfold" if fold_fits(c_out) else "unroll"


@functools.lru_cache(maxsize=1)
def _pallas_available() -> bool:
    """Mosaic kernels need a real TPU backend (the CPU path uses the XLA
    formulations; tests drive the kernel via interpret mode explicitly)."""
    try:
        return "TPU" in jax.devices()[0].device_kind
    except Exception:
        return False


def conv4d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    precision=None,
    pad_ha: bool = True,
    pad_hb: bool = True,
    pad_wa: bool = True,
    pad_wb: bool = True,
    variant: str = "auto",
) -> jnp.ndarray:
    """4D convolution over the correlation volume ("same" by default).

    Args:
      x:      ``(B, hA, wA, hB, wB, C_in)`` channels-last volume.
      weight: ``(kA, kWA, kB, kWB, C_in, C_out)``.
      bias:   ``(C_out,)`` or None.
      pad_ha / pad_hb / pad_wa / pad_wb: when False, that dim is treated as
        *valid* — the caller already padded it (the spatially-sharded path
        pre-pads with halo slabs exchanged between shards,
        parallel/spatial.py; the 2D-sharded path halos hB AND wB, or hA AND
        wA on the transposed pass) and the output is ``k//2`` smaller on
        each side of that dim.
      variant: 'auto' (per-layer MXU heuristic, `choose_conv4d_variant`), or
        an explicit formulation from 'unroll' / 'tapfold' / 'coutfold' /
        'afold' / 'toeplitz_b' (see module docstring).  All variants are
        numerically equivalent up to float reassociation (afold/toeplitz_b
        support the same-padded w dims only).

    Returns:
      ``(B, hA', wA', hB', wB', C_out)`` (primed dims shrink iff unpadded).
    """
    c_in, c_out = weight.shape[4], weight.shape[5]
    hb, wb = x.shape[3], x.shape[4]
    assert x.shape[5] == c_in, f"channel mismatch: {x.shape[5]} vs {c_in}"
    if variant == "auto":
        variant = choose_conv4d_variant(
            c_in, c_out, hb, wb,
            shape_a=(x.shape[1], x.shape[2]),
            kernel=tuple(weight.shape[:4]),
            # the pallas kernel runs its dot at default MXU precision: keep
            # explicit-precision calls on the XLA variants, which honor it
            same_pad=(pad_ha and pad_hb and pad_wa and pad_wb
                      and precision is None),
            dtype=x.dtype,
            batch=x.shape[0],
        )
    if variant == "pallas":
        from ncnet_tpu.ops.conv4d_pallas import conv4d_small_cout

        assert pad_ha and pad_hb and pad_wa and pad_wb, (
            "the pallas variant supports only the same-padded volume form"
        )
        assert precision is None, (
            "the pallas variant does not honor an explicit precision; use an "
            "XLA variant"
        )
        out = conv4d_small_cout(x, weight)
    else:
        out = _VARIANTS[variant](
            x, weight, precision=precision, pad_ha=pad_ha, pad_hb=pad_hb,
            pad_wa=pad_wa, pad_wb=pad_wb,
        )
    if bias is not None:
        out = out + bias
    return out


def conv4d_transpose_weights(weight: jnp.ndarray) -> jnp.ndarray:
    """Weights of the transposed conv4d: all four spatial dims flipped,
    channel roles swapped — ``(kA,kWA,kB,kWB,C_in,C_out) →
    (kA,kWA,kB,kWB,C_out,C_in)``.  For odd kernels the cotangent of a
    same-padded stride-1 cross-correlation is the same-padded
    cross-correlation with these weights."""
    return jnp.transpose(weight[::-1, ::-1, ::-1, ::-1], (0, 1, 2, 3, 5, 4))


# Formulation whose XLA transpose computes the weight gradient.  Measured on
# v5e at the 25⁴ symmetric stack (tools/vjp_probe.py, bs8 fp32, ms/pair /
# XLA temp): coutfold 55.8 / 12.4G beats tapfold 73.4 / 13.7G and unroll
# 89.0 / 13.3G — unroll additionally makes XLA pick channel-minor layouts
# padded 8-10x for whole-volume relu/copy temporaries.
_DW_VARIANT = "coutfold"


@functools.lru_cache(maxsize=None)
def make_conv4d_same(dx_variant: str = "auto", dw_variant: str = _DW_VARIANT):
    """Same-padded ``conv4d`` with an explicitly-routed backward pass.

    Forward is exactly ``conv4d(x, weight, bias)`` (auto variant).  The
    difference is under autodiff: XLA's mechanical transpose of the fastest
    forward formulation (``coutfold``) is pathological — measured 69 ms for
    the 16→16 layer's backward vs 24 ms forward (fp32 bs8 v5e; VERDICT r2) —
    so each gradient is routed through its own explicitly-chosen
    formulation instead:

      * ``dx``  — itself a same-padded conv4d: ``conv4d(g, flipped/swapped
        weights, variant=dx_variant)``; the default ``'auto'`` re-enters the
        variant chooser with the *gradient's* channel shape (a 16→1 layer's
        dx is a 1→16 conv → tapfold, etc.).
      * ``dw``  — AD of the ``dw_variant`` formulation (measured default,
        see tools/vjp_probe.py; demoted to ``unroll`` past the
        channel-folding memory gate).
      * ``db``  — a plain sum reduction.

    Odd kernel sizes only (the reference's only case) — asserted, because
    the dx identity above needs them.  The factory is cached so each
    (dx, dw) routing is ONE custom_vjp primitive (stable jit caching).
    """

    @jax.custom_vjp
    def _conv4d_same(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray):
        return conv4d(x, weight, bias)

    def _fwd(x, weight, bias):
        assert all(k % 2 == 1 for k in weight.shape[:4]), (
            "conv4d_same requires odd kernel sizes (same-padding transpose)"
        )
        return conv4d(x, weight, bias), (x, weight)

    def _bwd(res, g):
        x, weight = res
        dx = conv4d(g, conv4d_transpose_weights(weight), variant=dx_variant)
        dwv = dw_variant
        # honor the same channel-folding memory gate as the forward
        # auto-chooser: at volumes where the kA·ch whole-volume copy cannot
        # fit, demote to the 1x-footprint unroll formulation
        fold_ch = {"coutfold": weight.shape[5], "tapfold": weight.shape[4],
                   "afold": weight.shape[1] * weight.shape[5]}.get(dwv)
        if fold_ch is not None and not conv4d_fold_fits(
            x.shape[0], x.shape[1], x.shape[2], x.shape[3], x.shape[4],
            weight.shape[0], fold_ch, x.dtype,
        ):
            dwv = "unroll"
        _, w_vjp = jax.vjp(lambda ww: conv4d(x, ww, variant=dwv), weight)
        (dw,) = w_vjp(g)
        db = jnp.sum(g, axis=(0, 1, 2, 3, 4))
        return dx, dw, db

    _conv4d_same.defvjp(_fwd, _bwd)
    return _conv4d_same


#: the default routing (kept as a module-level callable for back-compat)
conv4d_same = make_conv4d_same()


def conv4d_init(
    key: jax.Array, kernel_size: int, c_in: int, c_out: int, dtype=jnp.float32
):
    """torch-_ConvNd-style uniform init ±1/√(C_in·k⁴), the distribution the
    reference's Conv4d inherits (conv4d.py:53-82 via _ConvNd defaults), so
    training dynamics start from a comparable point.

    Returns ``(weight, bias)`` with weight ``(k,k,k,k,C_in,C_out)``.
    """
    k_w, k_b = jax.random.split(key)
    fan_in = c_in * kernel_size**4
    bound = 1.0 / math.sqrt(fan_in)
    weight = jax.random.uniform(
        k_w,
        (kernel_size,) * 4 + (c_in, c_out),
        minval=-bound,
        maxval=bound,
        dtype=dtype,
    )
    bias = jax.random.uniform(k_b, (c_out,), minval=-bound, maxval=bound, dtype=dtype)
    return weight, bias
