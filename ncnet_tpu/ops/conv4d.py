"""4D convolution for neighbourhood-consensus filtering.

The reference implements conv4d as a *Python loop* over the first spatial dim,
each iteration dispatching an F.conv3d (/root/reference/lib/conv4d.py:39-48) —
the single hottest anti-pattern to avoid on TPU.  Here the k_A-tap
decomposition becomes whole-volume ``lax.conv_general_dilated`` programs, with
three MXU-aware formulations selected per layer (measured on TPU v5e at the
PF-Pascal 25⁴ workload):

  * ``unroll``   — statically-unrolled sum of kA 3D convs over shifted views;
                   the balanced default for fat in/out channels.
  * ``tapfold``  — folds the kA taps into *input* channels (one 3D conv with
                   kA·C_in inputs); wins when C_in is tiny (the 1-channel
                   first NC layer), where the plain conv's reduction dim
                   underfills the MXU.
  * ``coutfold`` — folds the kA taps into *output* channels (one 3D conv
                   producing kA·C_out channels + a cheap shifted sum); ~2.6×
                   faster when C_out is tiny (the 1-channel last NC layer),
                   where 128-wide MXU output lanes would sit 99% idle.

``variant='auto'`` picks per-layer by channel shape.  All variants share the
reference's semantics: cross-correlation (like torch convNd), "same" zero
padding of ``k//2`` per spatial dim, stride/dilation/groups fixed at 1 —
exactly the envelope the reference supports (conv4d.py:59-62).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _dn3(x_shape, w_shape):
    return lax.conv_dimension_numbers(x_shape, w_shape, ("NDHWC", "DHWIO", "NDHWC"))


def _pads3(kwa: int, kb: int, kwb: int, pad_hb: bool):
    return [
        (kwa // 2, kwa // 2),
        (kb // 2, kb // 2) if pad_hb else (0, 0),
        (kwb // 2, kwb // 2),
    ]


def _conv4d_unroll(x, weight, *, precision, pad_ha, pad_hb):
    """Sum over kA taps of a 3D conv on shifted whole-volume views."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    if pad_ha:
        x = jnp.pad(x, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    ha = x.shape[1] - (ka - 1)
    hb_out = hb if pad_hb else hb - (kb - 1)
    dn = _dn3((b * ha, wa, hb, wb, c_in), (kwa, kb, kwb, c_in, c_out))
    out = None
    for p in range(ka):  # static unroll: ka ≤ 5, traced once under jit
        sl = lax.slice_in_dim(x, p, p + ha, axis=1)
        o = lax.conv_general_dilated(
            sl.reshape(b * ha, wa, hb, wb, c_in),
            weight[p],
            window_strides=(1, 1, 1),
            padding=_pads3(kwa, kb, kwb, pad_hb),
            dimension_numbers=dn,
            precision=precision,
        )
        out = o if out is None else out + o
    return out.reshape(b, ha, wa, hb_out, wb, c_out)


def _conv4d_tapfold(x, weight, *, precision, pad_ha, pad_hb):
    """One 3D conv with the kA taps folded into input channels."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    if pad_ha:
        x = jnp.pad(x, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 4)
    ha = x.shape[1] - (ka - 1)
    hb_out = hb if pad_hb else hb - (kb - 1)
    shifts = jnp.concatenate(
        [lax.slice_in_dim(x, p, p + ha, axis=1) for p in range(ka)], axis=-1
    )
    wf = jnp.transpose(weight, (1, 2, 3, 0, 4, 5)).reshape(
        kwa, kb, kwb, ka * c_in, c_out
    )
    dn = _dn3((b * ha, wa, hb, wb, ka * c_in), wf.shape)
    o = lax.conv_general_dilated(
        shifts.reshape(b * ha, wa, hb, wb, ka * c_in),
        wf,
        window_strides=(1, 1, 1),
        padding=_pads3(kwa, kb, kwb, pad_hb),
        dimension_numbers=dn,
        precision=precision,
    )
    return o.reshape(b, ha, wa, hb_out, wb, c_out)


def _conv4d_coutfold(x, weight, *, precision, pad_ha, pad_hb):
    """One 3D conv producing kA·C_out channels + shifted sum over hA."""
    b, ha_in, wa, hb, wb, c_in = x.shape
    ka, kwa, kb, kwb, _, c_out = weight.shape
    hb_out = hb if pad_hb else hb - (kb - 1)
    wf = jnp.transpose(weight, (1, 2, 3, 4, 0, 5)).reshape(
        kwa, kb, kwb, c_in, ka * c_out
    )
    dn = _dn3((b * ha_in, wa, hb, wb, c_in), wf.shape)
    y = lax.conv_general_dilated(
        x.reshape(b * ha_in, wa, hb, wb, c_in),
        wf,
        window_strides=(1, 1, 1),
        padding=_pads3(kwa, kb, kwb, pad_hb),
        dimension_numbers=dn,
        precision=precision,
    )
    y = y.reshape(b, ha_in, wa, hb_out, wb, ka, c_out)
    # out[i] = Σ_p y[i + p − (pad: ka//2 / valid: 0), …, tap p]
    if pad_ha:
        y = jnp.pad(y, ((0, 0), (ka // 2, ka // 2)) + ((0, 0),) * 5)
    ha = y.shape[1] - (ka - 1)
    out = None
    for p in range(ka):
        o = lax.slice_in_dim(y, p, p + ha, axis=1)[..., p, :]
        out = o if out is None else out + o
    return out


_VARIANTS = {
    "unroll": _conv4d_unroll,
    "tapfold": _conv4d_tapfold,
    "coutfold": _conv4d_coutfold,
}


def conv4d(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    precision=None,
    pad_ha: bool = True,
    pad_hb: bool = True,
    variant: str = "auto",
) -> jnp.ndarray:
    """4D convolution over the correlation volume ("same" by default).

    Args:
      x:      ``(B, hA, wA, hB, wB, C_in)`` channels-last volume.
      weight: ``(kA, kWA, kB, kWB, C_in, C_out)``.
      bias:   ``(C_out,)`` or None.
      pad_ha / pad_hb: when False, the hA / hB dim is treated as *valid* —
        the caller already padded it (the spatially-sharded path pre-pads
        with halo slabs exchanged between shards, parallel/spatial.py) and
        the output is ``k//2`` smaller on each side of that dim.
      variant: 'auto' (per-layer MXU heuristic), or an explicit formulation
        from 'unroll' / 'tapfold' / 'coutfold' (see module docstring).  All
        variants are numerically equivalent up to fp32 reassociation.

    Returns:
      ``(B, hA', wA, hB', wB, C_out)`` (primed dims shrink iff unpadded).
    """
    c_in, c_out = weight.shape[4], weight.shape[5]
    assert x.shape[5] == c_in, f"channel mismatch: {x.shape[5]} vs {c_in}"
    if variant == "auto":
        if c_in <= 4:
            variant = "tapfold"
        elif c_out <= 4:
            variant = "coutfold"
        else:
            variant = "unroll"
    out = _VARIANTS[variant](
        x, weight, precision=precision, pad_ha=pad_ha, pad_hb=pad_hb
    )
    if bias is not None:
        out = out + bias
    return out


def conv4d_init(
    key: jax.Array, kernel_size: int, c_in: int, c_out: int, dtype=jnp.float32
):
    """torch-_ConvNd-style uniform init ±1/√(C_in·k⁴), the distribution the
    reference's Conv4d inherits (conv4d.py:53-82 via _ConvNd defaults), so
    training dynamics start from a comparable point.

    Returns ``(weight, bias)`` with weight ``(k,k,k,k,C_in,C_out)``.
    """
    k_w, k_b = jax.random.split(key)
    fan_in = c_in * kernel_size**4
    bound = 1.0 / math.sqrt(fan_in)
    weight = jax.random.uniform(
        k_w,
        (kernel_size,) * 4 + (c_in, c_out),
        minval=-bound,
        maxval=bound,
        dtype=dtype,
    )
    bias = jax.random.uniform(k_b, (c_out,), minval=-bound, maxval=bound, dtype=dtype)
    return weight, bias
