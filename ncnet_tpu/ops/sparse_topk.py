"""Coarse-pass candidate selection for coarse-to-fine sparse correlation.

The dense pipeline's defining cost is the full 4D volume: every (source
cell, target cell) pair is materialized and filtered — O((hw)²) cells.
*Dual-Resolution Correspondence Networks* (arXiv:2006.08844) and
*XResolution Correspondence Networks* (arXiv:2012.09842) break that wall by
filtering at COARSE resolution first and evaluating fine correlation only
around the top-k candidate target neighbourhoods of each source cell.  This
module is the selection half of that pipeline (the gathered fine evaluation
lives in ``ops/sparse_corr.py``):

  * :func:`pool_features` — stride-f average pooling of the backbone grid
    (stride-16 features → stride-32 at ``factor=2``), re-L2-normalized so
    the coarse correlation stays a cosine similarity;
  * :func:`topk_candidates` — in-graph ``lax.top_k`` over the FILTERED
    coarse volume, one candidate row per coarse source cell;
  * the **coverage-padding contract** (:func:`topk_candidates`,
    :func:`candidate_origins`, :func:`block_origins`): every shape is
    static no matter what ``k`` or where a candidate sits.  ``k`` larger
    than the coarse target grid pads the candidate row by repeating the
    top-1 candidate (duplicates are harmless downstream — the sparse
    scatter resolves them by max), and every candidate's fine patch origin
    is clamped into the volume such that the patch ALWAYS contains the
    candidate's full ``factor×factor`` fine block.  Callers can therefore
    jit one program per shape bucket exactly like the dense path.

Pure ``jnp`` throughout — jittable, shardable, differentiable-free
(selection is an argmax-family op; the training path keeps the dense
volume).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.ops.norm import feature_l2_norm


def resolve_halo(halo: int, factor: int) -> int:
    """The fine-cell patch halo: ``halo < 0`` means auto — one coarse ring
    (``factor`` fine cells), the measured sweet spot between tile cost
    (patch⁴ cells per candidate) and filter-support truncation.  A halo
    below the NC stack's receptive radius truncates conv support at patch
    edges — the standard sparse-refinement approximation; raise it toward
    ``sum((k−1)/2)`` when fidelity at patch borders matters more than
    FLOPs."""
    return factor if halo < 0 else halo


def patch_side(factor: int, halo: int) -> int:
    """Fine patch side: the candidate's ``factor``-cell block plus the halo
    on each side."""
    return factor + 2 * halo


def pool_features(f: jnp.ndarray, factor: int,
                  renormalize: bool = True) -> jnp.ndarray:
    """Average-pool a feature grid ``(B, H, W, C)`` by ``factor`` (dims must
    divide) and optionally re-L2-normalize per location — the stride-32
    proxy of a dual-resolution trunk's coarse head.  Pooling runs in f32
    (bf16 feature sums at factor² terms would lose mantissa) and casts back
    to the input dtype."""
    b, h, w, c = f.shape
    assert h % factor == 0 and w % factor == 0, (
        f"feature grid {h}x{w} does not pool by {factor}"
    )
    pooled = f.astype(jnp.float32).reshape(
        b, h // factor, factor, w // factor, factor, c
    ).mean(axis=(2, 4))
    if renormalize:
        pooled = feature_l2_norm(pooled)
    return pooled.astype(f.dtype)


def topk_candidates(coarse_corr: jnp.ndarray, k: int) -> jnp.ndarray:
    """Per-row top-k candidate selection over the filtered coarse volume.

    Args:
      coarse_corr: ``(B, Hc, Wc, hc, wc)`` FILTERED coarse volume (the
        coarse NC pass's output — selection quality rides on the filter's
        consensus, exactly the Dual-Resolution recipe).
      k: requested candidates per coarse source cell (static).

    Returns:
      ``(B, Hc·Wc, k)`` int32 — flattened coarse target indices (row-major
      ``i·wc + j``), best first.  Coverage padding: when ``k`` exceeds the
      coarse target grid the trailing slots repeat the top-1 candidate, so
      the shape contract holds for any (k, grid) combination and the
      compiled program is reusable across k sweeps.
    """
    b, ha, wa, hb, wb = coarse_corr.shape
    flat = coarse_corr.reshape(b, ha * wa, hb * wb).astype(jnp.float32)
    k_eff = min(int(k), hb * wb)
    _, idx = jax.lax.top_k(flat, k_eff)
    idx = idx.astype(jnp.int32)
    if k > k_eff:
        pad = jnp.broadcast_to(idx[:, :, :1], (b, ha * wa, k - k_eff))
        idx = jnp.concatenate([idx, pad], axis=2)
    return idx


def block_origins(n_coarse: int, factor: int, patch: int,
                  length: int) -> np.ndarray:
    """Static fine-grid patch origins for every coarse cell along one axis:
    ``clip(c·factor − halo, 0, length − patch)``.  The clamp is the
    coverage contract's edge rule — a patch near the border shifts inward
    instead of shrinking, so it stays static-shaped AND still contains the
    cell's full ``factor``-cell block (``patch ≥ factor + halo`` makes the
    shifted window cover it; asserted by construction in
    ``patch_side``)."""
    halo = (patch - factor) // 2
    c = np.arange(n_coarse) * factor - halo
    return np.clip(c, 0, length - patch).astype(np.int32)


def candidate_origins(
    cand: jnp.ndarray, wc: int, factor: int, patch: int,
    hb: int, wb: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fine-grid patch origins ``(oi, ob)`` of candidate coarse cells.

    Args:
      cand: ``(B, N, K)`` int32 flattened coarse target indices.
      wc: coarse target grid width (``cand`` decodes as ``(c // wc,
        c % wc)``).
      factor, patch: fine cells per coarse cell / patch side.
      hb, wb: fine target grid dims.

    Returns the same-rule clamped origins as :func:`block_origins`, shaped
    like ``cand``.  Origins are multiples of ``factor`` whenever the halo
    is (the Pallas gather tier's band-alignment precondition,
    ``ops/sparse_corr.py``)."""
    halo = (patch - factor) // 2
    ic = cand // wc
    jc = cand % wc
    oi = jnp.clip(ic * factor - halo, 0, hb - patch).astype(jnp.int32)
    oj = jnp.clip(jc * factor - halo, 0, wb - patch).astype(jnp.int32)
    return oi, oj


def candidate_recall(cand: np.ndarray, dense_corr: np.ndarray,
                     factor: int) -> float:
    """Selection-quality diagnostic: the fraction of fine source cells
    whose DENSE fine-volume argmax target cell falls inside one of their
    coarse source cell's candidate neighbourhoods.  1.0 means top-k
    coverage provably contains every true argmax (the sparse match table
    then reproduces the dense one row-for-row on peak-dominated volumes —
    tests/test_sparse_corr.py); the recall-vs-k curve is the k-tuning
    instrument (``tools/sparse_corr_probe.py``)."""
    cand = np.asarray(cand)
    dense_corr = np.asarray(dense_corr, dtype=np.float64)
    b, ha, wa, hb, wb = dense_corr.shape
    wc = wb // factor
    wac = wa // factor
    flat = dense_corr.reshape(b, ha * wa, hb * wb)
    best = np.argmax(flat, axis=2)                      # (B, n_a) fine B idx
    bi, bj = best // wb, best % wb
    best_coarse = (bi // factor) * wc + (bj // factor)  # (B, n_a)
    a = np.arange(ha * wa)
    coarse_a = (a // wa // factor) * wac + ((a % wa) // factor)
    hit = 0
    for bb in range(b):
        rows = cand[bb, coarse_a]                       # (n_a, K)
        hit += np.mean(np.any(rows == best_coarse[bb][:, None], axis=1))
    return float(hit / b)
