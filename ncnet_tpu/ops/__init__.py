"""Pure-function compute ops (JAX).

Conventions (TPU-first, channels-last):
  * image features:   ``(B, H, W, C)``
  * 4D corr volume:   ``(B, hA, wA, hB, wB)`` — scalar cells
  * NC filter state:  ``(B, hA, wA, hB, wB, C)`` — channels-last for conv

The reference keeps PyTorch NCHW / (B,1,hA,wA,hB,wB) layouts
(/root/reference/lib/model.py:115); we deliberately do not.
"""

from ncnet_tpu.ops.norm import feature_l2_norm
from ncnet_tpu.ops.correlation import correlation_4d, correlation_3d
from ncnet_tpu.ops.conv4d import (
    choose_conv4d_variant,
    conv4d,
    conv4d_fold_fits,
    conv4d_init,
    conv4d_same,
    make_conv4d_same,
    conv4d_transpose_weights,
)
from ncnet_tpu.ops.nc_fused_lane import (  # noqa: F401
    choose_fused_stack,
    demote_fused_tier,
    last_selected_tier,
    demoted_fused_tiers,
    fused_resident_feasible,
    nc_stack_resident,
    fused_lane_feasible,
    nc_stack_fused,
    nc_stack_fused_lane,
    note_forced_tier,
    reset_fused_tier_demotions,
)
from ncnet_tpu.ops.conv4d_cp import (  # noqa: F401
    cp_apply_layer,
    cp_feasible,
    cp_reconstruct,
    cp_stack_ranks,
    exact_cp_factors,
    nc_stack_cp,
)
from ncnet_tpu.ops.conv4d_fft import (  # noqa: F401
    conv4d_fft,
    fft_feasible,
    nc_stack_fft,
)
from ncnet_tpu.ops.nc_fused_lane_vjp import (  # noqa: F401
    choose_fused_vjp,
    fused_vjp_feasible,
    nc_stack_fused_vjp,
)
from ncnet_tpu.ops.pooling import maxpool4d_with_argmax
from ncnet_tpu.ops.sparse_topk import (  # noqa: F401
    candidate_recall,
    pool_features,
    topk_candidates,
)
from ncnet_tpu.ops.sparse_corr import (  # noqa: F401
    choose_match_pipeline,
    choose_tracked_pipeline,
    coarse2fine_feasible,
    sparse_fine_corr,
    sparse_mutual_matching,
    sparse_refine,
    tracking_feasible,
)
from ncnet_tpu.ops.temporal import (  # noqa: F401
    FEATURE_STRIDE,
    identity_prior,
    prior_from_table,
    temporal_candidates,
    tracking_recall_proxy,
    window_size,
)
from ncnet_tpu.ops.matching import (
    Matches,
    mutual_argmax_agreement,
    mutual_matching,
    corr_to_matches,
    scatter_sparse_scores,
    nearest_neighbor_point_tnf,
    bilinear_interp_point_tnf,
    normalize_axis,
    unnormalize_axis,
    points_to_unit_coords,
    points_to_pixel_coords,
)
from ncnet_tpu.ops.image import (
    resize_bilinear_align_corners,
    resize_bilinear_align_corners_np,
    IMAGENET_MEAN,
    IMAGENET_STD,
    normalize_imagenet,
)

__all__ = [
    "Matches",
    "feature_l2_norm",
    "correlation_4d",
    "correlation_3d",
    "choose_conv4d_variant",
    "conv4d",
    "conv4d_fold_fits",
    "conv4d_init",
    "conv4d_same",
    "make_conv4d_same",
    "conv4d_transpose_weights",
    "choose_fused_stack",
    "choose_fused_vjp",
    "conv4d_fft",
    "cp_apply_layer",
    "cp_feasible",
    "cp_reconstruct",
    "cp_stack_ranks",
    "exact_cp_factors",
    "fft_feasible",
    "nc_stack_cp",
    "nc_stack_fft",
    "note_forced_tier",
    "demote_fused_tier",
    "last_selected_tier",
    "demoted_fused_tiers",
    "fused_lane_feasible",
    "fused_resident_feasible",
    "fused_vjp_feasible",
    "nc_stack_fused_vjp",
    "nc_stack_fused",
    "nc_stack_fused_lane",
    "nc_stack_resident",
    "reset_fused_tier_demotions",
    "maxpool4d_with_argmax",
    "candidate_recall",
    "pool_features",
    "topk_candidates",
    "choose_match_pipeline",
    "choose_tracked_pipeline",
    "coarse2fine_feasible",
    "sparse_fine_corr",
    "sparse_mutual_matching",
    "sparse_refine",
    "tracking_feasible",
    "FEATURE_STRIDE",
    "identity_prior",
    "prior_from_table",
    "temporal_candidates",
    "tracking_recall_proxy",
    "window_size",
    "scatter_sparse_scores",
    "mutual_argmax_agreement",
    "mutual_matching",
    "corr_to_matches",
    "nearest_neighbor_point_tnf",
    "bilinear_interp_point_tnf",
    "normalize_axis",
    "unnormalize_axis",
    "points_to_unit_coords",
    "points_to_pixel_coords",
    "resize_bilinear_align_corners",
    "resize_bilinear_align_corners_np",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "normalize_imagenet",
]
