"""Image resizing + normalization.

The reference resizes by sampling an identity affine grid with bilinear
``F.grid_sample`` (/root/reference/lib/transformation.py:25-46) and upsamples
InLoc images with ``F.upsample(mode='bilinear')`` (eval_inloc.py:84-89) — both
are *align-corners* bilinear resampling in torch-0.3 semantics.
``jax.image.resize`` uses half-pixel centers, which would shift every feature
half a cell and move PCK; so we implement align-corners bilinear directly
(a gather + lerp, fully fused by XLA).  A numpy twin serves the host-side
input pipeline without bouncing images through the device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# torchvision ImageNet statistics (reference lib/normalization.py:19-20)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def _align_corners_coords(out_len: int, in_len: int, xp):
    if out_len == 1 or in_len == 1:
        return xp.zeros((out_len,), dtype=xp.float32)
    return xp.linspace(0.0, in_len - 1.0, out_len, dtype=xp.float32)


def _resize_bilinear(img, out_h: int, out_w: int, xp):
    """Shared align-corners bilinear body; ``img``: (B, H, W, C)."""
    b, h, w, c = img.shape
    ys = _align_corners_coords(out_h, h, xp)
    xs = _align_corners_coords(out_w, w, xp)
    y0 = xp.clip(xp.floor(ys).astype(xp.int32), 0, h - 1)
    x0 = xp.clip(xp.floor(xs).astype(xp.int32), 0, w - 1)
    y1 = xp.minimum(y0 + 1, h - 1)
    x1 = xp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    top_rows = img[:, y0]
    bot_rows = img[:, y1]
    top = top_rows[:, :, x0] * (1 - wx) + top_rows[:, :, x1] * wx
    bot = bot_rows[:, :, x0] * (1 - wx) + bot_rows[:, :, x1] * wx
    return top * (1 - wy) + bot * wy


def resize_bilinear_align_corners(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align-corners sampling.

    Args:
      img: ``(B, H, W, C)`` or ``(H, W, C)``.
    """
    squeeze = img.ndim == 3
    if squeeze:
        img = img[None]
    out = _resize_bilinear(img, out_h, out_w, jnp)
    return out[0] if squeeze else out


def resize_bilinear_align_corners_np(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Numpy twin of :func:`resize_bilinear_align_corners` for the host-side
    data pipeline (no device bounce).  ``img``: (H, W, C) float."""
    return _resize_bilinear(img[None], out_h, out_w, np)[0]


def normalize_imagenet(img, *, scale_255: bool = True):
    """0-255 image → ImageNet-normalized float (lib/normalization.py:16-27).
    Works on numpy or jnp arrays, channels-last."""
    xp = jnp if isinstance(img, jnp.ndarray) else np
    x = img / 255.0 if scale_255 else img
    return (x - xp.asarray(IMAGENET_MEAN)) / xp.asarray(IMAGENET_STD)
