"""Image resizing + normalization.

The reference resizes by sampling an identity affine grid with bilinear
``F.grid_sample`` (/root/reference/lib/transformation.py:25-46) and upsamples
InLoc images with ``F.upsample(mode='bilinear')`` (eval_inloc.py:84-89) — both
are *align-corners* bilinear resampling in torch-0.3 semantics.
``jax.image.resize`` uses half-pixel centers, which would shift every feature
half a cell and move PCK; so we implement align-corners bilinear directly.

The DEVICE path contracts the image against per-axis interpolation matrices
(each output row/column is a 2-tap combination of input rows/columns) — two
MXU matmuls instead of the gather+lerp form, whose fancy-index gathers with
a 3-channel minor dim dominate the InLoc per-pair device time on TPU.  The
numpy twin (host-side input pipeline, no device bounce) keeps the
gather+lerp form; both implement the identical sampling weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# torchvision ImageNet statistics (reference lib/normalization.py:19-20)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def _align_corners_coords(out_len: int, in_len: int, xp):
    if out_len == 1 or in_len == 1:
        return xp.zeros((out_len,), dtype=xp.float32)
    return xp.linspace(0.0, in_len - 1.0, out_len, dtype=xp.float32)


def _tap_weights(out_n: int, in_n: int, xp):
    """Align-corners 2-tap sampling: ``(y0, y1, f)`` with output sample ``i``
    = ``(1-f_i)·src[y0_i] + f_i·src[y1_i]``.  The ONE definition of the
    sampling weights — both the host gather path and the device matmul path
    derive from it, so they cannot desync."""
    ys = _align_corners_coords(out_n, in_n, xp)
    y0 = xp.clip(xp.floor(ys).astype(xp.int32), 0, in_n - 1)
    y1 = xp.minimum(y0 + 1, in_n - 1)
    return y0, y1, ys - y0


def _resize_bilinear(img, out_h: int, out_w: int, xp):
    """Shared align-corners bilinear body; ``img``: (B, H, W, C)."""
    b, h, w, c = img.shape
    y0, y1, fy = _tap_weights(out_h, h, xp)
    x0, x1, fx = _tap_weights(out_w, w, xp)
    wy = fy[None, :, None, None]
    wx = fx[None, None, :, None]
    top_rows = img[:, y0]
    bot_rows = img[:, y1]
    top = top_rows[:, :, x0] * (1 - wx) + top_rows[:, :, x1] * wx
    bot = bot_rows[:, :, x0] * (1 - wx) + bot_rows[:, :, x1] * wx
    return top * (1 - wy) + bot * wy


def _interp_matrix(out_n: int, in_n: int) -> jnp.ndarray:
    """``(in_n, out_n)`` align-corners interpolation matrix: column ``i`` has
    weight ``1-f`` at row ``y0_i`` and ``f`` at ``y1_i`` (summing to 1 when
    the taps coincide at the last row) — the matmul form of the exact
    ``_tap_weights`` sampling.  Built in-graph from iota — cheap on device,
    and avoids baking multi-MB constants into every InLoc shape bucket's
    program."""
    y0, y1, f = _tap_weights(out_n, in_n, jnp)
    rows = jax.lax.broadcasted_iota(jnp.int32, (in_n, out_n), 0)
    return jnp.where(rows == y0[None, :], 1.0 - f[None, :], 0.0) + jnp.where(
        rows == y1[None, :], f[None, :], 0.0
    )


def resize_bilinear_align_corners(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align-corners sampling (device path: two MXU
    contractions against interpolation matrices — see module docstring).

    Args:
      img: ``(B, H, W, C)`` or ``(H, W, C)``.
    """
    squeeze = img.ndim == 3
    if squeeze:
        img = img[None]
    h, w = img.shape[1], img.shape[2]
    wy = _interp_matrix(out_h, h)
    wx = _interp_matrix(out_w, w)
    # f32 throughout with exact-precision dots: the interp weights are the
    # same 2-tap lerps as the gather form, so torch-oracle parity holds
    # float32 result for every input dtype — the gather form's promotion
    # semantics (uint8/bf16 in → f32 out; f32 weights promote the lerp)
    x = img.astype(jnp.float32)
    x = jnp.einsum("hH,bhwc->bHwc", wy, x,
                   precision=jax.lax.Precision.HIGHEST)
    out = jnp.einsum("wW,bHwc->bHWc", wx, x,
                     precision=jax.lax.Precision.HIGHEST)
    return out[0] if squeeze else out


def resize_bilinear_align_corners_np(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Numpy twin of :func:`resize_bilinear_align_corners` for the host-side
    data pipeline (no device bounce).  ``img``: (H, W, C) float."""
    return _resize_bilinear(img[None], out_h, out_w, np)[0]


def normalize_imagenet(img, *, scale_255: bool = True):
    """0-255 image → ImageNet-normalized float (lib/normalization.py:16-27).
    Works on numpy or jnp arrays, channels-last."""
    xp = jnp if isinstance(img, jnp.ndarray) else np
    x = img / 255.0 if scale_255 else img
    return (x - xp.asarray(IMAGENET_MEAN)) / xp.asarray(IMAGENET_STD)


def quantize_u8(img: np.ndarray) -> np.ndarray:
    """0-255 float image → uint8 by round-to-nearest (≤0.5/255 error before
    normalization) — the ONE quantization contract of the uint8-upload fast
    paths (evaluation/pf_pascal.py, point_transfer_demo.py): the transfer
    carries raw bytes and :func:`normalize_imagenet` runs on device."""
    return np.clip(np.rint(img), 0, 255).astype(np.uint8)
