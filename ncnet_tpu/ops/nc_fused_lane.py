"""Fused-(hB·wB)-lane Pallas kernels for the NC filter stack.

The r5 composed breakdown (tools/filter_stage_probe.py, v5e, PF-Pascal 25⁴
bf16 bs4) pinned the filter's cost: the 16→16 layer runs at 28% of MXU peak
and the 16→1 layer at 3.7% under XLA's conv lowering, and every XLA-level
reformulation measured worse (tools/filter_combo_probe.py: 'abfold' 25.7 vs
7.7 ms/pair baseline).  This module implements the one formulation XLA
cannot express, in Pallas:

  * volume rows ride as ``(j, C sublanes, fused padded (hB+h)(wB+h) lanes)``
    — for the 25⁴ volume with k=5, 841 lanes (94% lane fill at the 896 pad);
  * the matmul contracts K = (kA, kWA, C_in) — 400 for the 16-channel
    layers, filling the MXU contraction depth (measured ~88% of peak on the
    dot, tools/pallas_l2_probe.py ablations);
  * the B-side (kB, kWB) taps become PURE LANE OFFSETS of the fused kl dim
    (tap (r,s) ↔ lane shift r·(wB+h)+s), resolved by a vectorized VMEM
    epilogue over the dot's N = (kB, kWB, C_out) — which measured FREE (it
    hides behind the MXU);
  * bias + ReLU fuse into the epilogue; inter-layer volumes stay in the
    fused layout (no per-layer HBM transpose).

Every primitive was legality-probed on this toolchain before the design was
fixed (tools/mosaic_probes.py ``r5_*`` battery — the round-2/3 kernel's
lane-dim reshape is exactly what Mosaic rejects, ops/conv4d_pallas.py).

Thin channel dims are padded up to ``_MIN_CB`` sublanes with zero weights:
a 1-sublane epilogue block would pay ~3k tiny VPU ops per volume (op-
overhead-bound); an 8-sublane block rides full native rows.  The extra dot
FLOPs are the cheaper currency (the dots run at ~88% of peak).

Measured at the bench workload (v5e, bf16, 8 batch-folded volumes,
tools/pallas_l2_probe.py): 16→16 layer 1.87 ms/volume including the layout
conversion vs XLA coutfold 2.52 in the same process.

Round 6 adds the RESIDENT tier (``nc_stack_resident``): the whole composed
stack as one ``pallas_call`` whose intermediate volumes live in VMEM ring
buffers across grid steps — no inter-layer HBM round trips, no k× row
refetch of 16-channel volumes, exact (unpadded) contraction/output widths
for the thin 1→16 / 16→1 layers, and the layout conversion reduced to one
scalar-volume pad in / minor-dim slice out.  ``choose_fused_stack`` is the
tier authority: resident → per-layer chain → XLA, each Pallas tier gated by
a real-compile probe (see the resident section below for the design).

Reference semantics match ``ops/conv4d.py`` 'same' conv (cross-correlation,
zero padding) + bias + ReLU — the reference's NeighConsensus layer
(/root/reference/lib/model.py:122-153 with lib/conv4d.py:39-48).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# NOTE: jax.experimental.pallas is imported lazily inside _conv_fused_lane —
# the package re-exports this module, and `import ncnet_tpu.ops` must stay
# light and pallas-independent (the same discipline as ops/conv4d.py's
# function-local pallas imports)

# VMEM working-set budget (v5e: ~16 MiB/core usable by one Pallas program)
_VMEM_BUDGET = 13 * 2 ** 20
# pad thin channel dims (c_in of the first layer, c_out of the last) up to
# this many sublanes.  Swept on v5e at the PF-Pascal stack
# (tools/nc_fused_lane_probe.py, ms/volume): 8 → 2.63, 4 → 2.20, 2 → 1.997,
# 1 → 2.04 — the dot's padded-FLOP cost beats the thin-tile epilogue cost
# down to 2 sublanes, below which tiny epilogue ops dominate.
import os as _os

_MIN_CB = int(_os.environ.get("NCNET_FUSED_LANE_MIN_CB", "2"))
# j-chunk of the dot/epilogue loop (measured insensitive across 4-6 at the
# bench workload; env knob for probes)
_JCH = int(_os.environ.get("NCNET_FUSED_LANE_JCH", "5"))


def _kernel(*refs, k, c_in, c_out, s_j, sp_j, kl, sp_l, je_list):
    """One (b, i) output row of relu(conv4d_same(x) + bias).

    refs = (x_0..x_{k-1}, w, bias, mask, out):
      x_p:  (1, 1, sp_j, c_in, kl) — padded input row i+p.
      w:    (k²·c_in, k²·c_out) = w4d[(p,q,c), (r,s,o)].
      bias: (1, c_out, 1); mask: (1, 1, kl) halo zeroing.
      out:  (1, 1, s_j, c_out, kl) — same fused frame, halo lanes zeroed.
    """
    x_refs, w_ref, b_ref, m_ref, out_ref = \
        refs[:k], refs[k], refs[k + 1], refs[k + 2], refs[k + 3]
    w = w_ref[:]
    n_lane = kl - sp_l * (k - 1) - (k - 1)  # valid-support slice length
    h = k - 1
    for j0, je in je_list:
        # A[(j), (p,q,c), (kl)]: k² shifted row slabs along the sublane dim
        a3 = jnp.concatenate(
            [x_refs[p][0, 0, j0 + q:j0 + q + je] for p in range(k)
             for q in range(k)],
            axis=1,
        )  # (je, k²·c_in, kl)
        ys = []
        for j in range(je):
            y = jax.lax.dot_general(
                w, a3[j], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (k²·c_out, kl) f32, rows ordered (r, s, o)
            ys.append(y.astype(jnp.bfloat16))
        ybuf = jnp.stack(ys, axis=0)
        acc = jnp.zeros((je, c_out, n_lane), jnp.float32)
        for r in range(k):
            for s in range(k):
                blk = (r * k + s) * c_out
                off = r * sp_l + s
                acc = acc + ybuf[:, blk:blk + c_out, off:off + n_lane].astype(
                    jnp.float32)
        acc = jnp.maximum(acc + b_ref[:].astype(jnp.float32), 0.0)
        pad_lo = (h // 2) * sp_l + h // 2
        full = jnp.pad(acc, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane)))
        out_ref[0, 0, j0:j0 + je] = (
            full * m_ref[:].astype(jnp.float32)).astype(out_ref.dtype)


def _conv_fused_lane(xp, w2, bias, mask, *, k, c_in, c_out, s_j, sp_l, kl,
                     interpret=False):
    """xp: (B, sp_i, sp_j, c_in, kl) padded fused-lane rows (bf16).
    Returns (B, s_i, s_j, c_out, kl) with halo lanes zeroed."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sp_i, sp_j = xp.shape[:3]
    s_i = sp_i - (k - 1)
    je_list = tuple((j0, min(_JCH, s_j - j0)) for j0 in range(0, s_j, _JCH))
    kern = functools.partial(
        _kernel, k=k, c_in=c_in, c_out=c_out, s_j=s_j, sp_j=sp_j, kl=kl,
        sp_l=sp_l, je_list=je_list,
    )
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, sp_j, c_in, kl), lambda bi, ii, p=p: (bi, ii + p, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kern,
        grid=(b, s_i),
        in_specs=[row_spec(p) for p in range(k)] + [
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, s_j, c_out, kl), lambda bi, ii: (bi, ii, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, s_i, s_j, c_out, kl), xp.dtype),
        interpret=interpret,
    )(*([xp] * k), w2, bias, mask)


def _pad_c(c: int) -> int:
    return max(c, _MIN_CB)


def _pack_weight(w, k, c_in, c_out, pad: bool = True):
    """(k,k,k,k,C_in,C_out) -> (k²·ci, k²·co) [(p,q,c),(r,s,o)].  With
    ``pad`` (the per-layer chain) thin channel dims are zero-padded to
    ``_MIN_CB`` sublanes; the resident kernel packs exact widths."""
    ci, co = (_pad_c(c_in), _pad_c(c_out)) if pad else (c_in, c_out)
    wp = jnp.pad(
        w, ((0, 0),) * 4 + ((0, ci - c_in), (0, co - c_out))
    )
    return jnp.transpose(wp, (0, 1, 4, 2, 3, 5)).reshape(
        k * k * ci, k * k * co
    )


def _make_mask(s_kl: tuple, k: int) -> np.ndarray:
    """(1, 1, kl) bf16: 1 on the valid (k,l) support, 0 on halo lanes."""
    hb, wb = s_kl
    h = k - 1
    m = np.zeros((hb + h, wb + h), np.float32)
    m[h // 2:h // 2 + hb, h // 2:h // 2 + wb] = 1.0
    return m.reshape(1, 1, -1)


def fused_lane_feasible(ha, wa, hb, wb, kernels, channels) -> bool:
    """Whether every layer's working set fits the VMEM budget and the shape
    class matches the kernel (cubic odd kernels, one k for the stack)."""
    ks = set(kernels)
    if len(ks) != 1 or kernels[0] % 2 == 0:
        return False
    if channels[-1] != 1:
        # the chain's un-fuse step returns the scalar volume (channel 0);
        # a wider final layer is not the NC-stack shape class
        return False
    k = kernels[0]
    sp_l = wb + k - 1
    kl = (hb + k - 1) * sp_l
    sp_j = wa + k - 1
    c_in = 1
    for c_out in channels:
        ci, co = _pad_c(c_in), _pad_c(c_out)
        rows = k * sp_j * ci * kl * 2                       # k input rows
        a3 = _JCH * k * k * ci * kl * 2                     # A build
        ybuf = _JCH * k * k * co * kl * 2                   # bf16 Y
        yf32 = k * k * co * kl * 4                          # one dot output
        out = wa * co * kl * 2
        w = (k * k * ci) * (k * k * co) * 2
        if rows + a3 + ybuf + yf32 + out + w > _VMEM_BUDGET:
            return False
        c_in = c_out
    return True


def _record_probe_memory(program: str, tier: str, ha, wa, hb, wb,
                         kernels, channels, compiled) -> None:
    """Ledger row from a successful compile probe — the analysis object is
    already in hand, so the row is free (observability/memory.py).  The
    shape-class string mirrors ``tier_cache.signature_key``."""
    try:
        from ncnet_tpu.observability import memory as obs_memory

        obs_memory.record_program(
            program,
            f"{ha}x{wa}x{hb}x{wb}"
            f"|k={','.join(str(k) for k in kernels)}"
            f"|c={','.join(str(c) for c in channels)}",
            analysis=compiled, tier=tier, source="tier_probe")
    except Exception:  # noqa: BLE001 — the ledger never fails a probe
        pass


@functools.lru_cache(maxsize=8)
def fused_lane_compiles(ha, wa, hb, wb, kernels, channels) -> bool:
    """Real-compile probe at batch 1 (cached per shape class): Mosaic
    lowering legality depends on concrete shapes, so the chooser verifies an
    actual compile and any failure falls back to the XLA formulations —
    the same discipline as ops/conv4d_pallas.pallas_compiles."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.bfloat16)
        ws, bs = [], []
        c_in = 1
        for kk, c_out in zip(kernels, channels):
            ws.append(jax.ShapeDtypeStruct(
                (kk,) * 4 + (c_in, c_out), jnp.bfloat16))
            bs.append(jax.ShapeDtypeStruct((c_out,), jnp.bfloat16))
            c_in = c_out
        def run(x, ws, bs):
            params = [{"w": w, "b": b} for w, b in zip(ws, bs)]
            return nc_stack_fused_lane(params, x)
        compiled = jax.jit(run).lower(x, ws, bs).compile()
        _record_probe_memory("nc_fused_lane_probe", "fused_lane",
                             ha, wa, hb, wb, kernels, channels, compiled)
        return True
    except Exception:
        return False


def nc_stack_fused_lane(nc_params: List[dict], x: jnp.ndarray,
                        interpret: bool = False,
                        _allow_wide_final: bool = False) -> jnp.ndarray:
    """The full [conv4d_same + bias + ReLU]×N stack on ``x``
    ``(B, hA, wA, hB, wB, 1)``, chained through the fused-lane layout.

    Numerically equivalent (up to bf16 rounding; the dots accumulate f32) to
    the XLA stack in models/ncnet.py `neigh_consensus.stack`.  Forward-only:
    wrap under `jax.custom_vjp` at the call site for training (the chooser
    only routes eval/forward here — see neigh_consensus).
    """
    b, ha, wa, hb, wb, _ = x.shape
    assert _allow_wide_final or nc_params[-1]["w"].shape[5] == 1, (
        "nc_stack_fused_lane requires a 1-channel final layer (the NC-stack "
        "shape class); wider stacks must use the XLA formulations "
        "(_allow_wide_final: bench prefix probes only — the un-fuse step "
        "still returns channel 0)"
    )
    # the lane packing below keeps only channel 0 of the input (x[..., 0]):
    # reject wider inputs loudly instead of silently dropping channels
    assert x.shape[-1] == 1 and nc_params[0]["w"].shape[4] == 1, (
        "nc_stack_fused_lane requires a 1-channel input volume and first "
        "layer (the NC-stack shape class); wider inputs must use the XLA "
        "formulations"
    )
    k = nc_params[0]["w"].shape[0]
    h = k - 1
    sp_l = wb + h
    kl = (hb + h) * sp_l
    mask = jnp.asarray(_make_mask((hb, wb), k), jnp.bfloat16)

    # (B, hA, wA, hB, wB, 1) -> (B, hA+h, wA+h, 1->cinP, kl): pure pads +
    # minor-dim reshape (no transpose: (k,l) is already minor)
    xp = jnp.pad(
        x[..., 0],
        ((0, 0),) + ((h // 2, h // 2),) * 4,
    ).reshape(b, ha + h, wa + h, 1, kl)
    xp = jnp.pad(xp, ((0, 0),) * 3 + ((0, _pad_c(1) - 1), (0, 0)))
    xp = xp.astype(jnp.bfloat16)

    c_in = 1
    for li, layer in enumerate(nc_params):
        c_out = layer["w"].shape[5]
        co_p = _pad_c(c_out)
        w2 = _pack_weight(
            layer["w"].astype(jnp.bfloat16), k, c_in, c_out)
        bias = jnp.pad(
            layer["b"].astype(jnp.bfloat16), (0, co_p - c_out)
        ).reshape(1, co_p, 1)
        y = _conv_fused_lane(
            xp, w2, bias, mask, k=k, c_in=_pad_c(c_in), c_out=co_p,
            s_j=wa, sp_l=sp_l, kl=kl, interpret=interpret,
        )
        if li + 1 < len(nc_params):
            # re-pad rows/cols for the next layer's halo (cheap leading-dim
            # pads; the lane halos are already zeroed by the kernel mask)
            xp = jnp.pad(
                y, ((0, 0), (h // 2, h // 2), (h // 2, h // 2), (0, 0),
                    (0, 0)),
            )
        c_in = c_out

    # (B, hA, wA, coP, kl) -> take channel 0, unfuse lanes, drop halo
    out = y[:, :, :, 0, :].reshape(b, ha, wa, hb + h, wb + h)
    out = out[:, :, :, h // 2:h // 2 + hb, h // 2:h // 2 + wb]
    return out[..., None]


# ---------------------------------------------------------------------------
# resident whole-stack kernel (round 6)
#
# The r5 per-layer chain above still round-trips every intermediate volume
# through HBM — and because each grid step fetches its k input rows via
# overlapping row BlockSpecs, every inter-layer volume is READ k times (the
# 16-channel PF-Pascal volume is ~22.6 MB/volume, so the middle layers alone
# move ~0.7 GB/pair where the algorithmic minimum is ~20 MB/pair).  The
# resident kernel below runs the ENTIRE composed stack inside ONE
# ``pallas_call``: a wavefront over hA rows where layer ``l`` emits volume
# row ``ii − l·(k−1)/2`` at grid step ``ii``, with each intermediate layer's
# live rows held in a k-slot VMEM ring buffer (scratch persists across grid
# steps; the TPU grid is sequential).  Intermediate activations never touch
# HBM, the inter-layer re-pads disappear (ring rows are written pre-padded
# with zeroed halos), and the layout conversion shrinks to one cheap XLA pad
# of the SCALAR input volume in and one minor-dim slice of the scalar output
# out — fused into the first/last rows' producing/consuming kernel steps in
# the sense that no 16-channel tensor ever exists outside the kernel.
#
# Thin-layer lowering: the r5 per-layer kernel pads the 1-channel first
# layer's contraction to ``_MIN_CB`` sublanes (2× its dot FLOPs) because a
# thin EPILOGUE block is the costlier currency there; in the resident kernel
# the first layer contracts K = k² exactly (c_in = 1, no padding — its
# epilogue is over c_out = 16 full rows), and the last layer runs N = k²·C_out
# exactly (C_out ∈ {1, 2}) instead of padding C_out up — together removing
# ~20% of the stack's executed dot FLOPs at the PF-Pascal arch.
#
# Ring protocol (d = (k−1)/2, slot(r) = (r + k) mod k):
#   * step 0 primes rows −d..−1 of every ring with zeros (bottom i-halo);
#   * at step ii, layer l computes row r = ii − l·d when 0 ≤ r < hA, reading
#     previous-layer rows r−d..r+d from the ring (layer 0 reads the k
#     halo-padded input rows the BlockSpecs stage);
#   * when r lands in the top halo [hA, hA+d) the producing step writes a
#     zero row instead, so consumers never mask: out-of-range reads are
#     zeros by construction (also across batch items — the priming and halo
#     writes re-establish the invariant at every ii == 0).
# Only primitives from the r5 Mosaic legality battery are used (sublane
# concat/slices, lane slices/pads at any offset, dynamic leading-dim ring
# indexing, both dot orientations); the tier is still gated by a real
# compile probe and falls back to the per-layer chain, then XLA.
# ---------------------------------------------------------------------------

# j-chunk candidates for the resident kernel's per-row loop, largest first;
# the chooser takes the largest that fits the VMEM budget (env-overridable
# for probes: NCNET_FUSED_RES_JCH pins it)
_RES_JCH = tuple(
    int(v) for v in _os.environ.get("NCNET_FUSED_RES_JCH", "5 4 3 2 1").split()
)


def _tap_reduce_conv(slabs, w, *, je, c_out, k, sp_l, n_lane):
    """The fused-lane conv row-chunk shared by the resident forward and its
    VJP kernels (ops/nc_fused_lane_vjp.py): concatenate the k² shifted row
    slabs into the A operand, dot against the packed weight, and reduce the
    B-side taps as pure lane offsets.

    ``slabs``: k² arrays ``(je, c_in, kl)`` ordered ``(p, q)`` row-major
    (matching ``_pack_weight``'s ``(p, q, c)`` row order).
    Returns ``(acc, a3)``: the pre-bias f32 row chunk ``(je, c_out, n_lane)``
    and the A operand ``(je, k²·c_in, kl)`` (the VJP's dW contraction reuses
    it, so it is returned rather than rebuilt)."""
    a3 = jnp.concatenate(slabs, axis=1)  # (je, k²·c_in, kl)
    ys = []
    for j in range(je):
        y = jax.lax.dot_general(
            w, a3[j], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (k²·c_out, kl) f32, rows ordered (r, s, o)
        ys.append(y.astype(jnp.bfloat16))
    ybuf = jnp.stack(ys, axis=0)
    acc = jnp.zeros((je, c_out, n_lane), jnp.float32)
    for rr in range(k):
        for ss in range(k):
            blk = (rr * k + ss) * c_out
            off = rr * sp_l + ss
            acc = acc + ybuf[:, blk:blk + c_out, off:off + n_lane].astype(
                jnp.float32)
    return acc, a3


def _resident_kernel(*refs, k, chans, s_i, s_j, sp_j, kl, sp_l, je_list):
    """One wavefront step: layer ``l`` emits volume row ``ii − l·d``.

    refs = (x_0..x_{k-1}, w_0, b_0, ..., w_{L-1}, b_{L-1}, mask, out,
            ring_0..ring_{L-2}):
      x_p:    (1, 1, sp_j, 1, kl) — halo-padded input row ii+p (clamped).
      w_l:    (k²·c_in_l, k²·c_out_l) = w4d[(p,q,c), (r,s,o)], exact widths.
      b_l:    (1, c_out_l, 1); mask: (1, 1, kl) lane-halo zeroing.
      out:    (1, 1, s_j, c_out_last, kl) — final-layer row ii − (L−1)·d.
      ring_l: (k, sp_j, c_out_l, kl) scratch ring of layer l's padded rows.
    """
    from jax import lax
    from jax.experimental import pallas as pl

    n_layers = len(chans)
    h = k - 1
    d = h // 2
    x_refs = refs[:k]
    wb_refs = refs[k:k + 2 * n_layers]
    m_ref = refs[k + 2 * n_layers]
    out_ref = refs[k + 2 * n_layers + 1]
    rings = refs[k + 2 * n_layers + 2:]

    ii = pl.program_id(1)
    n_lane = kl - sp_l * h - h
    pad_lo = d * sp_l + d
    mask = m_ref[:].astype(jnp.float32)

    def slot(r):
        return lax.rem(r + k, k)  # r ≥ −d > −k, so the +k keeps rem ≥ 0

    def zero_row(ring_ref, r, c_out):
        ring_ref[pl.ds(slot(r), 1)] = jnp.zeros(
            (1, sp_j, c_out, kl), ring_ref.dtype
        )

    @pl.when(ii == 0)
    def _prime():
        for l in range(n_layers - 1):
            for r in range(-d, 0):
                zero_row(rings[l], r, chans[l][1])

    def compute_row(l, r):
        c_in, c_out = chans[l]
        w = wb_refs[2 * l][:]
        bias = wb_refs[2 * l + 1][:].astype(jnp.float32)
        last = l == n_layers - 1
        if l > 0:
            slots = [slot(r - d + p) for p in range(k)]
        if not last and d:
            # j-halo columns: re-zeroed on every write (the slot's previous
            # occupant — possibly from the previous batch item, or raw
            # scratch garbage on the very first pass — is overwritten)
            rings[l][pl.ds(slot(r), 1), :d] = jnp.zeros(
                (1, d, c_out, kl), rings[l].dtype)
            rings[l][pl.ds(slot(r), 1), d + s_j:] = jnp.zeros(
                (1, sp_j - d - s_j, c_out, kl), rings[l].dtype)
        for j0, je in je_list:
            if l == 0:
                slabs = [
                    x_refs[p][0, 0, j0 + q:j0 + q + je, :, :]
                    for p in range(k) for q in range(k)
                ]
            else:
                slabs = [
                    rings[l - 1][pl.ds(slots[p], 1), j0 + q:j0 + q + je][0]
                    for p in range(k) for q in range(k)
                ]
            acc, _ = _tap_reduce_conv(
                slabs, w, je=je, c_out=c_out, k=k, sp_l=sp_l, n_lane=n_lane)
            acc = jnp.maximum(acc + bias, 0.0)
            full = jnp.pad(
                acc, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane))
            ) * mask
            if last:
                out_ref[0, 0, j0:j0 + je] = full.astype(out_ref.dtype)
            else:
                rings[l][pl.ds(slot(r), 1), d + j0:d + j0 + je] = (
                    full[None].astype(rings[l].dtype))

    for l in range(n_layers):
        r = ii - l * d if d else ii  # d == 0 ⇒ k == 1: no wavefront delay
        if n_layers == 1:
            compute_row(l, r)  # grid is exactly s_i: r is always in range
            continue

        @pl.when((r >= 0) & (r < s_i))
        def _(l=l, r=r):
            compute_row(l, r)

        if l < n_layers - 1 and d:

            @pl.when((r >= s_i) & (r < s_i + d))
            def _(l=l, r=r):
                zero_row(rings[l], r, chans[l][1])


def _resident_vmem_bytes(wa, hb, wb, kernels, channels, je) -> int:
    """Worst-step VMEM working set of the resident kernel (bytes)."""
    k = kernels[0]
    h = k - 1
    sp_j = wa + h
    sp_l = wb + h
    kl = (hb + h) * sp_l
    n_lane = kl - sp_l * h - h
    chans = list(zip((1,) + tuple(channels[:-1]), channels))
    rings = sum(k * sp_j * co * kl * 2 for _, co in chans[:-1])
    weights = sum((k * k * ci) * (k * k * co) * 2 for ci, co in chans)
    inputs = 2 * k * sp_j * 1 * kl * 2          # k row blocks, double-buffered
    out = 2 * wa * chans[-1][1] * kl * 2
    temps = max(
        je * k * k * ci * kl * 2                # a3 build
        + k * k * co * kl * 4                   # one f32 dot output
        + je * k * k * co * kl * 2              # bf16 ybuf
        + je * co * n_lane * 4                  # f32 accumulator
        + je * co * kl * 4                      # padded/masked row chunk
        for ci, co in chans
    )
    return rings + weights + inputs + out + temps


def _resident_shape_class(kernels, channels) -> bool:
    ks = set(kernels)
    if len(ks) != 1 or kernels[0] % 2 == 0:
        return False
    if channels[-1] > 4:
        # the chain returns a thin final volume (the NC-stack shape class:
        # 1 channel, or 2 for the tap-swap block-diagonal chain)
        return False
    return True


def _resident_je(ha, wa, hb, wb, kernels, channels) -> int:
    for je in _RES_JCH:
        je = min(je, wa)
        if _resident_vmem_bytes(wa, hb, wb, kernels, channels, je) \
                <= _VMEM_BUDGET:
            return je
    return 0


def fused_resident_feasible(ha, wa, hb, wb, kernels, channels) -> bool:
    """Whether the resident whole-stack kernel fits this shape class: cubic
    odd uniform kernels, thin final layer, and a VMEM working set (rings +
    weights + worst-layer temps) inside the budget at some j-chunk size."""
    if not _resident_shape_class(kernels, channels):
        return False
    return _resident_je(ha, wa, hb, wb, kernels, channels) > 0


@functools.lru_cache(maxsize=8)
def fused_resident_compiles(ha, wa, hb, wb, kernels, channels) -> bool:
    """Real-compile probe for the resident kernel (cached per shape class) —
    same discipline as :func:`fused_lane_compiles`: Mosaic legality depends
    on concrete shapes, so the chooser verifies an actual compile and any
    failure falls back to the per-layer chain / XLA formulations."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.bfloat16)
        ws, bs = [], []
        c_in = 1
        for kk, c_out in zip(kernels, channels):
            ws.append(jax.ShapeDtypeStruct(
                (kk,) * 4 + (c_in, c_out), jnp.bfloat16))
            bs.append(jax.ShapeDtypeStruct((c_out,), jnp.bfloat16))
            c_in = c_out

        def run(x, ws, bs):
            params = [{"w": w, "b": b} for w, b in zip(ws, bs)]
            return nc_stack_resident(params, x)

        compiled = jax.jit(run).lower(x, ws, bs).compile()
        _record_probe_memory("nc_resident_probe", "resident",
                             ha, wa, hb, wb, kernels, channels, compiled)
        return True
    except Exception:
        return False


def fused_layout_in(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """The resident path's whole layout-in: halo-pad the SCALAR volume
    ``(B, hA, wA, hB, wB, 1)`` on all four spatial dims and fuse the minor
    pair — ``(B, hA+h, wA+h, 1, (hB+h)·(wB+h))`` bf16.  Exposed so the bench
    can time the conversion stage in isolation."""
    b, ha, wa, hb, wb, _ = x.shape
    h = k - 1
    d = h // 2
    return jnp.pad(
        x[..., 0], ((0, 0),) + ((d, d),) * 4
    ).reshape(b, ha + h, wa + h, 1, (hb + h) * (wb + h)).astype(jnp.bfloat16)


def fused_layout_out(out: jnp.ndarray, hb: int, wb: int, k: int) -> jnp.ndarray:
    """The resident path's layout-out: unfuse the minor lane pair of the
    kernel output ``(B, hA, wA, C_out, kl)``, crop the lane halo, move the
    channel dim last — ``(B, hA, wA, hB, wB, C_out)``."""
    b, ha, wa, co, _ = out.shape
    h = k - 1
    d = h // 2
    out = out.reshape(b, ha, wa, co, hb + h, wb + h)
    out = out[:, :, :, :, d:d + hb, d:d + wb]
    return jnp.moveaxis(out, 3, 5)


def nc_stack_resident(nc_params: List[dict], x: jnp.ndarray,
                      interpret: bool = False,
                      _allow_wide_final: bool = False) -> jnp.ndarray:
    """The full [conv4d_same + bias + ReLU]×N stack on ``x``
    ``(B, hA, wA, hB, wB, 1)`` as ONE resident Pallas program.

    Returns ``(B, hA, wA, hB, wB, C_out_last)`` — unlike
    :func:`nc_stack_fused_lane` the final layer may be up to 4 channels wide
    (the tap-swap block-diagonal chain uses 2).  Numerically equivalent to
    the XLA stack up to bf16 rounding (f32 dot accumulation, bf16 ring
    activations — the same inter-layer precision as the per-layer chain).
    Forward-only; see :func:`nc_stack_fused` for the differentiable wrapper.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, ha, wa, hb, wb, _ = x.shape
    assert x.shape[-1] == 1 and nc_params[0]["w"].shape[4] == 1, (
        "nc_stack_resident requires a 1-channel input volume and first "
        "layer (the NC-stack shape class)"
    )
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    channels = tuple(layer["w"].shape[5] for layer in nc_params)
    assert _resident_shape_class(kernels, channels) or (
        _allow_wide_final
        and _resident_shape_class(kernels, channels[:-1] + (1,))
    ), (
        f"resident stack does not support kernels={kernels} "
        f"channels={channels}"
    )  # _allow_wide_final: bench prefix probes time truncated chains whose
    # final layer is wide — same kernel, bigger output block; not a
    # production shape class
    k = kernels[0]
    h = k - 1
    d = h // 2
    n_layers = len(nc_params)
    sp_l = wb + h
    kl = (hb + h) * sp_l
    sp_j = wa + h
    sp_i = ha + h
    chans = tuple(zip((1,) + channels[:-1], channels))
    je = _resident_je(ha, wa, hb, wb, kernels, channels)
    assert je > 0, "resident stack infeasible; gate with fused_resident_feasible"
    je_list = tuple((j0, min(je, wa - j0)) for j0 in range(0, wa, je))
    mask = jnp.asarray(_make_mask((hb, wb), k), jnp.bfloat16)

    # layout-in: ONE pad of the scalar volume (no 16-channel tensor ever
    # exists outside the kernel) + minor-dim reshape into the fused frame
    xp = fused_layout_in(x, k)

    ops = [xp] * k
    for (ci, co), layer in zip(chans, nc_params):
        ops.append(_pack_weight(
            layer["w"].astype(jnp.bfloat16), k, ci, co, pad=False))
        ops.append(layer["b"].astype(jnp.bfloat16).reshape(1, co, 1))
    ops.append(mask)

    kern = functools.partial(
        _resident_kernel, k=k, chans=chans, s_i=ha, s_j=wa, sp_j=sp_j, kl=kl,
        sp_l=sp_l, je_list=je_list,
    )
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, sp_j, 1, kl),
        lambda bi, ii, p=p: (bi, jnp.minimum(ii + p, sp_i - 1), 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    full_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    co_last = channels[-1]
    delay = (n_layers - 1) * d
    out = pl.pallas_call(
        kern,
        grid=(b, ha + delay),
        in_specs=[row_spec(p) for p in range(k)]
        + [full_spec() for _ in range(2 * n_layers + 1)],
        out_specs=pl.BlockSpec(
            (1, 1, wa, co_last, kl),
            lambda bi, ii: (bi, jnp.maximum(ii - delay, 0), 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (b, ha, wa, co_last, kl), jnp.bfloat16),
        scratch_shapes=[
            pltpu.VMEM((k, sp_j, co, kl), jnp.bfloat16)
            for _, co in chans[:-1]
        ],
        interpret=interpret,
    )(*ops)
    # layout-out: minor-dim unfuse of the thin output + halo crop
    return fused_layout_out(out, hb, wb, k)


# Tiers disabled at RUNTIME, after a compiled program failed mid-run
# (XlaRuntimeError / RESOURCE_EXHAUSTED under eval-loop memory pressure —
# conditions the compile-time probe cannot see).  Process-global by design:
# a Pallas kernel that just OOMed will OOM again on the next shape bucket
# too, so the demotion applies to every subsequent trace, and only an
# explicit reset (a fresh process, or reset_fused_tier_demotions) re-arms
# the tier.  See models/ncnet.recover_from_device_failure for the
# demote-retrace-retry recovery that writes into this registry.
_runtime_demoted: set = set()

# the FORWARD tier ladder walked by tier=None demotion (eval recovery);
# "resident_vjp" — the training backward tier (ops/nc_fused_lane_vjp.py) —
# is demotable only by NAME (training's recovery passes it explicitly via
# recover_from_device_failure(prefer_tier=...)), so an eval-loop device
# failure never wastes a demotion cycle on a tier the eval path cannot run.
# "coarse2fine" — the sparse match PIPELINE (ops/sparse_corr.py) — sits
# above the fused-stack tiers (it replaces the whole dense volume, so when
# it is routing traffic it is the first failure suspect), but the ladder
# walk skips it unless it IS the active pipeline (see demote_fused_tier):
# demoting a tier no traffic runs would burn the recovery's free retry on
# a bit-identical program.
# "cp" / "fft" — the ARITHMETIC tiers (ops/conv4d_cp.py, ops/conv4d_fft.py:
# rank-R separable and spectral conv4d) — outrank the Pallas tiers because
# their gates only pass where the ALGORITHM beats the dense k⁴ FLOP count
# the Pallas tiers merely schedule well; like coarse2fine, the ladder walk
# only treats them as failure suspects while they are routing traffic.
_TIER_ORDER = ("coarse2fine", "cp", "fft", "resident", "perlayer")
_ALL_TIERS = ("resident_vjp",) + _TIER_ORDER


def demote_fused_tier(tier: Optional[str] = None) -> Optional[str]:
    """Disable a fused-stack tier for the rest of the process.

    ``tier=None`` demotes the highest still-enabled FORWARD tier (the one
    ``choose_fused_stack`` would have picked first); returns the tier
    demoted, or None when every Pallas tier is already disabled (the caller
    is on plain XLA — a failure there is a real error, not a tier problem).
    The training backward tier ``"resident_vjp"`` must be named explicitly
    (see ``_TIER_ORDER`` note above).
    """
    from ncnet_tpu.ops import tier_cache

    if tier is None:
        # walk past persistently-demoted tiers too: "demoting" a tier a
        # previous process already disabled would burn the recovery cycle
        # without changing the program
        dead = _runtime_demoted | tier_cache.persistent_demotions()
        tier = None
        for t in _TIER_ORDER:
            if t == "coarse2fine" \
                    and _last_selected.get("pipeline") != "coarse2fine":
                # the sparse pipeline is only a failure suspect when it is
                # actually routing traffic (sparse_topk off, or already on
                # dense fallback: demoting it changes no program)
                continue
            if t in ("cp", "fft") and _last_selected.get("forward") != t:
                # same rule for the arithmetic tiers: most programs never
                # select them (no factors attached / gate predicts a loss),
                # and demoting an inactive tier changes no program
                continue
            if t not in dead:
                tier = t
                break
        if tier is None:
            return None
    elif tier not in _ALL_TIERS or tier in _runtime_demoted:
        return None
    _runtime_demoted.add(tier)
    # negative cache entry: a tier that crashed mid-run stays demoted
    # across restarts (and its cached positive decisions are dropped)
    tier_cache.record_demotion(tier)
    from ncnet_tpu.observability import events as _obs_events

    _obs_events.emit("tier_demoted", tier=tier,
                     demoted=sorted(_runtime_demoted))
    return tier


def demoted_fused_tiers() -> frozenset:
    """The tiers currently disabled by runtime demotion."""
    return frozenset(_runtime_demoted)


def reset_fused_tier_demotions() -> None:
    """Re-arm all runtime-demoted tiers (tests; or a deliberate re-probe).

    A deliberate re-probe must mean what it says: the persistent tier cache
    (``ops/tier_cache.py``) is cleared too, or a stale cached decision —
    including the negative entry the demotion just wrote — would answer the
    very probe this reset requests."""
    _runtime_demoted.clear()
    _emitted_choices.clear()
    _last_selected.clear()
    from ncnet_tpu.ops import tier_cache

    tier_cache.clear()


# last-emitted tier selection per shape signature: the telemetry event
# fires only when the authority's DECISION changes for a shape class (first
# trace, or a post-demotion retrace landing on a lower tier), not on every
# retrace of an unchanged decision
_emitted_choices: dict = {}

# most recent decision per STAGE ("forward" / "backward"), regardless of
# shape — the "active fused tier" label the quality-observability layer
# stamps on its signals (observability/quality.py::active_tier).  Updated on
# EVERY chooser consult (not just decision changes), so a post-demotion
# retrace relabels subsequent quality events immediately.
_last_selected: dict = {}


def last_selected_tier(stage: str = "forward"):
    """The tier name the stage's chooser most recently decided on for ANY
    shape ('resident' / 'perlayer' / 'resident_vjp' / 'xla'; for the
    "pipeline" stage: 'coarse2fine' / 'dense' — ops/sparse_corr.py's
    match-pipeline chooser), or None when the chooser has not run this
    process (a pure-XLA path that never consulted it — fp32/CPU volumes)."""
    return _last_selected.get(stage)


def _emit_tier_selected(stage: str, sig, tier, cached: bool = False,
                        none_label: str = "xla") -> None:
    # none_label: what a None decision means for the stage — "xla" for the
    # fused-stack choosers, "dense" for the match-pipeline chooser
    _last_selected[stage] = tier or none_label
    if _emitted_choices.get((stage, sig)) == tier:
        return
    _emitted_choices[(stage, sig)] = tier
    from ncnet_tpu.observability import events as _obs_events

    # sig may carry a 7th element (the CP ranks context / a "forced" tag —
    # see choose_fused_stack): it keys the decision but is not a wire field
    ha, wa, hb, wb, kernels, channels = sig[:6]
    _obs_events.emit(
        "tier_selected", stage=stage, tier=tier or none_label,
        shape=[ha, wa, hb, wb], kernels=list(kernels),
        channels=list(channels), cached=bool(cached),
    )


def choose_fused_stack(ha, wa, hb, wb, kernels, channels,
                       cp_ranks=None, pallas_ok: bool = True):
    """The one authority for the fused-stack tier at a shape class:
    ``'cp'`` (rank-R separable chain, ops/conv4d_cp.py), ``'fft'``
    (spectral conv, ops/conv4d_fft.py), ``'resident'`` (whole-stack
    Pallas kernel), ``'perlayer'`` (r5 chain), or ``None`` (XLA
    formulations).  Every tier is gated by a cheap arithmetic feasibility
    gate plus a real compile probe, and skipped when runtime-demoted: a
    tier that failed MID-RUN (``demote_fused_tier``) stays off even where
    its probe is green, because the failure mode (OOM under eval-loop
    memory pressure, Mosaic runtime faults) is invisible to the probe.

    Round 17 adds the two ARITHMETIC tiers above the Pallas ladder — they
    cut the k⁴ FLOPs themselves rather than scheduling them, run as plain
    XLA on any backend/dtype, and engage only where their gates predict a
    FLOP win.  ``cp_ranks``: the per-layer CP ranks when every layer of
    the caller's stack carries factors (``conv4d_cp.cp_stack_ranks``) —
    the CP tier's opt-in context, part of the decision's cache signature.
    ``pallas_ok``: whether the caller's program can run the Pallas tiers
    at all (bf16 volume + weights); the arithmetic tiers are considered
    either way, which is what lets fp32/CPU programs route through them.

    Round 9: the persistent tier cache (``ops/tier_cache.py``) is consulted
    before the compile probes — a warm process replays a previous process's
    probed decision (the cheap feasibility gates still run) and skips the
    compile entirely; demotions persisted there apply like runtime
    ones.  A miss probes as before and records the outcome."""
    cp_ranks = tuple(cp_ranks) if cp_ranks else None
    sig = (ha, wa, hb, wb, tuple(kernels), tuple(channels))
    tier, cached = _choose_fused_stack(
        *sig, cp_ranks=cp_ranks, pallas_ok=pallas_ok)
    sig_ext = sig if cp_ranks is None else sig + (cp_ranks,)
    _emit_tier_selected("forward", sig_ext, tier, cached=cached)
    return tier


def note_forced_tier(ha, wa, hb, wb, kernels, channels, tier) -> None:
    """Record an explicitly FORCED forward tier (``ModelConfig.nc_tier`` /
    the CP fine-tune path) as the stage's active decision, bypassing the
    chooser — so quality events are tagged with the tier that actually ran
    and the demotion ladder sees it as routing traffic.  The "forced" tag
    keys the telemetry separately from chooser decisions at the same
    shape (a forced run must not suppress — or be suppressed by — the
    chooser's own tier_selected event)."""
    sig = (ha, wa, hb, wb, tuple(kernels), tuple(channels), "forced")
    _emit_tier_selected("forward", sig, tier)


def _forward_tier_usable(tier, ha, wa, hb, wb, kernels, channels,
                         cp_ranks=None, pallas_ok: bool = True) -> bool:
    """Whether a CACHED forward decision is still admissible without a
    probe: the tier is not demoted and passes its (cheap, arithmetic)
    feasibility gate — so a cache written under different VMEM budget
    constants degrades to a re-probe, not a doomed dispatch.  A cached
    XLA decision (None) is never trusted: the probe failure that produces
    one may be transient (device busy, tunnel hiccup), and replaying it
    would pin the shape to the slow tier forever — XLA outcomes re-probe
    every process instead (the pre-cache behavior)."""
    if tier is None:
        return False
    from ncnet_tpu.ops import tier_cache

    if tier in _runtime_demoted or tier in tier_cache.persistent_demotions():
        return False
    if tier == "cp":
        from ncnet_tpu.ops.conv4d_cp import cp_feasible

        return cp_ranks is not None and cp_feasible(
            ha, wa, hb, wb, kernels, channels, cp_ranks)
    if tier == "fft":
        from ncnet_tpu.ops.conv4d_fft import fft_feasible

        return fft_feasible(ha, wa, hb, wb, kernels, channels)
    if not pallas_ok:
        return False
    if tier == "resident":
        return fused_resident_feasible(ha, wa, hb, wb, kernels, channels)
    if tier == "perlayer":
        return (channels[-1] == 1
                and fused_lane_feasible(ha, wa, hb, wb, kernels, channels))
    return False


def _choose_fused_stack(ha, wa, hb, wb, kernels, channels,
                        cp_ranks=None, pallas_ok: bool = True):
    """Returns ``(tier, from_cache)``."""
    from ncnet_tpu.ops import tier_cache
    from ncnet_tpu.ops.conv4d import _pallas_available
    from ncnet_tpu.ops.conv4d_cp import cp_compiles, cp_feasible
    from ncnet_tpu.ops.conv4d_fft import fft_compiles, fft_feasible

    sig = (ha, wa, hb, wb, kernels, channels)
    sig_ext = sig if cp_ranks is None else sig + (cp_ranks,)
    hit = tier_cache.lookup("forward", sig_ext)
    if hit is not None and _forward_tier_usable(
            hit[0], *sig, cp_ranks=cp_ranks, pallas_ok=pallas_ok):
        return hit[0], True
    demoted = _runtime_demoted | tier_cache.persistent_demotions()
    # a failed compile probe may be TRANSIENT (device busy, tunnel
    # hiccup), so any decision downstream of one is not cacheable: caching
    # it would pin the shape below its fast tier across every future
    # process.  Only a decision reached without skipping past a failed
    # probe is persisted; the rest re-probe next process (the pre-cache
    # behavior).
    probe_failed = False
    tier = None
    # arithmetic tiers first (backend/dtype-agnostic): they only pass their
    # gates where the ALGORITHM undercuts the dense FLOPs the Pallas tiers
    # schedule, so when one engages it outranks the whole Pallas ladder
    if cp_ranks is not None and "cp" not in demoted \
            and cp_feasible(ha, wa, hb, wb, kernels, channels, cp_ranks):
        if cp_compiles(ha, wa, hb, wb, kernels, channels, cp_ranks):
            tier = "cp"
        else:
            probe_failed = True
    if tier is None and "fft" not in demoted \
            and fft_feasible(ha, wa, hb, wb, kernels, channels):
        if fft_compiles(ha, wa, hb, wb, kernels, channels):
            tier = "fft"
        else:
            probe_failed = True
    if tier is None and pallas_ok and _pallas_available():
        if "resident" not in demoted \
                and fused_resident_feasible(ha, wa, hb, wb, kernels,
                                            channels):
            if fused_resident_compiles(ha, wa, hb, wb, kernels, channels):
                tier = "resident"
            else:
                probe_failed = True
        if tier is None and "perlayer" not in demoted \
                and channels[-1] == 1 \
                and fused_lane_feasible(ha, wa, hb, wb, kernels, channels):
            if fused_lane_compiles(ha, wa, hb, wb, kernels, channels):
                tier = "perlayer"
            else:
                probe_failed = True
    if tier is not None and not probe_failed:
        tier_cache.record("forward", sig_ext, tier)
    return tier, False


def _fused_stack_impl(nc_params, x):
    """Dispatch the forward to the best available tier for this shape."""
    from ncnet_tpu.ops.conv4d_cp import cp_stack_ranks

    b, ha, wa, hb, wb, _ = x.shape
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    channels = tuple(layer["w"].shape[5] for layer in nc_params)
    tier = choose_fused_stack(
        ha, wa, hb, wb, kernels, channels,
        cp_ranks=cp_stack_ranks(nc_params),
        pallas_ok=x.dtype == jnp.bfloat16)
    if tier == "cp":
        from ncnet_tpu.ops.conv4d_cp import nc_stack_cp

        return nc_stack_cp(nc_params, x)
    if tier == "fft":
        from ncnet_tpu.ops.conv4d_fft import nc_stack_fft

        return nc_stack_fft(nc_params, x)
    if tier == "resident":
        return nc_stack_resident(nc_params, x)
    if tier == "perlayer":
        return nc_stack_fused_lane(nc_params, x)
    return _xla_stack(nc_params, x)


def _xla_stack(nc_params, x):
    """The equivalent XLA stack (conv4d auto) — the custom-VJP backward."""
    from ncnet_tpu.ops.conv4d import conv4d

    for layer in nc_params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


@jax.custom_vjp
def nc_stack_fused(nc_params, x):
    """The fused NC stack (resident kernel when the shape class compiles,
    else the per-layer chain, else the XLA stack) with a tiered backward.

    Pallas kernels have no AD rule, so this op carries its own VJP.  The
    backward dispatches through ``choose_fused_vjp``
    (ops/nc_fused_lane_vjp.py): the RESIDENT staged Pallas backward —
    in-kernel forward replay for the ReLU masks, true dX/dW kernels, f32
    accumulators — when the shape class compiles, else a replay of the
    equivalent XLA stack's VJP (one extra XLA forward).  The residuals are
    only ``(nc_params, x)``: no activation is ever saved to HBM in either
    tier."""
    return _fused_stack_impl(nc_params, x)


def _fused_fwd(nc_params, x):
    return _fused_stack_impl(nc_params, x), (nc_params, x)


def _fused_bwd(res, g):
    nc_params, x = res
    from ncnet_tpu.ops import nc_fused_lane_vjp as vjp_mod

    b, ha, wa, hb, wb, _ = x.shape
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    channels = tuple(layer["w"].shape[5] for layer in nc_params)
    tier = vjp_mod.choose_fused_vjp(ha, wa, hb, wb, kernels, channels)
    if tier is not None:
        return vjp_mod.nc_stack_fused_vjp(
            nc_params, x, g, interpret=tier == "interpret")
    _, vjp = jax.vjp(_xla_stack, nc_params, x)
    return vjp(g.astype(x.dtype))


nc_stack_fused.defvjp(_fused_fwd, _fused_bwd)
