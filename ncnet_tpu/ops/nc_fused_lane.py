"""Fused-(hB·wB)-lane Pallas kernels for the NC filter stack.

The r5 composed breakdown (tools/filter_stage_probe.py, v5e, PF-Pascal 25⁴
bf16 bs4) pinned the filter's cost: the 16→16 layer runs at 28% of MXU peak
and the 16→1 layer at 3.7% under XLA's conv lowering, and every XLA-level
reformulation measured worse (tools/filter_combo_probe.py: 'abfold' 25.7 vs
7.7 ms/pair baseline).  This module implements the one formulation XLA
cannot express, in Pallas:

  * volume rows ride as ``(j, C sublanes, fused padded (hB+h)(wB+h) lanes)``
    — for the 25⁴ volume with k=5, 841 lanes (94% lane fill at the 896 pad);
  * the matmul contracts K = (kA, kWA, C_in) — 400 for the 16-channel
    layers, filling the MXU contraction depth (measured ~88% of peak on the
    dot, tools/pallas_l2_probe.py ablations);
  * the B-side (kB, kWB) taps become PURE LANE OFFSETS of the fused kl dim
    (tap (r,s) ↔ lane shift r·(wB+h)+s), resolved by a vectorized VMEM
    epilogue over the dot's N = (kB, kWB, C_out) — which measured FREE (it
    hides behind the MXU);
  * bias + ReLU fuse into the epilogue; inter-layer volumes stay in the
    fused layout (no per-layer HBM transpose).

Every primitive was legality-probed on this toolchain before the design was
fixed (tools/mosaic_probes.py ``r5_*`` battery — the round-2/3 kernel's
lane-dim reshape is exactly what Mosaic rejects, ops/conv4d_pallas.py).

Thin channel dims are padded up to ``_MIN_CB`` sublanes with zero weights:
a 1-sublane epilogue block would pay ~3k tiny VPU ops per volume (op-
overhead-bound); an 8-sublane block rides full native rows.  The extra dot
FLOPs are the cheaper currency (the dots run at ~88% of peak).

Measured at the bench workload (v5e, bf16, 8 batch-folded volumes,
tools/pallas_l2_probe.py): 16→16 layer 1.87 ms/volume including the layout
conversion vs XLA coutfold 2.52 in the same process.

Reference semantics match ``ops/conv4d.py`` 'same' conv (cross-correlation,
zero padding) + bias + ReLU — the reference's NeighConsensus layer
(/root/reference/lib/model.py:122-153 with lib/conv4d.py:39-48).
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

# NOTE: jax.experimental.pallas is imported lazily inside _conv_fused_lane —
# the package re-exports this module, and `import ncnet_tpu.ops` must stay
# light and pallas-independent (the same discipline as ops/conv4d.py's
# function-local pallas imports)

# VMEM working-set budget (v5e: ~16 MiB/core usable by one Pallas program)
_VMEM_BUDGET = 13 * 2 ** 20
# pad thin channel dims (c_in of the first layer, c_out of the last) up to
# this many sublanes.  Swept on v5e at the PF-Pascal stack
# (tools/nc_fused_lane_probe.py, ms/volume): 8 → 2.63, 4 → 2.20, 2 → 1.997,
# 1 → 2.04 — the dot's padded-FLOP cost beats the thin-tile epilogue cost
# down to 2 sublanes, below which tiny epilogue ops dominate.
import os as _os

_MIN_CB = int(_os.environ.get("NCNET_FUSED_LANE_MIN_CB", "2"))
# j-chunk of the dot/epilogue loop (measured insensitive across 4-6 at the
# bench workload; env knob for probes)
_JCH = int(_os.environ.get("NCNET_FUSED_LANE_JCH", "5"))


def _kernel(*refs, k, c_in, c_out, s_j, sp_j, kl, sp_l, je_list):
    """One (b, i) output row of relu(conv4d_same(x) + bias).

    refs = (x_0..x_{k-1}, w, bias, mask, out):
      x_p:  (1, 1, sp_j, c_in, kl) — padded input row i+p.
      w:    (k²·c_in, k²·c_out) = w4d[(p,q,c), (r,s,o)].
      bias: (1, c_out, 1); mask: (1, 1, kl) halo zeroing.
      out:  (1, 1, s_j, c_out, kl) — same fused frame, halo lanes zeroed.
    """
    x_refs, w_ref, b_ref, m_ref, out_ref = \
        refs[:k], refs[k], refs[k + 1], refs[k + 2], refs[k + 3]
    w = w_ref[:]
    n_lane = kl - sp_l * (k - 1) - (k - 1)  # valid-support slice length
    h = k - 1
    for j0, je in je_list:
        # A[(j), (p,q,c), (kl)]: k² shifted row slabs along the sublane dim
        a3 = jnp.concatenate(
            [x_refs[p][0, 0, j0 + q:j0 + q + je] for p in range(k)
             for q in range(k)],
            axis=1,
        )  # (je, k²·c_in, kl)
        ys = []
        for j in range(je):
            y = jax.lax.dot_general(
                w, a3[j], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (k²·c_out, kl) f32, rows ordered (r, s, o)
            ys.append(y.astype(jnp.bfloat16))
        ybuf = jnp.stack(ys, axis=0)
        acc = jnp.zeros((je, c_out, n_lane), jnp.float32)
        for r in range(k):
            for s in range(k):
                blk = (r * k + s) * c_out
                off = r * sp_l + s
                acc = acc + ybuf[:, blk:blk + c_out, off:off + n_lane].astype(
                    jnp.float32)
        acc = jnp.maximum(acc + b_ref[:].astype(jnp.float32), 0.0)
        pad_lo = (h // 2) * sp_l + h // 2
        full = jnp.pad(acc, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane)))
        out_ref[0, 0, j0:j0 + je] = (
            full * m_ref[:].astype(jnp.float32)).astype(out_ref.dtype)


def _conv_fused_lane(xp, w2, bias, mask, *, k, c_in, c_out, s_j, sp_l, kl,
                     interpret=False):
    """xp: (B, sp_i, sp_j, c_in, kl) padded fused-lane rows (bf16).
    Returns (B, s_i, s_j, c_out, kl) with halo lanes zeroed."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sp_i, sp_j = xp.shape[:3]
    s_i = sp_i - (k - 1)
    je_list = tuple((j0, min(_JCH, s_j - j0)) for j0 in range(0, s_j, _JCH))
    kern = functools.partial(
        _kernel, k=k, c_in=c_in, c_out=c_out, s_j=s_j, sp_j=sp_j, kl=kl,
        sp_l=sp_l, je_list=je_list,
    )
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, sp_j, c_in, kl), lambda bi, ii, p=p: (bi, ii + p, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kern,
        grid=(b, s_i),
        in_specs=[row_spec(p) for p in range(k)] + [
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, s_j, c_out, kl), lambda bi, ii: (bi, ii, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, s_i, s_j, c_out, kl), xp.dtype),
        interpret=interpret,
    )(*([xp] * k), w2, bias, mask)


def _pad_c(c: int) -> int:
    return max(c, _MIN_CB)


def _pack_weight(w, k, c_in, c_out):
    """(k,k,k,k,C_in,C_out) -> (k²·cinP, k²·coutP) [(p,q,c),(r,s,o)], with
    thin channel dims zero-padded to _MIN_CB sublanes."""
    ci, co = _pad_c(c_in), _pad_c(c_out)
    wp = jnp.pad(
        w, ((0, 0),) * 4 + ((0, ci - c_in), (0, co - c_out))
    )
    return jnp.transpose(wp, (0, 1, 4, 2, 3, 5)).reshape(
        k * k * ci, k * k * co
    )


def _make_mask(s_kl: tuple, k: int) -> np.ndarray:
    """(1, 1, kl) bf16: 1 on the valid (k,l) support, 0 on halo lanes."""
    hb, wb = s_kl
    h = k - 1
    m = np.zeros((hb + h, wb + h), np.float32)
    m[h // 2:h // 2 + hb, h // 2:h // 2 + wb] = 1.0
    return m.reshape(1, 1, -1)


def fused_lane_feasible(ha, wa, hb, wb, kernels, channels) -> bool:
    """Whether every layer's working set fits the VMEM budget and the shape
    class matches the kernel (cubic odd kernels, one k for the stack)."""
    ks = set(kernels)
    if len(ks) != 1 or kernels[0] % 2 == 0:
        return False
    if channels[-1] != 1:
        # the chain's un-fuse step returns the scalar volume (channel 0);
        # a wider final layer is not the NC-stack shape class
        return False
    k = kernels[0]
    sp_l = wb + k - 1
    kl = (hb + k - 1) * sp_l
    sp_j = wa + k - 1
    c_in = 1
    for c_out in channels:
        ci, co = _pad_c(c_in), _pad_c(c_out)
        rows = k * sp_j * ci * kl * 2                       # k input rows
        a3 = _JCH * k * k * ci * kl * 2                     # A build
        ybuf = _JCH * k * k * co * kl * 2                   # bf16 Y
        yf32 = k * k * co * kl * 4                          # one dot output
        out = wa * co * kl * 2
        w = (k * k * ci) * (k * k * co) * 2
        if rows + a3 + ybuf + yf32 + out + w > _VMEM_BUDGET:
            return False
        c_in = c_out
    return True


@functools.lru_cache(maxsize=8)
def fused_lane_compiles(ha, wa, hb, wb, kernels, channels) -> bool:
    """Real-compile probe at batch 1 (cached per shape class): Mosaic
    lowering legality depends on concrete shapes, so the chooser verifies an
    actual compile and any failure falls back to the XLA formulations —
    the same discipline as ops/conv4d_pallas.pallas_compiles."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.bfloat16)
        ws, bs = [], []
        c_in = 1
        for kk, c_out in zip(kernels, channels):
            ws.append(jax.ShapeDtypeStruct(
                (kk,) * 4 + (c_in, c_out), jnp.bfloat16))
            bs.append(jax.ShapeDtypeStruct((c_out,), jnp.bfloat16))
            c_in = c_out
        def run(x, ws, bs):
            params = [{"w": w, "b": b} for w, b in zip(ws, bs)]
            return nc_stack_fused_lane(params, x)
        jax.jit(run).lower(x, ws, bs).compile()
        return True
    except Exception:
        return False


def nc_stack_fused_lane(nc_params: List[dict], x: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """The full [conv4d_same + bias + ReLU]×N stack on ``x``
    ``(B, hA, wA, hB, wB, 1)``, chained through the fused-lane layout.

    Numerically equivalent (up to bf16 rounding; the dots accumulate f32) to
    the XLA stack in models/ncnet.py `neigh_consensus.stack`.  Forward-only:
    wrap under `jax.custom_vjp` at the call site for training (the chooser
    only routes eval/forward here — see neigh_consensus).
    """
    b, ha, wa, hb, wb, _ = x.shape
    assert nc_params[-1]["w"].shape[5] == 1, (
        "nc_stack_fused_lane requires a 1-channel final layer (the NC-stack "
        "shape class); wider stacks must use the XLA formulations"
    )
    # the lane packing below keeps only channel 0 of the input (x[..., 0]):
    # reject wider inputs loudly instead of silently dropping channels
    assert x.shape[-1] == 1 and nc_params[0]["w"].shape[4] == 1, (
        "nc_stack_fused_lane requires a 1-channel input volume and first "
        "layer (the NC-stack shape class); wider inputs must use the XLA "
        "formulations"
    )
    k = nc_params[0]["w"].shape[0]
    h = k - 1
    sp_l = wb + h
    kl = (hb + h) * sp_l
    mask = jnp.asarray(_make_mask((hb, wb), k), jnp.bfloat16)

    # (B, hA, wA, hB, wB, 1) -> (B, hA+h, wA+h, 1->cinP, kl): pure pads +
    # minor-dim reshape (no transpose: (k,l) is already minor)
    xp = jnp.pad(
        x[..., 0],
        ((0, 0),) + ((h // 2, h // 2),) * 4,
    ).reshape(b, ha + h, wa + h, 1, kl)
    xp = jnp.pad(xp, ((0, 0),) * 3 + ((0, _pad_c(1) - 1), (0, 0)))
    xp = xp.astype(jnp.bfloat16)

    c_in = 1
    for li, layer in enumerate(nc_params):
        c_out = layer["w"].shape[5]
        co_p = _pad_c(c_out)
        w2 = _pack_weight(
            layer["w"].astype(jnp.bfloat16), k, c_in, c_out)
        bias = jnp.pad(
            layer["b"].astype(jnp.bfloat16), (0, co_p - c_out)
        ).reshape(1, co_p, 1)
        y = _conv_fused_lane(
            xp, w2, bias, mask, k=k, c_in=_pad_c(c_in), c_out=co_p,
            s_j=wa, sp_l=sp_l, kl=kl, interpret=interpret,
        )
        if li + 1 < len(nc_params):
            # re-pad rows/cols for the next layer's halo (cheap leading-dim
            # pads; the lane halos are already zeroed by the kernel mask)
            xp = jnp.pad(
                y, ((0, 0), (h // 2, h // 2), (h // 2, h // 2), (0, 0),
                    (0, 0)),
            )
        c_in = c_out

    # (B, hA, wA, coP, kl) -> take channel 0, unfuse lanes, drop halo
    out = y[:, :, :, 0, :].reshape(b, ha, wa, hb + h, wb + h)
    out = out[:, :, :, h // 2:h // 2 + hb, h // 2:h // 2 + wb]
    return out[..., None]


def _xla_stack(nc_params, x):
    """The equivalent XLA stack (conv4d auto) — the custom-VJP backward."""
    from ncnet_tpu.ops.conv4d import conv4d

    for layer in nc_params:
        x = jax.nn.relu(conv4d(x, layer["w"], layer["b"]))
    return x


@jax.custom_vjp
def nc_stack_fused(nc_params, x):
    """:func:`nc_stack_fused_lane` with an XLA-fallback backward.

    Pallas kernels have no AD rule; differentiating this op replays the
    equivalent XLA stack's VJP (one extra XLA forward).  Training paths
    route to the XLA stack directly (``allow_pallas=False`` in
    models/ncnet.py) — this VJP exists so a user-level ``jax.grad`` over
    the eval forward stays correct rather than erroring."""
    return nc_stack_fused_lane(nc_params, x)


def _fused_fwd(nc_params, x):
    return nc_stack_fused_lane(nc_params, x), (nc_params, x)


def _fused_bwd(res, g):
    nc_params, x = res
    _, vjp = jax.vjp(_xla_stack, nc_params, x)
    return vjp(g.astype(x.dtype))


nc_stack_fused.defvjp(_fused_fwd, _fused_bwd)
