"""Feature normalization."""

from __future__ import annotations

import jax.numpy as jnp


def feature_l2_norm(x: jnp.ndarray, axis: int = -1, eps: float = 1e-6) -> jnp.ndarray:
    """Per-location L2 normalization over `axis`.

    Matches the reference's featureL2Norm (/root/reference/lib/model.py:14-17):
    the epsilon sits *inside* the square root — ``x / sqrt(sum(x^2) + eps)`` —
    which matters for golden parity on near-zero features.
    """
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return x / norm
