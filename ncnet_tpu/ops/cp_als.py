"""HOSVD + ALS canonical-polyadic factorization of conv4d kernels.

The numerical core of the dense→CP checkpoint conversion (ISSUE 17;
*Speeding-up Convolutional Neural Networks Using Fine-tuned
CP-Decomposition*, Lebedev et al., PAPERS.md) for the 6-way
``(kA, kWA, kB, kWB, C_in, C_out)`` kernels the NC filter stacks: HOSVD
initialization (leading left singular vectors of each mode unfolding — a
deterministic, nested-subspace start) refined by alternating least
squares, each mode solved exactly per sweep so the Frobenius
reconstruction error is monotonically non-increasing over sweeps.

Plain numpy on purpose — conversion is offline host work (seconds for the
InLoc arch), and keeping it out of jax means the tool runs identically
with no accelerator.  The factor layout matches ``ops/conv4d_cp.py``::

    w[p,q,r,s,c,o] ≈ Σ_ρ ka[p,ρ]·kwa[q,ρ]·kb[r,ρ]·kwb[s,ρ]·cin[c,ρ]·cout[ρ,o]

:func:`nested_decompose` warm-starts each rank from the previous rank's
solved factors with the new components' ``cout`` rows ZEROED, so the
starting error at rank R+1 equals the final error at rank R; combined
with ALS's monotone sweeps this makes reconstruction error provably
non-increasing in rank — the property tests/test_conv4d_tiers.py pins.

CLI wrapper: ``tools/cp_decompose.py``; fine-tune consumer:
``training/train.py`` (``TrainConfig.finetune_cp_rank``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_FACTOR_KEYS = ("ka", "kwa", "kb", "kwb", "cin", "cout")
DEFAULT_ALS_ITERS = 60


def _unfold(t: np.ndarray, mode: int) -> np.ndarray:
    """Mode-``mode`` unfolding: ``(d_mode, prod(other dims))``, remaining
    modes flattened row-major in original order (the khatri-rao column
    order below matches this)."""
    return np.moveaxis(t, mode, 0).reshape(t.shape[mode], -1)


def _khatri_rao(mats: Sequence[np.ndarray]) -> np.ndarray:
    """Column-wise Kronecker of ``(d_j, R)`` factors, first factor slowest
    — the column order of a row-major unfolding's remaining modes."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return out


def _hosvd_init(w: np.ndarray, rank: int, seed: int = 0) -> List[np.ndarray]:
    """Per-mode leading left singular vectors, padded with small seeded
    Gaussian columns where a mode is thinner than the rank."""
    rng = np.random.RandomState(seed)
    factors = []
    for mode in range(w.ndim):
        u, _, _ = np.linalg.svd(_unfold(w, mode), full_matrices=False)
        u = u[:, :rank]
        if u.shape[1] < rank:
            pad = rng.standard_normal((u.shape[0], rank - u.shape[1]))
            u = np.concatenate([u, 0.01 * pad], axis=1)
        factors.append(u)
    return factors


def _reconstruct(factors: Sequence[np.ndarray]) -> np.ndarray:
    return np.einsum("pr,qr,sr,tr,cr,or->pqstco", *factors)


def _rel_err(w: np.ndarray, factors: Sequence[np.ndarray]) -> float:
    denom = float(np.linalg.norm(w)) or 1.0
    return float(np.linalg.norm(w - _reconstruct(factors))) / denom


def _als(w: np.ndarray, factors: List[np.ndarray],
         iters: int) -> List[np.ndarray]:
    """Exact per-mode least-squares sweeps: each update solves its mode's
    normal equations against the current others, so the Frobenius error
    never increases across sweeps."""
    for _ in range(iters):
        for mode in range(w.ndim):
            others = [factors[j] for j in range(w.ndim) if j != mode]
            gram = np.ones((factors[0].shape[1],) * 2)
            for u in others:
                gram *= u.T @ u
            kr = _khatri_rao(others)
            factors[mode] = _unfold(w, mode) @ kr @ np.linalg.pinv(gram)
    return factors


def decompose_kernel(
    w: np.ndarray, rank: int, iters: int = DEFAULT_ALS_ITERS,
    init: Optional[List[np.ndarray]] = None,
) -> Tuple[Dict[str, np.ndarray], float]:
    """Rank-``rank`` CP factors of one dense conv4d kernel.

    Returns ``(cp_dict, relative_error)`` with the ``ops/conv4d_cp.py``
    factor layout in float32.  ``init``: optional warm-start factor list
    (6 mode matrices, ``cout`` transposed to ``(C_out, R')`` like the
    internal layout); thinner inits are zero-padded on the ``cout`` mode so
    the warm start reproduces its source solution exactly."""
    w64 = np.asarray(w, dtype=np.float64)
    if init is None:
        factors = _hosvd_init(w64, rank)
    else:
        rng = np.random.RandomState(1)
        factors = []
        for mode, u in enumerate(init):
            u = np.asarray(u, dtype=np.float64)
            if u.shape[1] < rank:
                extra = rank - u.shape[1]
                if mode == w64.ndim - 1:
                    # zero cout rows: the new components start invisible,
                    # so the initial error equals the warm start's
                    pad = np.zeros((u.shape[0], extra))
                else:
                    pad = 0.01 * rng.standard_normal((u.shape[0], extra))
                u = np.concatenate([u, pad], axis=1)
            factors.append(u[:, :rank])
    factors = _als(w64, factors, iters)
    cp = {key: factors[m].astype(np.float32)
          for m, key in enumerate(_FACTOR_KEYS[:5])}
    cp["cout"] = factors[5].T.astype(np.float32)
    return cp, _rel_err(w64, factors)


def nested_decompose(
    w: np.ndarray, ranks: Sequence[int], iters: int = DEFAULT_ALS_ITERS,
) -> List[Tuple[Dict[str, np.ndarray], float]]:
    """Decompose at each rank (ascending), warm-starting every rank from
    the previous one — the construction that makes reconstruction error
    non-increasing in rank (module docstring)."""
    if list(ranks) != sorted(ranks):
        raise ValueError(f"ranks must ascend, got {list(ranks)}")
    results = []
    init = None
    for rank in ranks:
        cp, err = decompose_kernel(w, rank, iters=iters, init=init)
        results.append((cp, err))
        init = [cp[k].astype(np.float64) for k in _FACTOR_KEYS[:5]]
        init.append(cp["cout"].T.astype(np.float64))
    return results


def decompose_stack(nc_params: Sequence[dict], rank: int,
                    iters: int = DEFAULT_ALS_ITERS):
    """Attach ``"cp"`` factors to every layer of an NC stack at the
    requested rank (used verbatim per layer so the tier's FLOP model stays
    predictable).  Returns ``(new_params, per_layer_rel_errs)``; dense
    ``"w"``/``"b"`` ride along untouched so every non-CP tier keeps
    working and the chooser can fall back freely."""
    out, errs = [], []
    for layer in nc_params:
        cp, err = decompose_kernel(np.asarray(layer["w"], np.float32),
                                   rank, iters=iters)
        new_layer = dict(layer)
        new_layer["cp"] = cp
        out.append(new_layer)
        errs.append(err)
    return out, errs
