"""FFT-domain conv4d: the spectral tier of the NC filter.

Direct "same" conv4d pays ``2·cells·k⁴·C_in·C_out`` FLOPs; following *Fast
Training of Convolutional Networks through FFTs* (Mathieu, Henaff & LeCun,
PAPERS.md) the convolution is evaluated in the frequency domain instead —
``rfftn`` over the four spatial dims (each zero-padded to ``n+k−1``, so the
circular theorem computes the LINEAR correlation exactly), a per-frequency
complex contraction over C_in, ``irfftn``, and an exact crop back to the
"same" output window.  Transform cost grows like ``S·log S`` of the padded
volume while the spectral multiply is k-independent, so the win grows with
k — at k=3 the gate below rejects it, at the k=5 InLoc arch it clears.

Semantics: bit-exact in exact arithmetic to ``ops/conv4d.py``'s "same"
cross-correlation (zero pad ``k//2``, stride/dilation 1).  The correlation
theorem gives ``c[n] = Σ_j x[(n+j) mod S]·w[j] = IFFT(FFT(x)·conj(FFT(w)))``;
with both operands zero-padded to ``S = n+k−1`` no wraparound term touches a
nonzero product, and the "same" window is ``out[i] = c[(i − k//2) mod S]`` —
a roll by ``k//2`` and a leading slice per dim (the negative indices wrap
into the tail positions the zero padding vacated).  Everything is computed
in f32 (complex64 spectra) and cast back to the input dtype, so the bf16
path gets an f32-accumulated result like the MXU tiers.

Tier contract (ops/nc_fused_lane.py): shape-only opt-in — no per-layer
state, so the chooser consults :func:`fft_feasible` (an arithmetic gate
with a VPU-vs-MXU penalty on the spectral FLOPs, plus a spectrum-bytes
budget: the weight spectrum is ``C_in·C_out`` padded volumes and is the
known FFT-conv memory blowup at large spatial dims) and a real compile
probe (:func:`fft_compiles`, memory-ledger row).  Plain differentiable
XLA — any backend, any dtype.
"""

from __future__ import annotations

import functools
import math
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

# real-FFT cost model: ~``coeff·S·log2 S`` real FLOPs per S-cell 4-D
# transform (split-radix ballpark; a heuristic constant for the gate, not a
# measurement)
_FFT_COST_COEFF = 2.5
# spectral work runs on the VPU (complex mul/add) while the dense baseline
# rides the MXU at far higher FLOP throughput — penalize spectral FLOPs by
# this factor before comparing.  With it the k=3 NC arches keep the dense
# tiers and the k=5 arch clears the gate (the paper's crossover direction).
_FFT_VPU_PENALTY = 4.0
# weight-spectrum budget: Cin·Cout complex64 padded volumes must fit this
# many bytes or the tier is rejected (e.g. the 56M-cell InLoc volume's
# 16→16 layer would need ~59 GB).  Env-overridable for probes.
_FFT_TEMP_BUDGET = int(os.environ.get(
    "NCNET_FFT_TEMP_BUDGET", str(2 * 1024 ** 3)))

_SPATIAL_AXES = (1, 2, 3, 4)


def _rfft4(x: jnp.ndarray, sizes, axes) -> jnp.ndarray:
    """Real 4-D FFT as rfft(last axis) ∘ fftn(first three): XLA's FFT op
    tops out at 3 contiguous dims, so the fourth runs as its own pass —
    the transforms commute, the composition is the exact 4-D transform."""
    y = jnp.fft.rfft(x, n=sizes[3], axis=axes[3])
    return jnp.fft.fftn(y, s=sizes[:3], axes=axes[:3])


def _irfft4(y: jnp.ndarray, sizes, axes) -> jnp.ndarray:
    y = jnp.fft.ifftn(y, s=sizes[:3], axes=axes[:3])
    return jnp.fft.irfft(y, n=sizes[3], axis=axes[3])


def conv4d_fft(x: jnp.ndarray, weight: jnp.ndarray,
               bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """"Same" 4D cross-correlation + bias via the frequency domain.

    Args:
      x:      ``(B, hA, wA, hB, wB, C_in)`` channels-last volume.
      weight: ``(kA, kWA, kB, kWB, C_in, C_out)`` (odd taps).
      bias:   ``(C_out,)`` or None.
    Returns:
      ``(B, hA, wA, hB, wB, C_out)`` in ``x.dtype`` (f32 compute inside).
    """
    dtype = x.dtype
    spatial = tuple(x.shape[a] for a in _SPATIAL_AXES)
    taps = tuple(weight.shape[:4])
    assert all(k % 2 == 1 for k in taps), (
        f"conv4d_fft serves the same-pad odd-tap shape class, got {taps}")
    sizes = tuple(n + k - 1 for n, k in zip(spatial, taps))
    xf = _rfft4(x.astype(jnp.float32), sizes, _SPATIAL_AXES)
    wf = _rfft4(weight.astype(jnp.float32), sizes, (0, 1, 2, 3))
    # correlation theorem: FFT(x)·conj(FFT(w)), contracting C_in per bin
    yf = jnp.einsum("bpqrsc,pqrsco->bpqrso", xf, jnp.conj(wf))
    c = _irfft4(yf, sizes, _SPATIAL_AXES)
    # exact "same" crop: out[i] = c[(i − k//2) mod S] per dim — the wrapped
    # entries c[S−t] hold the left-edge rows (only zero-padding positions
    # contribute to their circular sum, see module docstring)
    c = jnp.roll(c, shift=tuple(k // 2 for k in taps), axis=_SPATIAL_AXES)
    out = c[:, :spatial[0], :spatial[1], :spatial[2], :spatial[3], :]
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)


def nc_stack_fft(nc_params: List[dict], x: jnp.ndarray) -> jnp.ndarray:
    """The full [conv4d_same + bias + ReLU]×N stack through
    :func:`conv4d_fft` — the "fft" tier's stack body."""
    for layer in nc_params:
        x = jax.nn.relu(conv4d_fft(x, layer["w"], layer["b"]))
    return x


# ---------------------------------------------------------------------------
# arithmetic gate + compile probe (the chooser's two checks)
# ---------------------------------------------------------------------------


def _fft_cost(cells: int) -> float:
    return _FFT_COST_COEFF * cells * math.log2(max(cells, 2))


def fft_layer_flops(spatial: Sequence[int], k: int, c_in: int,
                    c_out: int) -> float:
    """Predicted real FLOPs of one spectral layer: forward transforms of
    the C_in input channels, the weight's C_in·C_out transforms (recomputed
    per call — the weights are not spectrum-cached across steps), C_out
    inverse transforms, and the per-bin complex contraction (~8 real FLOPs
    per multiply-add over the Hermitian half-spectrum)."""
    padded = 1
    for n in spatial:
        padded *= n + k - 1
    transforms = (c_in + c_out + c_in * c_out) * _fft_cost(padded)
    multiply = 8.0 * (padded / 2) * c_in * c_out
    return transforms + multiply


def fft_spectrum_bytes(spatial: Sequence[int], kernels: Sequence[int],
                       channels: Sequence[int]) -> int:
    """Peak weight-spectrum footprint across the stack: ``C_in·C_out``
    complex64 half-spectra of the padded volume (the dominant FFT-conv
    temp at volume scale; activations are a C-fold smaller)."""
    peak = 0
    c_in = 1
    for k, c_out in zip(kernels, channels):
        padded = 1
        for n in spatial:
            padded *= n + k - 1
        peak = max(peak, int(c_in * c_out * (padded // 2 + 1) * 8))
        c_in = c_out
    return peak


def fft_feasible(ha: int, wa: int, hb: int, wb: int,
                 kernels: Sequence[int], channels: Sequence[int]) -> bool:
    """The FFT tier's arithmetic gate: odd kernels, the weight spectrum
    inside ``_FFT_TEMP_BUDGET``, and VPU-penalized spectral FLOPs beating
    the dense stack's direct-k⁴ FLOPs over the whole stack."""
    if any(k % 2 == 0 for k in kernels):
        return False
    spatial = (ha, wa, hb, wb)
    if fft_spectrum_bytes(spatial, kernels, channels) > _FFT_TEMP_BUDGET:
        return False
    from ncnet_tpu.ops.conv4d_cp import dense_layer_flops

    cells = ha * wa * hb * wb
    spectral = dense = 0.0
    c_in = 1
    for k, c_out in zip(kernels, channels):
        spectral += fft_layer_flops(spatial, k, c_in, c_out)
        dense += dense_layer_flops(cells, k, c_in, c_out)
        c_in = c_out
    return _FFT_VPU_PENALTY * spectral < dense


@functools.lru_cache(maxsize=16)
def fft_compiles(ha, wa, hb, wb, kernels, channels) -> bool:
    """Real-compile probe for the spectral stack (cached per shape class);
    records the tier's AOT memory analysis in the ledger like every other
    tier probe (ops/nc_fused_lane.py::_record_probe_memory)."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.float32)
        params = []
        c_in = 1
        for k, c_out in zip(kernels, channels):
            params.append({
                "w": jax.ShapeDtypeStruct(
                    (k,) * 4 + (c_in, c_out), jnp.float32),
                "b": jax.ShapeDtypeStruct((c_out,), jnp.float32),
            })
            c_in = c_out
        compiled = jax.jit(nc_stack_fft).lower(params, x).compile()
        from ncnet_tpu.ops.nc_fused_lane import _record_probe_memory

        _record_probe_memory("nc_fft_probe", "fft", ha, wa, hb, wb,
                             kernels, channels, compiled)
        return True
    except Exception:  # noqa: BLE001 — any compile failure demotes, never raises
        return False
