"""Sparse/gathered fine-level correlation + NC refinement (coarse-to-fine).

The fine half of the coarse-to-fine pipeline (selection: ``ops/sparse_topk``):
given per-coarse-source-cell candidate target neighbourhoods, evaluate and
FILTER correlation only on the gathered ``(source patch × candidate patch)``
tiles — fine-level FLOPs and bytes scale with ``k·patch⁴`` per coarse cell
instead of ``(hw)²``, which is what opens 2–4× feature resolution and
shrinks the serving bucket footprints (ROADMAP item 2).

Tile semantics (the whole module's contract):

  * one tile per ``(coarse source cell n, candidate c)``: the source side is
    the cell's ``patch×patch`` fine block (halo-expanded, origin-clamped —
    the sparse_topk coverage contract), the target side the candidate's;
  * the tile values are the exact dense correlation restricted to the tile
    (gathered features, same f32-accumulated inner product);
  * mutual-matching gating uses CROSS-TILE scatter-max vectors — the max
    over every *covered* cell of a source row / target column, exactly the
    dense ``ops.matching.mutual_matching`` formula with "max over all"
    relaxed to "max over covered" (equal whenever coverage contains the
    row/column maxima; exact at k = full coverage);
  * the NC stack runs on the tiles as a folded batch of small dense 4D
    volumes with zero padding at patch edges — conv support truncates at
    the halo boundary (the standard sparse-refinement approximation; exact
    for cells whose receptive field lies inside the patch);
  * filtered scores scatter back to a zero-initialized DENSE volume
    (:func:`ncnet_tpu.ops.matching.scatter_sparse_scores`, duplicates
    resolved by max) so every downstream consumer — ``extract_match_table``,
    the quality-signal extractor, the serving wire format, the InLoc .mat
    writers — runs UNCHANGED on a bitwise-compatible wire shape.

Kernel tiers (the ``choose_fused_stack`` discipline):

  * **XLA reference tier** (:func:`gather_tile_corr`): pure gathers + one
    einsum.  Always available; CPU tests and correctness never depend on
    Mosaic.
  * **Pallas gather-into-VMEM tier** (:func:`gather_tile_corr_pallas`): a
    scalar-prefetch grid kernel alongside ``nc_fused_lane.py`` — the
    candidate indices ride ahead of the grid as prefetched scalars and
    drive the BlockSpec index maps, so each grid step DMAs only the
    candidate's ``patch``-row bands of the target feature map into VMEM
    (a gather ring the pallas pipeline double-buffers) and contracts them
    against the resident source patch on the MXU.  Feasibility-gated,
    real-compile-probed, tier-cached; any failure falls back to the XLA
    tier.  The NC refinement of the gathered tiles then reuses the
    resident fused-lane kernel family (tiles are exactly its shape class,
    batch-folded), completing the Pallas path end to end.

The pipeline itself is a first-class named tier, ``"coarse2fine"``
(:func:`choose_match_pipeline`): demotable at runtime like
resident/perlayer (``ops.demote_fused_tier``), persisted across restarts
through the tier cache's negative entries, with dense as the fallback edge.
"""

from __future__ import annotations

import functools
import os as _os
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.ops.sparse_topk import (
    block_origins,
    candidate_origins,
    patch_side,
)

# VMEM working-set budget for the gather kernel (the nc_fused_lane rule)
_VMEM_BUDGET = 13 * 2 ** 20

# mutual-matching epsilon — MUST equal ops.matching.mutual_matching's so
# the k=full sparse path reproduces the dense gating bit-for-bit
_MM_EPS = 1e-5


class SparseTiles(NamedTuple):
    """Gathered correlation tiles plus their global fine-grid indexing.

    ``values``: ``(B, N, K, p, p, p, p)`` — tile (n, c) holds the raw (or
    filtered) correlation of source patch n against candidate patch (n, c);
    dims are (source rows, source cols, target rows, target cols).
    ``ia``/``ja``: ``(N, p)`` int32 — fine source row/col indices of patch
    n's rows/cols (static per shape: one source patch per coarse cell).
    ``ib``/``jb``: ``(B, N, K, p)`` int32 — fine target row/col indices of
    each candidate patch.
    """

    values: jnp.ndarray
    ia: jnp.ndarray
    ja: jnp.ndarray
    ib: jnp.ndarray
    jb: jnp.ndarray


def source_patch_index(ha: int, wa: int, factor: int,
                       patch: int) -> Tuple[np.ndarray, np.ndarray]:
    """Static ``(ia, ja)`` of :class:`SparseTiles`: per coarse source cell
    (row-major over the ``(ha/factor, wa/factor)`` coarse grid), the fine
    row/col indices of its halo-expanded patch."""
    oi = block_origins(ha // factor, factor, patch, ha)   # (Hc,)
    oj = block_origins(wa // factor, factor, patch, wa)   # (Wc,)
    rows = oi[:, None] + np.arange(patch)[None, :]        # (Hc, p)
    cols = oj[:, None] + np.arange(patch)[None, :]        # (Wc, p)
    hc, wc = len(oi), len(oj)
    ia = np.repeat(rows, wc, axis=0)                      # (Hc·Wc, p)
    ja = np.tile(cols, (hc, 1))                           # (Hc·Wc, p)
    return ia.astype(np.int32), ja.astype(np.int32)


def gather_source_patches(fa: jnp.ndarray, ia: np.ndarray,
                          ja: np.ndarray) -> jnp.ndarray:
    """``(B, N, p, p, C)`` source feature patches (XLA gather — the source
    side is a regular halo view; both tiers share it)."""
    return fa[:, ia[:, :, None], ja[:, None, :], :]


def gather_target_patches(fb: jnp.ndarray, ib: jnp.ndarray,
                          jb: jnp.ndarray) -> jnp.ndarray:
    """``(B, N, K, p, p, C)`` candidate feature patches (XLA gather tier)."""
    b = fb.shape[0]
    bidx = jnp.arange(b)[:, None, None, None, None]
    return fb[bidx, ib[..., :, None], jb[..., None, :], :]


def gather_tile_corr(fa: jnp.ndarray, fb: jnp.ndarray, tiles: SparseTiles,
                     accumulate_dtype=jnp.float32) -> jnp.ndarray:
    """XLA reference tier: tile correlation values ``(B, N, K, p, p, p, p)``
    — the dense ``correlation_4d`` inner product restricted to the gathered
    patches (same f32 MXU accumulation, cast back to the feature dtype)."""
    fa_p = gather_source_patches(fa, tiles.ia, tiles.ja)
    fb_p = gather_target_patches(fb, tiles.ib, tiles.jb)
    out = jnp.einsum(
        "bnijc,bnkpqc->bnkijpq", fa_p, fb_p,
        preferred_element_type=accumulate_dtype,
    )
    if accumulate_dtype is not None and fa.dtype != accumulate_dtype:
        out = out.astype(fa.dtype)
    return out


# ---------------------------------------------------------------------------
# Pallas gather-into-VMEM tier
#
# Grid (B, N, K); candidate band rows + column starts ride as PREFETCHED
# SCALARS so the target feature map's BlockSpec index maps can gather just
# the candidate's rows: the patch is ``patch = bands·factor`` rows tall and
# its clamped origin is a multiple of ``factor`` whenever the halo is
# (sparse_topk.candidate_origins), so ``bands`` stacked (factor, wB, C)
# row-band blocks cover it exactly — each grid step DMAs only those bands
# into VMEM (double-buffered by the pallas pipeline: the gather ring), lane-
# slices the patch columns at the prefetched start, and contracts the
# (p², C) source patch against the (p², C) gathered target patch on the MXU.
# ---------------------------------------------------------------------------


def _gather_corr_kernel(rband_ref, cstart_ref, fa_ref, *band_refs,
                        out_ref, patch, factor, c_dim):
    from jax.experimental import pallas as pl

    bi, ni, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    j0 = cstart_ref[bi, ni, ki]
    bands = [
        ref[0, :, pl.ds(j0, patch), :]            # (factor, patch, C)
        for ref in band_refs
    ]
    bt = jnp.concatenate(bands, axis=0)           # (patch, patch, C)
    bt = bt.reshape(patch * patch, c_dim)         # leading-dim collapse only
    a = fa_ref[0, 0]                              # (patch², C)
    y = jax.lax.dot_general(
        a, bt, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (p², p²)
    out_ref[0, 0, 0] = y.astype(out_ref.dtype)


def sparse_gather_feasible(hb: int, wb: int, c_dim: int, patch: int,
                           factor: int, halo: int,
                           itemsize: int = 2) -> bool:
    """Whether the gather kernel's per-step VMEM working set fits: the
    band blocks (double-buffered), the resident source patch, the f32 dot
    output — and the band-alignment precondition (halo a multiple of the
    factor, so candidate origins land on band boundaries)."""
    if halo % factor != 0 or patch % factor != 0:
        return False
    bands = patch // factor
    band_bytes = 2 * bands * factor * wb * c_dim * itemsize  # double-buffered
    a_bytes = 2 * patch * patch * c_dim * itemsize
    out_bytes = (patch * patch) ** 2 * (4 + itemsize)
    bt_bytes = patch * patch * c_dim * itemsize
    return band_bytes + a_bytes + out_bytes + bt_bytes <= _VMEM_BUDGET


def gather_tile_corr_pallas(
    fa_p2: jnp.ndarray, fb: jnp.ndarray,
    row_blocks: jnp.ndarray, col_starts: jnp.ndarray,
    *, patch: int, factor: int, interpret: bool = False,
) -> jnp.ndarray:
    """Pallas gather tier: ``(B, N, K, p², p²)`` tile correlations.

    Args:
      fa_p2: ``(B, N, p², C)`` source patches (pre-gathered, pre-reshaped —
        XLA's half of the layout work).
      fb: ``(B, hB, wB, C)`` full target feature map (stays in HBM; only
        candidate bands reach VMEM).
      row_blocks: ``(B, N, K)`` int32 — candidate patch origin row divided
        by ``factor`` (the band block index; the alignment precondition is
        ``sparse_gather_feasible``'s to check).
      col_starts: ``(B, N, K)`` int32 — candidate patch origin column.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, p2, c_dim = fa_p2.shape
    k = row_blocks.shape[2]
    bands = patch // factor
    kern = functools.partial(
        _kernel_entry, patch=patch, factor=factor, c_dim=c_dim)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n, k),
        in_specs=[
            pl.BlockSpec((1, 1, p2, c_dim),
                         lambda bi, ni, ki, rref, cref: (bi, ni, 0, 0)),
        ] + [
            pl.BlockSpec(
                (1, factor, fb.shape[2], c_dim),
                lambda bi, ni, ki, rref, cref, d=d: (
                    bi, rref[bi, ni, ki] + d, 0, 0),
            )
            for d in range(bands)
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 1, p2, p2),
            lambda bi, ni, ki, rref, cref: (bi, ni, ki, 0, 0)),
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, k, p2, p2), fa_p2.dtype),
        interpret=interpret,
    )(row_blocks, col_starts, fa_p2, *([fb] * bands))


def _kernel_entry(rband_ref, cstart_ref, fa_ref, *rest, patch, factor, c_dim):
    *band_refs, out_ref = rest
    _gather_corr_kernel(rband_ref, cstart_ref, fa_ref, *band_refs,
                        out_ref=out_ref, patch=patch, factor=factor,
                        c_dim=c_dim)


@functools.lru_cache(maxsize=8)
def sparse_gather_compiles(b, n, k, hb, wb, c_dim, patch, factor,
                           dtype_name: str) -> bool:
    """Real-compile probe for the gather kernel (per shape class, cached;
    consults/feeds the persistent tier cache) — Mosaic legality depends on
    concrete shapes, so the chooser verifies an actual compile and any
    failure keeps the XLA gather tier."""
    from ncnet_tpu.ops import tier_cache

    sig = (b, n, hb, wb, (k, patch), (factor, c_dim))
    hit = tier_cache.lookup("sparse_gather", sig)
    if hit is not None and hit[0] == "gather":
        return True
    try:
        dt = jnp.dtype(dtype_name)
        fa_p2 = jax.ShapeDtypeStruct((b, n, patch * patch, c_dim), dt)
        fb = jax.ShapeDtypeStruct((b, hb, wb, c_dim), dt)
        rb = jax.ShapeDtypeStruct((b, n, k), jnp.int32)
        cs = jax.ShapeDtypeStruct((b, n, k), jnp.int32)
        compiled = jax.jit(functools.partial(
            gather_tile_corr_pallas, patch=patch, factor=factor,
        )).lower(fa_p2, fb, rb, cs).compile()
        try:
            from ncnet_tpu.observability import memory as obs_memory

            obs_memory.record_program(
                "sparse_gather_probe",
                f"{b}x{n}x{k}|{hb}x{wb}x{c_dim}|p={patch}",
                analysis=compiled, tier="gather", source="tier_probe")
        except Exception:  # noqa: BLE001 — the ledger never fails a probe
            pass
        tier_cache.record("sparse_gather", sig, "gather")
        return True
    except Exception:
        return False


def _use_pallas_gather(b, n, k, hb, wb, c_dim, patch, factor, halo,
                       dtype) -> bool:
    if _os.environ.get("NCNET_SPARSE_GATHER", "").lower() in ("0", "off"):
        return False
    from ncnet_tpu.ops.conv4d import _pallas_available

    if not _pallas_available() or dtype != jnp.bfloat16:
        return False
    if not sparse_gather_feasible(hb, wb, c_dim, patch, factor, halo,
                                  itemsize=jnp.dtype(dtype).itemsize):
        return False
    return sparse_gather_compiles(b, n, k, hb, wb, c_dim, patch, factor,
                                  jnp.dtype(dtype).name)


# ---------------------------------------------------------------------------
# sparse mutual matching + refinement orchestration
# ---------------------------------------------------------------------------


def sparse_mutual_matching(t: SparseTiles, eps: float = _MM_EPS,
                           grid_a: Tuple[int, int] = None,
                           grid_b: Tuple[int, int] = None) -> SparseTiles:
    """Mutual-matching gating on the sparse structure.

    The dense formula (``ops.matching.mutual_matching``, same eps and
    parenthesization) with its "max over all A / all B cells" computed as
    scatter-max over every COVERED cell across tiles: a fine cell covered
    by several overlapping tiles contributes each tile's value, so the
    per-row/per-column vectors are exact over the covered support.  Equal
    to the dense gating whenever coverage contains the row/column maxima
    (always at k = full; on peak-dominated volumes whenever top-k covers
    the peaks)."""
    v = t.values
    b = v.shape[0]
    ha, wa = grid_a
    hb, wb = grid_b
    neg = jnp.asarray(-jnp.inf, v.dtype)
    # max over covered target cells per fine SOURCE cell (dense max_over_b)
    per_a = v.max(axis=(2, 5, 6))                          # (B, N, p, p)
    max_b = jnp.full((b, ha, wa), neg, v.dtype).at[
        :, t.ia[:, :, None], t.ja[:, None, :]].max(per_a)
    # max over covered source cells per fine TARGET cell (dense max_over_a)
    per_b = v.max(axis=(3, 4))                             # (B, N, K, p, p)
    bidx = jnp.arange(b)[:, None, None, None, None]
    max_a = jnp.full((b, hb, wb), neg, v.dtype).at[
        bidx, t.ib[..., :, None], t.jb[..., None, :]].max(per_b)
    g_b = max_b[:, t.ia[:, :, None], t.ja[:, None, :]]     # (B, N, p, p)
    g_a = max_a[bidx, t.ib[..., :, None], t.jb[..., None, :]]  # (B,N,K,p,p)
    ratio_b = v / (g_a[:, :, :, None, None, :, :] + eps)
    ratio_a = v / (g_b[:, :, None, :, :, None, None] + eps)
    return t._replace(values=v * (ratio_a * ratio_b))


def sparse_fine_corr(fa: jnp.ndarray, fb: jnp.ndarray, cand: jnp.ndarray,
                     *, factor: int, halo: int) -> SparseTiles:
    """Gathered raw fine correlation tiles for the candidate set.

    Dispatches the tile contraction to the Pallas gather tier when the
    shape class compiles (TPU, bf16, VMEM-feasible, band-aligned halo),
    else the XLA gather tier — correctness never depends on Mosaic."""
    b, ha, wa, c_dim = fa.shape
    hb, wb = fb.shape[1], fb.shape[2]
    patch = patch_side(factor, halo)
    wc = wb // factor
    ia, ja = source_patch_index(ha, wa, factor, patch)
    oi, oj = candidate_origins(cand, wc, factor, patch, hb, wb)
    rng = jnp.arange(patch, dtype=jnp.int32)
    ib = oi[..., None] + rng                               # (B, N, K, p)
    jb = oj[..., None] + rng
    tiles = SparseTiles(None, jnp.asarray(ia), jnp.asarray(ja), ib, jb)
    n, k = cand.shape[1], cand.shape[2]
    if _use_pallas_gather(b, n, k, hb, wb, c_dim, patch, factor, halo,
                          fa.dtype):
        fa_p2 = gather_source_patches(fa, ia, ja).reshape(
            b, n, patch * patch, c_dim)
        v = gather_tile_corr_pallas(
            fa_p2, fb, oi // factor, oj, patch=patch, factor=factor,
        ).reshape(b, n, k, patch, patch, patch, patch)
        return tiles._replace(values=v)
    return tiles._replace(values=gather_tile_corr(fa, fb, tiles))


def core_mask(tiles: SparseTiles, cand: jnp.ndarray, wc: int,
              wac: int, factor: int) -> jnp.ndarray:
    """``(B, N, K, p, p, p, p)``-broadcastable 0/1 mask of each tile's CORE
    — the coarse cell's own ``factor×factor`` fine block on both the source
    and the candidate side.  Core cells are the tile's READOUT: with
    ``halo ≥`` the stack's receptive radius their conv support lies inside
    the patch, so their filtered values equal the dense computation
    exactly; halo cells exist only to provide that support, and their
    truncated values must neither feed the post-filter mutual-matching
    maxima nor win the scatter against an exact duplicate from the cell's
    own home tile."""
    ic = cand // wc
    jc = cand % wc
    # source side: a patch position is core iff its global fine index
    # pools back to the patch's own coarse cell.  Source patches are
    # row-major over the (Hc, Wc) source coarse grid
    # (source_patch_index), so cell n decodes as (n // wac, n % wac).
    n = tiles.ia.shape[0]
    a_cell = jnp.arange(n)
    ra = (tiles.ia // factor) == (a_cell // wac)[:, None]        # (N, p)
    ca = (tiles.ja // factor) == (a_cell % wac)[:, None]         # (N, p)
    rb = (tiles.ib // factor) == ic[..., None]                   # (B,N,K,p)
    cb = (tiles.jb // factor) == jc[..., None]
    m = (
        ra[None, :, None, :, None, None, None]
        & ca[None, :, None, None, :, None, None]
        & rb[:, :, :, None, None, :, None]
        & cb[:, :, :, None, None, None, :]
    )
    return m


def sparse_refine(
    fa: jnp.ndarray, fb: jnp.ndarray, cand: jnp.ndarray, *,
    factor: int, halo: int,
    stack_fn: Callable[[jnp.ndarray], jnp.ndarray],
) -> jnp.ndarray:
    """The full sparse fine pass: gather → gate → NC-filter → core readout
    → gate → scatter back dense.

    ``stack_fn`` maps a scalar 4D volume batch ``(T, p, p, p, p)`` through
    the NC consensus stack (the caller closes over params/symmetric mode —
    ``models.ncnet.neigh_consensus``, whose own tier chooser routes the
    folded tiles through the resident Pallas kernel family where the shape
    class compiles).  Only each tile's CORE cells (:func:`core_mask`) are
    read out — their conv support is complete, so at full coverage the
    scattered volume reproduces the dense filter exactly (up to float
    reassociation); halo cells are support-only.  Returns the DENSE
    ``(B, hA, wA, hB, wB)`` volume with filtered scores scattered onto
    their fine cells (zeros elsewhere, duplicates resolved by max) —
    bitwise wire-compatible with the dense filter's output shape.
    """
    from ncnet_tpu.ops.matching import scatter_sparse_scores

    b, ha, wa, _ = fa.shape
    hb, wb = fb.shape[1], fb.shape[2]
    patch = patch_side(factor, halo)
    wc = wb // factor
    tiles = sparse_fine_corr(fa, fb, cand, factor=factor, halo=halo)
    tiles = sparse_mutual_matching(tiles, grid_a=(ha, wa), grid_b=(hb, wb))
    n, k = cand.shape[1], cand.shape[2]
    folded = tiles.values.reshape(b * n * k, patch, patch, patch, patch)
    filtered = stack_fn(folded).reshape(
        b, n, k, patch, patch, patch, patch)
    # core readout: zero the support-only halo cells (filtered values are
    # post-ReLU non-negative, so 0 is the identity for every max downstream)
    filtered = filtered * core_mask(tiles, cand, wc, wa // factor,
                                    factor).astype(
        filtered.dtype)
    tiles = sparse_mutual_matching(
        tiles._replace(values=filtered), grid_a=(ha, wa), grid_b=(hb, wb))
    return scatter_sparse_scores(
        tiles.values, tiles.ia, tiles.ja, tiles.ib, tiles.jb,
        (ha, wa, hb, wb))


# ---------------------------------------------------------------------------
# pipeline tier: "coarse2fine" as a first-class demotable tier
# ---------------------------------------------------------------------------


def coarse2fine_feasible(ha: int, wa: int, hb: int, wb: int, *,
                         sparse_topk: int, factor: int, halo: int,
                         reloc_k: int = 0) -> bool:
    """Whether the coarse-to-fine pipeline applies to this shape class:
    the knob is on, relocalization pooling is off (maxpool4d composes with
    the dense volume only — the sparse analog is future work), every fine
    dim pools by the factor, and the patches fit the fine grids."""
    if sparse_topk <= 0 or factor <= 1 or reloc_k > 1:
        return False
    if any(d % factor for d in (ha, wa, hb, wb)):
        return False
    patch = patch_side(factor, halo)
    return min(ha, wa) >= patch and min(hb, wb) >= patch


def choose_match_pipeline(ha: int, wa: int, hb: int, wb: int, *,
                          sparse_topk: int, factor: int, halo: int,
                          reloc_k: int = 0) -> Optional[str]:
    """The one authority for the match-pipeline tier at a shape class:
    ``"coarse2fine"`` (sparse pipeline) or ``None`` (dense — the fallback
    edge).  Demotions apply exactly like the fused-stack tiers': a runtime
    failure of the sparse path (``ops.demote_fused_tier("coarse2fine")``,
    or the ladder walk when it is the active pipeline) disables it for the
    process AND persists through the tier cache's negative entries, so a
    crashed sparse tier greets the next process already demoted.  Every
    consult stamps the decision for the quality layer's tier tagging
    (``observability/quality.active_tier``)."""
    from ncnet_tpu.ops import nc_fused_lane as _nfl
    from ncnet_tpu.ops import tier_cache

    tier = None
    if coarse2fine_feasible(ha, wa, hb, wb, sparse_topk=sparse_topk,
                            factor=factor, halo=halo, reloc_k=reloc_k):
        dead = (_nfl.demoted_fused_tiers()
                | tier_cache.persistent_demotions())
        if "coarse2fine" not in dead:
            tier = "coarse2fine"
    sig = (ha, wa, hb, wb, (factor,), (sparse_topk,))
    _nfl._emit_tier_selected("pipeline", sig, tier, none_label="dense")
    return tier


def tracking_feasible(ha: int, wa: int, hb: int, wb: int, *,
                      factor: int, halo: int, radius: int,
                      reloc_k: int = 0) -> bool:
    """Whether the tracked (coarse-pass-skipping) pipeline applies to this
    shape class: identical geometry constraints to
    :func:`coarse2fine_feasible` — the tracked fine pass runs the SAME
    gathered-tile refine, just with temporally-seeded candidates — except
    the selection knob is the search radius instead of ``sparse_topk``."""
    if radius < 0 or factor <= 1 or reloc_k > 1:
        return False
    if any(d % factor for d in (ha, wa, hb, wb)):
        return False
    patch = patch_side(factor, halo)
    return min(ha, wa) >= patch and min(hb, wb) >= patch


def choose_tracked_pipeline(ha: int, wa: int, hb: int, wb: int, *,
                            factor: int, halo: int, radius: int,
                            reloc_k: int = 0) -> Optional[str]:
    """Tier authority for the tracked pipeline at a shape class:
    ``"tracked"`` or ``None`` (fall back to whatever
    :func:`choose_match_pipeline` picks).  The tracked tier shares the
    sparse refine machinery with "coarse2fine", so a demotion of EITHER
    name disables tracking — a crashed sparse fine pass must not keep
    being re-entered through the streaming door.  Decisions are stamped
    like every other tier consult."""
    from ncnet_tpu.ops import nc_fused_lane as _nfl
    from ncnet_tpu.ops import tier_cache

    tier = None
    if tracking_feasible(ha, wa, hb, wb, factor=factor, halo=halo,
                         radius=radius, reloc_k=reloc_k):
        dead = (_nfl.demoted_fused_tiers()
                | tier_cache.persistent_demotions())
        if not dead & {"tracked", "coarse2fine"}:
            tier = "tracked"
    sig = (ha, wa, hb, wb, (factor,), (radius,))
    _nfl._emit_tier_selected("pipeline", sig, tier, none_label="dense")
    return tier
