"""Resident Pallas BACKWARD for the fused-lane NC stack (round 7).

PR 2's resident forward left training as the last hot path on the XLA
conv4d formulations: under ``value_and_grad`` the fused kernels had no AD
rule, so ``training/loss.py`` pinned ``nc_pallas=False`` and the backward
ran XLA's transposed convs — ~10× the ~6 forward-equivalents a pos+neg
weakly-supervised step should cost (ISSUE r7; *Fast Training of
Convolutional Networks through FFTs*: conv training time is
backward-dominated, so the backward needs its own kernel, not an autodiff
replay).  This module is that kernel set.

Design — a staged reverse chain of resident wavefront kernels
=============================================================

The backward of ``[conv4d_same + bias + ReLU]×L`` at layer ``l`` needs
three things per volume row:

  * the ReLU mask ``z_l > 0`` — RECOMPUTED in-kernel by replaying the
    forward wavefront (layers ``0..l`` in k-slot VMEM ring buffers, exactly
    PR 2's residency protocol); the forward saves only the input volume and
    the params, no activation ever touches HBM;
  * ``dW_l = Σ_cells x_l ⊗ gz_l`` and ``db_l = Σ gz_l`` where
    ``gz_l = Γ_l ⊙ (z_l > 0)`` — accumulated into RESIDENT f32 VMEM blocks
    (constant-index outputs revisited across the whole grid, batch
    included) by one MXU dot per row chunk: the B-side tap offsets of the
    forward become pure LANE SHIFTS of the masked cotangent (``Gext``), so
    dW contracts the full fused lane dim at forward-dot shape;
  * ``Γ_{l-1} = conv4dᵀ(gz_l)`` — algebraically a plain fused-lane conv
    with the taps flipped in all four dims and the channel roles swapped
    (``w2b[(p,q,o),(r,s,c)] = w[k-1-p,…,c,o]``), so the transpose conv runs
    the SAME row kernel as the forward at exact thin widths (the 16→1
    layer's dX contracts K = k², the 1→16 layer's emits N = k²).

One ``pallas_call`` per layer ("stage"), walked last→first; each stage's
wavefront delay is ``(l+1)·(k−1)/2`` rows.  Why stages rather than ONE
fused program: holding every layer's replay ring AND cotangent ring
resident simultaneously needs ~22 MB of VMEM at the PF-Pascal shape
(25⁴, k=5, 16 channels: four 16-channel k-slot rings alone are ~15.6 MB)
— over the ~16 MiB a v5e core has.  The staged chain caps the working set
at one layer's rings (~8–15 MB, ``_vjp_stage_vmem_bytes``) and bounds
inter-stage cotangent traffic to ONE write + ONE staged read per layer
boundary (rows staged via a single revolving BlockSpec, no k× refetch):
~50 MB/volume total against the XLA backward's ~0.7 GB/pair, with zero
activation traffic.

Numerics: bf16 operands, f32 dot accumulation, bf16 ring rows — the same
precision class as the forward kernels.  The ReLU mask is taken on the
bf16-rounded pre-activation (``(acc + bias) → bf16 > 0``), matching the
forward's stored activations, so mask decisions agree with what the
forward actually computed.

``choose_fused_vjp`` is the tier authority: ``'resident_vjp'`` gated by a
shape-class check + per-stage VMEM accounting + a real-compile probe, and
honoring the PR 3 runtime-demotion registry (``demote_fused_tier``) so a
mid-run device failure demotes the backward tier too; ``None`` falls back
to the XLA-replay backward in ``nc_stack_fused``'s VJP.  The test-only
``NCNET_FUSED_VJP_FORCE=interpret`` env knob forces the chain in Pallas
interpret mode on any backend (grad-parity tests, the SIGKILL-resume
proof); ``=off`` pins the XLA replay.
"""

from __future__ import annotations

import functools
import os as _os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ncnet_tpu.ops.nc_fused_lane import (
    _RES_JCH,
    _make_mask,
    _pack_weight,
    _resident_shape_class,
    _tap_reduce_conv,
    demoted_fused_tiers,
    fused_layout_in,
    fused_layout_out,
)

# VMEM pre-gate for one backward stage.  Deliberately the PHYSICAL ~16 MiB
# rather than the forward's conservative 13 MiB: Mosaic's VMEM allocation
# is static, so a stage that does not fit FAILS TO COMPILE and the
# real-compile probe (the authority, same discipline as
# fused_resident_compiles) demotes the tier — this accounting exists only
# to skip obviously doomed probe compiles, not to be the gate.  The
# flagship PF-Pascal stage 1 accounts to ~15.7 MiB (three 16-channel
# structures resident at once: the y₀ replay ring, the gz ring, and the
# 400×400 dW accumulator + staging); whether v5e's Mosaic actually places
# it is exactly what tools/nc_vjp_resident_probe.py records next
# TPU-attached session.
_VJP_VMEM_BUDGET = 16 * 2 ** 20


def _flip_pack(w, k, c_in, c_out):
    """Pack the TRANSPOSE-conv weight: all four tap dims flipped, channel
    roles swapped — ``w2b[(p,q,o),(r,s,c)] = w[k-1-p,k-1-q,k-1-r,k-1-s,c,o]``
    — so ``conv4dᵀ(gz, w) == fused_lane_conv(gz, w2b)`` exactly."""
    wt = jnp.transpose(w[::-1, ::-1, ::-1, ::-1], (0, 1, 2, 3, 5, 4))
    return _pack_weight(wt, k, c_out, c_in, pad=False)


def _unpack_weight_grad(dw2, k, c_in, c_out):
    """Inverse of ``_pack_weight(pad=False)``: ``(k²·ci, k²·co)`` →
    ``(k, k, k, k, ci, co)``."""
    return jnp.transpose(
        dw2.reshape(k, k, c_in, k, k, c_out), (0, 1, 3, 4, 2, 5)
    )


def _lane_shift(x, off, kl):
    """``y[:, m] = x[:, m - off]`` with zero fill (a pure lane pad+slice —
    the Mosaic-legal primitive the whole fused-lane design rides on)."""
    if off == 0:
        return x
    if off > 0:
        return jnp.pad(x, ((0, 0), (off, 0)))[:, :kl]
    return jnp.pad(x, ((0, 0), (0, -off)))[:, -off:]


def cotangent_layout_in(g: jnp.ndarray, k: int) -> jnp.ndarray:
    """Inverse of :func:`fused_layout_out` for the incoming cotangent:
    ``(B, hA, wA, hB, wB, C)`` → ``(B, hA, wA, C, (hB+h)(wB+h))`` bf16 with
    zeroed halo lanes (one cheap pad of the thin top cotangent)."""
    b, ha, wa, hb, wb, c = g.shape
    d = (k - 1) // 2
    g = jnp.moveaxis(g, 5, 3)
    g = jnp.pad(g, ((0, 0),) * 4 + ((d, d), (d, d)))
    return g.reshape(b, ha, wa, c, -1).astype(jnp.bfloat16)


# ---------------------------------------------------------------------------
# the stage kernel: backward through ONE layer, forward replay in-kernel
# ---------------------------------------------------------------------------


def _vjp_stage_kernel(*refs, l, k, chans, co_out, s_i, s_j, sp_j, kl, sp_l,
                      je_list):
    """One wavefront step of backward stage ``l``.

    Lanes (d = (k−1)/2):
      * replay lane ``j < l``: forward layer j emits row ``ii − j·d`` into
        its k-slot ring (PR 2's protocol verbatim: bottom-halo priming,
        top-halo zero rows, j-halo rewrites);
      * gz lane: at row ``r = ii − l·d`` recompute ``z_l`` from the replay
        rings (layer 0 reads the staged input rows), mask the staged
        ``Γ_l`` row with ``bf16(z) > 0``, write ``gz_l`` into its ring, and
        accumulate ``dW_l``/``db_l`` into the resident f32 output blocks —
        the A operand of the z dot is REUSED as the dW contraction operand;
      * Γ lane: at row ``r = ii − (l+1)·d`` emit ``Γ_{l-1}`` (stage 0: dX)
        = the fused-lane conv of the gz ring against the flipped/transposed
        weight pack — no bias, no ReLU.

    refs = (x_0..x_{k-1}, Γ_l, w2f_0, b_0, …, w2f_l, b_l, w2b, mask,
            out_Γ, dW, db, ring_y_0..ring_y_{l-1}, ring_gz):
      x_p:    (1, 1, sp_j, 1, kl) halo-padded input row ii+p (clamped).
      Γ_l:    (1, 1, s_j, co_l, kl) staged cotangent row ii − l·d (clamped;
              fetched ONCE per row — no k× refetch).
      out_Γ:  (1, 1, s_j, co_out, kl) row ii − (l+1)·d.
      dW:     (k²·ci_l, k²·co_l) f32; db: (1, co_l, kl) f32 — constant-index
              blocks, resident across the whole grid (batch included),
              zeroed at the first step.
      ring_*: (k, sp_j, c, kl) bf16 scratch.
    """
    from jax import lax
    from jax.experimental import pallas as pl

    h = k - 1
    d = h // 2
    x_refs = refs[:k]
    g_ref = refs[k]
    wfb = refs[k + 1:k + 1 + 2 * (l + 1)]
    w2b_ref = refs[k + 1 + 2 * (l + 1)]
    m_ref = refs[k + 2 + 2 * (l + 1)]
    out_ref, dw_ref, db_ref = refs[k + 3 + 2 * (l + 1):k + 6 + 2 * (l + 1)]
    rings = refs[k + 6 + 2 * (l + 1):]
    y_rings, gz_ring = rings[:-1], rings[-1]

    bi = pl.program_id(0)
    ii = pl.program_id(1)
    n_lane = kl - sp_l * h - h
    pad_lo = d * sp_l + d
    mask = m_ref[:].astype(jnp.float32)
    ci_l, co_l = chans[l]

    def slot(r):
        return lax.rem(r + k, k)  # r ≥ −d > −k keeps rem ≥ 0

    def zero_row(ring_ref, r, c):
        ring_ref[pl.ds(slot(r), 1)] = jnp.zeros(
            (1, sp_j, c, kl), ring_ref.dtype)

    if d:
        @pl.when(ii == 0)
        def _prime():
            for ring, (_, co) in zip(y_rings, chans[:l]):
                for r in range(-d, 0):
                    zero_row(ring, r, co)
            for r in range(-d, 0):
                zero_row(gz_ring, r, co_l)

    @pl.when((ii == 0) & (bi == 0))
    def _init_accumulators():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    def ring_halo_zero(ring_ref, r, c):
        # j-halo columns re-zeroed on every slot write (the slot's previous
        # occupant — possibly the previous batch item's row, or raw scratch
        # garbage on the very first pass — is overwritten)
        if d:
            ring_ref[pl.ds(slot(r), 1), :d] = jnp.zeros(
                (1, d, c, kl), ring_ref.dtype)
            ring_ref[pl.ds(slot(r), 1), d + s_j:] = jnp.zeros(
                (1, sp_j - d - s_j, c, kl), ring_ref.dtype)

    def x_slabs(j0, je):
        return [
            x_refs[p][0, 0, j0 + q:j0 + q + je, :, :]
            for p in range(k) for q in range(k)
        ]

    def ring_slabs(ring_ref, slots, j0, je):
        return [
            ring_ref[pl.ds(slots[p], 1), j0 + q:j0 + q + je][0]
            for p in range(k) for q in range(k)
        ]

    def replay_row(j, r):
        """Forward layer ``j`` (ReLU'd, ring-resident) — PR 2's compute."""
        c_in, c_out = chans[j]
        w = wfb[2 * j][:]
        bias = wfb[2 * j + 1][:].astype(jnp.float32)
        if j > 0:
            slots = [slot(r - d + p) for p in range(k)]
        ring_halo_zero(y_rings[j], r, c_out)
        for j0, je in je_list:
            slabs = (x_slabs(j0, je) if j == 0
                     else ring_slabs(y_rings[j - 1], slots, j0, je))
            acc, _ = _tap_reduce_conv(
                slabs, w, je=je, c_out=c_out, k=k, sp_l=sp_l, n_lane=n_lane)
            acc = jnp.maximum(acc + bias, 0.0)
            full = jnp.pad(
                acc, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane))
            ) * mask
            y_rings[j][pl.ds(slot(r), 1), d + j0:d + j0 + je] = (
                full[None].astype(y_rings[j].dtype))

    def gz_row(r):
        """Recompute ``z_l`` row ``r``, mask the staged cotangent, ring the
        result, and fold the row into the resident dW/db accumulators."""
        w = wfb[2 * l][:]
        bias = wfb[2 * l + 1][:].astype(jnp.float32)
        if l > 0:
            slots = [slot(r - d + p) for p in range(k)]
        ring_halo_zero(gz_ring, r, co_l)
        for j0, je in je_list:
            slabs = (x_slabs(j0, je) if l == 0
                     else ring_slabs(y_rings[l - 1], slots, j0, je))
            acc, a3 = _tap_reduce_conv(
                slabs, w, je=je, c_out=co_l, k=k, sp_l=sp_l, n_lane=n_lane)
            # mask on the bf16-ROUNDED pre-activation: the forward stores
            # bf16 rows, so a z that rounds to bf16 zero was a dead cell in
            # the forward this backward must agree with
            keep = (acc + bias).astype(jnp.bfloat16) > 0
            gval = g_ref[0, 0, j0:j0 + je, :, pad_lo:pad_lo + n_lane]
            gz = jnp.where(keep, gval.astype(jnp.float32), 0.0)
            full = jnp.pad(
                gz, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane)))
            gz_bf = full.astype(jnp.bfloat16)
            gz_ring[pl.ds(slot(r), 1), d + j0:d + j0 + je] = gz_bf[None]
            db_ref[:] = db_ref[:] + jnp.sum(full, axis=0)[None]
            # dW: the forward's B-side tap offsets become lane shifts of the
            # masked cotangent; one full-lane-depth dot per output column
            # reuses the z dot's A operand
            for j in range(je):
                gext = jnp.concatenate(
                    [_lane_shift(gz_bf[j], (rr - d) * sp_l + (ss - d), kl)
                     for rr in range(k) for ss in range(k)],
                    axis=0,
                )  # (k²·co_l, kl), rows ordered (r, s, o)
                dw_ref[:] = dw_ref[:] + jax.lax.dot_general(
                    a3[j], gext, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

    def out_row(r):
        """``Γ_{l-1}`` (stage 0: dX) row ``r``: the transpose conv as a
        plain fused-lane conv of the gz ring — no bias, no ReLU."""
        w2b = w2b_ref[:]
        slots = [slot(r - d + p) for p in range(k)]
        for j0, je in je_list:
            slabs = ring_slabs(gz_ring, slots, j0, je)
            acc, _ = _tap_reduce_conv(
                slabs, w2b, je=je, c_out=co_out, k=k, sp_l=sp_l,
                n_lane=n_lane)
            # the valid-support window is CONTIGUOUS and so includes the
            # inter-row halo columns of the fused frame; the next stage's
            # gz slice reads them back, so they must be zeroed here (the
            # invariant every Γ array carries: halo lanes are zero)
            full = jnp.pad(
                acc, ((0, 0), (0, 0), (pad_lo, kl - pad_lo - n_lane))
            ) * mask
            out_ref[0, 0, j0:j0 + je] = full.astype(out_ref.dtype)

    for j in range(l):
        r = ii - j * d if d else ii

        @pl.when((r >= 0) & (r < s_i))
        def _(j=j, r=r):
            replay_row(j, r)

        if d:
            @pl.when((r >= s_i) & (r < s_i + d))
            def _(j=j, r=r):
                zero_row(y_rings[j], r, chans[j][1])

    r = ii - l * d if d else ii
    if d:
        @pl.when((r >= 0) & (r < s_i))
        def _(r=r):
            gz_row(r)

        @pl.when((r >= s_i) & (r < s_i + d))
        def _(r=r):
            zero_row(gz_ring, r, co_l)

        r2 = ii - l * d - d

        @pl.when((r2 >= 0) & (r2 < s_i))
        def _(r2=r2):
            out_row(r2)
    else:
        gz_row(r)
        out_row(r)


# ---------------------------------------------------------------------------
# VMEM accounting + host-side stage driver
# ---------------------------------------------------------------------------


def _stage_chans(kernels, channels, l) -> Tuple[Tuple[int, int], ...]:
    return tuple(zip((1,) + tuple(channels), channels))[:l + 1]


def _vjp_stage_vmem_bytes(l, wa, hb, wb, kernels, channels, je) -> int:
    """Worst-step VMEM working set of backward stage ``l`` (bytes)."""
    k = kernels[0]
    h = k - 1
    sp_j = wa + h
    sp_l = wb + h
    kl = (hb + h) * sp_l
    n_lane = kl - sp_l * h - h
    chans = _stage_chans(kernels, channels, l)
    ci_l, co_l = chans[l]
    rings = sum(k * sp_j * co * kl * 2 for _, co in chans[:l]) \
        + k * sp_j * co_l * kl * 2
    weights = sum((k * k * ci) * (k * k * co) * 2 for ci, co in chans) \
        + (k * k * co_l) * (k * k * ci_l) * 2
    accs = (k * k * ci_l) * (k * k * co_l) * 4 + co_l * kl * 4
    inputs = 2 * k * sp_j * 1 * kl * 2 + 2 * wa * co_l * kl * 2
    out = 2 * wa * ci_l * kl * 2
    temps = max(
        je * k * k * ci * kl * 2                 # a3 build
        + k * k * co * kl * 4                    # one f32 dot output
        + je * k * k * co * kl * 2               # bf16 ybuf
        + je * co * n_lane * 4                   # f32 accumulator
        + je * co * kl * 4                       # padded row chunk
        for ci, co in chans + ((co_l, ci_l),)  # + the Γ lane's dot
    ) + k * k * co_l * kl * 2 \
        + (k * k * ci_l) * (k * k * co_l) * 4    # Gext + the dW dot output
    return rings + weights + accs + inputs + out + temps


def _vjp_stage_je(l, ha, wa, hb, wb, kernels, channels) -> int:
    for je in _RES_JCH:
        je = min(je, wa)
        if _vjp_stage_vmem_bytes(l, wa, hb, wb, kernels, channels, je) \
                <= _VJP_VMEM_BUDGET:
            return je
    return 0


def fused_vjp_feasible(ha, wa, hb, wb, kernels, channels) -> bool:
    """Whether the staged resident backward fits this shape class: the
    resident forward's shape class (cubic odd uniform kernels, thin final
    layer) and EVERY stage's working set inside the budget at some j-chunk
    size."""
    if not _resident_shape_class(tuple(kernels), tuple(channels)):
        return False
    return all(
        _vjp_stage_je(l, ha, wa, hb, wb, kernels, channels) > 0
        for l in range(len(kernels))
    )


@functools.lru_cache(maxsize=8)
def fused_vjp_compiles(ha, wa, hb, wb, kernels, channels) -> bool:
    """Real-compile probe of the whole staged backward chain (cached per
    shape class) — the authority over the VMEM pre-gate: Mosaic's static
    VMEM allocation and lowering legality both surface as compile failures,
    and any failure falls back to the XLA-replay backward."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.bfloat16)
        g = jax.ShapeDtypeStruct(
            (1, ha, wa, hb, wb, channels[-1]), jnp.bfloat16)
        ws, bs = [], []
        c_in = 1
        for kk, c_out in zip(kernels, channels):
            ws.append(jax.ShapeDtypeStruct(
                (kk,) * 4 + (c_in, c_out), jnp.bfloat16))
            bs.append(jax.ShapeDtypeStruct((c_out,), jnp.bfloat16))
            c_in = c_out

        def run(x, g, ws, bs):
            params = [{"w": w, "b": b} for w, b in zip(ws, bs)]
            return nc_stack_fused_vjp(params, x, g)

        compiled = jax.jit(run).lower(x, g, ws, bs).compile()
        from ncnet_tpu.ops.nc_fused_lane import _record_probe_memory

        _record_probe_memory("nc_vjp_probe", "resident_vjp",
                             ha, wa, hb, wb, kernels, channels, compiled)
        return True
    except Exception:
        return False


def choose_fused_vjp(ha, wa, hb, wb, kernels, channels) -> Optional[str]:
    """The one authority for the training-backward tier at a shape class:
    ``'resident_vjp'`` (the staged Pallas chain), ``'interpret'`` (test-only
    force), or ``None`` (XLA-replay backward).  Mirrors
    ``choose_fused_stack``'s discipline — real TPU backend, green compile
    probe, no runtime demotion (``demote_fused_tier('resident_vjp')`` after
    a mid-run device failure sends every later trace back to XLA) — plus
    the round-9 persistent tier cache: a warm process replays a previous
    process's probed decision (the cheap VMEM/shape gate still runs) and
    skips the whole-chain compile probe; ``NCNET_FUSED_VJP_FORCE`` paths
    bypass the cache in both directions (a forced decision is not a probe
    result and must not poison real runs)."""
    from ncnet_tpu.ops.nc_fused_lane import _emit_tier_selected

    kernels, channels = tuple(kernels), tuple(channels)
    tier, cached = _choose_fused_vjp(ha, wa, hb, wb, kernels, channels)
    _emit_tier_selected(
        "backward", (ha, wa, hb, wb, kernels, channels), tier, cached=cached)
    return tier


def _choose_fused_vjp(ha, wa, hb, wb, kernels, channels):
    """Returns ``(tier, from_cache)``."""
    force = _os.environ.get("NCNET_FUSED_VJP_FORCE", "")
    if force == "interpret":
        # still honor the shape/VMEM gate: the knob forces the BACKEND
        # (interpret mode on any device), not an infeasible shape — which
        # must keep degrading to the XLA-replay backward, not trip the
        # kernel's trace-time asserts
        if fused_vjp_feasible(ha, wa, hb, wb, kernels, channels):
            return "interpret", False
        return None, False
    if force == "off":
        return None, False
    from ncnet_tpu.ops.conv4d import _pallas_available

    if not _pallas_available():
        return None, False
    from ncnet_tpu.ops import tier_cache

    demoted = demoted_fused_tiers() | tier_cache.persistent_demotions()
    if "resident_vjp" in demoted:
        return None, False
    sig = (ha, wa, hb, wb, kernels, channels)
    hit = tier_cache.lookup("backward", sig)
    # a cached None (XLA) is a miss, not a hit: the probe failure behind it
    # may have been transient and must not pin the shape to XLA forever
    if hit is not None and hit[0] == "resident_vjp" \
            and fused_vjp_feasible(ha, wa, hb, wb, kernels, channels):
        return hit[0], True
    if fused_vjp_feasible(ha, wa, hb, wb, kernels, channels) \
            and fused_vjp_compiles(ha, wa, hb, wb, kernels, channels):
        tier = "resident_vjp"
        tier_cache.record("backward", sig, tier)
    else:
        tier = None
    return tier, False


def _vjp_stage(l, nc_params, xp, gamma, *, ha, wa, hb, wb, interpret):
    """Backward stage ``l`` as one ``pallas_call``: returns
    ``(Γ_{l-1} (B, hA, wA, ci_l, kl), dW2 f32, db_partial f32)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = xp.shape[0]
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    channels = tuple(layer["w"].shape[5] for layer in nc_params)
    k = kernels[0]
    h = k - 1
    d = h // 2
    sp_l = wb + h
    kl = (hb + h) * sp_l
    sp_j = wa + h
    sp_i = ha + h
    chans = _stage_chans(kernels, channels, l)
    ci_l, co_l = chans[l]
    je = _vjp_stage_je(l, ha, wa, hb, wb, kernels, channels)
    assert je > 0, "vjp stage infeasible; gate with fused_vjp_feasible"
    je_list = tuple((j0, min(je, wa - j0)) for j0 in range(0, wa, je))
    mask = jnp.asarray(_make_mask((hb, wb), k), jnp.bfloat16)

    ops = [xp] * k + [gamma]
    for (ci, co), layer in zip(chans, nc_params):
        ops.append(_pack_weight(
            layer["w"].astype(jnp.bfloat16), k, ci, co, pad=False))
        ops.append(layer["b"].astype(jnp.bfloat16).reshape(1, co, 1))
    ops.append(_flip_pack(
        nc_params[l]["w"].astype(jnp.bfloat16), k, ci_l, co_l))
    ops.append(mask)

    kern = functools.partial(
        _vjp_stage_kernel, l=l, k=k, chans=chans, co_out=ci_l, s_i=ha,
        s_j=wa, sp_j=sp_j, kl=kl, sp_l=sp_l, je_list=je_list,
    )
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, sp_j, 1, kl),
        lambda bi, ii, p=p: (bi, jnp.minimum(ii + p, sp_i - 1), 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    g_spec = pl.BlockSpec(
        (1, 1, wa, co_l, kl),
        lambda bi, ii: (bi, jnp.clip(ii - l * d, 0, ha - 1), 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    full_spec = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    delay = (l + 1) * d
    out_gamma, dw2, db = pl.pallas_call(
        kern,
        grid=(b, ha + delay),
        in_specs=[row_spec(p) for p in range(k)] + [g_spec]
        + [full_spec() for _ in range(2 * (l + 1) + 2)],
        out_specs=[
            pl.BlockSpec(
                (1, 1, wa, ci_l, kl),
                lambda bi, ii: (bi, jnp.clip(ii - delay, 0, ha - 1), 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (k * k * ci_l, k * k * co_l), lambda bi, ii: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, co_l, kl), lambda bi, ii: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, ha, wa, ci_l, kl), jnp.bfloat16),
            jax.ShapeDtypeStruct((k * k * ci_l, k * k * co_l), jnp.float32),
            jax.ShapeDtypeStruct((1, co_l, kl), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, sp_j, co, kl), jnp.bfloat16)
            for _, co in chans[:l]
        ] + [pltpu.VMEM((k, sp_j, co_l, kl), jnp.bfloat16)],
        interpret=interpret,
    )(*ops)
    return out_gamma, dw2, db


def nc_stack_fused_vjp(
    nc_params: List[dict], x: jnp.ndarray, g: jnp.ndarray,
    interpret: bool = False,
) -> Tuple[List[dict], jnp.ndarray]:
    """The full stack VJP: ``(d_nc_params, dx)`` for cotangent ``g`` of
    ``nc_stack_fused(nc_params, x)`` — the resident staged Pallas chain.

    Matches ``jax.vjp`` of the equivalent XLA stack up to bf16 accumulation
    order (the grad-parity suite in tests/test_nc_vjp.py locks every shape
    class).  Only ``(nc_params, x)`` are consumed: activations and masks
    are recomputed in-kernel.
    """
    b, ha, wa, hb, wb, _ = x.shape
    assert x.shape[-1] == 1 and nc_params[0]["w"].shape[4] == 1, (
        "nc_stack_fused_vjp requires a 1-channel input volume and first "
        "layer (the NC-stack shape class)"
    )
    kernels = tuple(layer["w"].shape[0] for layer in nc_params)
    k = kernels[0]
    xp = fused_layout_in(x, k)
    gamma = cotangent_layout_in(g.astype(jnp.bfloat16), k)
    d_params: List[Optional[dict]] = [None] * len(nc_params)
    for l in reversed(range(len(nc_params))):
        gamma, dw2, dbp = _vjp_stage(
            l, nc_params, xp, gamma, ha=ha, wa=wa, hb=hb, wb=wb,
            interpret=interpret,
        )
        ci, co = _stage_chans(kernels,
                              tuple(p["w"].shape[5] for p in nc_params), l)[l]
        d_params[l] = {
            "w": _unpack_weight_grad(dw2, k, ci, co).astype(
                nc_params[l]["w"].dtype),
            # halo lanes and j-halo columns of gz are zero by construction,
            # so the lane sum counts each valid cell exactly once
            "b": jnp.sum(dbp, axis=(0, 2)).astype(nc_params[l]["b"].dtype),
        }
    dx = fused_layout_out(gamma, hb, wb, k).astype(x.dtype)
    return d_params, dx
