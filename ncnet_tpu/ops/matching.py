"""Mutual matching, match extraction, and point-transfer transforms.

Everything here is pure ``jnp`` — reshapes, reductions and gathers — and is
therefore trivially jittable and shardable.  Reference semantics being matched:
  * MutualMatching         /root/reference/lib/model.py:155-175
  * corr_to_matches        /root/reference/lib/point_tnf.py:12-80
  * nearest/bilinear tnf   /root/reference/lib/point_tnf.py:82-148
  * axis (un)normalization /root/reference/lib/point_tnf.py:6-10,151-167
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp


def mutual_matching(corr: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Soft mutual-nearest-neighbour gating of the 4D volume.

    ``corr * (corr / (max_over_Bdims + eps)) * (corr / (max_over_Adims + eps))``
    with the reference's eps=1e-5 and its symmetry-preserving parenthesization
    (model.py:166-173).

    Args:
      corr: ``(B, hA, wA, hB, wB)``.
    """
    max_over_a = jnp.max(corr, axis=(1, 2), keepdims=True)  # best A for each B cell
    max_over_b = jnp.max(corr, axis=(3, 4), keepdims=True)  # best B for each A cell
    ratio_b = corr / (max_over_a + eps)
    ratio_a = corr / (max_over_b + eps)
    return corr * (ratio_a * ratio_b)


def mutual_argmax_agreement(corr: jnp.ndarray) -> jnp.ndarray:
    """Hard mutual-nearest-neighbour agreement ratio per pair.

    The HARD twin of :func:`mutual_matching`'s soft gating: for each B cell
    take its argmax A cell, then ask whether that A cell's own argmax points
    back.  The returned ``(B,)`` fraction of B cells in a mutual-argmax
    cycle is a label-free match-confidence signal — 1.0 for a volume whose
    matches form a bijection (e.g. a delta-peaked/identity volume), near
    ``1/(hB·wB)`` for an uninformative one (ties all collapse onto argmax's
    first index).  Pure reductions/gathers: jits and shards freely, so the
    quality-observability layer fuses it into the eval fetch.

    Args:
      corr: ``(B, hA, wA, hB, wB)``.
    """
    b, ha, wa, hb, wb = corr.shape
    flat = corr.reshape(b, ha * wa, hb * wb)
    best_a = jnp.argmax(flat, axis=1)   # (B, n_b): best A cell per B cell
    best_b = jnp.argmax(flat, axis=2)   # (B, n_a): best B cell per A cell
    back = jnp.take_along_axis(best_b, best_a, axis=1)  # (B, n_b)
    agree = back == jnp.arange(hb * wb)[None, :]
    return jnp.mean(agree.astype(jnp.float32), axis=1)


def scatter_sparse_scores(
    values: jnp.ndarray,
    ia: jnp.ndarray,
    ja: jnp.ndarray,
    ib: jnp.ndarray,
    jb: jnp.ndarray,
    shape: tuple,
) -> jnp.ndarray:
    """Scatter sparse tile scores back onto the dense volume shape.

    The sparse-aware half of match extraction (coarse-to-fine pipeline,
    ``ops/sparse_corr.py``): filtered tile values land on their global fine
    cells in a ZERO-initialized ``(B, hA, wA, hB, wB)`` volume, so every
    dense consumer — :func:`corr_to_matches`, ``extract_match_table``, the
    quality-signal extractor, the serving wire tables — runs unchanged on a
    bitwise-compatible wire shape.  Semantics:

      * uncovered cells stay 0 — the filtered volume is non-negative (every
        NC layer ReLUs), so a zero background reproduces the dense
        argmax/score behavior wherever coverage contains the per-row maxima
        (an all-zero column argmaxes to index 0 with score 0, exactly like
        a dense volume that is zero there);
      * cells covered by several overlapping tiles resolve by max — patch
        halos overlap by construction, and near-edge tiles recompute the
        same cell with more or less truncated conv support; max keeps the
        best-supported estimate and is deterministic regardless of tile
        order.

    The scatter targets the volume reshaped to ``(B, hA·wA, hB·wB)`` through
    TWO linearized int32 indices (source cell ``ia·wA+ja``, target cell
    ``ib·wB+jb``) — two index arrays the size of ``values`` instead of four,
    keeping the scatter's temp footprint a small multiple of the sparse
    cell count (the memory claim the ledger gates,
    ``mem_filter_temp_bytes_sparse``).  Each HALF of the split stays far
    inside int32 at any resolution (hw < 2³¹ per side), where a single
    fully-linearized index would silently wrap above 2³¹ cells — already
    reached at ~3× InLoc feature resolution, exactly the workloads the
    sparse path exists for (jit-mode scatter drops or misplaces wrapped
    indices without erroring).

    Args:
      values: ``(B, N, K, p, p, p, p)`` tile scores (dims: source rows/cols,
        target rows/cols).
      ia, ja: ``(N, p)`` int32 fine source row/col indices per source patch.
      ib, jb: ``(B, N, K, p)`` int32 fine target row/col indices.
      shape: ``(hA, wA, hB, wB)`` dense fine-grid dims.
    """
    b = values.shape[0]
    ha, wa, hb, wb = (int(d) for d in shape)
    lin_a = (ia[None, :, None, :, None, None, None].astype(jnp.int32) * wa
             + ja[None, :, None, None, :, None, None])
    lin_b = (ib[:, :, :, None, None, :, None] * wb
             + jb[:, :, :, None, None, None, :])
    lin_a = jnp.broadcast_to(lin_a, values.shape).reshape(b, -1)
    lin_b = jnp.broadcast_to(lin_b, values.shape).reshape(b, -1)
    flat = jnp.zeros((b, ha * wa, hb * wb), values.dtype)
    flat = flat.at[jnp.arange(b)[:, None], lin_a, lin_b].max(
        values.reshape(b, -1))
    return flat.reshape(b, ha, wa, hb, wb)


def normalize_axis(x, length):
    """Pixel coord (1-indexed convention) → [-1, 1] (point_tnf.py:6-7)."""
    return (x - 1 - (length - 1) / 2) * 2 / (length - 1)


def unnormalize_axis(x, length):
    """[-1, 1] → pixel coord (1-indexed convention) (point_tnf.py:9-10)."""
    return x * (length - 1) / 2 + 1 + (length - 1) / 2


class Matches(NamedTuple):
    """Dense matches extracted from a corr volume; all fields ``(B, N)``."""

    xA: jnp.ndarray
    yA: jnp.ndarray
    xB: jnp.ndarray
    yB: jnp.ndarray
    score: jnp.ndarray


def corr_to_matches(
    corr: jnp.ndarray,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = False,
    scale: str = "centered",
    invert_matching_direction: bool = False,
    return_indices: bool = False,
):
    """Read hard matches + scores out of the (filtered) 4D volume.

    Args:
      corr: ``(B, hA, wA, hB, wB)``.
      delta4d: optional relocalization offsets from
        :func:`ncnet_tpu.ops.pooling.maxpool4d_with_argmax`; when given, match
        coordinates live on the ``k_size``× finer grid.
      do_softmax: softmax over the match dim before scoring.
      scale: 'centered' → coords in [-1,1]; 'positive' → [0,1].
      invert_matching_direction: False → for every B cell pick the best A
        (reference default); True → for every A cell pick the best B.

    Returns:
      :class:`Matches`, optionally extended with integer grid indices
      ``(iA, jA, iB, jB)`` when ``return_indices``.
    """
    b, fs1, fs2, fs3, fs4 = corr.shape
    lo = -1.0 if scale == "centered" else 0.0
    if scale not in ("centered", "positive"):
        raise ValueError(f"unknown scale {scale!r}")
    grid_ya = jnp.linspace(lo, 1.0, fs1 * k_size)
    grid_xa = jnp.linspace(lo, 1.0, fs2 * k_size)
    grid_yb = jnp.linspace(lo, 1.0, fs3 * k_size)
    grid_xb = jnp.linspace(lo, 1.0, fs4 * k_size)

    if invert_matching_direction:
        # for each A cell, best B (point_tnf.py:32-44)
        nc = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            nc = jax.nn.softmax(nc, axis=2)
        score = jnp.max(nc, axis=2)
        idx = jnp.argmax(nc, axis=2)  # (B, fs1*fs2) into flattened B dims
        i_b, j_b = idx // fs4, idx % fs4
        i_a = jnp.broadcast_to(
            (jnp.arange(fs1 * fs2) // fs2)[None, :], idx.shape
        )
        j_a = jnp.broadcast_to((jnp.arange(fs1 * fs2) % fs2)[None, :], idx.shape)
    else:
        # for each B cell, best A (point_tnf.py:47-59)
        nc = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            nc = jax.nn.softmax(nc, axis=1)
        score = jnp.max(nc, axis=1)
        idx = jnp.argmax(nc, axis=1)  # (B, fs3*fs4) into flattened A dims
        i_a, j_a = idx // fs2, idx % fs2
        i_b = jnp.broadcast_to((jnp.arange(fs3 * fs4) // fs4)[None, :], idx.shape)
        j_b = jnp.broadcast_to((jnp.arange(fs3 * fs4) % fs4)[None, :], idx.shape)

    if delta4d is not None:  # relocalization onto the fine grid (point_tnf.py:61-70)
        di_a, dj_a, di_b, dj_b = delta4d
        bidx = jnp.arange(b)[:, None]
        # gather all four offsets at the coarse (iA,jA,iB,jB) cells, then
        # promote coarse indices to the fine grid: fine = coarse*k + delta
        g = lambda d: d[bidx, i_a, j_a, i_b, j_b]  # noqa: E731
        d_ia, d_ja, d_ib, d_jb = g(di_a), g(dj_a), g(di_b), g(dj_b)
        i_a = i_a * k_size + d_ia
        j_a = j_a * k_size + d_ja
        i_b = i_b * k_size + d_ib
        j_b = j_b * k_size + d_jb

    xa = grid_xa[j_a]
    ya = grid_ya[i_a]
    xb = grid_xb[j_b]
    yb = grid_yb[i_b]
    m = Matches(xa, ya, xb, yb, score)
    if return_indices:
        return m, (i_a, j_a, i_b, j_b)
    return m


def nearest_neighbor_point_tnf(matches: Matches, target_points_norm: jnp.ndarray):
    """Warp normalized target points by snapping to the nearest match's B
    coordinate and emitting its A coordinate (point_tnf.py:82-94).

    Args:
      target_points_norm: ``(B, 2, N)`` in [-1, 1].
    Returns:
      ``(B, 2, N)`` warped points.
    """
    dx = target_points_norm[:, 0, :, None] - matches.xB[:, None, :]
    dy = target_points_norm[:, 1, :, None] - matches.yB[:, None, :]
    dist = jnp.sqrt(dx**2 + dy**2)  # (B, N, M)
    idx = jnp.argmin(dist, axis=2)
    bidx = jnp.arange(dist.shape[0])[:, None]
    wx = matches.xA[bidx, idx]
    wy = matches.yA[bidx, idx]
    return jnp.stack([wx, wy], axis=1)


def bilinear_interp_point_tnf(
    matches: Matches,
    target_points_norm: jnp.ndarray,
    grid_hw: tuple | None = None,
):
    """Warp normalized target points by inverse-bilinear interpolation of the
    match field at the 4 surrounding B-grid corners (point_tnf.py:96-148).

    Assumes matches came from the default (B→A) direction of
    :func:`corr_to_matches`, so ``(xB, yB)`` is the regular row-major B grid.
    ``grid_hw`` gives that grid's ``(hB, wB)`` shape; when None it is inferred
    as square — the reference bakes the square case in via
    ``feature_size = sqrt(len(xB))``, which breaks on rectangular (InLoc)
    grids, so callers with rectangular volumes must pass ``grid_hw``.

    Args:
      target_points_norm: ``(B, 2, N)`` in [-1, 1].
    Returns:
      ``(B, 2, N)`` warped points.
    """
    b, _, n = target_points_norm.shape
    if grid_hw is None:
        # static shape math (math.sqrt, not jnp: must stay concrete under jit)
        fs = int(round(math.sqrt(matches.xB.shape[-1])))
        fs_h = fs_w = fs
    else:
        fs_h, fs_w = int(grid_hw[0]), int(grid_hw[1])
    if fs_h * fs_w != matches.xB.shape[-1]:
        raise ValueError(
            f"grid {fs_h}x{fs_w} does not tile {matches.xB.shape[-1]} matches"
        )
    grid_y = jnp.linspace(-1.0, 1.0, fs_h)
    grid_x = jnp.linspace(-1.0, 1.0, fs_w)

    def lower_index(coords, grid, fs):  # (B, N) → index of grid node strictly below
        cnt = jnp.sum((coords[:, :, None] - grid[None, None, :]) > 0, axis=2) - 1
        return jnp.clip(cnt, 0, fs - 2)

    x_minus = lower_index(target_points_norm[:, 0, :], grid_x, fs_w)
    y_minus = lower_index(target_points_norm[:, 1, :], grid_y, fs_h)
    x_plus = x_minus + 1
    y_plus = y_minus + 1

    to_idx = lambda x, y: y * fs_w + x  # noqa: E731 — row-major B grid
    bidx = jnp.arange(b)[:, None]

    def at(field_x, field_y, idx):
        return jnp.stack([field_x[bidx, idx], field_y[bidx, idx]], axis=1)

    mm, pp = to_idx(x_minus, y_minus), to_idx(x_plus, y_plus)
    pm, mp = to_idx(x_plus, y_minus), to_idx(x_minus, y_plus)

    p_mm = at(matches.xB, matches.yB, mm)
    p_pp = at(matches.xB, matches.yB, pp)
    p_pm = at(matches.xB, matches.yB, pm)
    p_mp = at(matches.xB, matches.yB, mp)

    area = lambda d: jnp.abs(d[:, 0, :] * d[:, 1, :])  # noqa: E731
    f_pp = area(target_points_norm - p_mm)
    f_mm = area(target_points_norm - p_pp)
    f_mp = area(target_points_norm - p_pm)
    f_pm = area(target_points_norm - p_mp)

    q_mm = at(matches.xA, matches.yA, mm)
    q_pp = at(matches.xA, matches.yA, pp)
    q_pm = at(matches.xA, matches.yA, pm)
    q_mp = at(matches.xA, matches.yA, mp)

    num = (
        q_mm * f_mm[:, None, :]
        + q_pp * f_pp[:, None, :]
        + q_mp * f_mp[:, None, :]
        + q_pm * f_pm[:, None, :]
    )
    den = (f_pp + f_mm + f_mp + f_pm)[:, None, :]
    return num / den


def points_to_unit_coords(points: jnp.ndarray, im_size: jnp.ndarray):
    """Pixel → [-1,1] coords.  ``points``: (B,2,N) with row 0 = x (normalized
    by width), row 1 = y (by height); ``im_size``: (B,2) as (h, w)
    (point_tnf.py:151-158)."""
    h, w = im_size[:, 0], im_size[:, 1]
    x = normalize_axis(points[:, 0, :], w[:, None])
    y = normalize_axis(points[:, 1, :], h[:, None])
    return jnp.stack([x, y], axis=1)


def points_to_pixel_coords(points: jnp.ndarray, im_size: jnp.ndarray):
    """[-1,1] → pixel coords; inverse of :func:`points_to_unit_coords`
    (point_tnf.py:160-167)."""
    h, w = im_size[:, 0], im_size[:, 1]
    x = unnormalize_axis(points[:, 0, :], w[:, None])
    y = unnormalize_axis(points[:, 1, :], h[:, None])
    return jnp.stack([x, y], axis=1)
