"""Temporal candidate selection for streaming (tracked) matching.

The coarse-to-fine tier (``ops/sparse_topk.py`` + ``ops/sparse_corr.py``)
pays a full dense coarse filter per query just to pick each source cell's
top-k candidate target neighbourhoods.  A video stream has a better prior
for free: frame ``t-1``'s match table.  This module turns that table into
candidate rows of the EXACT shape/contract ``topk_candidates`` produces —
``(B, N, K)`` int32 flattened coarse target indices under the static-shape
coverage-padding contract — so the gathered-tile fine pass, the scatter
readout, and the wire format are reused unchanged and frame ``t`` skips the
coarse pass entirely on steady frames:

  * :func:`temporal_candidates` — in-graph dilation of a per-cell prior by
    a static ``(2r+1)²`` search window, clamped into the coarse grid (edge
    duplicates are harmless: the sparse scatter resolves by max, exactly
    the ``topk_candidates`` padding rule);
  * :func:`prior_from_table` — host-side inversion of a served ``(5|6, N)``
    match table into the per-coarse-cell prior pair the next frame seeds
    from (both families: A→B for ``cand_ab``, B→A for ``cand_ba``);
  * :func:`tracking_recall_proxy` — the cut/drift detector's candidate-
    containment proxy for ``sparse_topk.candidate_recall`` (the real recall
    needs the dense volume the tracked frame deliberately never computed).

Stream/session state (who owns which prior, cut fallback, eviction) lives
in the serving layer (``serving/stream.py``); everything here is stateless.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

# every trunk in models/backbone.py (resnet layer3, vgg pool4, densenet,
# tiny) downsamples by 16: the serving layer maps image buckets to feature
# grids with this constant
FEATURE_STRIDE = 16


def window_size(radius: int) -> int:
    """Candidates per prior cell: the static ``(2r+1)²`` search window —
    the tracked analog of ``sparse_topk``'s ``k``."""
    r = int(radius)
    if r < 0:
        raise ValueError(f"track radius must be >= 0, got {radius}")
    return (2 * r + 1) ** 2


def temporal_candidates(prior: jnp.ndarray, hc: int, wc: int,
                        radius: int) -> jnp.ndarray:
    """Dilate a per-cell prior into candidate rows — the tracked
    counterpart of :func:`~ncnet_tpu.ops.sparse_topk.topk_candidates`.

    Args:
      prior: ``(B, N)`` int32 — for every coarse SOURCE-side cell, the
        flattened coarse TARGET-side index (row-major ``i·wc + j``) frame
        ``t-1`` matched it to.
      hc, wc: coarse target-side grid dims (``prior`` decodes against
        ``wc``; values are clipped into the grid, so a stale or padded
        prior can never index out of bounds).
      radius: static search-window radius in coarse cells.

    Returns:
      ``(B, N, (2r+1)²)`` int32 candidate rows under the same coverage
      contract as top-k selection: static shape for any (radius, grid)
      combination, window cells clamped into the grid (edge windows shift
      inward, producing duplicates the sparse scatter resolves by max),
      and every row containing its prior cell's full block.
    """
    k = window_size(radius)  # validates radius
    r = int(radius)
    prior = jnp.clip(prior.astype(jnp.int32), 0, hc * wc - 1)
    ic = prior // wc
    jc = prior % wc
    d = np.arange(-r, r + 1, dtype=np.int32)
    di = np.repeat(d, 2 * r + 1)
    dj = np.tile(d, 2 * r + 1)
    wi = jnp.clip(ic[..., None] + di[None, None, :], 0, hc - 1)
    wj = jnp.clip(jc[..., None] + dj[None, None, :], 0, wc - 1)
    out = (wi * wc + wj).astype(jnp.int32)
    assert out.shape[-1] == k
    return out


def _cells_from_coords(x: np.ndarray, y: np.ndarray, h: int, w: int,
                       scale: str) -> Tuple[np.ndarray, np.ndarray]:
    """Invert ``corr_to_matches``' normalized coordinates back onto integer
    grid cells (the ``linspace(lo, 1, n)`` convention, k_size=1 — the only
    relocalization class the sparse tier admits)."""
    lo = -1.0 if scale == "centered" else 0.0
    span = 1.0 - lo
    j = np.rint((np.asarray(x, np.float64) - lo) * (w - 1) / span) \
        if w > 1 else np.zeros_like(x)
    i = np.rint((np.asarray(y, np.float64) - lo) * (h - 1) / span) \
        if h > 1 else np.zeros_like(y)
    return (np.clip(i, 0, h - 1).astype(np.int64),
            np.clip(j, 0, w - 1).astype(np.int64))


def identity_prior(n_src_coarse: int, wc_src: int, hc_tgt: int,
                   wc_tgt: int) -> np.ndarray:
    """Zero-motion prior: every coarse source cell looks at the same
    (row, col) on the target grid, clamped — the coverage-padding value for
    cells frame ``t-1`` never claimed, and a valid cold seed for
    same-scene streams."""
    c = np.arange(n_src_coarse)
    i = np.minimum(c // wc_src, hc_tgt - 1)
    j = np.minimum(c % wc_src, wc_tgt - 1)
    return (i * wc_tgt + j).astype(np.int32)


def prior_from_table(table: np.ndarray, grid_a: Tuple[int, int],
                     grid_b: Tuple[int, int], factor: int,
                     scale: str = "centered"
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Invert one served match table into the next frame's prior pair.

    Args:
      table: ``(5|6, N)`` float32 — the serving wire rows (xA, yA, xB, yB,
        score; row 5, when present, is the quality row and is ignored).
        ``N == hB·wB``: one entry per fine TARGET cell, each naming its
        best source cell (``corr_to_matches``' default direction).
      grid_a, grid_b: fine source/target grids ``(h, w)``.
      factor: coarse pooling factor (``config.sparse_factor``).
      scale: the table's coordinate scale ('centered' | 'positive').

    Returns:
      ``(prior_ab, prior_ba)`` int32 —
      ``prior_ab[c]``: per coarse SOURCE cell, the coarse target cell its
      best-scoring claimant sat in (unclaimed cells fall back to the
      zero-motion :func:`identity_prior`);
      ``prior_ba[c]``: per coarse TARGET cell, the coarse source cell of
      its best-scoring fine entry.  Both are coverage-total by
      construction — every cell holds a valid in-grid index.
    """
    t = np.asarray(table, dtype=np.float32)
    if t.ndim != 2 or t.shape[0] < 5:
        raise ValueError(f"match table must be (5|6, N), got {t.shape}")
    ha, wa = grid_a
    hb, wb = grid_b
    if t.shape[1] != hb * wb:
        raise ValueError(
            f"table has {t.shape[1]} rows, target grid {hb}x{wb} needs "
            f"{hb * wb}")
    xa, ya, xb, yb, score = t[0], t[1], t[2], t[3], t[4]
    ia, ja = _cells_from_coords(xa, ya, ha, wa, scale)
    ib, jb = _cells_from_coords(xb, yb, hb, wb, scale)
    hac, wac = ha // factor, wa // factor
    hbc, wbc = hb // factor, wb // factor
    ca = (ia // factor) * wac + (ja // factor)
    cb = (ib // factor) * wbc + (jb // factor)
    # score-ascending order: the last write per cell below is the max-score
    # entry — one vectorized pass instead of a python argmax per cell
    order = np.argsort(score, kind="stable")
    prior_ba = identity_prior(hbc * wbc, wbc, hac, wac)
    prior_ba[cb[order]] = ca[order]
    prior_ab = identity_prior(hac * wac, wac, hbc, wbc)
    prior_ab[ca[order]] = cb[order]
    return prior_ab.astype(np.int32), prior_ba.astype(np.int32)


def tracking_recall_proxy(prior_ab: np.ndarray, table: np.ndarray,
                          grid_a: Tuple[int, int], grid_b: Tuple[int, int],
                          factor: int, radius: int,
                          scale: str = "centered") -> float:
    """Candidate-containment proxy for ``candidate_recall`` on a tracked
    frame: the fraction of served entries whose (source → target) coarse
    pairing falls inside the search window the frame was seeded with.

    The true recall compares candidates against the DENSE volume's argmax
    — exactly the volume a tracked frame skipped computing.  But the
    merged two-family readout can land a row's match outside its source
    cell's A→B window (the B→A tiles contribute their own support), and on
    a scene cut it mostly does: the prior stops describing the scene, so
    containment collapses along with the quality signals.  Steady frames
    sit near 1.0.  Host-side numpy, like ``candidate_recall``."""
    t = np.asarray(table, dtype=np.float32)
    ha, wa = grid_a
    hb, wb = grid_b
    ia, ja = _cells_from_coords(t[0], t[1], ha, wa, scale)
    ib, jb = _cells_from_coords(t[2], t[3], hb, wb, scale)
    wac = wa // factor
    wbc = wb // factor
    ca = (ia // factor) * wac + (ja // factor)
    cb_i, cb_j = (ib // factor), (jb // factor)
    prior = np.asarray(prior_ab).reshape(-1)[ca]
    di = np.abs(cb_i - prior // wbc)
    dj = np.abs(cb_j - prior % wbc)
    r = int(radius)
    return float(np.mean((di <= r) & (dj <= r)))
