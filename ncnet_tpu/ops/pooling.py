"""4D max-pooling with argmax decomposition ("relocalization").

NCNet's long-context trick (/root/reference/lib/model.py:177-191): correlate at
k× grid resolution, 4D-max-pool by k (k⁴× volume reduction) while remembering
*relative* argmax offsets, filter the pooled volume, and add the offsets back
at match extraction.  The reference gathers k⁴ strided slices in a Python
loop; here it is one reshape + transpose + argmax — a fully fused XLA program.
"""

from __future__ import annotations

import jax.numpy as jnp


def maxpool4d_with_argmax(corr: jnp.ndarray, k: int):
    """Pool ``(B, hA, wA, hB, wB)`` by ``k`` along all four spatial dims.

    Returns:
      pooled: ``(B, hA/k, wA/k, hB/k, wB/k)``
      deltas: tuple ``(di, dj, dk, dl)`` of int32 arrays shaped like
        ``pooled`` — the offset of the max within each k⁴ box, with the same
        ``((di·k + dj)·k + dk)·k + dl`` linearization the reference decodes
        by repeated fmod/div (model.py:186-189).
    """
    b, ha, wa, hb, wb = corr.shape
    assert ha % k == 0 and wa % k == 0 and hb % k == 0 and wb % k == 0, (
        f"volume dims {corr.shape[1:]} must be divisible by k={k}"
    )
    v = corr.reshape(b, ha // k, k, wa // k, k, hb // k, k, wb // k, k)
    # bring the four intra-box dims to the back, in (di, dj, dk, dl) order
    v = v.transpose(0, 1, 3, 5, 7, 2, 4, 6, 8).reshape(
        b, ha // k, wa // k, hb // k, wb // k, k**4
    )
    idx = jnp.argmax(v, axis=-1)
    pooled = jnp.max(v, axis=-1)
    dl = idx % k
    dk = (idx // k) % k
    dj = (idx // (k * k)) % k
    di = idx // (k * k * k)
    return pooled, (di.astype(jnp.int32), dj.astype(jnp.int32), dk.astype(jnp.int32), dl.astype(jnp.int32))
