"""CP-decomposed conv4d: the rank-R separable tier of the NC filter.

A dense NC layer contracts a ``(k, k, k, k, C_in, C_out)`` kernel against
every volume cell — ``2·cells·k⁴·C_in·C_out`` FLOPs, the k⁴ wall ROADMAP
item 2 names.  Following *Speeding-up Convolutional Neural Networks Using
Fine-tuned CP-Decomposition* (Lebedev et al., PAPERS.md), the kernel is
factorized as a rank-R canonical polyadic (CP) sum of separable terms::

    w[p,q,r,s,c,o] = Σ_ρ  ka[p,ρ]·kwa[q,ρ]·kb[r,ρ]·kwb[s,ρ]·cin[c,ρ]·cout[ρ,o]

and the layer becomes a chain of cheap contractions — a ``C_in→R``
pointwise map, four 1-D "same" cross-correlations (one per spatial dim,
each a k-tap depthwise filter over the R rank channels), and an ``R→C_out``
pointwise map + bias::

    FLOPs ≈ 2·cells·R·(C_in + C_out + 4k)    vs    2·cells·k⁴·C_in·C_out

At the PF-Pascal/InLoc k=5 16→16 layer and the default rank 16 that is a
~190× algebraic cut.  The rank is an accuracy knob: factors come from
``tools/cp_decompose.py`` (HOSVD init + ALS refinement of a trained dense
checkpoint) and PCK is recovered by fine-tuning them with the frozen trunk
(``train.py --finetune_cp_rank R`` — the paper's recipe).

Tier contract (ops/nc_fused_lane.py): a layer OPTS IN by carrying a
``"cp"`` factor dict beside its dense ``"w"``/``"b"`` — the chooser
considers the ``"cp"`` tier only when every layer has factors
(:func:`cp_stack_ranks`) AND the arithmetic gate (:func:`cp_feasible`)
predicts a FLOP win over the dense stack, and gates it behind a real
compile probe (:func:`cp_compiles`, with a memory-ledger row).  The chain
is plain differentiable XLA — no Pallas, no custom VJP — so it runs on any
backend and any dtype, and the fine-tune path trains the factors directly
through it.

Exactness seam for tests: :func:`exact_cp_factors` builds a rank-
``k⁴·C_in`` factorization that reconstructs ANY kernel exactly (one-hot
spatial/input factors; the kernel's fibers as ``cout``), so the rank-full
chain must match dense ``conv4d`` to fp32 tolerance on every shape class.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# the checkpoint-conversion default (tools/cp_decompose.py, bench.py): at
# the k=5 16→16 InLoc layer R=16 keeps the rank channels as wide as the
# dense channels (HOSVD captures the kernel's leading subspace exactly at
# C=16) while cutting layer FLOPs ~190×
DEFAULT_CP_RANK = 16

# the arithmetic gate's win margin: predicted CP FLOPs must undercut the
# dense stack by at least this factor before the tier engages — the chain
# is 6 XLA ops per layer vs 1, so a marginal FLOP tie loses to launch and
# layout overhead
_CP_GATE_MARGIN = 0.75

_FACTOR_KEYS = ("ka", "kwa", "kb", "kwb", "cin", "cout")


def cp_stack_ranks(nc_params: Sequence[dict]) -> Optional[Tuple[int, ...]]:
    """Per-layer CP ranks when EVERY layer carries factors, else None (the
    chooser's opt-in signal: a stack without full factor coverage cannot
    route through the CP tier)."""
    ranks = []
    for layer in nc_params:
        cp = layer.get("cp") if isinstance(layer, dict) else None
        if not cp or any(k not in cp for k in _FACTOR_KEYS):
            return None
        ranks.append(int(cp["cout"].shape[0]))
    return tuple(ranks) if ranks else None


def _corr1d_same(y: jnp.ndarray, taps: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Per-rank-channel 1-D "same" cross-correlation along ``axis``:
    ``out[i] = Σ_p y[i + p - k//2] · taps[p]`` with zero padding — the
    one-dimensional factor of conv4d's cross-correlation semantics.
    ``y``: ``(..., R)`` with the rank dim last; ``taps``: ``(k, R)``."""
    k = taps.shape[0]
    d = k // 2
    n = y.shape[axis]
    pad = [(0, 0)] * y.ndim
    pad[axis] = (d, d)
    yp = jnp.pad(y, pad)
    out = None
    for p in range(k):
        term = lax.slice_in_dim(yp, p, p + n, axis=axis) * taps[p]
        out = term if out is None else out + term
    return out


def cp_apply_layer(x: jnp.ndarray, cp: Dict[str, jnp.ndarray],
                   bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """One CP-decomposed conv4d layer ("same", stride 1) on the volume
    ``x`` ``(B, hA, wA, hB, wB, C_in)`` → ``(..., C_out)``.

    The spatial taps separate because the CP term is an outer product: the
    four 1-D passes compose to exactly the rank's 4-D tap tensor, and the
    rank sum rides the R channel dim through all four."""
    dtype = x.dtype
    fac = {k: cp[k].astype(dtype) for k in _FACTOR_KEYS}
    y = jnp.einsum("...c,cr->...r", x, fac["cin"])
    for axis, key in ((1, "ka"), (2, "kwa"), (3, "kb"), (4, "kwb")):
        y = _corr1d_same(y, fac[key], axis)
    y = jnp.einsum("...r,ro->...o", y, fac["cout"])
    if bias is not None:
        y = y + bias.astype(dtype)
    return y


def nc_stack_cp(nc_params: List[dict], x: jnp.ndarray) -> jnp.ndarray:
    """The full [conv4d_same + bias + ReLU]×N stack through each layer's CP
    factors — the "cp" tier's stack body (differentiable plain XLA; the
    fine-tune path takes gradients w.r.t. the factors through this)."""
    for layer in nc_params:
        x = jax.nn.relu(cp_apply_layer(x, layer["cp"], layer["b"]))
    return x


def cp_reconstruct(cp: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Materialize the dense ``(kA, kWA, kB, kWB, C_in, C_out)`` kernel a
    factor dict represents (tests / conversion-error reporting)."""
    return jnp.einsum("pr,qr,sr,tr,cr,ro->pqstco",
                      cp["ka"], cp["kwa"], cp["kb"], cp["kwb"],
                      cp["cin"], cp["cout"])


def exact_cp_factors(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """A rank-``k⁴·C_in`` CP factorization that is EXACT for any kernel:
    component ``ρ = (p,q,r,s,c)`` gets one-hot spatial/input factors and
    ``cout[ρ] = w[p,q,r,s,c,:]``.  The parity fixture for the tier tests —
    rank-full CP must equal dense conv4d to float tolerance."""
    dims = tuple(w.shape[:5])  # (kA, kWA, kB, kWB, C_in)
    c_out = w.shape[5]
    rank = 1
    for n in dims:
        rank *= n

    def mode_factor(mode: int) -> jnp.ndarray:
        # (dim_mode, rank): e_{idx_mode(ρ)} per component ρ = (p,q,r,s,c)
        # row-major — the mode's identity broadcast over the other modes
        n = dims[mode]
        shape = [1] * 5
        shape[mode] = n
        t = jnp.broadcast_to(
            jnp.eye(n, dtype=w.dtype).reshape((n,) + tuple(shape)),
            (n,) + dims)
        return t.reshape(n, rank)

    factors = {key: mode_factor(m)
               for m, key in enumerate(("ka", "kwa", "kb", "kwb", "cin"))}
    factors["cout"] = w.reshape(rank, c_out)
    return factors


# ---------------------------------------------------------------------------
# arithmetic gate + compile probe (the chooser's two checks)
# ---------------------------------------------------------------------------


def cp_layer_flops(cells: int, k: int, c_in: int, c_out: int,
                   rank: int) -> int:
    """Predicted FLOPs of one CP layer on a ``cells``-cell volume: the
    C_in→R map, four k-tap 1-D passes over R channels, and the R→C_out
    map (multiply-adds counted as 2)."""
    return 2 * cells * rank * (c_in + c_out + 4 * k)


def dense_layer_flops(cells: int, k: int, c_in: int, c_out: int) -> int:
    """Direct-k⁴ FLOPs of one dense conv4d layer (the baseline both
    arithmetic tiers' gates compare against)."""
    return 2 * cells * (k ** 4) * c_in * c_out


def cp_feasible(ha: int, wa: int, hb: int, wb: int,
                kernels: Sequence[int], channels: Sequence[int],
                ranks: Sequence[int]) -> bool:
    """The CP tier's arithmetic gate: odd kernels (the "same"-pad shape
    class conv4d serves) and a predicted whole-stack FLOP win of at least
    ``_CP_GATE_MARGIN`` over the dense stack.  A rank high enough to lose
    the arithmetic (rank-full parity factors on a tiny kernel) keeps the
    dense tiers — exactness is the test fixture's job, not the chooser's."""
    if len(ranks) != len(kernels) or any(k % 2 == 0 for k in kernels):
        return False
    cells = ha * wa * hb * wb
    cp = dense = 0
    c_in = 1
    for k, c_out, r in zip(kernels, channels, ranks):
        cp += cp_layer_flops(cells, k, c_in, c_out, r)
        dense += dense_layer_flops(cells, k, c_in, c_out)
        c_in = c_out
    return cp <= _CP_GATE_MARGIN * dense


@functools.lru_cache(maxsize=16)
def cp_compiles(ha, wa, hb, wb, kernels, channels, ranks) -> bool:
    """Real-compile probe for the CP chain (cached per shape class) — the
    chain is plain XLA so failures are rare, but the tier discipline
    (ops/nc_fused_lane.py) is uniform: every tier proves an actual compile
    before the chooser routes traffic, and the probe's AOT memory analysis
    lands in the ledger as the tier's temp-bytes evidence."""
    try:
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, 1), jnp.float32)
        params = []
        c_in = 1
        for k, c_out, r in zip(kernels, channels, ranks):
            params.append({
                "cp": {
                    "ka": jax.ShapeDtypeStruct((k, r), jnp.float32),
                    "kwa": jax.ShapeDtypeStruct((k, r), jnp.float32),
                    "kb": jax.ShapeDtypeStruct((k, r), jnp.float32),
                    "kwb": jax.ShapeDtypeStruct((k, r), jnp.float32),
                    "cin": jax.ShapeDtypeStruct((c_in, r), jnp.float32),
                    "cout": jax.ShapeDtypeStruct((r, c_out), jnp.float32),
                },
                "b": jax.ShapeDtypeStruct((c_out,), jnp.float32),
            })
            c_in = c_out
        compiled = jax.jit(nc_stack_cp).lower(params, x).compile()
        from ncnet_tpu.ops.nc_fused_lane import _record_probe_memory

        _record_probe_memory("nc_cp_probe", "cp", ha, wa, hb, wb,
                             kernels, channels, compiled)
        return True
    except Exception:  # noqa: BLE001 — any compile failure demotes, never raises
        return False
