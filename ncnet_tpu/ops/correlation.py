"""Dense feature correlation.

The reference builds the 4D correlation volume with a batched matmul over
flattened spatial dims (/root/reference/lib/model.py:106-115).  On TPU the
natural expression is a single einsum — XLA lowers it straight onto the MXU
with no reshapes materialized.
"""

from __future__ import annotations

import jax.numpy as jnp

from ncnet_tpu.ops.norm import feature_l2_norm


def correlation_4d(
    feature_a: jnp.ndarray,
    feature_b: jnp.ndarray,
    *,
    accumulate_dtype: jnp.dtype | None = jnp.float32,
) -> jnp.ndarray:
    """Full 4D correlation volume between two feature maps.

    Args:
      feature_a: ``(B, hA, wA, C)`` (channels-last; the reference is NCHW).
      feature_b: ``(B, hB, wB, C)``.
      accumulate_dtype: MXU accumulation type.  bf16 inputs with f32
        accumulation is the TPU-native analog of the reference's fp16 volume
        (/root/reference/lib/model.py:265-267) with better numerics.

    Returns:
      ``(B, hA, wA, hB, wB)`` — cell (i,j,k,l) is ⟨f_A[i,j], f_B[k,l]⟩,
      the same indexing as the reference's ``[batch, row_A, col_A, row_B,
      col_B]`` volume (/root/reference/lib/model.py:114).
    """
    out = jnp.einsum(
        "bijc,bklc->bijkl",
        feature_a,
        feature_b,
        preferred_element_type=accumulate_dtype,
    )
    if accumulate_dtype is not None and feature_a.dtype != accumulate_dtype:
        out = out.astype(feature_a.dtype)
    return out


def correlation_3d(
    feature_a: jnp.ndarray,
    feature_b: jnp.ndarray,
    *,
    normalization: bool = True,
) -> jnp.ndarray:
    """Legacy '3D' correlation (reference FeatureCorrelation shape='3D',
    /root/reference/lib/model.py:97-105): same-shape maps, output indexed
    ``[batch, idx_A = row_A + h*col_A, row_B, col_B]``.

    Args:
      feature_a, feature_b: ``(B, H, W, C)``.

    Returns:
      ``(B, H*W, H, W)`` with the reference's column-major A index, optionally
      ReLU + L2-normalized over the match dim (model.py:117-118).
    """
    b, h, w, c = feature_a.shape
    # idx_A = row_A + h * col_A  →  A flattened column-major (transpose(2,3)
    # in the reference); implemented by swapping to (w, h) then flattening.
    fa = jnp.transpose(feature_a, (0, 2, 1, 3)).reshape(b, w * h, c)
    corr = jnp.einsum("bmc,bklc->bmkl", fa, feature_b)
    if normalization:
        corr = feature_l2_norm(jnp.maximum(corr, 0.0), axis=1)
    return corr
