"""Persistent tier autotune cache: tier decisions that survive the process.

``choose_fused_stack`` / ``choose_fused_vjp`` decide per shape class from
real-compile probes — the right authority, but one that costs a Mosaic
compile (seconds on a tunneled device) PER SHAPE PER PROCESS, re-paid on
every restart even though the PR 5 ``tier_selected`` / ``tier_demoted``
events already encode the answer.  This module is the consume side of that
telemetry (the first concrete slice of ROADMAP item 4's kernel registry):

  * every PROBE-BACKED tier decision is persisted per ``(device_kind,
    stage, shape-class)``; the choosers consult the cache first and skip
    the compile probe on a hit — a warm process reaches identical
    decisions with zero probes.  A decision reached by skipping past a
    FAILED compile probe — an XLA outcome, or a lower tier after a
    higher-ranked candidate's probe failed — is deliberately never cached:
    the failure may have been transient (device busy, tunnel hiccup), and
    replaying the decision would pin the shape below its fast tier across
    every future process;
  * runtime demotions (``ops.demote_fused_tier`` — a tier that CRASHED
    mid-run) are recorded as negative entries per device kind, so a
    crashed tier stays demoted across restarts instead of greeting every
    new process with the same mid-run failure;
  * invalidation is by construction: entries are keyed under the device
    kind (a different accelerator simply misses) and the file carries a
    schema version (a reader that does not understand the file ignores it
    wholesale and overwrites on the next record).  The cheap arithmetic
    feasibility gates still run on every hit — a cached tier that no
    longer passes them (changed VMEM budgets after a code update) is
    treated as a miss and re-probed.

Knob: ``NCNET_TPU_TIER_CACHE`` — a file path, or ``0``/``off`` to disable
(every process probes from scratch, the pre-round-9 behavior).  Default:
``~/.cache/ncnet_tpu/tier_cache.json`` (honors ``XDG_CACHE_HOME``).

All paths are fail-open: a cache that cannot be read or written degrades to
probing, never to an error — the cache is an accelerator, not an authority.
The probe remains the authority on a miss; the cache only replays what a
probe once proved.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, FrozenSet, Optional, Sequence, Tuple

SCHEMA_VERSION = 1
CACHE_ENV = "NCNET_TPU_TIER_CACHE"

_lock = threading.Lock()
# in-process mirror of the on-disk doc: {"path": resolved path or None,
# "doc": parsed doc} — loaded once, refreshed only by _reset_state (tests)
_state: Dict[str, object] = {"loaded": False, "path": None, "doc": None}


def cache_path() -> Optional[str]:
    """Resolved cache file path, or None when disabled via the env knob."""
    raw = os.environ.get(CACHE_ENV)
    if raw is not None:
        raw = raw.strip()
        if raw.lower() in ("", "0", "off", "none"):
            return None
        return raw
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "ncnet_tpu", "tier_cache.json")


def device_kind() -> str:
    """The local device kind the cache keys under ('unknown' when no
    backend is reachable — such entries never collide with real ones).
    Shares the perf store's probe so the two cross-run consumers can never
    key the same machine under different kinds."""
    from ncnet_tpu.observability.events import local_device_kind

    return local_device_kind() or "unknown"


def signature_key(stage: str,
                  sig: Tuple[int, int, int, int,
                             Sequence[int], Sequence[int]]) -> str:
    """Stable string key for one (stage, shape-class): the same tuple the
    choosers and ``tier_selected`` events use.  An optional 7th element
    (the CP tier's per-layer rank context) extends the key — a stack that
    gains or loses factors is a DIFFERENT decision, not a cache hit."""
    ha, wa, hb, wb, kernels, channels = sig[:6]
    key = (f"{stage}|{ha}x{wa}x{hb}x{wb}"
           f"|k={','.join(str(k) for k in kernels)}"
           f"|c={','.join(str(c) for c in channels)}")
    if len(sig) > 6 and sig[6] is not None:
        key += f"|r={','.join(str(r) for r in sig[6])}"
    return key


def _empty_doc() -> dict:
    return {"kind": "ncnet_tpu_tier_cache", "schema": SCHEMA_VERSION,
            "devices": {}}


def _load_locked() -> dict:
    """The parsed on-disk doc (cached in-process).  A missing, corrupt,
    foreign or newer-schema file reads as empty — and is overwritten
    wholesale on the next record (the invalidation rule)."""
    if _state["loaded"]:
        path = cache_path()
        if path == _state["path"]:
            return _state["doc"]  # type: ignore[return-value]
    path = cache_path()
    doc = _empty_doc()
    if path is not None:
        try:
            with open(path) as f:
                cand = json.load(f)
            if (isinstance(cand, dict)
                    and cand.get("kind") == "ncnet_tpu_tier_cache"
                    and cand.get("schema") == SCHEMA_VERSION
                    and isinstance(cand.get("devices"), dict)):
                doc = cand
        except (OSError, ValueError):
            pass
    _state.update(loaded=True, path=path, doc=doc)
    return doc


def _save_locked(doc: dict) -> None:
    path = cache_path()
    if path is None:
        return
    try:
        from ncnet_tpu.utils.io import atomic_write_json

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_json(path, doc)
    except (OSError, ValueError):
        pass  # fail-open: an unwritable cache just means probing next time


def _device_entry(doc: dict, kind: str) -> dict:
    entry = doc["devices"].setdefault(kind, {})
    entry.setdefault("decisions", {})
    entry.setdefault("demoted", [])
    return entry


def lookup(stage: str, sig) -> Optional[Tuple[Optional[str]]]:
    """Cached decision for (device kind, stage, shape class): a 1-tuple
    ``(tier,)`` — ``(None,)`` is a cached "use XLA" — or None on a miss."""
    if cache_path() is None:
        return None
    with _lock:
        doc = _load_locked()
        entry = doc["devices"].get(device_kind())
        if not entry:
            return None
        key = signature_key(stage, sig)
        decisions = entry.get("decisions", {})
        if key not in decisions:
            return None
        tier = decisions[key]
        return (tier if isinstance(tier, str) else None,)


def record(stage: str, sig, tier: Optional[str]) -> None:
    """Persist one fresh probe decision (no-op when disabled/unwritable)."""
    if cache_path() is None:
        return
    with _lock:
        doc = _load_locked()
        entry = _device_entry(doc, device_kind())
        key = signature_key(stage, sig)
        if entry["decisions"].get(key, "\0miss") == tier:
            return
        entry["decisions"][key] = tier
        _save_locked(doc)
    from ncnet_tpu.observability import events as _events

    _events.emit("tier_cache", op="store", stage=stage,
                 key=signature_key(stage, sig), tier=tier or "xla")


def record_demotion(tier: str) -> None:
    """Persist a runtime demotion as a negative entry, and drop any cached
    decisions that named the demoted tier (they are now known-bad: a warm
    restart must re-probe those shapes on the surviving ladder)."""
    if cache_path() is None:
        return
    with _lock:
        doc = _load_locked()
        entry = _device_entry(doc, device_kind())
        changed = False
        if tier not in entry["demoted"]:
            entry["demoted"].append(tier)
            changed = True
        for key, cached in list(entry["decisions"].items()):
            if cached == tier:
                del entry["decisions"][key]
                changed = True
        if changed:
            _save_locked(doc)
    from ncnet_tpu.observability import events as _events

    _events.emit("tier_cache", op="demote", tier=tier)


def persistent_demotions() -> FrozenSet[str]:
    """Tiers demoted in a PREVIOUS process of this device kind (negative
    entries) — unioned with the runtime registry by the choosers."""
    if cache_path() is None:
        return frozenset()
    with _lock:
        doc = _load_locked()
        entry = doc["devices"].get(device_kind())
        if not entry:
            return frozenset()
        return frozenset(t for t in entry.get("demoted", [])
                         if isinstance(t, str))


def clear() -> None:
    """Drop the cache file and the in-process mirror (a deliberate
    re-probe; the runtime demotion registry is separate — see
    ``ops.reset_fused_tier_demotions``)."""
    path = cache_path()
    with _lock:
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass
        _state.update(loaded=False, path=None, doc=None)


def _reset_state() -> None:
    """Tests: forget the in-process mirror so the next access re-reads the
    file — the in-process analog of starting a fresh process."""
    with _lock:
        _state.update(loaded=False, path=None, doc=None)
