"""Pallas TPU kernel for small-C_out 4D convolution.

The last NC layer (C_out=1) is the one conv4d shape XLA cannot make fast: any
conv formulation leaves it with one useful MXU output lane in 128, and the
dense-Toeplitz rewrite (ops/conv4d.py `toeplitz_b`) buys utilization with a
kB·kWB× FLOP overhead and an O((hB·wB)²) mask.  This kernel gets full lanes
at TRUE FLOPs by folding the ``(kA, kB, kWB)`` taps into the matmul's N
dimension — N = k³·C_out = 125 for the PF-Pascal 5⁴ kernel — and resolving
the tap shifts in a VMEM epilogue, where the partial-product tensor that
dooms the same idea in XLA HBM (125× volume materialization) never leaves
the chip.

Shape/grid design:
  * the volume rides as ``(B, hA, wA, hB, (wB+halo)·C_in)`` — fusing the
    minor pair keeps VMEM tiles ~1× padded where a 16-channel minor dim
    pads 8×;
  * grid = (B, hA); the kA input rows an output row needs arrive as kA
    separate BlockSpecs with hA-block-size 1, whose index maps select rows
    ``i..i+kA-1`` of the halo-padded volume (block-unit maps cannot express
    overlapping windows, row-granular specs can);
  * per wA slab: one MXU dot
      P[(p, j, k', l'), (q, c)] @ W[(q, c), (p, r, s, o)] → Y
    then the VPU epilogue  out[j,k,l,o] = Σ_{p,r,s} Y[p, j, k+r, l+s, (p,r,s,o)].

Applicability: needs ``kA·(wA+h)·(hB+h)·(wB+h)·C_in`` to fit VMEM — the
PF-Pascal regime (hB·wB ≈ 625).  The InLoc-resolution volume stays on the
XLA formulations.  Forward-only: the ``jax.custom_vjp`` backward falls back
to the XLA path (training uses it anyway; this kernel serves eval/bench).

Status (round 3, jax 0.9.0 / v5e): the Mosaic compiler still REJECTS this
kernel ("unsupported shape cast").  A systematic legality sweep
(tools/mosaic_probes.py) pinned the boundary: lane-dim reshape splits/merges
and lane rolls are rejected, while lane CONCAT (any width), lane pads, lane
slices at ANY offset, lane-offset stores, sublane slices/merges/splits, and
both dot_general orientations compile.  Redesigns restricted to the legal
set were costed before building: every arrangement either re-creates the
lane split (output cells and fused channels cannot share the lane dim), or
folds taps into the dot's M/N with a 5-10× tap-cross-product FLOP waste, or
pays a banded-Toeplitz K-overhead of (T+4)/T — and the XLA formulations
moved: measured coutfold for the 16→16 layer (1.5-2.7 ms/pair bf16 bs4)
already beats the equivalent bare GEMM shape (4.7, tools/xla_conv_probe.py),
bounding the best realistic Mosaic kernel at roughly parity.  The kernel
therefore stays gated on ``pallas_compiles`` (a cached real-compile probe) —
live automatically the day the toolchain accepts lane reshapes — with
numerics locked by interpret-mode tests (tests/test_ops_basic.py), and the
fast-path effort went to the measured XLA formulation choices instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# VMEM working-set budget for the feasibility gate (v5e has 16MB more or less
# fully available to one Pallas program)
_VMEM_BUDGET = 12 * 2 ** 20


def pallas_feasible(ha, wa, hb, wb, c_in, c_out, k, itemsize=4) -> bool:
    """True when the per-step tile + dot working set fits the VMEM budget."""
    h = k - 1
    xt = k * (wa + h) * (hb + h) * (wb + h) * c_in * itemsize
    m = k * (hb + h) * (wb + h)  # js=1 slab rows
    work = xt + m * k * c_in * itemsize + m * k ** 3 * c_out * 4
    return work <= _VMEM_BUDGET


@functools.lru_cache(maxsize=16)
def pallas_compiles(ha, wa, hb, wb, c_in, c_out, k, dtype_name="float32") -> bool:
    """True iff Mosaic actually compiles the kernel for this shape class.

    Lowering Pallas TPU kernels can fail on layout constraints that depend on
    the concrete shape AND dtype (16-bit types pack sublanes differently, so
    bf16 legality is independent of f32 legality — e.g. 'unsupported shape
    cast'), so the variant chooser probes a real compile at the execution
    dtype (batch 1 — the grid batch dim cannot change layout legality) and
    falls back to the XLA formulations on any failure.  Cached per
    (shape, dtype) class; a probe costs one ahead-of-time compile."""
    try:
        dtype = jnp.dtype(dtype_name)
        x = jax.ShapeDtypeStruct((1, ha, wa, hb, wb, c_in), dtype)
        w = jax.ShapeDtypeStruct((k,) * 4 + (c_in, c_out), dtype)
        jax.jit(_fwd_impl).lower(x, w).compile()
        return True
    except Exception:
        return False


def _kernel(*refs, k, c_in, c_out, wa, hb, wb, js):
    """One (b, i) step: refs = (x_0..x_{k-1}, w, out).

    x_p: VMEM (1, 1, wa+h, hb+h, (wb+h)*c_in) — input row i+p of the padded
         volume.
    w:   VMEM (k*c_in, k**3*c_out) ordered (q,c) × (p,r,s,o).
    out: VMEM (1, 1, wa, hb, wb*c_out).
    """
    x_refs, w_ref, out_ref = refs[:k], refs[k], refs[k + 1]
    h = k - 1
    k_n, l_n = hb + h, wb + h
    w = w_ref[:]
    # xt[p, j'', k', l', c]
    xt = jnp.stack(
        [x_refs[p][0, 0].reshape(wa + h, k_n, l_n, c_in) for p in range(k)],
        axis=0,
    )
    for j0 in range(0, wa, js):
        je = min(js, wa - j0)
        # P[(p, j, k', l'), (q, c)]: q-shifts gathered over the wa halo
        p_mat = jnp.stack(
            [xt[:, j0 + q:j0 + q + je] for q in range(k)], axis=4
        ).reshape(k * je * k_n * l_n, k * c_in)
        y = jax.lax.dot_general(
            p_mat, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(k, je, k_n, l_n, k ** 3 * c_out)
        # out[j,k,l,o] = Σ_{p,r,s} Y[p, j, k+r, l+s, (p,r,s,o)]
        acc = jnp.zeros((je, hb, wb * c_out), jnp.float32)
        for p in range(k):
            for r in range(k):
                for s in range(k):
                    lane0 = ((p * k + r) * k + s) * c_out
                    term = y[p, :, r:r + hb, s:s + wb, lane0:lane0 + c_out]
                    acc = acc + term.reshape(je, hb, wb * c_out)
        out_ref[0, 0, j0:j0 + je] = acc.astype(out_ref.dtype)


@jax.custom_vjp
def conv4d_small_cout(x, weight):
    """'Same'-padded 4D conv via the Pallas tap-folding kernel.

    Args:
      x: ``(B, hA, wA, hB, wB, C_in)`` volume.
      weight: ``(k, k, k, k, C_in, C_out)`` — one kernel size on all four
        dims (the only case the reference uses per layer).

    Returns ``(B, hA, wA, hB, wB, C_out)``.
    """
    return _fwd_impl(x, weight)


def _fwd_impl(x, weight, js: int = 1, interpret: bool = False):
    b, ha, wa, hb, wb, c_in = x.shape
    k = weight.shape[0]
    assert weight.shape[:4] == (k,) * 4, "kernel must be cubic (k,k,k,k)"
    assert k % 2 == 1, "same-padding requires an odd kernel size"
    c_out = weight.shape[5]
    h = k - 1

    # halo-pad every spatial dim; fuse (wb+h, c) as the minor dim
    xp = jnp.pad(
        x, ((0, 0),) + ((h // 2, h // 2),) * 4 + ((0, 0),)
    ).reshape(b, ha + h, wa + h, hb + h, (wb + h) * c_in)
    # W[(q, c), (p, r, s, o)]
    wf = jnp.transpose(weight, (1, 4, 0, 2, 3, 5)).reshape(
        k * c_in, k ** 3 * c_out
    )

    kern = functools.partial(
        _kernel, k=k, c_in=c_in, c_out=c_out, wa=wa, hb=hb, wb=wb, js=js,
    )
    row_spec = lambda p: pl.BlockSpec(  # noqa: E731
        (1, 1, wa + h, hb + h, (wb + h) * c_in),
        lambda bi, ii, p=p: (bi, ii + p, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    out = pl.pallas_call(
        kern,
        grid=(b, ha),
        in_specs=[row_spec(p) for p in range(k)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(
            (1, 1, wa, hb, wb * c_out),
            lambda bi, ii: (bi, ii, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, ha, wa, hb, wb * c_out), x.dtype),
        interpret=interpret,
    )(*([xp] * k), wf.astype(x.dtype))
    return out.reshape(b, ha, wa, hb, wb, c_out)


def _fwd_rule(x, weight):
    return _fwd_impl(x, weight), (x, weight)


def _bwd_rule(res, g):
    """XLA fallback backward (the kernel is an eval/bench fast path; training
    gradients flow through the equivalent ops/conv4d.py formulations)."""
    from ncnet_tpu.ops.conv4d import conv4d

    x, weight = res
    _, vjp = jax.vjp(
        lambda xx, ww: conv4d(xx, ww, variant="coutfold"), x, weight
    )
    return vjp(g)


conv4d_small_cout.defvjp(_fwd_rule, _bwd_rule)
