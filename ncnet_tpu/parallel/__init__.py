"""Distributed execution: device mesh, data-parallel and spatially-sharded paths."""

from ncnet_tpu.parallel.mesh import (
    DATA_AXIS,
    SPATIAL_AXIS,
    batch_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
    volume_sharding,
)
from ncnet_tpu.parallel.distributed import host_shard, initialize_distributed
from ncnet_tpu.parallel.spatial import (
    spatial_correlation,
    spatial_filter,
    spatial_forward,
)

__all__ = [
    "DATA_AXIS",
    "SPATIAL_AXIS",
    "batch_sharding",
    "host_shard",
    "initialize_distributed",
    "make_mesh",
    "replicate",
    "replicated",
    "shard_batch",
    "spatial_correlation",
    "spatial_filter",
    "spatial_forward",
    "volume_sharding",
]
