"""Distributed execution: device mesh, data-parallel and spatially-sharded paths."""

from ncnet_tpu.parallel.mesh import (
    DATA_AXIS,
    SPATIAL_AXIS,
    batch_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
    volume_sharding,
)

__all__ = [
    "DATA_AXIS",
    "SPATIAL_AXIS",
    "batch_sharding",
    "make_mesh",
    "replicate",
    "replicated",
    "shard_batch",
    "volume_sharding",
]
