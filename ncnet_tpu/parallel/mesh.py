"""Device mesh construction + sharding helpers.

The reference has no distributed code at all (SURVEY §2.4); this module is the
TPU-native foundation: one global mesh with two logical axes —

  * ``data``    — image pairs (data parallelism; gradients psum here)
  * ``spatial`` — the (hB, wB) dims of the 4D correlation volume
                  (sequence-parallel analog for high-res matching)

Built on ``jax.sharding.Mesh`` + ``NamedSharding``; jit consumes these
directly and XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"


def make_mesh(
    data: Optional[int] = None,
    spatial: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh over the available devices.

    ``data=None`` uses every device not consumed by ``spatial``.  The mesh is
    laid out so ``spatial`` is the minor (fastest-varying) axis: spatial
    shards of one pair-group sit on adjacent devices, keeping the halo/max
    collectives of the sharded volume on the shortest ICI paths.
    """
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devices) % spatial:
            raise ValueError(f"{len(devices)} devices not divisible by spatial={spatial}")
        data = len(devices) // spatial
    n = data * spatial
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(data, spatial)
    return Mesh(grid, (DATA_AXIS, SPATIAL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (pair) axis over 'data'; everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a ``(B, hA, wA, hB, wB)`` correlation volume: pairs over 'data',
    hB over 'spatial' (the ring-attention-style layout, SURVEY §5.7)."""
    return NamedSharding(mesh, P(DATA_AXIS, None, None, SPATIAL_AXIS, None))


def shard_batch(mesh: Mesh, batch):
    """Device-put a host batch (dict of arrays) with the pair axis sharded."""
    s = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)


def replicate(mesh: Mesh, tree):
    """Device-put a pytree fully replicated over the mesh."""
    s = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), tree)
