"""Multi-host initialization: ``jax.distributed`` over ICI/DCN.

The reference has no distributed backend at all (SURVEY §5.8 — no NCCL/MPI
anywhere); this is the TPU-native equivalent: one ``jax.distributed``
initialization per process, after which ``jax.devices()`` spans every host,
the global mesh covers the pod slice, and XLA routes collectives over
ICI within a slice / DCN across slices.

Usage (behind flags — single-host runs never touch this):

    from ncnet_tpu.parallel import initialize_distributed, host_shard
    initialize_distributed()            # env-driven (TPU pods auto-detect)
    loader = DataLoader(..., **host_shard())   # per-host input sharding
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the jax distributed runtime (idempotent).

    With no arguments, jax auto-detects the topology from the TPU pod
    environment; the explicit arguments serve CPU/GPU fleets or tests.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized = True


def host_shard() -> Dict[str, int]:
    """This process's slice of the input pipeline:
    ``DataLoader(..., **host_shard())`` gives each host a disjoint shard of
    every (globally-seeded, identically-shuffled) epoch."""
    return {
        "num_shards": jax.process_count(),
        "shard_index": jax.process_index(),
    }
