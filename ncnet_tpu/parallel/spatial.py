"""Spatially-sharded 4D-volume forward: the sequence-parallel analog.

NCNet's memory wall is the correlation volume — ``(B, hA, wA, hB, wB)`` is
quadratic in resolution (~56M cells/pair at InLoc settings, SURVEY §5.7).
The reference's only mitigations are single-device (fp16, maxpool4d,
resolution caps).  Here the volume is sharded over its ``hB`` dim across the
mesh's ``spatial`` axis, ring-attention style, and every stage of the
post-correlation pipeline runs shard-local with explicit collectives:

  * correlation     — local einsum against an hB-sharded feature map
  * maxpool4d       — shard-local (shard boundaries are multiples of k)
  * MutualMatching  — max over A dims is local; max over B dims is a
                      shard-local max + ``lax.pmax`` over 'spatial'
                      (reference semantics: lib/model.py:155-175)
  * conv4d          — halo exchange of k//2 hB-slabs via ``lax.ppermute``
                      (neighbor ICI links), then a *valid* conv along hB
                      (``conv4d(pad_hb=False)``); the symmetric pass
                      transposes A↔B, exchanges halos along the volume's
                      leading dim instead (``pad_ha=False``), and transposes
                      back — reference semantics: lib/model.py:122-153
  * match extraction— runs downstream on the shard_map output; XLA/GSPMD
                      inserts the gather/reductions it needs

Built on ``jax.experimental.shard_map`` over the global mesh
(parallel/mesh.py); global-edge shards receive zeros from ppermute's
non-wraparound permutation, which reproduces 'same' zero padding exactly.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ncnet_tpu.config import ModelConfig
from ncnet_tpu.models.ncnet import NCNetOutput, extract_features
from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.pooling import maxpool4d_with_argmax
from ncnet_tpu.parallel.mesh import DATA_AXIS, SPATIAL_AXIS

# (B, hA, wA, hB, wB) volume: pairs over 'data', hB over 'spatial'
VOLUME_SPEC = P(DATA_AXIS, None, None, SPATIAL_AXIS, None)
FEATURE_B_SPEC = P(DATA_AXIS, SPATIAL_AXIS, None, None)  # (B, hB, wB, C)
FEATURE_A_SPEC = P(DATA_AXIS, None, None, None)


def padded_hb(hb_fine: int, k_size: int, n_shards: int) -> Optional[int]:
    """Fine-grid hB after pad-to-shardable: the smallest multiple of
    ``n_shards·k`` ≥ ``hb_fine``.  ``None`` when padding cannot make the
    volume shardable exactly — ``hb_fine`` must itself be a multiple of
    ``k`` (otherwise the unsharded pooling's ragged final window would mix
    real and pad rows, and the sharded result could not match it)."""
    k = max(k_size, 1)
    if hb_fine % k != 0:
        return None
    step = n_shards * k
    return ((hb_fine + step - 1) // step) * step


def shardable_hb(
    hb_fine: int, k_size: int, n_shards: int, kernel_sizes
) -> bool:
    """Whether a volume whose fine-grid hB is ``hb_fine`` can shard over
    ``n_shards`` — directly, or by zero-padding hB up to the next
    ``n_shards·k`` multiple with the pad rows masked out of every max and
    conv (the r4 pad-and-mask path; the canonical InLoc fine hB=200 now
    8-way shards via pad-to-208).  Each local shard must still be at least
    one conv halo tall after padding.  The single source of truth for the
    gating policy — :func:`spatial_filter` enforces it and callers (e.g.
    the InLoc matcher's fallback) pre-check it."""
    k = max(k_size, 1)
    hb_pad = padded_hb(hb_fine, k_size, n_shards)
    if hb_pad is None:
        return False
    max_halo = max(ks // 2 for ks in kernel_sizes)
    return hb_pad // n_shards // k >= max_halo


def _halo_pad(x: jnp.ndarray, axis: int, halo: int, n_shards: int) -> jnp.ndarray:
    """Concatenate each shard's boundary slabs onto its neighbors along the
    sharded ``axis``: shard i prepends shard i−1's trailing ``halo`` slices
    and appends shard i+1's leading ones.  The permutation does not wrap, so
    edge shards receive zeros — exactly the 'same'-conv zero padding of the
    unsharded path."""
    if halo == 0:
        return x
    size = x.shape[axis]
    assert size >= halo, f"shard dim {size} smaller than halo {halo}"
    send_right = lax.slice_in_dim(x, size - halo, size, axis=axis)
    send_left = lax.slice_in_dim(x, 0, halo, axis=axis)
    from_left = lax.ppermute(
        send_right, SPATIAL_AXIS, [(i, i + 1) for i in range(n_shards - 1)]
    )
    from_right = lax.ppermute(
        send_left, SPATIAL_AXIS, [(i, i - 1) for i in range(1, n_shards)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=axis)


def _valid_rows_mask(
    rows_local: int, valid_rows: int, axis: int, ndim: int
) -> jnp.ndarray:
    """Shard-local boolean mask along the sharded ``axis``: True for global
    rows < ``valid_rows`` (real data), False for the pad-to-shardable tail.
    Shape is 1 everywhere except ``axis``."""
    idx = lax.axis_index(SPATIAL_AXIS)
    rows_global = idx * rows_local + jnp.arange(rows_local)
    shape = [1] * ndim
    shape[axis] = rows_local
    return (rows_global < valid_rows).reshape(shape)


def _mutual_matching_sharded(
    corr: jnp.ndarray,
    eps: float = 1e-5,
    valid_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Shard-local body of :func:`ncnet_tpu.ops.matching.mutual_matching`:
    the per-B-cell max over A dims sees full A locally; the per-A-cell max
    over B dims needs a pmax across the hB shards.

    ``valid_mask`` (pad-and-mask path): pad hB rows carry zeros and must not
    win the B-side max — they are −inf'd out of that reduction, and their
    own output stays exactly 0 (0/x · 0/y · 0)."""
    max_over_a = jnp.max(corr, axis=(1, 2), keepdims=True)
    b_src = corr
    if valid_mask is not None:
        b_src = jnp.where(valid_mask, corr, jnp.asarray(-jnp.inf, corr.dtype))
    max_over_b = lax.pmax(
        jnp.max(b_src, axis=(3, 4), keepdims=True), SPATIAL_AXIS
    )
    ratio_b = corr / (max_over_a + eps)
    ratio_a = corr / (max_over_b + eps)
    return corr * (ratio_a * ratio_b)


def _nc_stack_sharded(
    nc_params: List[dict],
    x: jnp.ndarray,
    sharded_axis: int,
    n_shards: int,
    valid_rows: Optional[int] = None,
) -> jnp.ndarray:
    """[Conv4d+ReLU]×N with per-layer halo exchange along ``sharded_axis``
    (1 = the volume's leading spatial dim, 3 = hB).

    ``valid_rows`` (pad-and-mask path): global row count of real data along
    the sharded axis.  The pad tail is re-zeroed after every conv+ReLU —
    each layer's conv must see zeros beyond the true boundary, exactly like
    the unsharded 'same' zero padding (a conv's bias + halo contributions
    would otherwise leak nonzero pad rows into the next layer)."""
    assert sharded_axis in (1, 3)
    mask = None
    if valid_rows is not None:
        mask = _valid_rows_mask(
            x.shape[sharded_axis], valid_rows, sharded_axis, x.ndim
        )
    for layer in nc_params:
        halo = layer["w"].shape[0] // 2
        x = _halo_pad(x, sharded_axis, halo, n_shards)
        x = conv4d(
            x, layer["w"], layer["b"],
            pad_ha=sharded_axis != 1, pad_hb=sharded_axis != 3,
        )
        x = jax.nn.relu(x)
        if mask is not None:
            x = jnp.where(mask, x, jnp.zeros((), x.dtype))
    return x


def _neigh_consensus_sharded(
    nc_params: List[dict],
    corr: jnp.ndarray,
    n_shards: int,
    symmetric: bool,
    valid_rows: Optional[int] = None,
) -> jnp.ndarray:
    """Stack-level symmetric NC filtering on an hB-sharded volume.

    Mirrors :func:`ncnet_tpu.models.ncnet.neigh_consensus`'s rectangular
    branch (numerical parity within float tolerance — the halo-padded conv
    shapes can make the variant chooser and reassociation differ from the
    unsharded program, so InLoc resume artifacts produced under different
    ``spatial_shards`` settings agree to tolerance, not bit-exactly):

      * measured shape class (2 cubic layers, 1-channel input): the
        symmetric pass runs tap-SWAPPED on x — no volume transposes, both
        stacks halo along the same sharded hB, and the fused double-width
        first layer needs ONE halo exchange for both passes;
      * otherwise: the transposed pass swaps (hA,wA)↔(hB,wB), which moves
        the sharded dim to position 1 — halos are exchanged there instead
        (model.py:144-150 semantics, sharded).
    """
    from ncnet_tpu.models.ncnet import tap_swap_fusable, tap_swap_fused_layers

    x = corr[..., None]
    if symmetric and tap_swap_fusable(nc_params):
        fused_l1, l2, l2s = tap_swap_fused_layers(nc_params)
        y = _nc_stack_sharded([fused_l1], x, 3, n_shards, valid_rows)
        # one halo exchange serves BOTH second-layer convs (the channel
        # halves share the same hB neighborhood)
        halo = l2["w"].shape[2] // 2
        yp = _halo_pad(y, 3, halo, n_shards)
        c = l2["w"].shape[4]
        out = jax.nn.relu(
            conv4d(yp[..., :c], l2["w"], l2["b"], pad_hb=False)
        ) + jax.nn.relu(
            conv4d(yp[..., c:], l2s["w"], l2s["b"], pad_hb=False)
        )
        if valid_rows is not None:
            out = jnp.where(
                _valid_rows_mask(out.shape[3], valid_rows, 3, out.ndim),
                out, jnp.zeros((), out.dtype),
            )
        return out[..., 0]
    out = _nc_stack_sharded(nc_params, x, 3, n_shards, valid_rows)
    if symmetric:
        xt = jnp.transpose(x, (0, 3, 4, 1, 2, 5))
        yt = _nc_stack_sharded(nc_params, xt, 1, n_shards, valid_rows)
        out = out + jnp.transpose(yt, (0, 3, 4, 1, 2, 5))
    return out[..., 0]


def spatial_filter(
    config: ModelConfig,
    params,
    corr: jnp.ndarray,
    mesh: Mesh,
    hb_valid: Optional[int] = None,
) -> NCNetOutput:
    """The post-correlation pipeline ([maxpool4d] → MutualMatching →
    NeighConsensus → MutualMatching) with the volume sharded over hB.

    Drop-in parallel twin of :func:`ncnet_tpu.models.ncnet.ncnet_filter`
    (parity-tested against it); call under ``jit`` with ``mesh`` holding a
    ``spatial`` axis of size > 1.

    When hB does not divide ``n_shards·k`` the volume is zero-padded along
    hB up to the next multiple (pad-and-mask): pad rows stay exactly zero
    through every stage — they are −inf'd out of the mutual-matching B-max
    and re-zeroed after each conv layer, so the real region computes the
    same function as the unsharded filter — and the output is sliced back
    to the true pooled hB.  The canonical InLoc shape (fine hB=200, k=2)
    8-way shards via pad-to-208 this way.
    """
    n_shards = mesh.shape[SPATIAL_AXIS]
    k = config.relocalization_k_size
    # hb_valid: true fine-grid rows when the CALLER already padded hB (the
    # sharded-correlation path pads the feature rows so the einsum shards)
    hb = hb_valid if hb_valid is not None else corr.shape[3]
    if not shardable_hb(hb, k, n_shards, config.ncons_kernel_sizes):
        raise ValueError(
            f"hB={hb} cannot shard over {n_shards} spatial shards (needs "
            f"hB divisible by k={max(k, 1)} and post-pad shards ≥ the conv "
            "halo); use fewer shards for this volume"
        )
    hb_pad = padded_hb(hb, k, n_shards)
    kk = max(k, 1)
    valid_rows = hb // kk if hb_pad > hb else None  # pooled-grid real rows
    if corr.shape[3] < hb_pad:
        corr = jnp.pad(
            corr, ((0, 0),) * 3 + ((0, hb_pad - corr.shape[3]), (0, 0))
        )
    assert corr.shape[3] == hb_pad, (
        f"corr hB={corr.shape[3]} inconsistent with padded plan {hb_pad}"
    )

    nc_params = params["nc"]
    if config.half_precision:
        nc_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), nc_params)
        corr = corr.astype(jnp.bfloat16)

    delta_spec = (VOLUME_SPEC,) * 4
    out_specs = (VOLUME_SPEC, delta_spec) if k > 1 else VOLUME_SPEC

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(), VOLUME_SPEC), out_specs=out_specs,
    )
    def run(nc, corr_loc):
        delta = None
        if k > 1:
            corr_loc, delta = maxpool4d_with_argmax(corr_loc, k)
        vmask = None
        if valid_rows is not None:
            vmask = _valid_rows_mask(
                corr_loc.shape[3], valid_rows, 3, corr_loc.ndim
            )
        corr_loc = _mutual_matching_sharded(corr_loc, valid_mask=vmask)
        corr_loc = _neigh_consensus_sharded(
            nc, corr_loc, n_shards, config.symmetric_mode, valid_rows
        )
        corr_loc = _mutual_matching_sharded(corr_loc, valid_mask=vmask)
        return (corr_loc, delta) if k > 1 else corr_loc

    result = run(nc_params, corr)
    corr_out, delta = result if k > 1 else (result, None)
    if valid_rows is not None:
        # slice the pad tail off so downstream match extraction sees the
        # true pooled grid (the global slice of a sharded value is fine
        # under jit; GSPMD re-shards as needed)
        corr_out = corr_out[:, :, :, :valid_rows, :]
        if delta is not None:
            delta = tuple(d[:, :, :, :valid_rows, :] for d in delta)
    return NCNetOutput(corr_out, delta)


def spatial_correlation(
    fa: jnp.ndarray, fb: jnp.ndarray, mesh: Mesh
) -> jnp.ndarray:
    """4D correlation with the output sharded over hB: each shard contracts
    the full (replicated) source features against its local hB feature rows —
    no communication at all (the all-to-all structure lives in the volume's
    sharding, not in collectives)."""

    @partial(
        shard_map, mesh=mesh,
        in_specs=(FEATURE_A_SPEC, FEATURE_B_SPEC), out_specs=VOLUME_SPEC,
    )
    def run(fa_loc, fb_loc):
        # f32 accumulation on the MXU regardless of feature dtype
        # (ops/correlation.py semantics)
        out = jnp.einsum(
            "bijc,bklc->bijkl", fa_loc, fb_loc,
            preferred_element_type=jnp.float32,
        )
        return out.astype(fa_loc.dtype)

    return run(fa, fb)


def spatial_forward(
    config: ModelConfig,
    params,
    source_images: jnp.ndarray,
    target_images: jnp.ndarray,
    mesh: Mesh,
) -> NCNetOutput:
    """Full forward with an hB-sharded volume: backbone features run
    replicated (they are ~3 orders of magnitude smaller than the volume),
    correlation + filtering run sharded.  Twin of
    :func:`ncnet_tpu.models.ncnet.ncnet_forward`."""
    fa = extract_features(config, params, source_images)
    fb = extract_features(config, params, target_images)
    if config.half_precision:
        fa = fa.astype(jnp.bfloat16)
        fb = fb.astype(jnp.bfloat16)
    # pad-and-mask: zero feature rows make exactly-zero correlation rows,
    # so the padded volume is born sharded instead of padded after the fact
    hb = fb.shape[1]
    hb_pad = padded_hb(
        hb, config.relocalization_k_size, mesh.shape[SPATIAL_AXIS]
    )
    if hb_pad is not None and hb_pad > hb:
        fb = jnp.pad(fb, ((0, 0), (0, hb_pad - hb), (0, 0), (0, 0)))
    corr = spatial_correlation(fa, fb, mesh)
    return spatial_filter(config, params, corr, mesh, hb_valid=hb)
