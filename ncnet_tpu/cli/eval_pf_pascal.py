"""CLI for PF-Pascal PCK evaluation.

Flag names/defaults mirror the reference (/root/reference/eval_pf_pascal.py:
27-30) so existing command lines keep working; --batch_size is a TPU-native
extension (the reference hard-codes 1, eval_pf_pascal.py:52-53).
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Compute PF Pascal matches")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--eval_dataset_path", type=str, default="datasets/pf-pascal/",
                   help="path to PF Pascal dataset")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--num_workers", type=int, default=0)
    p.add_argument("--backbone", type=str, default="resnet101",
                   help="used only when no checkpoint is given")
    p.add_argument("--backbone_weights", type=str, default="",
                   help="torchvision state_dict (.pth) for the trunk when no "
                        "checkpoint is given")
    p.add_argument("--pipeline_depth", type=int, default=0,
                   help="dispatch/fetch queue depth; 0 = adaptive (the "
                        "InLoc controller, per-batch wall caps)")
    p.add_argument("--host_normalize", action="store_true",
                   help="upload host-normalized float images instead of the "
                        "default resized-uint8 + on-device normalization "
                        "(exact reference numerics; 4x the transfer bytes)")
    return p


def main(argv=None) -> int:
    print("NCNet evaluation script - PF Pascal dataset")
    args = build_parser().parse_args(argv)
    # deferred imports: --help and flag errors shouldn't pay the jax startup
    from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
    from ncnet_tpu.evaluation import run_eval

    config = EvalPFPascalConfig(
        checkpoint=args.checkpoint,
        image_size=args.image_size,
        eval_dataset_path=args.eval_dataset_path,
    )
    stats = run_eval(
        config,
        model_config=ModelConfig(backbone=args.backbone,
                                 backbone_weights=args.backbone_weights),
        batch_size=args.batch_size,
        num_workers=args.num_workers,
        device_normalize=not args.host_normalize,
        pipeline_depth=args.pipeline_depth,
    )
    print("Total: " + str(stats["total"]))
    print("Valid: " + str(stats["valid"]))
    print("PCK:", "{:.2%}".format(stats["pck"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
