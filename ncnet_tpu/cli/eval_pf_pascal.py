"""CLI for PF-Pascal PCK evaluation.

Flag names/defaults mirror the reference (/root/reference/eval_pf_pascal.py:
27-30) so existing command lines keep working; --batch_size is a TPU-native
extension (the reference hard-codes 1, eval_pf_pascal.py:52-53).
"""

from __future__ import annotations

import argparse

from ncnet_tpu.cli.common import str_to_bool as _str_to_bool


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Compute PF Pascal matches")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--eval_dataset_path", type=str, default="datasets/pf-pascal/",
                   help="path to PF Pascal dataset")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--num_workers", type=int, default=0)
    p.add_argument("--backbone", type=str, default="resnet101",
                   help="used only when no checkpoint is given")
    p.add_argument("--backbone_weights", type=str, default="",
                   help="torchvision state_dict (.pth) for the trunk when no "
                        "checkpoint is given")
    p.add_argument("--pipeline_depth", type=int, default=0,
                   help="dispatch/fetch queue depth; 0 = adaptive (the "
                        "InLoc controller, per-batch wall caps)")
    p.add_argument("--host_normalize", action="store_true",
                   help="upload host-normalized float images instead of the "
                        "default resized-uint8 + on-device normalization "
                        "(exact reference numerics; 4x the transfer bytes)")
    p.add_argument("--journal_dir", type=str, default="",
                   help="journal per-batch PCK contributions + run manifest "
                        "here; a rerun with the same settings resumes "
                        "mid-eval to a bitwise-identical result")
    p.add_argument("--query_retries", type=int, default=2,
                   help="per-batch retries after the first dispatch/fetch "
                        "failure, before quarantine")
    p.add_argument("--retry_backoff_s", type=float, default=0.5,
                   help="retry backoff seconds, doubled per attempt")
    p.add_argument("--decode_retries", type=int, default=1,
                   help="per-image transient decode retries (the eval twin "
                        "of train.py's flag)")
    p.add_argument("--quarantine", type=_str_to_bool, default=True,
                   help="exhausted retries quarantine the batch (its pairs "
                        "score invalid) instead of aborting the run")
    p.add_argument("--fetch_timeout_s", type=float, default=0.0,
                   help="watchdog around each result fetch; a hung tunnel "
                        "becomes a retryable timeout (0 = off)")
    p.add_argument("--telemetry_dir", type=str, default="",
                   help="open a structured event log here (per-batch eval "
                        "events + metrics; replay with tools/run_report.py)")
    p.add_argument("--sparse_topk", type=int, default=0,
                   help="coarse-to-fine sparse matching: filter a pooled "
                        "coarse volume, keep the top-k candidate target "
                        "neighbourhoods per coarse source cell, and "
                        "evaluate fine correlation only there (0 = dense, "
                        "the default; README 'Coarse-to-fine matching')")
    return p


def main(argv=None) -> int:
    print("NCNet evaluation script - PF Pascal dataset")
    args = build_parser().parse_args(argv)
    # deferred imports: --help and flag errors shouldn't pay the jax startup
    from ncnet_tpu.config import EvalPFPascalConfig, ModelConfig
    from ncnet_tpu.evaluation import run_eval

    config = EvalPFPascalConfig(
        checkpoint=args.checkpoint,
        image_size=args.image_size,
        eval_dataset_path=args.eval_dataset_path,
        journal_dir=args.journal_dir,
        query_retries=args.query_retries,
        retry_backoff_s=args.retry_backoff_s,
        quarantine=args.quarantine,
        fetch_timeout_s=args.fetch_timeout_s,
        decode_retries=args.decode_retries,
        telemetry_dir=args.telemetry_dir,
        sparse_topk=args.sparse_topk,
    )
    stats = run_eval(
        config,
        model_config=ModelConfig(backbone=args.backbone,
                                 backbone_weights=args.backbone_weights),
        batch_size=args.batch_size,
        num_workers=args.num_workers,
        device_normalize=not args.host_normalize,
        pipeline_depth=args.pipeline_depth,
    )
    print("Total: " + str(stats["total"]))
    print("Valid: " + str(stats["valid"]))
    print("PCK:", "{:.2%}".format(stats["pck"]))
    degraded = False
    if stats.get("quarantined_batches"):
        print("Quarantined batches: " + str(stats["quarantined_batches"]))
        degraded = True
    if stats.get("decode_quarantined"):
        print("Undecodable images (pairs scored invalid): "
              + str(stats["decode_quarantined"]))
        degraded = True
    # degraded result: exit nonzero so CI / schedulers notice even though
    # the run itself survived
    return 2 if degraded else 0


if __name__ == "__main__":
    raise SystemExit(main())
