"""Shared CLI helpers."""

from __future__ import annotations

import argparse


def str_to_bool(v: str) -> bool:
    """Boolean flag parser, reference lib/torch_util.py:64-70 semantics."""
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")
