"""CLI for weak-supervision training.

Flag names/defaults mirror the reference (/root/reference/train.py:34-47);
--backbone/--num_workers/--seed are TPU-native extensions.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Compute PF Pascal matches")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--dataset_image_path", type=str, default="datasets/pf-pascal/",
                   help="path to PF Pascal dataset")
    p.add_argument("--dataset_csv_path", type=str,
                   default="datasets/pf-pascal/image_pairs/",
                   help="path to PF Pascal training csv")
    p.add_argument("--num_epochs", type=int, default=5)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.0005)
    p.add_argument("--ncons_kernel_sizes", nargs="+", type=int, default=[5, 5, 5],
                   help="kernels sizes in neigh. cons.")
    p.add_argument("--ncons_channels", nargs="+", type=int, default=[16, 16, 1],
                   help="channels in neigh. cons")
    p.add_argument("--result_model_fn", type=str, default="checkpoint_adam")
    p.add_argument("--result-model-dir", dest="result_model_dir", type=str,
                   default="trained_models")
    p.add_argument("--fe_finetune_params", type=int, default=0,
                   help="number of backbone blocks to finetune")
    p.add_argument("--finetune_cp_rank", type=int, default=0,
                   help="decompose the (loaded) NC kernels to rank-R CP "
                        "factors and fine-tune the FACTORS with the trunk "
                        "frozen (tools/cp_decompose.py recipe); 0 = dense "
                        "training")
    p.add_argument("--backbone", type=str, default="resnet101")
    p.add_argument("--backbone_weights", type=str, default="",
                   help="torchvision state_dict (.pth) to initialize the trunk "
                        "(the reference always starts from ImageNet weights)")
    p.add_argument("--num_workers", type=int, default=0)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--half_precision", action="store_true",
                   help="bf16 volume + NC weights during training")
    p.add_argument("--remat_nc_layers", action="store_true",
                   help="rematerialize each NC layer in the backward — "
                        "fits batch 16 (with --half_precision) on one 16G "
                        "chip at ~30%% step-time cost")
    p.add_argument("--nc_custom_grad", action="store_true",
                   help="conv4d custom VJP: ~45%% less backward temp memory "
                        "at ~18%% step-time cost (the other memory knob)")
    p.add_argument("--accum_chunks", type=int, default=-1,
                   help="volume-chunked gradient accumulation (frozen trunk "
                        "only): -1 auto (default, the fastest measured "
                        "backward — any batch fits one 16G chip), 0 "
                        "whole-batch backward, >1 explicit chunk count")
    p.add_argument("--fold_pos_neg", action="store_true",
                   help="run the positive+negative volumes through ONE "
                        "2B-batch NC-filter call instead of two B-sized "
                        "calls (identical math; only applies with "
                        "--accum_chunks 0 — the chunked path already folds "
                        "the 2B volume batch).  Measured NO faster on the "
                        "r4 XLA backward; bench.py now measures it on the "
                        "Pallas-VJP path so the default can flip on "
                        "evidence")
    p.add_argument("--no_nc_pallas_vjp", action="store_true",
                   help="disable the resident Pallas NC backward (round 7 "
                        "training default where the shape class compiles) "
                        "and keep the XLA conv4d formulations under "
                        "value_and_grad")
    # fault tolerance (see the training/train.py module docstring)
    p.add_argument("--checkpoint_steps", type=int, default=0,
                   help="also checkpoint every N train steps (atomic "
                        "step_<N> versions with a mid-epoch resume "
                        "position); 0 = epoch-end saves only")
    p.add_argument("--keep_checkpoints", type=int, default=3,
                   help="retention window of step_<N> checkpoint versions "
                        "(the best_ copy is separate and never pruned)")
    p.add_argument("--max_bad_steps", type=int, default=3,
                   help="abort after this many CONSECUTIVE non-finite-loss "
                        "steps (each one is skipped, keeping the bad batch "
                        "out of Adam state)")
    p.add_argument("--no_nan_guard", action="store_true",
                   help="disable the jitted non-finite-loss guard (saves "
                        "one host sync per step; a NaN then poisons Adam "
                        "state, as in the reference)")
    p.add_argument("--decode_retries", type=int, default=1,
                   help="transient per-image decode retries before a sample "
                        "is quarantined")
    p.add_argument("--fail_on_bad_samples", action="store_true",
                   help="crash on an undecodable image instead of "
                        "quarantining it and substituting the next healthy "
                        "sample")
    # observability (README "Observability")
    p.add_argument("--no_telemetry", action="store_true",
                   help="disable the structured event log + heartbeat + "
                        "device snapshots (on by default; replay the log "
                        "with tools/run_report.py)")
    p.add_argument("--telemetry_dir", type=str, default="",
                   help="where the event log + heartbeat live (default: "
                        "<checkpoint root>/telemetry, so crash/resume "
                        "cycles of one lineage share one log)")
    return p


def main(argv=None) -> int:
    print("ImMatchNet training script")
    args = build_parser().parse_args(argv)
    print(args)

    from ncnet_tpu.config import ModelConfig, TrainConfig
    from ncnet_tpu.training import fit

    config = TrainConfig(
        model=ModelConfig(
            backbone=args.backbone,
            backbone_weights=args.backbone_weights,
            ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
            ncons_channels=tuple(args.ncons_channels),
            checkpoint=args.checkpoint,
            half_precision=args.half_precision,
        ),
        image_size=args.image_size,
        dataset_image_path=args.dataset_image_path,
        dataset_csv_path=args.dataset_csv_path,
        num_epochs=args.num_epochs,
        batch_size=args.batch_size,
        lr=args.lr,
        result_model_fn=args.result_model_fn,
        result_model_dir=args.result_model_dir,
        fe_finetune_params=args.fe_finetune_params,
        finetune_cp_rank=args.finetune_cp_rank,
        seed=args.seed,
        num_workers=args.num_workers,
        remat_nc_layers=args.remat_nc_layers,
        nc_custom_grad=args.nc_custom_grad,
        accum_chunks=args.accum_chunks,
        fold_pos_neg=args.fold_pos_neg,
        nc_pallas_vjp=not args.no_nc_pallas_vjp,
        checkpoint_steps=args.checkpoint_steps,
        keep_checkpoints=args.keep_checkpoints,
        max_bad_steps=args.max_bad_steps,
        nan_guard=not args.no_nan_guard,
        decode_retries=args.decode_retries,
        quarantine_decode_errors=not args.fail_on_bad_samples,
        telemetry=not args.no_telemetry,
        telemetry_dir=args.telemetry_dir,
    )
    fit(config)
    print("Done!")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
