"""CLI for the InLoc localization stage — the Python equivalent of the
reference's MATLAB driver (compute_densePE_NCNet.m), with its parameters
(score threshold 0.75, PnP threshold 0.2°, top-10, optional densePV) exposed
as flags."""

from __future__ import annotations

import argparse

import numpy as np

from ncnet_tpu.cli.common import str_to_bool as _str_to_bool


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="InLoc localization from NCNet matches "
        "(PnP + optional pose verification + curves)"
    )
    p.add_argument("--matches_dir", type=str, required=True,
                   help="matches/<experiment> directory from eval_inloc")
    p.add_argument("--shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18.mat")
    p.add_argument("--query_path", type=str,
                   default="datasets/inloc/query/iphone7/")
    p.add_argument("--cutout_path", type=str, default="datasets/inloc/pano/",
                   help="cutout images + their XYZcut depth .mat files")
    p.add_argument("--scan_path", type=str, default="datasets/inloc/scans/")
    p.add_argument("--transformation_path", type=str, default="datasets/inloc/")
    p.add_argument("--refposes", type=str,
                   default="datasets/inloc/DUC_refposes_all.mat")
    p.add_argument("--output_dir", type=str, default="outputs_localization")
    p.add_argument("--pnp_topN", type=int, default=10)
    p.add_argument("--thr", type=float, default=0.75,
                   help="match score threshold (params.ncnet.thr)")
    p.add_argument("--pnp_thr", type=float, default=0.2,
                   help="RANSAC inlier threshold, degrees (params.ncnet.pnp_thr)")
    p.add_argument("--ransac_iters", type=int, default=10000)
    p.add_argument("--do_densePV", type=_str_to_bool, default=True)
    p.add_argument("--query_focal_length", type=float, default=0.0,
                   help="query focal in pixels; 0 = iPhone 7 EXIF default")
    p.add_argument("--n_queries", type=int, default=0, help="0 = all")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--num_workers", type=int, default=0,
                   help="process-pool width for the PnP (per-query) and "
                        "pose-verification (per-scan) stages — the "
                        "reference's two parfor loops; 0 = in-process")
    p.add_argument("--query_retries", type=int, default=2,
                   help="per-query PnP retries after the first failure, "
                        "before quarantine")
    p.add_argument("--retry_backoff_s", type=float, default=0.5,
                   help="retry backoff seconds, doubled per attempt")
    p.add_argument("--quarantine", type=_str_to_bool, default=True,
                   help="exhausted retries quarantine the query into the "
                        "stage manifest (it scores as not-localized) "
                        "instead of aborting the stage")
    return p


def main(argv=None) -> int:
    print("NCNet localization - InLoc dataset")
    args = build_parser().parse_args(argv)
    from ncnet_tpu.config import LocalizationConfig
    from ncnet_tpu.localization.driver import run_localization

    config = LocalizationConfig(
        matches_dir=args.matches_dir,
        shortlist=args.shortlist,
        query_path=args.query_path,
        cutout_path=args.cutout_path,
        scan_path=args.scan_path,
        transformation_path=args.transformation_path,
        refposes=args.refposes,
        output_dir=args.output_dir,
        pnp_topN=args.pnp_topN,
        match_score_thr=args.thr,
        pnp_inlier_thr_deg=args.pnp_thr,
        ransac_iters=args.ransac_iters,
        do_pose_verification=args.do_densePV,
        query_focal_length=args.query_focal_length,
        n_queries=args.n_queries,
        seed=args.seed,
        num_workers=args.num_workers,
        query_retries=args.query_retries,
        retry_backoff_s=args.retry_backoff_s,
        quarantine=args.quarantine,
    )
    print(args)
    curves = run_localization(config)
    from ncnet_tpu.localization.curves import ERROR_THRESHOLDS

    for desc, curve in curves.items():
        at_05 = curve[np.abs(ERROR_THRESHOLDS - 0.5).argmin()]
        at_10 = curve[np.abs(ERROR_THRESHOLDS - 1.0).argmin()]
        print(f"{desc}: localized @0.5m {at_05 * 100:.1f}%  "
              f"@1.0m {at_10 * 100:.1f}%")
    print("Outputs in " + config.output_dir)
    from ncnet_tpu.localization.driver import pnp_stage_degraded

    if pnp_stage_degraded(config):
        # degraded result (quarantined PnP queries): exit nonzero so CI /
        # schedulers notice; a rerun retries them
        print("warning: PnP stage has quarantined queries (see its "
              "manifest.json); curves are partial")
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
