"""CLI for InLoc dense-matching evaluation.

Flag names/defaults mirror the reference (/root/reference/eval_inloc.py:29-40)
so existing command lines keep working; --output_root and --spatial_shards are
TPU-native extensions.
"""

from __future__ import annotations

import argparse


def _str_to_bool(v: str) -> bool:
    # reference lib/torch_util.py:64-70 semantics
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Compute InLoc matches")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--inloc_shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18.mat")
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--image_size", type=int, default=3200)
    p.add_argument("--n_queries", type=int, default=356)
    p.add_argument("--n_panos", type=int, default=10)
    p.add_argument("--softmax", type=_str_to_bool, default=True)
    p.add_argument("--matching_both_directions", type=_str_to_bool, default=True)
    p.add_argument("--flip_matching_direction", type=_str_to_bool, default=False)
    p.add_argument("--pano_path", type=str, default="datasets/inloc/pano/",
                   help="path to InLoc panos - should contain CSE3,CSE4,CSE5,"
                        "DUC1 and DUC2 folders")
    p.add_argument("--query_path", type=str, default="datasets/inloc/query/iphone7/",
                   help="path to InLoc queries")
    p.add_argument("--output_root", type=str, default="matches")
    p.add_argument("--spatial_shards", type=int, default=1,
                   help="shard the 4D volume over this many devices")
    p.add_argument("--pipeline_depth", type=int, default=0,
                   help="dispatch/fetch pipeline depth (0 = adaptive to the "
                        "link's latency regime; >0 pins it)")
    p.add_argument("--host_index", type=int, default=-1,
                   help="stripe queries across hosts: this host's index "
                        "(-1 = auto from jax.process_index)")
    p.add_argument("--host_count", type=int, default=0,
                   help="total hosts striping queries (0 = auto)")
    p.add_argument("--skip_existing", type=_str_to_bool, default=True,
                   help="resume: skip queries whose output .mat exists")
    return p


def main(argv=None) -> int:
    print("NCNet evaluation script - InLoc dataset")
    args = build_parser().parse_args(argv)
    # deferred imports: --help and flag errors shouldn't pay the jax startup
    from ncnet_tpu.config import EvalInLocConfig
    from ncnet_tpu.evaluation.inloc import output_folder_name, run_inloc_eval

    config = EvalInLocConfig(
        checkpoint=args.checkpoint,
        inloc_shortlist=args.inloc_shortlist,
        k_size=args.k_size,
        image_size=args.image_size,
        n_queries=args.n_queries,
        n_panos=args.n_panos,
        softmax=args.softmax,
        matching_both_directions=args.matching_both_directions,
        flip_matching_direction=args.flip_matching_direction,
        pano_path=args.pano_path,
        query_path=args.query_path,
        output_root=args.output_root,
        spatial_shards=args.spatial_shards,
        pipeline_depth=args.pipeline_depth,
        host_index=args.host_index,
        host_count=args.host_count,
        skip_existing=args.skip_existing,
    )
    print(args)
    print("Output matches folder: " + output_folder_name(config))
    out_dir = run_inloc_eval(config)
    print("Wrote matches to " + out_dir)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
