"""CLI for InLoc dense-matching evaluation.

Flag names/defaults mirror the reference (/root/reference/eval_inloc.py:29-40)
so existing command lines keep working; --output_root and --spatial_shards are
TPU-native extensions.
"""

from __future__ import annotations

import argparse

from ncnet_tpu.cli.common import str_to_bool as _str_to_bool


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Compute InLoc matches")
    p.add_argument("--checkpoint", type=str, default="")
    p.add_argument("--inloc_shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18.mat")
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--image_size", type=int, default=3200)
    p.add_argument("--n_queries", type=int, default=356)
    p.add_argument("--n_panos", type=int, default=10)
    p.add_argument("--softmax", type=_str_to_bool, default=True)
    p.add_argument("--matching_both_directions", type=_str_to_bool, default=True)
    p.add_argument("--flip_matching_direction", type=_str_to_bool, default=False)
    p.add_argument("--pano_path", type=str, default="datasets/inloc/pano/",
                   help="path to InLoc panos - should contain CSE3,CSE4,CSE5,"
                        "DUC1 and DUC2 folders")
    p.add_argument("--query_path", type=str, default="datasets/inloc/query/iphone7/",
                   help="path to InLoc queries")
    p.add_argument("--output_root", type=str, default="matches")
    p.add_argument("--spatial_shards", type=int, default=1,
                   help="shard the 4D volume over this many devices")
    p.add_argument("--pipeline_depth", type=int, default=0,
                   help="dispatch/fetch pipeline depth (0 = adaptive to the "
                        "link's latency regime; >0 pins it)")
    p.add_argument("--host_index", type=int, default=-1,
                   help="stripe queries across hosts: this host's index "
                        "(-1 = auto from jax.process_index)")
    p.add_argument("--host_count", type=int, default=0,
                   help="total hosts striping queries (0 = auto)")
    p.add_argument("--skip_existing", type=_str_to_bool, default=True,
                   help="resume: skip queries whose output .mat exists")
    p.add_argument("--validate_existing", type=_str_to_bool, default=True,
                   help="loadmat-validate an existing .mat before skipping "
                        "it, so a foreign/truncated artifact is recomputed")
    p.add_argument("--query_retries", type=int, default=2,
                   help="per-query retries after the first failure, before "
                        "quarantine")
    p.add_argument("--retry_backoff_s", type=float, default=0.5,
                   help="retry backoff seconds, doubled per attempt")
    p.add_argument("--quarantine", type=_str_to_bool, default=True,
                   help="exhausted retries quarantine the query into "
                        "manifest.json instead of aborting the run")
    p.add_argument("--fetch_timeout_s", type=float, default=0.0,
                   help="watchdog around each pair fetch; a hung tunnel "
                        "becomes a retryable timeout (0 = off)")
    p.add_argument("--telemetry_dir", type=str, default="",
                   help="open a structured event log here (per-query events "
                        "+ metrics; replay with tools/run_report.py)")
    p.add_argument("--feature_store_dir", type=str, default="",
                   help="persistent database-side feature store: cache pano "
                        "backbone features here (verified, crash-safe; see "
                        "README 'Feature store'); bulk-build with "
                        "tools/build_feature_store.py")
    p.add_argument("--sparse_topk", type=int, default=0,
                   help="coarse-to-fine sparse matching (requires --k_size "
                        "1; 0 = dense, the default — README 'Coarse-to-fine "
                        "matching')")
    p.add_argument("--feature_store_budget_mb", type=int, default=0,
                   help="LRU-evict store entries above this many MiB "
                        "(0 = unbounded)")
    return p


def main(argv=None) -> int:
    print("NCNet evaluation script - InLoc dataset")
    args = build_parser().parse_args(argv)
    # deferred imports: --help and flag errors shouldn't pay the jax startup
    from ncnet_tpu.config import EvalInLocConfig
    from ncnet_tpu.evaluation.inloc import output_folder_name, run_inloc_eval

    config = EvalInLocConfig(
        checkpoint=args.checkpoint,
        inloc_shortlist=args.inloc_shortlist,
        k_size=args.k_size,
        image_size=args.image_size,
        n_queries=args.n_queries,
        n_panos=args.n_panos,
        softmax=args.softmax,
        matching_both_directions=args.matching_both_directions,
        flip_matching_direction=args.flip_matching_direction,
        pano_path=args.pano_path,
        query_path=args.query_path,
        output_root=args.output_root,
        spatial_shards=args.spatial_shards,
        pipeline_depth=args.pipeline_depth,
        host_index=args.host_index,
        host_count=args.host_count,
        skip_existing=args.skip_existing,
        validate_existing=args.validate_existing,
        query_retries=args.query_retries,
        retry_backoff_s=args.retry_backoff_s,
        quarantine=args.quarantine,
        fetch_timeout_s=args.fetch_timeout_s,
        telemetry_dir=args.telemetry_dir,
        feature_store_dir=args.feature_store_dir,
        feature_store_budget_mb=args.feature_store_budget_mb,
        sparse_topk=args.sparse_topk,
    )
    print(args)
    print("Output matches folder: " + output_folder_name(config))
    out_dir = run_inloc_eval(config)
    print("Wrote matches to " + out_dir)
    # degraded result (quarantined queries in THIS host's manifest — not a
    # glob, which would read sibling stripes' or stale prior runs' files):
    # exit nonzero so CI / schedulers notice even though the run survived
    import os as _os

    from ncnet_tpu.evaluation.inloc import manifest_name, resolve_host_stripe
    from ncnet_tpu.evaluation.resilience import manifest_has_quarantined

    if config.write_manifest and manifest_has_quarantined(
            _os.path.join(out_dir, manifest_name(*resolve_host_stripe(config)))):
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
