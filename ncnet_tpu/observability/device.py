"""Device introspection + heartbeat: liveness signals for external watchdogs.

Two complementary signals:

  * :func:`device_snapshot` / :class:`DeviceMonitor` — periodic
    ``jax.local_devices()`` + per-device ``memory_stats()`` snapshots
    emitted as ``device_snapshot`` events, so a replayed run shows HBM
    pressure alongside the step/tier timeline (a tier demotion under
    RESOURCE_EXHAUSTED becomes attributable, not mysterious).
  * :class:`Heartbeat` — a tiny JSON file whose mtime is bumped atomically
    (temp + ``os.replace``) at every training step.  The contract for
    external watchdogs: *mtime age > a few step walls ⇒ the process is
    stalled or dead* — readable with ``stat`` alone, no JSON parse, no jax,
    no shared memory with the watched process.  The payload (step, pid,
    run id, time) is for the human who shows up next.

Both are fail-open: a snapshot or beat that cannot be taken degrades to
nothing — liveness reporting must never be the thing that kills the run.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ncnet_tpu.observability import events as _events
from ncnet_tpu.utils.profiling import annotate


def device_snapshot() -> List[Dict[str, Any]]:
    """One dict per local device: id/kind/platform (+ memory stats where the
    backend exposes them; CPU backends typically do not)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no initialized backend = no snapshot
        return []
    out: List[Dict[str, Any]] = []
    for d in devices:
        entry: Dict[str, Any] = {
            "id": int(d.id),
            "kind": str(d.device_kind),
            "platform": str(d.platform),
        }
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — optional per-backend API
            stats = None
        if stats:
            # keep the numbers watchdogs and the memory plane act on (the
            # full dict is large and backend-specific): the three pressure
            # watermarks, plus the reservation and largest-free-block
            # figures where the backend exposes them — without those two,
            # fragmentation (plenty of free bytes, no block big enough for
            # a correlation volume) is invisible
            for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use",
                        "bytes_reserved", "largest_free_block_bytes"):
                if key in stats:
                    entry[key] = int(stats[key])
        out.append(entry)
    return out


class DeviceMonitor:
    """Rate-limited ``device_snapshot`` event emitter.

    ``maybe_emit(step=...)`` snapshots at most once per ``every_s`` seconds
    (the first call always emits, so every instrumented run records its
    device inventory even if it dies young)."""

    def __init__(self, every_s: float = 60.0):
        self.every_s = float(every_s)
        self._last: Optional[float] = None

    def maybe_emit(self, step: Optional[int] = None) -> bool:
        now = time.monotonic()
        if self._last is not None and now - self._last < self.every_s:
            return False
        self._last = now
        with annotate("device_snapshot"):
            snap = device_snapshot()
        _events.emit("device_snapshot", devices=snap,
                     **({"step": step} if step is not None else {}))
        return True


class Heartbeat:
    """Atomic-mtime heartbeat file (see the module docstring contract).

    ``beat()`` writes ``{"time", "pid", "step", "run"}`` to a temp file and
    ``os.replace``s it over ``path`` — the mtime bump and the payload are
    one atomic unit, so a reader never sees a torn document and the mtime
    never moves without a consistent payload behind it."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def beat(self, step: Optional[int] = None, **fields) -> None:
        doc = {"time": time.time(), "pid": os.getpid()}
        if step is not None:
            doc["step"] = int(step)
        if self.run_id:
            doc["run"] = self.run_id
        doc.update(fields)
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            # fail-open: a beat that cannot land (disk full) must not kill
            # the step it reports on; the watchdog sees a stale mtime and
            # that is the correct signal for a host whose disk is gone
            try:
                os.remove(tmp)
            except OSError:
                pass

    @staticmethod
    def read(path: str) -> Optional[Dict[str, Any]]:
        """The last beat's payload, or None (missing/unreadable)."""
        try:
            with open(path) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    @staticmethod
    def age_s(path: str) -> Optional[float]:
        """Seconds since the last beat (mtime-based — the watchdog's one
        syscall), or None when the file is missing."""
        try:
            return max(0.0, time.time() - os.stat(path).st_mtime)
        except OSError:
            return None
