"""Prometheus text-format exposition of the in-process metrics objects.

PRs 5-7 built the write side of telemetry (event log, registry, digests) and
PRs 6-7 the *replay* side (run_report, perf store) — both after-the-fact.
This module is the LIVE read side's wire format: it renders any
:class:`~ncnet_tpu.observability.metrics.MetricsRegistry` snapshot (or
hand-built metric families) as Prometheus exposition text (version 0.0.4),
the format every scraping stack (Prometheus, VictoriaMetrics, Grafana
agent, or just ``curl``) ingests natively.  ``serving/introspect.py``
serves the result on ``/metrics``; ``tools/serve_top.py`` and the tier-1
scrape-validation tests read it back through :func:`parse_prometheus`.

Contract highlights (the tests pin these):

  * **Counters are monotonic across scrapes** — a ``Counter``'s value only
    ever increments, and the renderer never rebases or resets it, so two
    scrapes under load always satisfy ``v2 >= v1`` per series.
  * **Histograms are cumulative** — each fixed-bin
    :class:`~ncnet_tpu.observability.metrics.Histogram` renders as
    ``_bucket{le="<edge>"}`` series with cumulative counts, a final
    ``le="+Inf"`` bucket equal to ``_count``, plus ``_sum``/``_count``
    consistent with the in-process digest.  (Edge-bin clamping means the
    first/last finite buckets absorb out-of-range observations — counted,
    never lost, exactly like the digest itself.)
  * **Label escaping** — label values escape ``\\``, ``"`` and newlines per
    the exposition spec; metric names are sanitized to the legal charset
    (bucket labels like ``64x64-96x64`` ride as LABELS, never as name
    fragments).

Like every telemetry layer here, rendering is fail-open by construction: it
only reads plain snapshots, holds no locks, and raises nothing for a metric
it cannot represent (it skips it).
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ncnet_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary registry key to a legal Prometheus metric name
    (illegal characters → ``_``, leading digit prefixed).  Curated
    exporters should prefer labels over name-mangling; this is the
    fallback that keeps the GENERIC registry dump legal."""
    if _NAME_OK.match(name):
        return name
    out = _NAME_BAD_CHARS.sub("_", name)
    if out[:1].isdigit():
        out = "_" + out
    return out or "_"


def escape_label_value(value: Any) -> str:
    """Exposition-format label-value escaping: backslash, double quote,
    newline (in that order — escaping the escapes first)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: Any) -> str:
    """One sample value: integers render bare, floats shortest-round-trip,
    non-finite as the spec's ``+Inf``/``-Inf``/``NaN``."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Family:
    """One metric family: a name, a TYPE, optional HELP, and its samples.

    ``add(value, **labels)`` appends one sample; ``suffix`` covers the
    histogram/summary series (``_bucket``/``_sum``/``_count``) that share
    the family name."""

    def __init__(self, name: str, kind: str, help: str = ""):
        if kind not in ("counter", "gauge", "histogram", "summary",
                        "untyped"):
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = sanitize_metric_name(name)
        self.kind = kind
        self.help = help
        self.samples: List[Tuple[str, Dict[str, Any], float]] = []

    def add(self, value: Any, suffix: str = "", **labels: Any) -> "Family":
        self.samples.append((self.name + suffix, dict(labels), value))
        return self

    def add_histogram(self, hist: Histogram, **labels: Any) -> "Family":
        """Append one :class:`Histogram` digest as cumulative ``_bucket``
        series + ``_sum``/``_count`` under the given labels.  The bin
        counts are copied ONCE and the ``+Inf`` bucket / ``_count`` derive
        from that copy, so ``le="+Inf" == _count == sum(buckets)`` holds
        even when a writer lands mid-scrape."""
        counts = list(hist.counts)
        cum = 0
        for edge, n in zip(hist.bucket_edges(), counts):
            cum += n
            self.add(cum, suffix="_bucket",
                     **{**labels, "le": format_value(edge)})
        self.add(cum, suffix="_bucket", **{**labels, "le": "+Inf"})
        self.add(hist.sum, suffix="_sum", **labels)
        self.add(cum, suffix="_count", **labels)
        return self


def render(families: Iterable[Family]) -> str:
    """Render families as one exposition document (trailing newline
    included, as scrapers expect)."""
    lines: List[str] = []
    for fam in families:
        if not fam.samples:
            continue
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for name, labels, value in fam.samples:
            if labels:
                body = ",".join(
                    f'{sanitize_metric_name(str(k))}='
                    f'"{escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{body}}} {format_value(value)}")
            else:
                lines.append(f"{name} {format_value(value)}")
    return "\n".join(lines) + "\n"


def registry_families(registry: MetricsRegistry,
                      prefix: str = "ncnet") -> List[Family]:
    """The GENERIC renderer: every metric in a registry becomes one family
    (counters → ``<prefix>_<name>_total``, gauges → gauge, timers →
    summary with a p50 quantile, histograms → cumulative histogram).
    Curated exporters (``serving/introspect.py``) build label-structured
    families instead; this covers everything else so any registry can be
    scraped with zero per-metric code."""
    fams: List[Family] = []
    with registry._lock:
        items = sorted(registry._metrics.items())
    for name, m in items:
        base = f"{prefix}_{sanitize_metric_name(name)}"
        if isinstance(m, Counter):
            fams.append(Family(base + "_total", "counter").add(m.value))
        elif isinstance(m, Gauge):
            if m.value is not None:
                try:
                    fams.append(Family(base, "gauge").add(float(m.value)))
                except (TypeError, ValueError):
                    continue  # a non-numeric gauge cannot be plotted
        elif isinstance(m, Timer):
            if not m.count:
                continue
            fam = Family(base + "_seconds", "summary")
            snap = m.snapshot()
            if "p50_s" in snap:
                fam.add(snap["p50_s"], quantile="0.5")
            fam.add(m.total_s, suffix="_sum")
            fam.add(m.count, suffix="_count")
            fams.append(fam)
        elif isinstance(m, Histogram):
            if m.count:
                fams.append(Family(base, "histogram").add_histogram(m))
    return fams


# ---------------------------------------------------------------------------
# the read side: a minimal exposition parser (serve_top + the scrape tests)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<v>(?:[^"\\]|\\.)*)"\s*,?')


def _unescape(v: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(s: str) -> float:
    low = s.lower()
    if low in ("+inf", "inf"):
        return math.inf
    if low == "-inf":
        return -math.inf
    if low == "nan":
        return math.nan
    return float(s)


def parse_prometheus(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse one exposition document into
    ``{family_name: {"type": ..., "help": ..., "samples":
    [(series_name, labels_dict, value), ...]}}``.

    A sample series like ``x_bucket``/``x_sum``/``x_count`` files under its
    ``# TYPE``'d family name when one precedes it, else under its own
    name.  Raises ``ValueError`` on a malformed sample line — the scrape
    tests WANT a hard failure, a tolerant parser would mask a renderer
    bug."""
    families: Dict[str, Dict[str, Any]] = {}
    current: Optional[str] = None

    def fam(name: str) -> Dict[str, Any]:
        return families.setdefault(
            name, {"type": "untyped", "help": "", "samples": []})

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            fam(name)["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            fam(name)["type"] = kind.strip()
            current = name
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition sample line: {raw!r}")
        sname = m.group("name")
        labels: Dict[str, str] = {}
        body = m.group("labels")
        if body is not None:
            pos = 0
            while pos < len(body):
                lm = _LABEL_RE.match(body, pos)
                if lm is None:
                    if body[pos:].strip():
                        raise ValueError(
                            f"malformed label body in: {raw!r}")
                    break
                labels[lm.group("k")] = _unescape(lm.group("v"))
                pos = lm.end()
        value = _parse_value(m.group("value"))
        home = current if current is not None and (
            sname == current or sname.startswith(current + "_")) else sname
        fam(home)["samples"].append((sname, labels, value))
    return families


def histogram_percentile(bucket_samples: Sequence[Tuple[str, Dict[str, Any],
                                                        float]],
                         q: float) -> Optional[float]:
    """Approximate q-th percentile (0-100) from one series' cumulative
    ``_bucket`` samples (the serve_top read-side twin of
    ``Histogram.percentile``): linear interpolation inside the winning
    bucket, lower edge taken from the previous bucket's ``le``."""
    edges: List[Tuple[float, float]] = []
    for name, labels, value in bucket_samples:
        if not name.endswith("_bucket") or "le" not in labels:
            continue
        edges.append((_parse_value(str(labels["le"])), value))
    edges.sort(key=lambda p: p[0])
    if not edges or edges[-1][1] <= 0:
        return None
    total = edges[-1][1]
    target = q / 100.0 * total
    prev_edge, prev_cum = None, 0.0
    for edge, cum in edges:
        if cum >= target and cum > prev_cum:
            if math.isinf(edge):
                return prev_edge  # the overflow bucket has no upper edge
            lo = prev_edge if prev_edge is not None and \
                not math.isinf(prev_edge) else 0.0
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + frac * (edge - lo)
        prev_edge, prev_cum = edge, cum
    return edges[-1][0] if not math.isinf(edges[-1][0]) else None
