"""Unified run telemetry: event log, metrics registry, leveled logging,
device introspection + heartbeat.

Wired through training (``training/train.py``), eval
(``evaluation/pf_pascal.py`` / ``inloc.py``), ops tiering
(``ops/nc_fused_lane*.py``) and the resilience layer
(``evaluation/resilience.py``).  ``tools/run_report.py`` replays the event
logs into a run report; ``tools/check_no_bare_print.py`` (tier-1 enforced)
keeps library modules on the structured logger.  See README
"Observability" for the event schema and knobs.
"""

from ncnet_tpu.observability.events import (  # noqa: F401
    SCHEMA_VERSION,
    EventLog,
    bound,
    emit,
    get_global_sink,
    git_revision,
    make_run_id,
    replay_events,
    run_envelope,
    set_global_sink,
)
from ncnet_tpu.observability.logging import (  # noqa: F401
    LOG_LEVEL_ENV,
    Logger,
    get_logger,
)
from ncnet_tpu.observability.metrics import (  # noqa: F401
    PEAK_BF16_TFLOPS,
    PEAK_HBM_GBPS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
    device_peak_tflops,
    filter_flops,
    train_step_flops,
)
from ncnet_tpu.observability.device import (  # noqa: F401
    DeviceMonitor,
    Heartbeat,
    device_snapshot,
)
from ncnet_tpu.observability.tracing import (  # noqa: F401
    current_span_id,
    span,
    traced,
)
from ncnet_tpu.observability.quality import (  # noqa: F401
    QUALITY_SIGNALS,
    active_tier,
    emit_quality,
    quality_signals,
    quality_table,
)
from ncnet_tpu.observability.perfstore import (  # noqa: F401
    PerfStore,
    check_regressions,
    maybe_record,
    metric_direction,
    resolve_store_path,
)
from ncnet_tpu.observability.memory import (  # noqa: F401
    LeakSentinel,
    hbm_stats,
    is_oom,
    ledger_rows,
    live_array_census,
    predicted_footprint_bytes,
    record_program,
    report_oom,
)

__all__ = [
    "SCHEMA_VERSION",
    "EventLog",
    "bound",
    "emit",
    "get_global_sink",
    "git_revision",
    "make_run_id",
    "replay_events",
    "run_envelope",
    "set_global_sink",
    "LOG_LEVEL_ENV",
    "Logger",
    "get_logger",
    "PEAK_BF16_TFLOPS",
    "PEAK_HBM_GBPS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Timer",
    "device_peak_tflops",
    "filter_flops",
    "train_step_flops",
    "DeviceMonitor",
    "Heartbeat",
    "device_snapshot",
    "current_span_id",
    "span",
    "traced",
    "QUALITY_SIGNALS",
    "active_tier",
    "emit_quality",
    "quality_signals",
    "quality_table",
    "PerfStore",
    "check_regressions",
    "maybe_record",
    "metric_direction",
    "resolve_store_path",
    "LeakSentinel",
    "hbm_stats",
    "is_oom",
    "ledger_rows",
    "live_array_census",
    "predicted_footprint_bytes",
    "record_program",
    "report_oom",
]
