"""Structured event log: the machine-readable twin of the console output.

PR 1-4 built fault-tolerant training, resilient eval, and a tiered Pallas
stack — but every signal those layers emit (NaN-guard skips, tier demotions,
quarantines, retries, step walls) was an unstructured stdout line that died
with the terminal.  This module gives every run a durable, replayable trace:

  * :class:`EventLog` — append-only JSONL.  Line 1 is a schema-versioned
    header carrying the run envelope (run id, host, pid, device kinds);
    every later line is one typed event ``{"t": ..., "run": ..., "seq": ...,
    "event": ..., **fields}``.  Appends are flushed+fsynced and a process
    killed mid-append leaves at worst a torn trailing line that
    :func:`replay_events` detects and drops — the same discipline (and the
    same fault-injection proof obligations) as
    ``evaluation/resilience.EvalJournal``.
  * Resume lineage: re-opening an existing log with a matching schema
    APPENDS (each run/resume contributes its own ``run_start`` /
    ``resume`` events under a fresh run id), so one file holds the whole
    crash/resume history of a training root and
    ``tools/run_report.py`` can reconstruct it.  A schema-mismatched or
    foreign file is set aside as ``<path>.stale``, never destroyed.
  * A process-global sink (:func:`set_global_sink` / :func:`emit` /
    :func:`bound`) so deep layers — the ops tier registry, the resilience
    retry loop, the data loader — can emit events without threading a log
    handle through every signature.  ``emit`` is a no-op returning after one
    ``is None`` check when no sink is bound: library code pays nothing in
    un-instrumented processes (the ``utils/faults.py`` hook discipline).

Telemetry must never kill the run it observes: a failing global-sink append
(disk full, revoked mount) disables the sink and reports through stderr
instead of raising into the training loop.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

SCHEMA_VERSION = 1
_LOG_KIND = "ncnet_tpu_events"

# injected wall-clock skew (seconds), read once at import.  Every wall
# stamp this process publishes — event `t` fields, the header envelope,
# the wire's clock-sync request/response stamps — goes through
# :func:`wall_now`, so setting NCNET_TPU_CLOCK_SKEW_S makes the process
# behave exactly like a host whose clock is off by that much: the chaos
# seam the pod-federation tests use to prove skew correction end to end.
try:
    _WALL_SKEW_S = float(os.environ.get("NCNET_TPU_CLOCK_SKEW_S", "") or 0.0)
except ValueError:
    _WALL_SKEW_S = 0.0


def wall_now() -> float:
    """This process's wall clock as published in telemetry: ``time.time()``
    plus the injected test skew (``NCNET_TPU_CLOCK_SKEW_S``, normally 0).
    Every cross-host comparison (event ``t``, clock-sync stamps) MUST use
    this, never ``time.time()`` directly — otherwise an injected skew would
    shift some stamps and not others and the federation math would be
    unverifiable."""
    return time.time() + _WALL_SKEW_S


def make_run_id() -> str:
    """Unique-enough run id: seconds + pid + random suffix (readable in the
    log, collision-safe across hosts restarting in the same second)."""
    import secrets

    return f"{int(time.time()):x}-{os.getpid():x}-{secrets.token_hex(3)}"


def run_envelope(run_id: Optional[str] = None) -> Dict[str, Any]:
    """The who/where envelope stamped into headers, ``run_start`` events and
    bench artifacts: schema version, run id, host, pid, and the device
    kinds jax sees (absent when jax is not importable/initialized — the
    envelope must be buildable from tools that never touch an accelerator).
    """
    env: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id or make_run_id(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "time": wall_now(),
    }
    try:
        import jax

        devices = jax.local_devices()
        env["device_kind"] = devices[0].device_kind if devices else None
        env["device_count"] = len(devices)
        env["process_index"] = jax.process_index()
    except Exception:  # noqa: BLE001 — tools without jax still get an envelope
        pass
    return env


def local_device_kind() -> Optional[str]:
    """The local accelerator kind (``devices[0].device_kind``), or None when
    no backend is reachable — the ONE fail-open probe every cross-run
    consumer (envelope, perf store, tier cache) keys device entries by, so
    they can never silently key under different kinds."""
    try:
        import jax

        devices = jax.local_devices()
        return str(devices[0].device_kind) if devices else None
    except Exception:  # noqa: BLE001 — no backend = no kind, never a crash
        return None


def git_revision(repo_dir: Optional[str] = None) -> Optional[str]:
    """Short git revision of ``repo_dir`` (default: this package's repo),
    or None outside a work tree — bench stamps it into its envelope so a
    metrics artifact is attributable to the exact code that produced it."""
    import subprocess

    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", repo_dir, "rev-parse", "--short", "HEAD"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def _parse_lines(raw: bytes) -> Tuple[Optional[dict], List[dict], int]:
    """Shared tail-tolerant JSONL parse: ``(header, records, good_bytes)``.

    ``header`` is None when line 1 is missing/torn/foreign.  ``good_bytes``
    is the offset of the end of the last newline-TERMINATED line — the
    truncation point for an appender (a newline-less tail is dropped even if
    it parses; see EvalJournal._load for the full argument).  Undecodable
    terminated lines mid-file are skipped, not fatal: records are
    independent and a torn-then-sealed write must not poison later events.
    """
    lines = raw.split(b"\n")
    if len(lines) < 2 or not lines[0]:
        return None, [], 0
    try:
        head = json.loads(lines[0])
    except ValueError:
        head = None
    if not isinstance(head, dict) or head.get("kind") != _LOG_KIND:
        return None, [], 0
    good_bytes = len(lines[0]) + 1
    records: List[dict] = []
    for i, line in enumerate(lines[1:], start=2):
        if i == len(lines):
            break  # the unterminated tail (or the clean-file b"")
        good_bytes += len(line) + 1
        if not line:
            continue  # a sealing newline after a repaired torn write
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn-but-terminated line: skip, keep later records
        if isinstance(rec, dict):
            records.append(rec)
    return head, records, good_bytes


def replay_events(path: str) -> Tuple[Dict[str, Any], List[dict]]:
    """Replay an event log from disk: ``(header, events)``.

    Torn-tail tolerant (a process killed mid-append loses at most the
    partial trailing line).  Raises ``FileNotFoundError`` for a missing
    file and ``ValueError`` for a file that is not an ncnet_tpu event log
    or whose schema version this code does not read.
    """
    with open(path, "rb") as f:
        raw = f.read()
    head, records, _ = _parse_lines(raw)
    if head is None:
        raise ValueError(f"{path} is not an ncnet_tpu event log")
    if head.get("schema", 0) > SCHEMA_VERSION:
        raise ValueError(
            f"{path} has schema {head.get('schema')}, newer than this "
            f"reader ({SCHEMA_VERSION})"
        )
    return head, records


class EventLog:
    """Append-only, schema-versioned, crash-safe event log (JSONL).

    Opening a path that already holds a compatible log APPENDS under a new
    run id (the resume lineage); a foreign/newer-schema file is set aside
    as ``<path>.stale`` and a fresh log started.  Every append is
    flushed+fsynced and seals any torn previous write with a newline first,
    exactly like ``EvalJournal`` — the kill-mid-append fault hook
    (``faults.event_kill_hook``) proves the replay contract in-test.
    """

    def __init__(self, path: str, run_meta: Optional[dict] = None,
                 run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id or make_run_id()
        self._seq = 0
        self._appends = 0
        self._dirty = False
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        good_bytes = 0
        if os.path.exists(path) and os.path.getsize(path):
            with open(path, "rb") as f:
                raw = f.read()
            head, _, good_bytes = _parse_lines(raw)
            if head is None or head.get("schema", 0) > SCHEMA_VERSION:
                # never destroy what might be another run's data: set the
                # unreadable file aside and start fresh
                stale = path + ".stale"
                os.replace(path, stale)
                _warn_stderr(f"event log {path} is foreign or "
                             f"newer-schema; set aside as {stale}")
                good_bytes = 0
        if good_bytes:
            # truncate the torn tail BEFORE appending so the next record
            # starts on a fresh line (same contract as EvalJournal)
            with open(path, "rb+") as f:
                f.truncate(good_bytes)
            self._f = open(path, "a")
        else:
            self._f = open(path, "w")
            header = {
                "kind": _LOG_KIND,
                "header": {**run_envelope(self.run_id),
                           **({"meta": run_meta} if run_meta else {})},
            }
            self._write_raw(json.dumps(header, sort_keys=True) + "\n")

    def _write_raw(self, text: str) -> None:
        # _dirty spans the write: a failure part-way may land a torn prefix
        # on disk, and the NEXT append must start on a fresh line
        self._dirty = True
        self._f.write(text)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = text[-1:] != "\n"

    def emit(self, event: str, **fields) -> None:
        """Append one typed event.  Crash-safe: the record is either fully
        on disk (fsynced) or detectably torn on replay."""
        from ncnet_tpu.utils import faults

        rec = {"t": wall_now(), "run": self.run_id, "seq": self._seq,
               "event": str(event)}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._dirty:
                self._write_raw("\n")  # seal a torn previous write
            self._seq += 1
            self._appends += 1
            # injected SIGKILL mid-append: a torn prefix is flushed first,
            # so the replayed log must prove partial-trailing-line tolerance
            faults.event_kill_hook(
                self._appends,
                lambda: self._write_raw(line[: max(1, len(line) // 2)]),
            )
            self._write_raw(line + "\n")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _jsonable(v):
    """Coerce one field to a JSON-serializable value.  Numpy scalars/arrays
    and other exotic types must degrade to something representable rather
    than abort the append (telemetry never kills the run)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        # a non-finite float is valid Python but not strict JSON
        if isinstance(v, float) and v != v:
            return "nan"
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return _jsonable(v.item())
        if isinstance(v, np.ndarray):
            return [_jsonable(x) for x in v.tolist()]
    except ImportError:  # pragma: no cover - numpy is a hard dep in-repo
        pass
    try:
        f = float(v)  # jax scalars land here without importing jax
        return _jsonable(f)
    except (TypeError, ValueError):
        return repr(v)


# ---------------------------------------------------------------------------
# process-global sink: deep layers (ops tiering, resilience, the loader)
# emit without a log handle; no-op when nothing is bound
# ---------------------------------------------------------------------------


_sink: Optional[EventLog] = None


def set_global_sink(log: Optional[EventLog]) -> Optional[EventLog]:
    """Bind ``log`` as the process-global event sink; returns the previous
    sink (callers restore it — or use :func:`bound`)."""
    global _sink
    prev = _sink
    _sink = log
    return prev


def get_global_sink() -> Optional[EventLog]:
    return _sink


def _warn_stderr(msg: str) -> None:
    import sys

    sys.stderr.write(f"[telemetry] {msg}\n")


def emit(event: str, **fields) -> None:
    """Emit to the global sink, if bound.  A failing append (disk full,
    revoked mount) unbinds the sink and reports to stderr — telemetry must
    never crash the run it observes."""
    global _sink
    if _sink is None:
        return
    try:
        _sink.emit(event, **fields)
    except (OSError, ValueError) as e:
        # OSError: disk full / revoked mount; ValueError: a closed file
        # (I/O on closed file) — either way the sink is unusable
        _sink = None
        _warn_stderr(f"event sink failed ({e}); telemetry disabled for the "
                     "rest of the process")


@contextlib.contextmanager
def bound(log: Optional[EventLog]) -> Iterator[Optional[EventLog]]:
    """``with bound(log):`` — global sink bound inside, restored after."""
    prev = set_global_sink(log)
    try:
        yield log
    finally:
        set_global_sink(prev)
