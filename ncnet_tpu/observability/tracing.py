"""Hierarchical span tracing: the read-side structure PR 5's flat events lack.

The PR 5 event log records *that* things happened (steps, commits, retries);
it cannot say *where the time went inside* one step or one eval batch.  Spans
add that structure without a new sink: a span is a named interval with a
``span_id``/``parent_id`` pair, emitted as ordinary events into the bound
:class:`~ncnet_tpu.observability.events.EventLog`, so the existing replay,
torn-tail and resume-lineage machinery applies unchanged and
``tools/trace_export.py`` can render any event log as a Chrome trace
(Perfetto-viewable) after the fact.

Design constraints, in order:

  1. **Crash visibility** — a span emits TWO events: ``span`` with
     ``ph="B"`` at entry and ``ph="E"`` (with ``dur_s``) at exit.  A process
     SIGKILLed mid-span leaves the ``B`` on disk (fsynced like every
     append), so the torn trace still shows *what was in flight when the
     process died* — exit-only emission would silently drop exactly the
     spans a postmortem needs most.
  2. **Zero unbound cost** — entering a span when no sink is bound is one
     ``is None`` check; no stack is maintained, nothing is allocated beyond
     the context manager itself.  Library code can annotate hot paths
     unconditionally (the ``events.emit`` discipline).
  3. **Thread correctness** — the parent relation comes from a per-thread
     stack (``threading.local``), so the eval pipelines' drain callbacks and
     the decode-ahead workers nest correctly within their own thread and
     never adopt another thread's parent.  The thread id is stamped on the
     ``B`` event so the exporter can lay spans out per track.

Span ids are process-unique monotonic ints; the event envelope's ``run``
field (stamped by the sink) disambiguates across resume lineages appending
to one file, so consumers key spans by ``(run, span)``.

**Pod scope** — spans and request timelines are process-local; a pod-wide
request needs an identity that survives the wire.  :class:`TraceContext`
is that identity: a W3C-traceparent-style triple (``trace_id``, the
sender's ``parent_span``, the ``origin`` host) rendered as one additive
header string (``serving/wire.py`` / ``retrieval/wire.py`` carry it; old
peers never read the key).  The router stamps a fresh context per admitted
request — or adopts one a caller already propagated — and every event a
request touches downstream (``route_*``, ``serve_*``, ``request_timeline``,
``retrieve_*``) carries the trace id, so ``tools/trace_export.py
--federate`` can stitch a router slice to its backend and shard slices
across N logs, and ``tools/run_report.py --pod`` can prove the pod-scope
outcome identity from the merged logs alone.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Optional

from ncnet_tpu.observability import events as _events

_ids = itertools.count(1)  # next() is atomic in CPython; no lock needed
_tls = threading.local()

# traceparent header version.  Like the wire schema byte, but SOFT: an
# unknown version parses as no-trace (the request still serves; it is
# merely untraced) — a trace header must never make a request fail.
TRACE_VERSION = "00"


class TraceContext:
    """One pod-wide request identity: ``trace_id`` (32 hex chars, minted at
    the stamping tier), the sender's ``parent_span`` (a process-local span
    id, or None), and the ``origin`` host that stamped the trace."""

    __slots__ = ("trace_id", "parent_span", "origin")

    def __init__(self, trace_id: str, parent_span: Optional[int] = None,
                 origin: Optional[str] = None):
        import socket

        self.trace_id = str(trace_id)
        self.parent_span = parent_span
        self.origin = origin if origin is not None else socket.gethostname()

    def to_header(self) -> str:
        """The wire form: ``00-<trace_id>-<parent_span hex>-<origin>``.
        The origin rides LAST so a hostname containing ``-`` still parses
        (the reader splits at most three times)."""
        parent = (f"{self.parent_span:x}"
                  if isinstance(self.parent_span, int) else "0")
        return f"{TRACE_VERSION}-{self.trace_id}-{parent}-{self.origin}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.to_header()!r})"


def new_trace(origin: Optional[str] = None) -> TraceContext:
    """Mint a fresh trace: 16 random bytes as 32 hex chars (the W3C
    trace-id width), parented to this thread's innermost open span (None
    outside any span)."""
    import os as _os

    return TraceContext(_os.urandom(16).hex(),
                        parent_span=current_span_id(), origin=origin)


def parse_trace(header: Optional[str]) -> Optional[TraceContext]:
    """Tolerant read of a wire trace header: a :class:`TraceContext`, or
    None for anything this build does not understand (missing, malformed,
    unknown version).  NEVER raises — an unreadable trace header must cost
    the caller nothing but the trace."""
    if not header or not isinstance(header, str):
        return None
    parts = header.split("-", 3)
    if len(parts) != 4 or parts[0] != TRACE_VERSION or not parts[1]:
        return None
    try:
        parent = int(parts[2], 16) or None
    except ValueError:
        return None
    return TraceContext(parts[1], parent_span=parent, origin=parts[3])


def trace_id_of(header: Optional[str]) -> Optional[str]:
    """Just the trace id out of a wire header (the field events carry), or
    None when the header does not parse."""
    ctx = parse_trace(header)
    return ctx.trace_id if ctx is not None else None


def adopt_trace(value, origin: Optional[str] = None) -> TraceContext:
    """The router's stamp-or-adopt step: a caller-provided context (a
    :class:`TraceContext`, a wire header, or a bare id) becomes THE
    context; anything unusable mints a fresh trace.  Always returns a
    context — at the stamping tier every admitted request is traced."""
    if isinstance(value, TraceContext):
        return value
    if value:
        ctx = parse_trace(str(value))
        if ctx is not None:
            return ctx
        return TraceContext(str(value), parent_span=current_span_id(),
                            origin=origin)
    return new_trace(origin)


def normalize_trace(value) -> Optional[str]:
    """What the serving tiers stamp on events: the bare trace id out of
    whatever a caller handed them — a :class:`TraceContext`, a full wire
    header, an already-bare id, or nothing.  Tolerant like
    :func:`parse_trace`; a junk value degrades to itself as an opaque id
    rather than raising (the trace is telemetry, never control flow)."""
    if value is None:
        return None
    if isinstance(value, TraceContext):
        return value.trace_id
    s = str(value)
    return trace_id_of(s) or (s or None)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span_id() -> Optional[int]:
    """The innermost open span id on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class span:
    """``with span("dispatch", step=3): ...`` — one traced interval.

    Emits ``span``/``ph="B"`` on entry and ``span``/``ph="E"`` (carrying the
    monotonic ``dur_s``) on exit; extra keyword fields ride on the ``B``
    event.  Inert (single sink check, no stack traffic) when no event sink
    is bound at entry; if the sink disappears mid-span the ``E`` is dropped
    by ``emit`` and the exporter treats the span as unclosed — the same
    degradation as a crash, never an error.
    """

    __slots__ = ("name", "fields", "_id", "_parent", "_t0")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self._id: Optional[int] = None

    def __enter__(self) -> "span":
        if _events.get_global_sink() is None:
            return self  # inert: _id stays None and __exit__ is one check
        st = _stack()
        self._parent = st[-1] if st else None
        self._id = next(_ids)
        st.append(self._id)
        self._t0 = time.perf_counter()
        _events.emit(
            "span", ph="B", name=self.name, span=self._id,
            parent=self._parent, tid=threading.get_ident(), **self.fields,
        )
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._id is None:
            return
        dur = time.perf_counter() - self._t0
        st = _stack()
        # normally a plain pop; identity removal tolerates a caller that
        # closed spans out of order (telemetry must never raise into the run)
        if st and st[-1] == self._id:
            st.pop()
        elif self._id in st:
            st.remove(self._id)
        fields = {"ph": "E", "name": self.name, "span": self._id,
                  "dur_s": round(dur, 6)}
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        _events.emit("span", **fields)


def traced(name: Optional[str] = None, **fields):
    """Decorator form: ``@traced("pnp_query")`` wraps the call in a span
    (default name: the function's ``__name__``)."""

    def deco(fn):
        span_name = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(span_name, **fields):
                return fn(*args, **kwargs)

        return wrapper

    return deco
