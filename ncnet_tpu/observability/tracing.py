"""Hierarchical span tracing: the read-side structure PR 5's flat events lack.

The PR 5 event log records *that* things happened (steps, commits, retries);
it cannot say *where the time went inside* one step or one eval batch.  Spans
add that structure without a new sink: a span is a named interval with a
``span_id``/``parent_id`` pair, emitted as ordinary events into the bound
:class:`~ncnet_tpu.observability.events.EventLog`, so the existing replay,
torn-tail and resume-lineage machinery applies unchanged and
``tools/trace_export.py`` can render any event log as a Chrome trace
(Perfetto-viewable) after the fact.

Design constraints, in order:

  1. **Crash visibility** — a span emits TWO events: ``span`` with
     ``ph="B"`` at entry and ``ph="E"`` (with ``dur_s``) at exit.  A process
     SIGKILLed mid-span leaves the ``B`` on disk (fsynced like every
     append), so the torn trace still shows *what was in flight when the
     process died* — exit-only emission would silently drop exactly the
     spans a postmortem needs most.
  2. **Zero unbound cost** — entering a span when no sink is bound is one
     ``is None`` check; no stack is maintained, nothing is allocated beyond
     the context manager itself.  Library code can annotate hot paths
     unconditionally (the ``events.emit`` discipline).
  3. **Thread correctness** — the parent relation comes from a per-thread
     stack (``threading.local``), so the eval pipelines' drain callbacks and
     the decode-ahead workers nest correctly within their own thread and
     never adopt another thread's parent.  The thread id is stamped on the
     ``B`` event so the exporter can lay spans out per track.

Span ids are process-unique monotonic ints; the event envelope's ``run``
field (stamped by the sink) disambiguates across resume lineages appending
to one file, so consumers key spans by ``(run, span)``.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Optional

from ncnet_tpu.observability import events as _events

_ids = itertools.count(1)  # next() is atomic in CPython; no lock needed
_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span_id() -> Optional[int]:
    """The innermost open span id on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class span:
    """``with span("dispatch", step=3): ...`` — one traced interval.

    Emits ``span``/``ph="B"`` on entry and ``span``/``ph="E"`` (carrying the
    monotonic ``dur_s``) on exit; extra keyword fields ride on the ``B``
    event.  Inert (single sink check, no stack traffic) when no event sink
    is bound at entry; if the sink disappears mid-span the ``E`` is dropped
    by ``emit`` and the exporter treats the span as unclosed — the same
    degradation as a crash, never an error.
    """

    __slots__ = ("name", "fields", "_id", "_parent", "_t0")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self._id: Optional[int] = None

    def __enter__(self) -> "span":
        if _events.get_global_sink() is None:
            return self  # inert: _id stays None and __exit__ is one check
        st = _stack()
        self._parent = st[-1] if st else None
        self._id = next(_ids)
        st.append(self._id)
        self._t0 = time.perf_counter()
        _events.emit(
            "span", ph="B", name=self.name, span=self._id,
            parent=self._parent, tid=threading.get_ident(), **self.fields,
        )
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._id is None:
            return
        dur = time.perf_counter() - self._t0
        st = _stack()
        # normally a plain pop; identity removal tolerates a caller that
        # closed spans out of order (telemetry must never raise into the run)
        if st and st[-1] == self._id:
            st.pop()
        elif self._id in st:
            st.remove(self._id)
        fields = {"ph": "E", "name": self.name, "span": self._id,
                  "dur_s": round(dur, 6)}
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        _events.emit("span", **fields)


def traced(name: Optional[str] = None, **fields):
    """Decorator form: ``@traced("pnp_query")`` wraps the call in a span
    (default name: the function's ``__name__``)."""

    def deco(fn):
        span_name = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(span_name, **fields):
                return fn(*args, **kwargs)

        return wrapper

    return deco
