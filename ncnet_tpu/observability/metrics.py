"""Metrics registry: counters / gauges / timers flushed into the event log.

One registry instance per run scope (a training run, an eval run, a bench
invocation).  The registry is deliberately tiny — the durable format is the
event log's ``metrics`` records (and bench's enveloped JSON line), not an
in-process object model:

  * :class:`Counter` — monotone event counts (NaN skips, retries,
    quarantines, checkpoint commits);
  * :class:`Gauge`   — last-value metrics (loss, MFU, pipeline depth);
  * :class:`Timer`   — wall accumulation with count/total/last/min/max
    (step walls, decode/dispatch/fetch splits, host→device staging).

``snapshot()`` renders everything to plain floats/ints; ``flush()`` emits
one ``metrics`` event carrying the snapshot (through a given
:class:`~ncnet_tpu.observability.events.EventLog` or the global sink).

The training MFU helpers live here too: ``train_step_flops`` is the
6×-filter-FLOP algorithmic basis (a pos+neg weak step = 2 symmetric filter
forwards + a ~2×-forward backward; backbone/correlation/score are <5%) and
``PEAK_BF16_TFLOPS``/``PEAK_HBM_GBPS`` are the public per-device-kind peaks
— shared with bench.py so the bench artifact and run telemetry can never
disagree on the denominator.
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence

from ncnet_tpu.observability import events as _events

# bf16 peak TFLOP/s by device kind (public specs) — THE MFU denominator,
# shared by bench.py and the per-step training scope
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5": 459.0,        # v5p
    "TPU v6 lite": 918.0,   # v6e (Trillium)
}

# HBM bandwidth GB/s by device kind (public specs), for rooflines
PEAK_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,   # v5e
    "TPU v5": 2765.0,       # v5p
    "TPU v6 lite": 1640.0,  # v6e
}


def filter_flops(feat_side: int, kernels: Sequence[int],
                 channels: Sequence[int]) -> float:
    """True per-pair FLOPs of the SYMMETRIC NC filter (both volume
    directions) at a square ``feat_side`` — the constant algorithmic-MFU
    numerator (README "MFU accounting"); ~281.2 GFLOP at the PF-Pascal
    bench arch (25⁴ volume, k=5³, 16/16/1 channels)."""
    cells = (feat_side * feat_side) ** 2
    chans = list(zip((1,) + tuple(channels[:-1]), channels))
    return 2 * cells * sum(
        2 * (k ** 4) * ci * co for k, (ci, co) in zip(kernels, chans)
    )


def train_step_flops(feat_side: int, kernels: Sequence[int],
                     channels: Sequence[int]) -> float:
    """Per-pair FLOPs of one weak-supervision train step on the
    6×-filter-FLOP algorithmic basis (2 filter forwards for pos+neg +
    a ~2×-forward backward each)."""
    return 6.0 * filter_flops(feat_side, kernels, channels)


def device_peak_tflops() -> Optional[float]:
    """bf16 peak of the local device kind, or None (CPU, unknown kinds) —
    callers skip MFU metrics rather than emit garbage."""
    try:
        import jax

        return PEAK_BF16_TFLOPS.get(jax.local_devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — no backend = no MFU, never a crash
        return None


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> int:
        self.value += int(n)
        return self.value


class Gauge:
    def __init__(self):
        self.value: Optional[float] = None

    def set(self, v) -> None:
        self.value = v


class Timer:
    """Accumulates wall intervals; use as a context manager or feed measured
    seconds via :meth:`observe` (the eval loops already hold their own
    ``perf_counter`` deltas).

    Keeps a bounded window of recent observations so :meth:`snapshot` can
    report ``p50_s``: for step walls the MEAN is dominated by the first
    step's compile (seconds vs milliseconds), which makes runs of different
    lengths incomparable — the median is what cross-run consumers (the perf
    store gate) should ingest."""

    _WINDOW = 1024  # recent observations kept for the median

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.last_s: Optional[float] = None
        self.min_s: Optional[float] = None
        self.max_s: Optional[float] = None
        self._recent: deque = deque(maxlen=self._WINDOW)

    def observe(self, seconds: float) -> None:
        s = float(seconds)
        self.count += 1
        self.total_s += s
        self.last_s = s
        self.min_s = s if self.min_s is None else min(self.min_s, s)
        self.max_s = s if self.max_s is None else max(self.max_s, s)
        self._recent.append(s)

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.observe(time.perf_counter() - self._t0)

    def snapshot(self) -> Dict[str, float]:
        out = {"count": self.count, "total_s": round(self.total_s, 6)}
        for k in ("last_s", "min_s", "max_s"):
            v = getattr(self, k)
            if v is not None:
                out[k] = round(v, 6)
        if self.count:
            out["mean_s"] = round(self.total_s / self.count, 6)
        if self._recent:
            out["p50_s"] = round(statistics.median(self._recent), 6)
        return out


class Histogram:
    """Fixed-bin histogram digest: bounded-memory distribution tracking.

    The quality-observability layer (``observability/quality.py``) streams
    per-pair match-quality signals through these so an eval-scale run can
    report percentiles and feed the drift sentinel WITHOUT per-pair
    storage: ``bins`` counters over ``[lo, hi]`` (values clamped to the
    edge bins, so outliers are counted, not lost) plus exact count/sum/
    min/max.  Two digests with identical binning merge by adding counts —
    the property the SIGKILL-resume proof relies on (journal-replayed
    batches re-feed the same values, so merged digests equal an
    uninterrupted run's).  Percentiles interpolate linearly inside a bin:
    exact to ±bin_width, which is all a drift gate needs.
    """

    def __init__(self, lo: float = 0.0, hi: float = 1.0, bins: int = 32):
        if not (hi > lo and bins > 0):
            raise ValueError(f"bad histogram binning [{lo}, {hi}] x {bins}")
        self.lo, self.hi, self.bins = float(lo), float(hi), int(bins)
        self.counts = [0] * int(bins)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def add(self, values) -> None:
        """Accumulate value(s); NaN/inf are dropped (a failed pair must not
        shift the distribution it failed to measure)."""
        import math

        try:
            values = list(values)
        except TypeError:
            values = [values]
        w = (self.hi - self.lo) / self.bins
        for v in values:
            v = float(v)
            if not math.isfinite(v):
                continue
            i = min(self.bins - 1, max(0, int((v - self.lo) / w)))
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def bucket_edges(self) -> list:
        """Upper edge of each finite bin (``lo + (i+1)·width``) — the
        Prometheus exporter's ``le`` values.  Edge-bin clamping means the
        first/last bins absorb out-of-range observations, so the cumulative
        ``_bucket`` series stays consistent with ``count`` by construction."""
        w = (self.hi - self.lo) / self.bins
        return [self.lo + (i + 1) * w for i in range(self.bins)]

    def merge(self, other: "Histogram") -> None:
        if (other.lo, other.hi, other.bins) != (self.lo, self.hi, self.bins):
            raise ValueError("cannot merge histograms with different binning")
        for i, n in enumerate(other.counts):
            self.counts[i] += n
        self.count += other.count
        self.sum += other.sum
        for name, pick in (("min", min), ("max", max)):
            ov = getattr(other, name)
            if ov is not None:
                mine = getattr(self, name)
                setattr(self, name, ov if mine is None else pick(mine, ov))

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Approximate q-th percentile (0-100), linear within the bin."""
        if not self.count:
            return None
        target = q / 100.0 * self.count
        w = (self.hi - self.lo) / self.bins
        seen = 0
        for i, n in enumerate(self.counts):
            if seen + n >= target and n:
                frac = (target - seen) / n
                return self.lo + (i + frac) * w
            seen += n
        return self.hi

    def snapshot(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "count": self.count, "lo": self.lo, "hi": self.hi,
            "counts": list(self.counts),
        }
        if self.count:
            out["mean"] = round(self.sum / self.count, 6)
            out["min"] = round(self.min, 6)
            out["max"] = round(self.max, 6)
            for q in (50, 90):
                out[f"p{q}"] = round(self.percentile(q), 6)
        return out

    @classmethod
    def from_snapshot(cls, snap: Dict[str, object]) -> "Histogram":
        """Rebuild a digest from its snapshot dict (the wire format the
        drift sentinel's reference file stores).  min/max/sum degrade to
        bin-resolution estimates when absent."""
        counts = list(snap["counts"])
        h = cls(float(snap["lo"]), float(snap["hi"]), len(counts))
        h.counts = [int(n) for n in counts]
        h.count = int(snap.get("count", sum(h.counts)))
        if h.count:
            h.sum = float(snap.get("mean", 0.0)) * h.count
            h.min = float(snap.get("min", h.lo))
            h.max = float(snap.get("max", h.hi))
        return h


class MetricsRegistry:
    """Named counters/gauges/timers for one run scope.

    Thread-safe creation (the eval pipelines touch timers from drain
    callbacks); metric objects themselves are updated from one loop each.
    """

    def __init__(self, scope: str = ""):
        self.scope = scope
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def histogram(self, name: str, lo: float = 0.0, hi: float = 1.0,
                  bins: int = 32) -> Histogram:
        """Fixed-bin digest; binning is set at first creation (later calls
        return the existing digest — mismatched binning raises rather than
        silently rebinning a live distribution)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(lo, hi, bins)
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not Histogram"
                )
            elif (m.lo, m.hi, m.bins) != (float(lo), float(hi), int(bins)):
                raise ValueError(
                    f"histogram {name!r} already registered with binning "
                    f"[{m.lo}, {m.hi}] x {m.bins}"
                )
            return m

    def snapshot(self) -> Dict[str, object]:
        """Plain-data view: counters/gauges to their value, timers to their
        stat dict.  Unset gauges are omitted (a null metric is noise)."""
        out: Dict[str, object] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Gauge):
                if m.value is not None:
                    out[name] = m.value
            elif isinstance(m, Timer):
                if m.count:
                    out[name] = m.snapshot()
            elif isinstance(m, Histogram):
                if m.count:
                    out[name] = m.snapshot()
        return out

    def flush(self, sink: Optional["_events.EventLog"] = None,
              event: str = "metrics", **extra) -> Dict[str, object]:
        """Emit one ``metrics`` event carrying the current snapshot (to
        ``sink``, else the global sink) and return the snapshot."""
        snap = self.snapshot()
        fields = dict(extra)
        if self.scope:
            fields.setdefault("scope", self.scope)
        if sink is not None:
            sink.emit(event, metrics=snap, **fields)
        else:
            _events.emit(event, metrics=snap, **fields)
        return snap
