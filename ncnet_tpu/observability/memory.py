"""Memory observability: the compiled-program ledger, live HBM pressure,
the leak sentinel, and OOM postmortems.

NCNet's defining cost is memory — the full 4D correlation volume caps
resolution, the resident VJP's stage-1 working set sits right at the v5e
VMEM ceiling, and every serving bucket multiplies a compiled program's HBM
footprint — yet until this module the telemetry stack measured walls,
quality and SLOs while memory was three numbers in a rate-limited
``device_snapshot``.  Four planes, one home:

  * **Compiled-program memory ledger** — every jit compile seam (the
    serving bucket warmup, the fused-lane tier probes, ``make_train_step``,
    ``make_point_matcher``) records XLA's own accounting,
    ``lowered.compile().memory_analysis()`` (argument / output / temp /
    generated-code bytes), keyed by ``(program, shape_class, tier,
    device_kind)``.  Rows are emitted as schema-versioned ``memory_ledger``
    events AND persisted beside the tier cache
    (``~/.cache/ncnet_tpu/memory_ledger.json``, knob
    ``NCNET_TPU_MEMORY_LEDGER`` — a path, or ``0``/``off``), so a warm
    process still knows its footprints without re-compiling for analysis.
  * **Live HBM pressure** — :func:`hbm_stats` reads a device's
    ``memory_stats()`` watermarks (bytes_in_use / peak / limit / reserved /
    largest free block, fill %).  The serving plane samples it per
    dispatched batch and exports ``ncnet_serve_hbm_*`` gauges with the
    bucket ladder's *predicted* aggregate footprint (sum of ledger
    temp+output bytes over warmed programs) shown against ``bytes_limit``
    — headroom BEFORE admitting a new bucket, not after the OOM.
  * **Leak sentinel** — :class:`LeakSentinel` takes a
    ``jax.live_arrays()`` census (count + bytes by shape class) at
    batch/epoch boundaries; a shape class whose count grows strictly
    across the whole trailing window is named in a
    ``memory_leak_suspect`` event.
  * **OOM postmortem** — :func:`report_oom` classifies a
    ``RESOURCE_EXHAUSTED`` surfacing through the demote-retrace path as a
    memory failure and emits ONE ``memory_postmortem`` event per failure
    bundling the live HBM snapshot, the ledger rows of the failed program,
    and the live-array census — rendered by ``run_report --memory``.

Everything here is fail-open (the telemetry-never-kills-the-run
discipline): a backend without ``memory_analysis``/``memory_stats``/
``live_arrays`` degrades to silence, an unwritable ledger file degrades to
events-only, and every public entry point absorbs its own exceptions.
"""

from __future__ import annotations

import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from ncnet_tpu.observability import events as _events

SCHEMA_VERSION = 1
LEDGER_ENV = "NCNET_TPU_MEMORY_LEDGER"

# the program label of the batched serving engine's jit seam — the serving
# plane sums this program's ledger rows into its predicted-footprint gauge
# (serving/engine.py labels its ResilientJit identically)
SERVE_PROGRAM = "serve_batch"

_lock = threading.Lock()
# rows recorded (or cache-replayed) THIS process, keyed by the ledger key:
# the "warmed programs" set the serving predicted-footprint gauge sums
_runtime_rows: Dict[str, Dict[str, Any]] = {}
# on-disk mirror state, tier_cache-style: loaded once per resolved path
_state: Dict[str, object] = {"loaded": False, "path": None, "doc": None}


# ---------------------------------------------------------------------------
# ledger persistence (beside the tier cache; same fail-open rules)
# ---------------------------------------------------------------------------


def ledger_path() -> Optional[str]:
    """Resolved ledger file path, or None when disabled via the env knob."""
    raw = os.environ.get(LEDGER_ENV)
    if raw is not None:
        raw = raw.strip()
        if raw.lower() in ("", "0", "off", "none"):
            return None
        return raw
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "ncnet_tpu", "memory_ledger.json")


def _device_kind() -> str:
    from ncnet_tpu.observability.events import local_device_kind

    return local_device_kind() or "unknown"


def ledger_key(program: str, shape_class: str, tier: Optional[str],
               device_kind: str) -> str:
    """Stable string key of one ledger row: the (program, shape-class,
    tier, device_kind) identity the tentpole keys everything by."""
    return f"{program}|{shape_class}|{tier or 'xla'}|{device_kind}"


def _empty_doc() -> dict:
    return {"kind": "ncnet_tpu_memory_ledger", "schema": SCHEMA_VERSION,
            "rows": {}}


def _load_locked() -> dict:
    """The parsed on-disk doc (cached in-process).  Missing/corrupt/foreign/
    newer-schema files read as empty and are overwritten wholesale on the
    next record — the tier-cache invalidation rule."""
    path = ledger_path()
    if _state["loaded"] and path == _state["path"]:
        return _state["doc"]  # type: ignore[return-value]
    doc = _empty_doc()
    if path is not None:
        try:
            import json

            with open(path) as f:
                cand = json.load(f)
            if (isinstance(cand, dict)
                    and cand.get("kind") == "ncnet_tpu_memory_ledger"
                    and cand.get("schema") == SCHEMA_VERSION
                    and isinstance(cand.get("rows"), dict)):
                doc = cand
        except (OSError, ValueError):
            pass
    _state.update(loaded=True, path=path, doc=doc)
    return doc


def _save_locked(doc: dict) -> None:
    path = ledger_path()
    if path is None:
        return
    try:
        from ncnet_tpu.utils.io import atomic_write_json

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_json(path, doc)
    except (OSError, ValueError):
        pass  # fail-open: events-only is still a working ledger


def _reset_state() -> None:
    """Tests: forget the in-process mirror AND the runtime rows — the
    in-process analog of starting a fresh process."""
    with _lock:
        _state.update(loaded=False, path=None, doc=None)
        _runtime_rows.clear()
        _pending_keys.clear()


# ---------------------------------------------------------------------------
# compiled-program analysis
# ---------------------------------------------------------------------------

_ANALYSIS_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def analysis_dict(compiled: Any) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` (a jax AOT ``Compiled``, or the
    analysis object itself, or an already-plain dict) reduced to the byte
    fields the ledger stores, plus ``total_bytes`` (arguments + outputs +
    temps − aliased).  None when the backend exposes no analysis."""
    try:
        ma = compiled
        if hasattr(ma, "memory_analysis"):
            ma = ma.memory_analysis()
        if ma is None:
            return None
        out: Dict[str, int] = {}
        for name, attr in _ANALYSIS_FIELDS:
            v = ma.get(name) if isinstance(ma, dict) else getattr(
                ma, attr, None)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[name] = int(v)
        if not out:
            return None
        out["total_bytes"] = (out.get("argument_bytes", 0)
                              + out.get("output_bytes", 0)
                              + out.get("temp_bytes", 0)
                              - out.get("alias_bytes", 0))
        return out
    except Exception:  # noqa: BLE001 — analysis is optional per backend
        return None


def shape_class(tree: Any, max_leaves: int = 3) -> str:
    """Compact, deterministic shape-class string for one args pytree: the
    ``max_leaves`` largest array leaves as ``dtype[d0xd1x...]`` plus the
    leaf count — same shapes always map to the same key, and a params
    pytree with hundreds of leaves stays one short string."""
    try:
        import jax

        leaves = [x for x in jax.tree.leaves(tree)
                  if hasattr(x, "shape") and hasattr(x, "dtype")]
        import numpy as np

        def nbytes(a) -> int:
            try:
                return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
            except Exception:  # noqa: BLE001 — exotic dtypes: size 0
                return 0

        def label(a) -> str:
            return (f"{np.dtype(a.dtype).name}"
                    f"[{'x'.join(str(d) for d in a.shape)}]")

        top = sorted(leaves, key=lambda a: (-nbytes(a), label(a)))
        parts = [label(a) for a in top[:max_leaves]]
        if len(leaves) > max_leaves:
            parts.append(f"+{len(leaves) - max_leaves}leaves")
        return ",".join(parts) or "scalar"
    except Exception:  # noqa: BLE001 — a key we cannot build is no key
        return "unknown"


def _evict_stale_tiers_locked(row: Dict[str, Any]) -> None:
    """Drop runtime rows for the same (program, shape_class, device_kind)
    under a DIFFERENT tier: after a demote-retrace the re-recorded program
    replaced the old tier's executable, and keeping both would double-count
    the shape in :func:`predicted_footprint_bytes`.  The persisted file
    keeps every tier's analysis (it is a cross-process cache — the chooser
    may pick either tier in a future process); only the live "warmed"
    registry is single-tier per shape."""
    for key, old in list(_runtime_rows.items()):
        if (old["program"] == row["program"]
                and old["shape_class"] == row["shape_class"]
                and old["device_kind"] == row["device_kind"]
                and old["tier"] != row["tier"]):
            del _runtime_rows[key]


def record_program(program: str, shape_cls: str, *,
                   analysis: Any = None, tier: Optional[str] = None,
                   device_kind: Optional[str] = None,
                   source: str = "probe") -> Optional[Dict[str, Any]]:
    """Record one compiled program's memory accounting: build the row,
    register it in-process, persist it beside the tier cache, and emit the
    ``memory_ledger`` event.  ``analysis`` may be a jax ``Compiled``, a
    ``CompiledMemoryStats``, or a plain dict of byte fields.  Returns the
    row (None when no analysis is extractable) — always fail-open."""
    try:
        fields = analysis_dict(analysis)
        if fields is None:
            return None
        kind = device_kind or _device_kind()
        row: Dict[str, Any] = {
            "schema": SCHEMA_VERSION, "program": str(program),
            "shape_class": str(shape_cls), "tier": tier or "xla",
            "device_kind": kind, **fields,
        }
        key = ledger_key(program, shape_cls, tier, kind)
        with _lock:
            _evict_stale_tiers_locked(row)
            _runtime_rows[key] = row
            if ledger_path() is not None:
                doc = _load_locked()
                if doc["rows"].get(key) != row:
                    doc["rows"][key] = dict(row)
                    _save_locked(doc)
        _events.emit("memory_ledger", source=source, **row)
        return row
    except Exception:  # noqa: BLE001 — the ledger never kills the compile
        return None


def ensure_program(program: str, shape_cls: str, *,
                   analyze: Callable[[], Any],
                   tier: Optional[str] = None,
                   device_kind: Optional[str] = None
                   ) -> Optional[Dict[str, Any]]:
    """The warm-process seam: return the ledger row for this key, analyzing
    (one AOT ``lower().compile()`` — the cost the persistence exists to
    avoid) only on a genuine miss.  A hit — in-process or persisted — still
    emits the ``memory_ledger`` event (``source="cache"``), so every warmed
    program of every run has its row in the event log, warm or cold."""
    try:
        kind = device_kind or _device_kind()
        key = ledger_key(program, shape_cls, tier, kind)
        with _lock:
            row = _runtime_rows.get(key)
            if row is None and ledger_path() is not None:
                cand = _load_locked()["rows"].get(key)
                if isinstance(cand, dict) and cand.get(
                        "schema") == SCHEMA_VERSION:
                    row = dict(cand)
                    _evict_stale_tiers_locked(row)
                    _runtime_rows[key] = row
        if row is not None:
            _events.emit("memory_ledger", source="cache", **row)
            return row
        return record_program(program, shape_cls, analysis=analyze(),
                              tier=tier, device_kind=kind)
    except Exception:  # noqa: BLE001
        return None


# in-flight background analyses (ensure_program_async misses), plus the
# keys they are computing — a second miss on a key already being analyzed
# (the multi-replica warmup dispatches identical programs back-to-back)
# must not spawn a duplicate AOT compile
_pending: List[threading.Thread] = []
_pending_keys: Dict[str, threading.Thread] = {}


def ensure_program_async(program: str, shape_cls: str, *,
                         analyze: Callable[[], Any],
                         tier: Optional[str] = None,
                         device_kind: Optional[str] = None
                         ) -> Optional[Dict[str, Any]]:
    """:func:`ensure_program` with the analysis compile OFF the caller's
    thread: a cache hit (in-process or persisted) resolves and emits
    synchronously; a genuine miss schedules ``analyze`` — an AOT
    ``lower().compile()`` that can take seconds-to-minutes on a tunneled
    TPU — on a background daemon thread so the dispatch path never blocks
    on it.  Returns the row on a hit, None when the analysis was
    scheduled; :func:`flush_pending` joins outstanding analyses (the
    serving warmup drains them so the predicted-footprint gauge is
    complete by READY)."""
    try:
        kind = device_kind or _device_kind()
        key = ledger_key(program, shape_cls, tier, kind)
        with _lock:
            row = _runtime_rows.get(key)
            if row is None and ledger_path() is not None:
                cand = _load_locked()["rows"].get(key)
                if isinstance(cand, dict) and cand.get(
                        "schema") == SCHEMA_VERSION:
                    row = dict(cand)
                    _evict_stale_tiers_locked(row)
                    _runtime_rows[key] = row
        if row is not None:
            _events.emit("memory_ledger", source="cache", **row)
            return row

        def work():
            try:
                record_program(program, shape_cls, analysis=analyze(),
                               tier=tier, device_kind=kind)
            except Exception:  # noqa: BLE001 — fail-open off-thread too
                pass
            finally:
                with _lock:
                    _pending_keys.pop(key, None)

        with _lock:
            if key in _pending_keys or key in _runtime_rows:
                # already being analyzed — or its analysis landed between
                # the cache check above and here: don't compile twice
                return None
            t = threading.Thread(target=work, name="memory-ledger-analyze",
                                 daemon=True)
            # prune finished threads here too: processes that never call
            # flush_pending (training, eval) must not accumulate dead
            # Thread objects for their whole lifetime
            _pending[:] = [p for p in _pending if p.is_alive()]
            _pending.append(t)
            _pending_keys[key] = t
        t.start()
        return None
    except Exception:  # noqa: BLE001
        return None


def flush_pending(timeout: Optional[float] = None) -> None:
    """Join in-flight background ledger analyses (bounded by ``timeout``
    across ALL of them) and prune finished threads — called at the end of
    the serving warmup, and by tests that assert on ledger events."""
    with _lock:
        threads = list(_pending)
    deadline = (time.monotonic() + timeout) if timeout is not None else None
    for t in threads:
        t.join(None if deadline is None
               else max(0.0, deadline - time.monotonic()))
    with _lock:
        _pending[:] = [t for t in _pending if t.is_alive()]


def ledger_rows(program: Optional[str] = None,
                device_kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Rows known to THIS process (recorded fresh or replayed from the
    persisted file by :func:`ensure_program`), optionally filtered —
    the "warmed programs" set the serving plane sums."""
    with _lock:
        rows = [dict(r) for r in _runtime_rows.values()]
    return [r for r in rows
            if (program is None or r["program"] == program)
            and (device_kind is None or r["device_kind"] == device_kind)]


def predicted_footprint_bytes(program: Optional[str] = None,
                              device_kind: Optional[str] = None
                              ) -> Optional[int]:
    """Predicted aggregate device footprint of the warmed programs: the sum
    of ledger temp+output bytes over this process's rows (arguments are
    shared staging, generated code is negligible next to the volume).  None
    when nothing is warmed — a gauge that guesses is worse than no gauge."""
    rows = ledger_rows(program=program, device_kind=device_kind)
    if not rows:
        return None
    return sum(int(r.get("temp_bytes", 0)) + int(r.get("output_bytes", 0))
               for r in rows)


# ---------------------------------------------------------------------------
# live HBM pressure
# ---------------------------------------------------------------------------

_HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "bytes_reserved", "largest_free_block_bytes")


def hbm_stats(device: Any = None) -> Optional[Dict[str, Any]]:
    """One device's ``memory_stats()`` watermarks (+ ``fill_pct`` when a
    limit is known), or None when the backend exposes none (CPU) — the
    plane stays silent, it never errors."""
    try:
        if device is None:
            import jax

            devices = jax.local_devices()
            if not devices:
                return None
            device = devices[0]
        stats = device.memory_stats()
        if not stats:
            return None
        out: Dict[str, Any] = {"device": int(getattr(device, "id", 0))}
        for key in _HBM_KEYS:
            if key in stats:
                out[key] = int(stats[key])
        if len(out) <= 1:
            return None
        in_use, limit = out.get("bytes_in_use"), out.get("bytes_limit")
        if in_use is not None and limit:
            out["fill_pct"] = round(100.0 * in_use / limit, 2)
        return out
    except Exception:  # noqa: BLE001 — optional per-backend API
        return None


# ---------------------------------------------------------------------------
# leak sentinel
# ---------------------------------------------------------------------------


def live_array_census(max_classes: int = 64) -> Optional[Dict[str, Any]]:
    """``jax.live_arrays()`` grouped by shape class: total count/bytes plus
    the per-class breakdown (largest ``max_classes`` classes by bytes).
    None when the census cannot be taken."""
    try:
        import jax
        import numpy as np

        by: Dict[str, Dict[str, int]] = {}
        n_total = 0
        b_total = 0
        for a in jax.live_arrays():
            try:
                cls = (f"{np.dtype(a.dtype).name}"
                       f"[{'x'.join(str(d) for d in a.shape)}]")
                nb = int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
            except Exception:  # noqa: BLE001 — exotic arrays: skip
                continue
            d = by.setdefault(cls, {"n": 0, "bytes": 0})
            d["n"] += 1
            d["bytes"] += nb
            n_total += 1
            b_total += nb
        top = dict(sorted(by.items(),
                          key=lambda kv: -kv[1]["bytes"])[:max_classes])
        return {"n": n_total, "bytes": b_total, "classes": len(by),
                "by_class": top}
    except Exception:  # noqa: BLE001 — no census beats a crashed loop
        return None


class LeakSentinel:
    """Trailing-window growth detector over live-array censuses.

    ``observe(step=...)`` takes one census (at a batch/epoch boundary).
    When a shape class's count has grown STRICTLY across every consecutive
    delta of the full window (``window`` deltas, so ``window+1``
    censuses), it is named in a ``memory_leak_suspect`` event along with
    its byte growth; the window then resets, so an ongoing leak re-fires
    once per window rather than once per batch.  Steady-state churn — a
    class whose count fluctuates, or stays flat — never trips it.
    ``min_interval_s`` rate-limits the census itself for hot loops."""

    def __init__(self, window: int = 4, min_growth_bytes: int = 0,
                 min_interval_s: float = 0.0, scope: str = ""):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.min_growth_bytes = int(min_growth_bytes)
        self.min_interval_s = float(min_interval_s)
        self.scope = scope
        self._censuses: Deque[Dict[str, Any]] = deque(maxlen=window + 1)
        self._last_t: Optional[float] = None
        # serving calls observe() from every per-replica fetcher thread:
        # an unsynchronized window would interleave censuses and mask real
        # monotone growth
        self._obs_lock = threading.Lock()

    def observe(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Take one census; returns the emitted suspect event's fields when
        the detector fired, else None.  Fail-open end to end; safe to call
        from several threads (one census at a time)."""
        try:
            with self._obs_lock:
                now = time.monotonic()
                if self._last_t is not None and self.min_interval_s > 0 \
                        and now - self._last_t < self.min_interval_s:
                    return None
                census = live_array_census()
                if census is None:
                    return None
                self._last_t = now
                self._censuses.append(census)
                if len(self._censuses) < self.window + 1:
                    return None
                suspects = self._suspects()
                if not suspects:
                    return None
                fields: Dict[str, Any] = {
                    "scope": self.scope, "window": self.window,
                    "suspects": suspects,
                    "live_n": census["n"], "live_bytes": census["bytes"],
                }
                if step is not None:
                    fields["step"] = int(step)
                self._censuses.clear()  # re-arm: one event per full window
            _events.emit("memory_leak_suspect", **fields)
            return fields
        except Exception:  # noqa: BLE001 — the sentinel never kills the loop
            return None

    def _suspects(self) -> List[Dict[str, Any]]:
        seq = list(self._censuses)
        first, last = seq[0]["by_class"], seq[-1]["by_class"]
        out: List[Dict[str, Any]] = []
        for cls in last:
            counts = []
            for c in seq:
                d = c["by_class"].get(cls)
                if d is None:
                    break
                counts.append(d["n"])
            if len(counts) != len(seq):
                continue  # absent somewhere in the window: not monotone
            if all(b > a for a, b in zip(counts, counts[1:])):
                growth = last[cls]["bytes"] - first[cls]["bytes"]
                if growth >= self.min_growth_bytes:
                    out.append({
                        "shape_class": cls,
                        "n_first": counts[0], "n_last": counts[-1],
                        "bytes_first": first[cls]["bytes"],
                        "bytes_last": last[cls]["bytes"],
                        "growth_bytes": growth,
                    })
        out.sort(key=lambda s: -s["growth_bytes"])
        return out


# ---------------------------------------------------------------------------
# OOM postmortem
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory",
                "allocation failure", "failed to allocate")
# bare "oom" must be word-bounded: a path like ".../reading_room_3.mat" in
# an IO error contains the substring but is not a memory failure
_OOM_RE = re.compile(r"\boom\b", re.IGNORECASE)

# exceptions already reported: the demote-retrace ladder sees one failure
# at several seams (the serving failure handler AND the shared
# recover_from_device_failure), and each injected RESOURCE_EXHAUSTED must
# produce exactly ONE memory_postmortem
_reported: "weakref.WeakSet" = weakref.WeakSet()


def is_oom(exc: BaseException) -> bool:
    """Whether an exception is a memory failure: a runtime device error
    whose message carries a RESOURCE_EXHAUSTED / out-of-memory marker."""
    try:
        msg = f"{type(exc).__name__}: {exc}".lower()
        return any(m in msg for m in _OOM_MARKERS) \
            or _OOM_RE.search(msg) is not None
    except Exception:  # noqa: BLE001
        return False


def report_oom(exc: BaseException, *, program: Optional[str] = None,
               scope: str = "", **extra: Any) -> bool:
    """Classify ``exc`` as a memory failure and emit ONE
    ``memory_postmortem`` event bundling the last HBM snapshot, the ledger
    rows of the failed program, and the live-array census.  Returns True
    when the event was emitted; False for non-OOM errors or an exception
    already reported at another seam of the same failure's ladder."""
    try:
        if not is_oom(exc):
            return False
        if exc in _reported:
            return False
        _reported.add(exc)
        from ncnet_tpu.observability.device import device_snapshot

        rows = ledger_rows(program=program) if program else ledger_rows()
        _events.emit(
            "memory_postmortem",
            scope=scope, program=program, kind="oom",
            error=f"{type(exc).__name__}: {exc}"[:500],
            snapshot=device_snapshot(),
            ledger=rows[:16],
            census=live_array_census(max_classes=16),
            **extra,
        )
        return True
    except Exception:  # noqa: BLE001 — the postmortem never compounds the OOM
        return False
