"""Leveled logger: one console sink + a structured tee into the event log.

Replaces the ~60 bare ``print()`` calls that used to be the framework's
only output (SURVEY §5.1 — the reference is print-only and our reproduction
inherited it).  Design constraints, in order:

  1. **Console compatibility** — the rendered lines keep the text the
     prints produced (tests capture stdout and assert substrings; operators
     grep the same phrases).  ``info`` renders the message verbatim;
     ``warning``/``error`` prefix ``warning: `` / ``error: `` exactly once —
     which also FIXES the old inconsistency where some recoverable failures
     carried the prefix and others did not: the level now decides, not the
     call site.
  2. **Structured tee** — every rendered line is also emitted to the
     process-global event sink (``events.emit``) as a ``log`` record with a
     single ``kind`` classification field (decode/device/timeout/io/
     quarantine/...), so a replayed run can aggregate recoverable failures
     without parsing message strings.  No sink bound → the tee is free.
  3. **No bare print** — the console write goes through ``sys.stdout``
     directly; ``tools/check_no_bare_print.py`` (tier-1 enforced) keeps
     library modules off ``print()`` so this stays the one sink.

``NCNET_TPU_LOG_LEVEL`` (debug|info|warning|error) filters both the console
and the tee; default ``info``.  ``sys.stdout`` is looked up per call so
pytest's capture and operator redirections both see the output.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

from ncnet_tpu.observability import events as _events

LOG_LEVEL_ENV = "NCNET_TPU_LOG_LEVEL"

_LEVELS: Dict[str, int] = {"debug": 10, "info": 20, "warning": 30,
                           "error": 40}
_PREFIXES = {"warning": "warning: ", "error": "error: "}


def _threshold() -> int:
    name = os.environ.get(LOG_LEVEL_ENV, "").strip().lower()
    return _LEVELS.get(name, _LEVELS["info"])


class Logger:
    """One named channel.  ``kind`` is the classification field: recoverable
    failures pass the same kinds ``resilience.classify_failure`` produces
    (decode/device/timeout/io/other) plus layer-specific ones (nan_guard,
    quarantine, tier, preemption, validation)."""

    def __init__(self, name: str):
        self.name = name

    def _log(self, level: str, msg: str, kind: Optional[str],
             console: bool = True) -> None:
        if _LEVELS[level] < _threshold():
            return
        if console:
            # per-call lookup: pytest capture / redirection must both work
            sys.stdout.write(_PREFIXES.get(level, "") + msg + "\n")
        fields = {"level": level, "logger": self.name, "msg": msg}
        if kind is not None:
            fields["kind"] = kind
        _events.emit("log", **fields)

    def debug(self, msg: str, kind: Optional[str] = None) -> None:
        self._log("debug", msg, kind)

    def info(self, msg: str, kind: Optional[str] = None) -> None:
        self._log("info", msg, kind)

    def warning(self, msg: str, kind: Optional[str] = None) -> None:
        self._log("warning", msg, kind)

    def error(self, msg: str, kind: Optional[str] = None) -> None:
        self._log("error", msg, kind)


_loggers: Dict[str, Logger] = {}


def get_logger(name: str) -> Logger:
    """Named loggers are cached (cheap identity for the tee's ``logger``
    field; there is no per-logger state to configure)."""
    log = _loggers.get(name)
    if log is None:
        log = _loggers[name] = Logger(name)
    return log
