"""Persistent cross-run perf history + the noise-aware regression check.

PR 5 made every run emit structured metrics, but each artifact was an
island: BENCH_r*.json files accumulate in the repo root, ``fit``/eval
summaries die with their event logs, and nothing gates a fresh number
against history.  This module is the durable, append-only store those
numbers flow into, and the statistics ``tools/perf_regress.py`` gates on:

  * :class:`PerfStore` — append-only JSONL, one self-describing record per
    line (``{"kind": "perf", "schema": ..., "metric", "value",
    "device_kind", "git_rev", ...}``).  No header line: records are
    independent, files concatenate/merge trivially, and a torn tail (or a
    foreign line) is skipped on read — the
    :func:`~ncnet_tpu.observability.events.replay_events` tolerance
    discipline without the lineage machinery a metrics history does not
    need.  History is keyed by ``(device_kind, metric)``; ``git_rev``
    attributes each point to the code that produced it.
  * Automatic ingestion — ``bench.py`` appends its whole artifact,
    ``fit`` appends its step-wall/throughput/MFU summary, the PF-Pascal
    eval appends PCK + wall splits.  The store path resolves from the
    ``NCNET_TPU_PERF_STORE`` env var (``0``/``off`` disables ingestion),
    defaulting to ``<repo>/perf/history.jsonl`` — the committed seed
    history lives there, built from BENCH_r01–r05 via
    ``tools/perf_regress.py --seed``.
  * :func:`check_regressions` — compare the newest value of each gated
    metric against a trailing window of its predecessors with a
    median + MAD threshold (robust to the odd outlier run) plus a relative
    floor (robust to a near-zero MAD from repeated identical values).
    Direction is inferred from the metric name (:func:`metric_direction`);
    derived ratios (MFU, TFLOP/s, vs_baseline) and roofline constants are
    deliberately ungated — they move for benign reasons (a faster wall
    LOWERS measured MFU at fixed batch) and gating them would teach
    operators to ignore the sentinel.

All write paths are fail-open (:func:`maybe_record`): perf bookkeeping must
never be the reason a run dies.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

SCHEMA_VERSION = 1
STORE_ENV = "NCNET_TPU_PERF_STORE"

_lock = threading.Lock()


def default_store_path() -> str:
    """``<repo>/perf/history.jsonl`` — beside the BENCH_r*.json trajectory
    it subsumes."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "perf", "history.jsonl")


def resolve_store_path(path: Optional[str] = None) -> Optional[str]:
    """Explicit path > ``$NCNET_TPU_PERF_STORE`` > the repo default.
    Returns None (ingestion disabled) for env values ``0``/``off``/``none``."""
    if path:
        return path
    raw = os.environ.get(STORE_ENV)
    if raw is not None:
        raw = raw.strip()
        if raw.lower() in ("", "0", "off", "none"):
            return None
        return raw
    return default_store_path()


class PerfStore:
    """Append-only JSONL perf history (see module docstring)."""

    def __init__(self, path: str):
        self.path = path

    # -- write ------------------------------------------------------------

    def append(self, metric: str, value: float, *,
               device_kind: Optional[str] = None,
               git_rev: Optional[str] = None,
               run_id: Optional[str] = None,
               unit: Optional[str] = None,
               source: Optional[str] = None,
               t: Optional[float] = None) -> Dict[str, Any]:
        """Append one record; returns it.  The write is flushed+fsynced so a
        killed process costs at most its own torn trailing line."""
        rec: Dict[str, Any] = {
            "kind": "perf", "schema": SCHEMA_VERSION,
            "metric": str(metric), "value": float(value),
            "device_kind": device_kind or "unknown",
            "t": float(t) if t is not None else time.time(),
        }
        for key, v in (("git_rev", git_rev), ("run_id", run_id),
                       ("unit", unit), ("source", source)):
            if v:
                rec[key] = v
        line = json.dumps(rec, sort_keys=True)
        with _lock:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        return rec

    def append_many(self, metrics: Dict[str, float], **meta) -> int:
        n = 0
        for name, value in metrics.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            if value != value:  # NaN: a failed measurement is not history
                continue
            self.append(name, value, **meta)
            n += 1
        return n

    # -- read -------------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """All readable records in file order.  Torn/foreign/newer-schema
        lines are skipped, not fatal — records are independent."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return []
        out: List[Dict[str, Any]] = []
        for line in raw.split(b"\n"):
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (isinstance(rec, dict) and rec.get("kind") == "perf"
                    and rec.get("schema", 0) <= SCHEMA_VERSION
                    and isinstance(rec.get("metric"), str)
                    and isinstance(rec.get("value"), (int, float))):
                out.append(rec)
        return out

    def history(self, metric: str,
                device_kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """Records for one metric (optionally one device kind), file order —
        which is append order, i.e. chronology."""
        return [r for r in self.records()
                if r["metric"] == metric
                and (device_kind is None or r["device_kind"] == device_kind)]


def maybe_record(metrics: Dict[str, float], *, source: str,
                 path: Optional[str] = None,
                 device_kind: Optional[str] = None,
                 git_rev: Optional[str] = None,
                 run_id: Optional[str] = None) -> int:
    """Best-effort ingestion for run exit paths: resolves the store (no-op
    when disabled), fills device/git metadata when not supplied, and absorbs
    I/O errors — returns the number of records written (0 on any failure)."""
    store_path = resolve_store_path(path)
    if store_path is None or not metrics:
        return 0
    try:
        if device_kind is None:
            from ncnet_tpu.observability.events import local_device_kind

            device_kind = local_device_kind()
        if git_rev is None:
            from ncnet_tpu.observability.events import git_revision

            git_rev = git_revision()
        return PerfStore(store_path).append_many(
            metrics, device_kind=device_kind, git_rev=git_rev,
            run_id=run_id, source=source,
        )
    except (OSError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# BENCH artifact ingestion (the seed path)
# ---------------------------------------------------------------------------


def _bench_metric_lines(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The ``{"metric": ...}`` dicts inside one artifact: a bare bench
    stdout line, or the harness wrapper (``{"n", "cmd", "parsed", "tail"}``)
    — falling back to scanning ``tail`` when ``parsed`` is null (a failed
    round like BENCH_r02 still yields whatever lines it printed)."""
    if "metric" in doc:
        return [doc]
    lines: List[Dict[str, Any]] = []
    if isinstance(doc.get("parsed"), dict):
        lines.append(doc["parsed"])
    elif isinstance(doc.get("tail"), str):
        for line in doc["tail"].splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict) and "metric" in cand:
                    lines.append(cand)
    return lines


def ingest_bench_artifact(store: PerfStore, path: str,
                          source: Optional[str] = None) -> int:
    """Ingest one bench artifact (bare line or harness wrapper) into the
    store; returns the record count.  Metadata comes from the artifact's
    envelope when present (post-PR 5), else from the ``device_kind`` extra;
    the record time falls back to the wrapper's round number so seeding the
    committed history is deterministic."""
    with open(path) as f:
        doc = json.load(f)
    n_round = doc.get("n") if isinstance(doc.get("n"), (int, float)) else None
    total = 0
    for line in _bench_metric_lines(doc):
        extra = line.get("extra") or {}
        env = line.get("envelope") or {}
        device_kind = env.get("device_kind") or extra.get("device_kind")
        meta = dict(
            device_kind=device_kind, git_rev=env.get("git_rev"),
            run_id=env.get("run_id"),
            source=source or f"bench:{os.path.basename(path)}",
            t=env.get("time") if isinstance(env.get("time"), (int, float))
            else (float(n_round) if n_round is not None else 0.0),
        )
        metrics: Dict[str, float] = {}
        if isinstance(line.get("value"), (int, float)) and line.get("metric"):
            metrics[line["metric"]] = line["value"]
        if isinstance(line.get("vs_baseline"), (int, float)):
            metrics["vs_baseline"] = line["vs_baseline"]
        for k, v in extra.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[k] = v
        total += store.append_many(metrics, **meta)
    return total


# ---------------------------------------------------------------------------
# regression sentinel
# ---------------------------------------------------------------------------

# report-only metrics: derived ratios move for benign reasons (a faster
# wall LOWERS measured MFU at fixed batch; vs_baseline tracks the torch
# host's mood), and roofline_* / torch_cpu_* are constants or the
# reference's numbers, not ours
_UNGATED_PREFIXES = ("roofline_", "torch_cpu")
_UNGATED_TOKENS = ("mfu", "tflops", "vs_baseline", "gflops")
# the ungated tokens are all higher-is-better quantities — when an operator
# FORCE-gates one via --metrics, this is the direction the gate must use
# (defaulting to lower-is-better would report an MFU improvement as a
# regression and wave a real drop through)
_FORCED_HIGHER_TOKENS = _UNGATED_TOKENS
_HIGHER_TOKENS = ("pck", "pairs_per_s", "pairs_per_sec", "qps",
                  "localization_rate",
                  # match-quality signals (observability/quality.py): the
                  # accuracy trajectory gates alongside the walls
                  "margin", "mnn_agreement", "coherence", "score_gap",
                  "quality_score",
                  # feature store (ncnet_tpu/store/): the cache-
                  # effectiveness fraction from the bench's cached-
                  # localization scenario — a falling hit rate is the
                  # store silently losing its reason to exist
                  "hit_pct",
                  # sharded retrieval (ncnet_tpu/retrieval/): coverage is
                  # the fraction of the database a sweep consulted — a
                  # falling coverage at fixed shard health is replication
                  # or planning regressing
                  "coverage_pct",
                  # CP tier (ops/conv4d_cp.py): argmax-match agreement of
                  # the rank-R filtered volume vs the dense filter — the
                  # label-free PCK-recovery proxy the bench tracks per rank
                  "recovery_pct",
                  # streaming tracked mode (serving/stream.py): the
                  # fraction of stream frames that skipped the coarse pass
                  # — the steady-state win the bench scenario gates; a
                  # falling skip rate means cut detection is over-firing
                  # or tracking stopped engaging
                  "skip_pct")
_LOWER_TOKENS = ("_ms", "ms_per_pair", "wall", "_s_per_pair", "_eval_s_",
                 "_step_s", "_wall_s",
                 # diffuse match distributions are worse: entropy gates
                 # lower-is-better
                 "entropy",
                 # serving: shed fraction at a FIXED offered load (the bench
                 # scenario pins the load, so more shedding = less capacity)
                 "shed_pct",
                 # SLO error-budget burn (serving/slo.py): a rising burn is
                 # the serving plane's accuracy-of-promise regressing
                 "burn_pct",
                 # memory observability (observability/memory.py): program
                 # temp/peak-HBM byte series (mem_*_temp_bytes,
                 # mem_peak_hbm_bytes) gate exactly like walls — a 2x
                 # footprint jump fails perf_regress --check
                 "_bytes",
                 # sharded retrieval: hedges are paid redundant work — a
                 # rising hedge rate at fixed shard health means straggler
                 # detection is firing where it should not
                 "hedge_pct",
                 # pod tracing (observability/tracing.py): the wire cost of
                 # carrying the trace header, as a percent of the untraced
                 # codec wall — the bench hard-fails at 1%, and this token
                 # lets perf_regress --check gate the drift below that line
                 "_overhead_pct")


def metric_direction(name: str) -> Optional[str]:
    """``"lower"`` / ``"higher"`` = smaller/bigger is better (gated), None =
    report-only.  Inference is by name token so new bench metrics get gated
    by following the existing naming conventions, not by registration."""
    n = name.lower()
    if n.startswith(_UNGATED_PREFIXES):
        return None
    if any(tok in n for tok in _UNGATED_TOKENS):
        return None
    if any(tok in n for tok in _HIGHER_TOKENS):
        return "higher"
    if any(tok in n for tok in _LOWER_TOKENS) or n.endswith("_s"):
        return "lower"
    return None


_median = statistics.median


def check_regressions(records: Iterable[Dict[str, Any]], *,
                      window: int = 8, mad_k: float = 4.0,
                      min_rel: float = 0.10, min_history: int = 2,
                      metrics: Optional[Sequence[str]] = None,
                      device_kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Judge the NEWEST value of each gated ``(device_kind, metric)`` series
    against its trailing baseline window.

    Threshold: the new value regresses when it is worse than the window
    median by more than ``max(mad_k · 1.4826 · MAD, min_rel · |median|)`` —
    the MAD term absorbs real run-to-run noise (scaled to a normal sigma),
    the relative floor absorbs a degenerate MAD from repeated identical
    values.  Series with fewer than ``min_history`` baseline points are
    reported as ``skipped`` (a gate that guesses is worse than no gate).

    Returns one finding dict per series: ``{"metric", "device_kind",
    "status": "ok"|"regression"|"skipped", "value", "baseline_median",
    "threshold", "direction", "n_history", ...}``, regressions first.
    """
    series: Dict[tuple, List[Dict[str, Any]]] = {}
    for rec in records:
        if device_kind is not None and rec.get("device_kind") != device_kind:
            continue
        if metrics is not None and rec["metric"] not in metrics:
            continue
        series.setdefault((rec.get("device_kind"), rec["metric"]), []).append(rec)

    findings: List[Dict[str, Any]] = []
    for (dev, name), recs in sorted(series.items(),
                                    key=lambda kv: (str(kv[0][0]), kv[0][1])):
        direction = metric_direction(name)
        explicit = metrics is not None and name in metrics
        if direction is None and not explicit:
            continue  # report-only metric: not a gate
        if direction is None and explicit:
            # force-gated but deliberately-ungated by name: the derived
            # ratios are all higher-is-better
            if any(tok in name.lower() for tok in _FORCED_HIGHER_TOKENS):
                direction = "higher"
        finding: Dict[str, Any] = {
            "metric": name, "device_kind": dev,
            "direction": direction or "unknown",
            "value": recs[-1]["value"], "n_history": len(recs) - 1,
            "source": recs[-1].get("source"),
        }
        if direction is None:
            # a gate that guesses the direction is worse than no gate
            finding["status"] = "skipped"
            finding["reason"] = ("direction not inferrable from the metric "
                                 "name; rename or gate a directional twin")
            findings.append(finding)
            continue
        baseline = [r["value"] for r in recs[:-1]][-window:]
        if len(baseline) < min_history:
            finding["status"] = "skipped"
            finding["reason"] = (
                f"only {len(baseline)} baseline point(s) "
                f"(< min_history={min_history})")
            findings.append(finding)
            continue
        med = _median(baseline)
        mad = _median([abs(v - med) for v in baseline])
        slack = max(mad_k * 1.4826 * mad, min_rel * abs(med))
        worse_by = ((recs[-1]["value"] - med)
                    if finding["direction"] == "lower"
                    else (med - recs[-1]["value"]))
        finding.update(
            baseline_median=round(med, 6), baseline_mad=round(mad, 6),
            slack=round(slack, 6), worse_by=round(worse_by, 6),
            status="regression" if worse_by > slack else "ok",
        )
        findings.append(finding)
    findings.sort(key=lambda f: (f["status"] != "regression",
                                 f["status"] == "skipped", f["metric"]))
    return findings
