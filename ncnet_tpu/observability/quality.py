"""Match-quality observability: label-free quality signals over the 4D volume.

PRs 5-6 made the system's *speed* observable; its *accuracy* was still
invisible between labeled evals — a bf16 tier promotion, a future CP/FFT
conv4d tier, or a quarantine-degraded run can silently shift match quality
and nothing fires until someone re-runs PF-Pascal.  NCNet's own construction
gives label-free confidence measures for free: the softmax match scores and
the mutual-NN structure (``ops/matching.py``) are exactly the correspondence-
confidence signals *Dual-Resolution Correspondence Networks* (PAPERS.md)
ranks matches by.  This module extracts them IN-GRAPH, so every consumer
(both eval loops, the warm serving matcher, training) fetches them with the
match table at zero extra host round trips and zero per-pair Python
postprocessing.

Signals (:data:`QUALITY_SIGNALS`; all per pair, all in their stated range):

  * ``score``          — mean over B cells of the max softmax match
    probability (the B→A direction :func:`corr_to_matches` scores by);
    [0, 1], higher = more confident.
  * ``entropy``        — mean normalized entropy of the per-B-cell softmax
    distribution over A cells (normalized by ``log(hA·wA)``); [0, 1],
    1.0 = uniform (uninformative volume), lower = peakier.
  * ``margin``         — mean top1−top2 softmax gap per B cell; [0, 1],
    ~1.0 for a delta-peaked volume, ~0 for a flat one.
  * ``mnn_agreement``  — hard mutual-argmax agreement ratio
    (:func:`ncnet_tpu.ops.matching.mutual_argmax_agreement`); [0, 1].
  * ``coherence``      — displacement-field smoothness: the fraction of
    adjacent B-grid cell pairs whose matched A cells advance within 0.9 of
    the expected grid step (the implied flow is locally smooth); [0, 1],
    1.0 for an identity/rigid-shift volume, low both for
    spatially-incoherent argmax noise and for a volume collapsed to a
    constant argmax (the band sits strictly below one step, so the
    degenerate constant field cannot masquerade as a perfect flow).

Training additionally reports ``score_gap`` = score(positive) −
score(negative) per step (the negation of the weak loss, [-1, 1]) — the
per-step health signal of the weak supervision itself.

Consumption path: signals stream into the PR 5 event log as ``quality``
events **tagged with the active fused tier** (:func:`active_tier`, fed by
``ops/nc_fused_lane.last_selected_tier``), aggregate through fixed-bin
:class:`~ncnet_tpu.observability.metrics.Histogram` digests in the metrics
registry (percentiles without per-pair storage), and gate against committed
reference distributions (``perf/quality_ref.jsonl``) with a PSI-style
divergence score — ``tools/quality_drift.py`` exits nonzero on drift, which
is the standing accuracy gate every future kernel-tier PR runs under.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ncnet_tpu.observability.metrics import Histogram

# signal order IS the wire order: the stacked quality table fetched beside
# the match table lays columns out in this sequence
QUALITY_SIGNALS = ("score", "entropy", "margin", "mnn_agreement", "coherence")

# per-signal digest range; everything the volume extractor emits is [0, 1]
# by construction, the training score gap is a difference of [0, 1] means
SIGNAL_RANGE: Dict[str, Tuple[float, float]] = {
    **{name: (0.0, 1.0) for name in QUALITY_SIGNALS},
    "score_gap": (-1.0, 1.0),
}
DIGEST_BINS = 32

REF_KIND = "ncnet_tpu_quality_ref"
REF_SCHEMA = 1


# ---------------------------------------------------------------------------
# in-graph extraction (pure jnp — fuses into the eval/serving programs)
# ---------------------------------------------------------------------------


def quality_signals(corr: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Per-pair quality signals of a filtered volume; ``{name: (B,)}``.

    Everything is reductions/gathers/top-k over the ``(B, hA, wA, hB, wB)``
    volume — jittable, shardable, and cheap next to the NC filter that
    produced the volume (one softmax the match extraction computes anyway,
    one top-2, two argmax).  The B→A matching direction is used throughout,
    matching :func:`~ncnet_tpu.ops.matching.corr_to_matches`'s default.
    """
    from ncnet_tpu.ops.matching import mutual_argmax_agreement

    b, ha, wa, hb, wb = corr.shape
    n_a, n_b = ha * wa, hb * wb
    flat = corr.astype(jnp.float32).reshape(b, n_a, n_b)
    # distribution over A cells per B cell (B→A, corr_to_matches default)
    p = jax.nn.softmax(flat, axis=1)

    # top-2 over the A axis: top1 is the softmax match score, the gap to
    # top2 is the match's decision margin
    top2 = jax.lax.top_k(jnp.swapaxes(p, 1, 2), 2)[0]  # (B, n_b, 2)
    score = jnp.mean(top2[..., 0], axis=1)
    margin = jnp.mean(top2[..., 0] - top2[..., 1], axis=1)

    ent = -jnp.sum(p * jnp.log(p + 1e-12), axis=1)  # (B, n_b)
    entropy = jnp.mean(ent, axis=1) / jnp.log(float(n_a))

    agreement = mutual_argmax_agreement(corr)

    # displacement-field coherence: matched A coordinates as a field over
    # the B grid; adjacent B cells of a coherent flow map to A cells one
    # expected-grid-step apart.  The tolerance band is 0.9 of a step (L∞,
    # per axis), DELIBERATELY below one full step: a volume collapsed to a
    # constant argmax (the tie behavior of a flattened/broken tier — every
    # B cell matching A cell 0) advances 0 per step, exactly one step off,
    # and an inclusive ±1-step band would score that pathology 1.0 like a
    # perfect identity flow.  The cost is that genuine plateaus (two
    # adjacent B cells sharing an A cell) also count incoherent — stricter,
    # but rigid/identity flows still score exactly 1.0 and the gate only
    # consumes the signal's DRIFT, not its absolute value.
    idx_a = jnp.argmax(flat, axis=1)  # (B, n_b) flattened A index per B cell
    ia = (idx_a // wa).reshape(b, hb, wb).astype(jnp.float32)
    ja = (idx_a % wa).reshape(b, hb, wb).astype(jnp.float32)
    # expected A-cells-per-B-cell step (1.0 on the square volumes)
    step_i = (ha - 1) / max(hb - 1, 1)
    step_j = (wa - 1) / max(wb - 1, 1)
    tol_i = 0.9 * max(step_i, 1.0)
    tol_j = 0.9 * max(step_j, 1.0)
    ok_terms: List[jnp.ndarray] = []
    if hb > 1:
        di = ia[:, 1:, :] - ia[:, :-1, :] - step_i
        dj = ja[:, 1:, :] - ja[:, :-1, :]
        ok_terms.append(((jnp.abs(di) <= tol_i) & (jnp.abs(dj) <= tol_j))
                        .astype(jnp.float32).reshape(b, -1))
    if wb > 1:
        di = ia[:, :, 1:] - ia[:, :, :-1]
        dj = ja[:, :, 1:] - ja[:, :, :-1] - step_j
        ok_terms.append(((jnp.abs(di) <= tol_i) & (jnp.abs(dj) <= tol_j))
                        .astype(jnp.float32).reshape(b, -1))
    if ok_terms:
        coherence = jnp.mean(jnp.concatenate(ok_terms, axis=1), axis=1)
    else:  # degenerate 1x1 B grid: no adjacency to judge
        coherence = jnp.ones((b,), jnp.float32)

    return {"score": score, "entropy": entropy, "margin": margin,
            "mnn_agreement": agreement, "coherence": coherence}


def quality_table(corr: jnp.ndarray) -> jnp.ndarray:
    """``(B, len(QUALITY_SIGNALS))`` float32 signal table — the stacked form
    the eval steps concatenate beside their per-pair results so ONE fetch
    carries both (the zero-per-pair-postprocessing contract)."""
    sigs = quality_signals(corr)
    return jnp.stack([sigs[name].astype(jnp.float32)
                      for name in QUALITY_SIGNALS], axis=1)


# ---------------------------------------------------------------------------
# host side: tier tagging, events, digests
# ---------------------------------------------------------------------------


def append_quality_rows(table: jnp.ndarray, corr: jnp.ndarray) -> jnp.ndarray:
    """Attach per-pair quality signals to a BATCHED ``(B, 5, N)`` match
    table as one extra zero-padded row per pair (values in the first
    ``len(QUALITY_SIGNALS)`` slots of row 5) → ``(B, 6, N)``.  THE wire
    layout — defined here, beside :data:`QUALITY_SIGNALS`, so every
    producer (``make_point_matcher``, InLoc's ``make_pair_matcher``, the
    serving ``BatchMatchEngine``) and both splitters can never disagree.
    A table too narrow to hold the signals (degenerate tiny grid) is
    returned unchanged; consumers detect the row by shape."""
    q = quality_table(corr)  # (B, S)
    if table.shape[2] < q.shape[1]:
        return table
    row = jnp.zeros((table.shape[0], 1, table.shape[2]), jnp.float32)
    row = row.at[:, 0, : q.shape[1]].set(q)
    return jnp.concatenate([table, row], axis=1)


def split_quality_rows(table: np.ndarray):
    """Invert :func:`append_quality_rows` on a fetched numpy batch table:
    ``(match_tables (B, 5, N), [per-pair {signal: float}] | None)`` — None
    when no quality rows were attached.  Anything that is not a batch
    table is a caller error (the single-pair splitter stays lenient for
    its legacy callers; a batch producer controls its own shape)."""
    if table.ndim != 3 or table.shape[1] not in (5, 6):
        raise ValueError(f"not a batched match table: {table.shape}")
    if table.shape[1] == 5:
        return table, None
    quality = [
        dict(zip(QUALITY_SIGNALS,
                 (float(v) for v in table[b, 5, : len(QUALITY_SIGNALS)])))
        for b in range(table.shape[0])
    ]
    return table[:, :5], quality


def append_quality_row(table: jnp.ndarray, corr: jnp.ndarray) -> jnp.ndarray:
    """Single-pair form of :func:`append_quality_rows` for the ``(5, N)``
    tables the batch-1 matchers pull (``corr`` must be batch 1)."""
    return append_quality_rows(table[None], corr)[0]


def split_quality_row(table: np.ndarray):
    """Invert :func:`append_quality_row` on the fetched numpy table:
    ``(match_rows (5, N), {signal: float} | None)`` — None when no quality
    row was attached."""
    if table.shape[0] != 6:
        return table, None
    signals = dict(zip(
        QUALITY_SIGNALS,
        (float(v) for v in table[5, : len(QUALITY_SIGNALS)]),
    ))
    return table[:5], signals


def active_tier(eligible: bool = True, stage: str = "forward") -> str:
    """The fused-tier label for quality events.

    ``eligible``: whether the program that produced the signals could have
    routed through the fused Pallas stack AT ALL — callers pass their
    config's ``half_precision`` (the chooser is only consulted for bf16
    volumes).  An ineligible program is ``"xla"`` by construction; asking
    the process-global ``last_selected_tier`` would return whatever a bf16
    program elsewhere in the process last decided (e.g. bench times the
    bf16 forward before measuring fp32 quality) and mis-file the digests
    under the wrong tier series.  For eligible programs the label is the
    stage chooser's most recent decision — per STAGE, not per shape, so a
    mixed-shape eligible run is tagged with its latest decision (shapes are
    constant within one eval/training run, where this is exact).

    The match-PIPELINE tier outranks the fused-stack tier: when the most
    recent pipeline decision (``ops/sparse_corr.choose_match_pipeline``,
    consulted by every feature-pair forward trace) routed through the
    coarse-to-fine sparse path, the signals describe THAT pipeline's
    volume — regardless of which fused-stack tier the coarse/tile stacks
    used inside it, and regardless of precision eligibility (the sparse
    pipeline runs in fp32 too).

    The ARITHMETIC forward tiers ('cp'/'fft', round 17) likewise pass
    through regardless of ``eligible``: they are precision-agnostic, fp32
    programs consult the chooser for them (and can force them via
    ``ModelConfig.nc_tier``), so when the stage's latest decision is one
    of them the signals really did flow through that arithmetic — the
    ``eligible`` guard exists only to keep Pallas-tier labels off
    programs that could not run Pallas."""
    from ncnet_tpu.ops import last_selected_tier

    if stage == "forward" and last_selected_tier("pipeline") == "coarse2fine":
        return "coarse2fine"
    selected = last_selected_tier(stage)
    if stage == "forward" and selected in ("cp", "fft"):
        return selected
    if not eligible:
        return "xla"
    return selected or "xla"


def emit_quality(scope: str, signals: Dict[str, Any], *,
                 tier: Optional[str] = None,
                 pck: Optional[Iterable[float]] = None,
                 registry=None, **ids) -> None:
    """Stream one unit's per-pair signals: a ``quality`` event into the
    bound sink (no-op when unbound), tagged with the active fused tier, and
    — when a registry is given — into its per-signal histogram digests
    (NaNs dropped there; they mark quarantined pairs).  ``pck`` rides along
    when labels exist so consumers can rank-correlate signal vs PCK."""
    from ncnet_tpu.observability import events as _events

    tier = tier or active_tier()
    sig_lists = {}
    for name, vals in signals.items():
        arr = np.atleast_1d(np.asarray(vals, dtype=np.float64))
        sig_lists[name] = [round(float(v), 6) for v in arr]
        if registry is not None:
            lo, hi = SIGNAL_RANGE.get(name, (0.0, 1.0))
            registry.histogram(f"q_{name}", lo, hi, DIGEST_BINS).add(
                arr[np.isfinite(arr)])
    if _events.get_global_sink() is not None:
        fields = dict(ids)
        if pck is not None:
            fields["pck"] = [round(float(v), 6)
                             for v in np.atleast_1d(np.asarray(pck))]
        _events.emit("quality", scope=scope, tier=tier,
                     signals=sig_lists, **fields)


def digests_from_events(events: Iterable[dict],
                        bins_like: Optional[dict] = None
                        ) -> Dict[Tuple[str, str], Histogram]:
    """Aggregate ``quality`` events into digests keyed ``(tier, signal)``.

    ``bins_like`` optionally maps signal name → snapshot dict whose binning
    must be matched (the drift check bins the current run exactly like the
    reference it is judged against)."""
    out: Dict[Tuple[str, str], Histogram] = {}
    for e in events:
        if e.get("event") != "quality":
            continue
        tier = str(e.get("tier") or "xla")
        for name, vals in (e.get("signals") or {}).items():
            key = (tier, name)
            h = out.get(key)
            if h is None:
                if bins_like is not None and name in bins_like:
                    ref = bins_like[name]
                    h = Histogram(float(ref["lo"]), float(ref["hi"]),
                                  len(ref["counts"]))
                else:
                    lo, hi = SIGNAL_RANGE.get(name, (0.0, 1.0))
                    h = Histogram(lo, hi, DIGEST_BINS)
                out[key] = h
            arr = np.atleast_1d(np.asarray(vals, dtype=np.float64))
            h.add(arr[np.isfinite(arr)])
    return out


# ---------------------------------------------------------------------------
# drift: PSI divergence against a committed reference distribution
# ---------------------------------------------------------------------------


def psi(ref: Histogram, cur: Histogram, eps: float = 1e-3) -> float:
    """Population Stability Index between two same-binned digests.

    ``sum((q_i - p_i) * ln(q_i / p_i))`` over bins with ``eps`` flooring
    (empty bins must not produce infinities).  Standard reading: < 0.1 no
    shift, 0.1-0.25 moderate, > 0.25 major — the drift gate defaults to
    0.25.  Symmetric and 0 for identical distributions.
    """
    if (ref.lo, ref.hi, ref.bins) != (cur.lo, cur.hi, cur.bins):
        raise ValueError("PSI requires identically-binned digests")
    if not ref.count or not cur.count:
        raise ValueError("PSI over an empty digest")
    p = np.maximum(np.asarray(ref.counts, np.float64) / ref.count, eps)
    q = np.maximum(np.asarray(cur.counts, np.float64) / cur.count, eps)
    p, q = p / p.sum(), q / q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


DEFAULT_PSI_THRESHOLD = 0.25


def default_reference_path() -> str:
    """``<repo>/perf/quality_ref.jsonl`` — beside the perf history it is the
    accuracy twin of."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "perf", "quality_ref.jsonl")


def write_reference(path: str,
                    digests: Dict[Tuple[str, str], Histogram], *,
                    device_kind: Optional[str],
                    meta: Optional[dict] = None) -> int:
    """Write (replace) a reference-distribution file: one self-describing
    JSONL record per (device_kind, tier, signal) series.  Returns the record
    count.  The file is the drift gate's committed baseline — re-seed it
    only from a CLEAN eval of the committed weights (README "Quality
    observability" documents the policy)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    n = 0
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for (tier, signal), h in sorted(digests.items()):
            if not h.count:
                continue
            rec = {"kind": REF_KIND, "schema": REF_SCHEMA,
                   "device_kind": device_kind or "unknown",
                   "tier": tier, "signal": signal,
                   "digest": h.snapshot()}
            if meta:
                rec["meta"] = meta
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            n += 1
    os.replace(tmp, path)
    return n


def load_reference(path: str) -> Dict[Tuple[str, str, str], Histogram]:
    """Reference digests keyed ``(device_kind, tier, signal)``.  Foreign or
    newer-schema lines are skipped (the perf-store tolerance discipline);
    a missing file is an empty reference, not an error — the drift tool
    reports the series it could not judge."""
    out: Dict[Tuple[str, str, str], Histogram] = {}
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return out
    for line in raw.split(b"\n"):
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if (not isinstance(rec, dict) or rec.get("kind") != REF_KIND
                or rec.get("schema", 0) > REF_SCHEMA):
            continue
        try:
            h = Histogram.from_snapshot(rec["digest"])
        except (KeyError, TypeError, ValueError):
            continue
        out[(str(rec.get("device_kind", "unknown")),
             str(rec.get("tier", "xla")),
             str(rec.get("signal", "")))] = h
    return out


def reference_binning(
        reference: Dict[Tuple[str, str, str], Histogram]) -> Dict[str, dict]:
    """Per-signal binning spec (``bins_like`` for
    :func:`digests_from_events`) from :func:`load_reference` output — THE
    rule both the standalone drift gate and ``run_report --quality`` bin
    current runs by, so their verdicts can never diverge.  First entry
    wins when one signal is binned differently across device kinds."""
    out: Dict[str, dict] = {}
    for (_dk, _tier, signal), h in reference.items():
        out.setdefault(signal, {"lo": h.lo, "hi": h.hi,
                                "counts": [0] * h.bins})
    return out


def check_drift(reference: Dict[Tuple[str, str, str], Histogram],
                current: Dict[Tuple[str, str], Histogram], *,
                device_kind: Optional[str],
                threshold: float = DEFAULT_PSI_THRESHOLD,
                min_count: int = 4) -> List[Dict[str, Any]]:
    """Judge every current (tier, signal) digest against the reference.

    Returns one finding per series — ``{"tier", "signal", "status":
    "ok"|"drift"|"skipped", "psi", ...}``, drifts first.  Series absent from
    the reference, binned differently, or with fewer than ``min_count``
    samples are ``skipped`` with a reason (a gate that guesses is worse
    than no gate) — and so are reference series this device kind SHOULD
    have produced but the run did not: a tier that silently stopped
    emitting must surface in the findings, not vanish from them.
    ``device_kind`` keys the reference lookup: digests are only comparable
    within one backend (the very shifts the gate hunts — bf16 tiers,
    kernel rewrites — are device-kind-shaped).
    """
    dk = device_kind or "unknown"
    findings: List[Dict[str, Any]] = []
    for (rdk, tier, signal) in sorted(reference):
        if rdk == dk and (tier, signal) not in current:
            findings.append({
                "tier": tier, "signal": signal, "device_kind": dk,
                "count": 0, "mean": None, "status": "skipped",
                "reason": "series present in the reference but absent "
                          "from this run (emitter broken, or the tier "
                          "never executed here)",
            })
    for (tier, signal), cur in sorted(current.items()):
        finding: Dict[str, Any] = {
            "tier": tier, "signal": signal, "device_kind": dk,
            "count": cur.count, "mean": cur.mean(),
        }
        ref = reference.get((dk, tier, signal))
        if ref is None:
            finding.update(status="skipped",
                           reason="no reference series for "
                                  f"({dk}, {tier}, {signal})")
        elif cur.count < min_count:
            finding.update(status="skipped",
                           reason=f"only {cur.count} sample(s) "
                                  f"(< min_count={min_count})")
        elif (ref.lo, ref.hi, ref.bins) != (cur.lo, cur.hi, cur.bins):
            finding.update(status="skipped",
                           reason="binning mismatch vs reference")
        else:
            d = psi(ref, cur)
            finding.update(
                psi=round(d, 6), threshold=threshold,
                ref_mean=ref.mean(), ref_count=ref.count,
                status="drift" if d > threshold else "ok",
            )
        findings.append(finding)
    findings.sort(key=lambda f: (f["status"] != "drift",
                                 f["status"] == "skipped",
                                 f["tier"], f["signal"]))
    return findings


# ---------------------------------------------------------------------------
# signal-vs-PCK validation (labels exist → the signals must track them)
# ---------------------------------------------------------------------------


def spearman(a, b) -> float:
    """Spearman rank correlation with average ranks for ties; NaN pairs are
    dropped, degenerate inputs (under 3 pairs, or a constant side) return
    NaN rather than a fake verdict."""
    a = np.asarray(a, dtype=np.float64).ravel()
    b = np.asarray(b, dtype=np.float64).ravel()
    m = np.isfinite(a) & np.isfinite(b)
    a, b = a[m], b[m]
    if a.size < 3:
        return float("nan")

    def rank(x):
        order = np.argsort(x, kind="stable")
        r = np.empty(x.size, dtype=np.float64)
        r[order] = np.arange(1, x.size + 1)
        # average ranks over tie groups
        for v in np.unique(x):
            tie = x == v
            if np.sum(tie) > 1:
                r[tie] = np.mean(r[tie])
        return r

    ra, rb = rank(a), rank(b)
    sa, sb = np.std(ra), np.std(rb)
    if sa == 0 or sb == 0:
        return float("nan")
    return float(np.mean((ra - np.mean(ra)) * (rb - np.mean(rb))) / (sa * sb))


def signal_pck_correlation(events: Iterable[dict]) -> Dict[str, float]:
    """Per-signal Spearman rank correlation between quality signals and
    per-pair PCK, over every ``quality`` event that carries both (the
    PF-Pascal eval emits them side by side).  The check that validates the
    signals as label-free PCK proxies."""
    pairs: Dict[str, List[Tuple[float, float]]] = {}
    for e in events:
        if e.get("event") != "quality" or not e.get("pck"):
            continue
        pck = e["pck"]
        for name, vals in (e.get("signals") or {}).items():
            if isinstance(vals, list) and len(vals) == len(pck):
                pairs.setdefault(name, []).extend(zip(vals, pck))
    return {
        name: spearman([p[0] for p in ps], [p[1] for p in ps])
        for name, ps in sorted(pairs.items())
    }
