"""Per-pair pose estimation from dense matches (parfor_NC4D_PE_pnponly.m).

Takes one query↔cutout match table (the ``(N,5)`` rows eval_inloc wrote),
thresholds by score, lifts query matches to viewing rays and database matches
to global 3D via the cutout's depth map, and runs the batched LO-RANSAC P3P.
Artifacts are saved per pair with a resume-by-artifact guard, mirroring the
reference's ``exist(...)~=2`` skip.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ncnet_tpu.localization import geometry
from ncnet_tpu.localization.p3p import lo_ransac_p3p
from ncnet_tpu.localization.scan import backproject_matches


class PnPResult(NamedTuple):
    P: np.ndarray               # (3,4) pose (NaN when not estimable)
    inliers: np.ndarray         # (M,) bool over the surviving tentatives
    tentatives_2d: np.ndarray   # (4,M) [xq; yq; xdb; ydb] pixel coords
    tentatives_3d: np.ndarray   # (6,M) [ray; X_global]
    idx_3d: np.ndarray          # (K,) bool: which thresholded matches had 3D


def estimate_pose_from_matches(
    matches: np.ndarray,
    query_size: Tuple[int, int],
    xyzcut: np.ndarray,
    P_after: np.ndarray,
    focal: float,
    score_thr: float = 0.75,
    inlier_thr_deg: float = 0.2,
    ransac_iters: int = 10000,
    seed: int = 0,
    max_tentatives: int = 0,
) -> PnPResult:
    """The reference's per-pair flow (parfor_NC4D_PE_pnponly.m):

      1. keep matches with ``score > score_thr``;
      2. query coords: ``pixel = size · normalized`` against the FULL-RES
         query image, rays through ``Kq⁻¹`` with the center principal point;
      3. db coords: floor-gather the cutout depth map, map through the scan
         transformation, drop non-finite 3D;
      4. LO-RANSAC P3P at the angular threshold (0.2° default, 10k samples).

    ``max_tentatives``: optional random subsample cap (the reference's
    ``params.ncnet.N_subsample`` branch); 0 = keep all.
    """
    m = np.asarray(matches, dtype=np.float64).reshape(-1, 5)
    m = m[m[:, 4] > score_thr]
    if max_tentatives and len(m) > max_tentatives:
        sel = np.random.default_rng(seed).permutation(len(m))[:max_tentatives]
        m = m[sel]
    qh, qw = query_size
    xq = np.stack([qw * m[:, 0], qh * m[:, 1]], axis=1)  # (K,2) query pixels

    X_global, keep, db_px = backproject_matches(xyzcut, m[:, 2:4], P_after)
    xq = xq[keep]
    db_px = db_px[keep]
    K = geometry.intrinsics(focal, qh, qw)
    rays = geometry.pixel_rays(K, xq)

    tent_2d = np.concatenate([xq.T, db_px.T.astype(np.float64)], axis=0)
    tent_3d = np.concatenate([rays.T, X_global.T], axis=0)

    if X_global.shape[0] < 3:
        return PnPResult(
            np.full((3, 4), np.nan),
            np.zeros((X_global.shape[0],), dtype=bool),
            tent_2d,
            tent_3d,
            keep,
        )
    res = lo_ransac_p3p(
        rays,
        X_global,
        np.deg2rad(inlier_thr_deg),
        iters=ransac_iters,
        seed=seed,
    )
    return PnPResult(res.P, res.inliers, tent_2d, tent_3d, keep)


def artifact_stem(db_fn: str) -> str:
    """Collision-free flat filename stem for a db cutout path: directory
    components (floor etc.) joined into the name with ``__``.  The reference
    keys artifacts on the basename only (params.output.pnp_nc4d.matformat),
    so ``DUC1/X.jpg`` and ``DUC2/X.jpg`` collide — fatal here because the
    artifact is also the resume source of truth.  When a path segment itself
    contains ``__`` the join is ambiguous; a short path hash is appended to
    keep the mapping injective while leaving InLoc-style names readable."""
    rel = os.path.splitext(db_fn)[0].replace("\\", "/").strip("/")
    parts = [p for p in rel.split("/") if p]
    stem = "__".join(parts)
    # the join is uniquely decodable iff no part contains "__" and no part
    # starts/ends with "_" (the latter shows up as a ≥3-underscore run)
    if any("__" in p for p in parts) or "___" in stem:
        import hashlib

        stem += "." + hashlib.sha1(rel.encode()).hexdigest()[:8]
    return stem


def pnp_artifact_path(out_dir: str, query_fn: str, db_fn: str) -> str:
    """``<out_dir>/<query>/<floor>__<db-basename>.pnp_nc4d_inlier.mat``."""
    return os.path.join(
        out_dir, query_fn, artifact_stem(db_fn) + ".pnp_nc4d_inlier.mat"
    )


def run_pair_pnp(
    out_dir: str,
    query_fn: str,
    db_fn: str,
    matches: np.ndarray,
    query_size: Tuple[int, int],
    xyzcut: np.ndarray,
    P_after: np.ndarray,
    focal: float,
    **kwargs,
) -> Tuple[np.ndarray, np.ndarray]:
    """Estimate (or reload) the pose for one pair; persists the result .mat
    and skips work whose artifact exists — the resume-by-artifact behavior
    the reference uses as failure recovery (SURVEY §5.3).  Returns
    ``(P, inliers)``."""
    from scipy.io import loadmat

    from ncnet_tpu.utils.io import atomic_savemat

    path = pnp_artifact_path(out_dir, query_fn, db_fn)
    if os.path.exists(path):
        mat = loadmat(path)
        return np.asarray(mat["P"]), np.asarray(mat["inls"]).ravel().astype(bool)
    res = estimate_pose_from_matches(
        matches, query_size, xyzcut, P_after, focal, **kwargs
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_savemat(
        path,
        {
            "P": res.P,
            "inls": res.inliers,
            "tentatives_2d": res.tentatives_2d,
            "tentatives_3d": res.tentatives_3d,
            "idx_3d": res.idx_3d,
        },
        do_compression=True,
    )
    return res.P, res.inliers
