"""Localization-rate curves vs reference poses (ht_plotcurve_WUSTL.m).

Given per-query top-1 poses and the ground-truth pose lists (DUC1/DUC2), a
query is "correctly localized" at distance threshold d when its top-1 cutout
is on the right floor, its pose is finite, its camera-center error is < d and
its orientation error is ≤ 10°.  The reference plots % localized over
thresholds 0→2 m and writes one ``error_<method>.txt`` with per-query errors.
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ncnet_tpu.localization.geometry import pose_distance

# the reference's threshold grid: 0:0.0625:1 then 1.125:0.125:2
ERROR_THRESHOLDS = np.concatenate(
    [np.arange(0.0, 1.0 + 1e-9, 0.0625), np.arange(1.125, 2.0 + 1e-9, 0.125)]
)
MAX_ORIENTATION_ERR_DEG = 10.0


class MethodResult(NamedTuple):
    description: str
    # per-query: queryname -> (top1 cutout name, top1 pose (3,4))
    top1: Dict[str, Tuple[str, np.ndarray]]


def load_reference_poses(path: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Parse the ground-truth pose .mat (lib_matlab/DUC_refposes_all.mat):
    ``{'DUC1': {queryname: P (3,4)}, 'DUC2': {...}}``."""
    from scipy.io import loadmat

    mat = loadmat(path, simplify_cells=True)
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for floor in ("DUC1", "DUC2"):
        reflist = mat[f"{floor}_RefList"]
        if isinstance(reflist, dict):  # single-entry lists simplify to a dict
            reflist = [reflist]
        out[floor] = {
            str(e["queryname"]): np.asarray(e["P"], dtype=np.float64)[:3, :4]
            for e in reflist
        }
    return out


def pose_errors(
    method: MethodResult,
    refposes: Dict[str, Dict[str, np.ndarray]],
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Per-query (position, orientation) errors against ground truth, inf for
    missing / wrong-floor / NaN poses — the reference's exact gating
    (ht_plotcurve_WUSTL.m: top-1 floor must match the GT floor prefix)."""
    poserr, orierr, names = [], [], []
    for floor, ref in refposes.items():
        for qname, P_ref in ref.items():
            names.append(qname)
            entry = method.top1.get(qname)
            if entry is None:
                poserr.append(np.inf)
                orierr.append(np.inf)
                continue
            top1_name, P = entry
            floor_ok = top1_name.replace("\\", "/").split("/")[0] == floor
            if floor_ok and np.all(np.isfinite(np.asarray(P))):
                dp, do = pose_distance(P_ref, P)
                poserr.append(dp)
                orierr.append(do)
            else:
                poserr.append(np.inf)
                orierr.append(np.inf)
    return np.asarray(poserr), np.asarray(orierr), names


def localized_rate_curve(
    poserr: np.ndarray,
    orierr: np.ndarray,
    thresholds: np.ndarray = ERROR_THRESHOLDS,
    max_orierr_deg: float = MAX_ORIENTATION_ERR_DEG,
) -> np.ndarray:
    """Fraction of queries with position error < each threshold, orientation
    gated at ``max_orierr_deg`` (ht_plotcurve_WUSTL.m:70-84)."""
    err = np.where(
        np.rad2deg(orierr) > max_orierr_deg, np.inf, poserr
    )
    return (err[:, None] < thresholds[None, :]).mean(axis=0)


def write_error_txt(
    path: str, names: Sequence[str], poserr: np.ndarray, orierr: np.ndarray
) -> None:
    """Per-query ``<name> <poserr> <orierr>`` lines
    (the reference's error_<method>.txt)."""
    with open(path, "w") as f:
        for n, dp, do in zip(names, poserr, orierr):
            f.write(f"{n} {dp:f} {do:f}\n")


def plot_localization_curves(
    methods: Sequence[MethodResult],
    refposes: Dict[str, Dict[str, np.ndarray]],
    out_dir: str,
    markers: Optional[Sequence[str]] = None,
) -> Dict[str, np.ndarray]:
    """Compute, plot and persist the localization curves for all methods.

    Writes ``error_<method>.txt`` per method plus the curve figure
    (``athr10_<N>.png``/.eps twins of the reference's .fig/.eps) into
    ``out_dir``.  Returns ``{description: curve}``.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    curves: Dict[str, np.ndarray] = {}
    fig, ax = plt.subplots(figsize=(5, 5))
    styles = markers or ["--b", "--c", "--m", "--g"]
    n_queries = 0
    for i, method in enumerate(methods):
        poserr, orierr, names = pose_errors(method, refposes)
        n_queries = len(names)
        write_error_txt(
            os.path.join(out_dir, f"error_{method.description}.txt"),
            names, poserr, orierr,
        )
        curve = localized_rate_curve(poserr, orierr)
        curves[method.description] = curve
        ax.plot(
            ERROR_THRESHOLDS, curve * 100.0, styles[i % len(styles)],
            linewidth=2.0, label=method.description,
        )
    ax.set_xlim(0, 2)
    ax.set_ylim(0, 80)
    ax.grid(True)
    ax.set_xticks(np.arange(0, 2.25, 0.25))
    ax.set_xlabel("Distance threshold [meters]")
    ax.set_ylabel("Correctly localized queries [%]")
    ax.legend(loc="lower right", fontsize=10)
    base = os.path.join(
        out_dir, f"athr{MAX_ORIENTATION_ERR_DEG:.4f}_{n_queries}"
    )
    fig.savefig(base + ".png", dpi=160)
    fig.savefig(base + ".eps")
    plt.close(fig)
    return curves
