"""The InLoc localization driver (compute_densePE_NCNet.m, end to end).

Consumes the ``matches/<experiment>/<q>.mat`` tables written by
``eval_inloc``, estimates a pose per (query, top-10 cutout) with the batched
LO-RANSAC P3P, optionally re-ranks the candidates by synthetic-view pose
verification, and emits the localization-rate curves against the reference
poses.  Every stage persists .mat artifacts and resumes from them — the
reference's resume-by-artifact failure story (SURVEY §5.3) — and the PnP
stage adds per-query fault isolation (retry → quarantine into a stage
manifest, evaluation/resilience.py) so one broken query's inputs cannot
abort the whole localization run.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ncnet_tpu.config import LocalizationConfig
from ncnet_tpu.localization import geometry
from ncnet_tpu.observability import get_logger
from ncnet_tpu.observability.tracing import span

log = get_logger("localization")
from ncnet_tpu.localization.curves import (
    MethodResult,
    load_reference_poses,
    plot_localization_curves,
)
from ncnet_tpu.localization.pnp import run_pair_pnp
from ncnet_tpu.localization.scan import (
    load_transformation,
    load_xyzcut,
    transformation_path,
)
from ncnet_tpu.localization.verification import (
    PVItem,
    rerank_by_scores,
    run_pose_verification,
)


def image_size(path: str) -> Tuple[int, int]:
    """(height, width) from the image header, without decoding pixels."""
    from PIL import Image

    with Image.open(path) as im:
        w, h = im.size
    return h, w


def query_focal(config: LocalizationConfig, height: int, width: int) -> float:
    """Configured query focal length, or the iPhone 7 EXIF-derived default
    (the reference reads ``params.data.q.fl`` from its external project
    setup).  Derived from the image's long side — see
    :func:`geometry.iphone7_focal`."""
    if config.query_focal_length > 0:
        return config.query_focal_length
    return geometry.iphone7_focal(height, width)


def _cell_row(items) -> np.ndarray:
    """(1, N) object array — a MATLAB cell row.  Built element-wise because
    ``np.array(list_of_equal_shape_arrays, dtype=object)`` would broadcast
    into one numeric block instead of N cells."""
    out = np.empty((1, len(items)), dtype=object)
    for i, v in enumerate(items):
        out[0, i] = v
    return out


def _save_imglist(path: str, imglist: List[dict]) -> None:
    from ncnet_tpu.utils.io import atomic_savemat as savemat

    savemat(
        path,
        {
            "ImgList": np.array(
                [
                    (
                        e["queryname"],
                        _cell_row(e["topNname"]),
                        np.asarray(e.get("topNscore", []), dtype=np.float64
                                   ).reshape(1, -1),
                        _cell_row(e["P"]),
                    )
                    for e in imglist
                ],
                dtype=[
                    ("queryname", object),
                    ("topNname", object),
                    ("topNscore", object),
                    ("P", object),
                ],
            ).reshape(1, -1)
        },
        do_compression=True,
    )


def _load_imglist(path: str) -> List[dict]:
    from scipy.io import loadmat

    mat = loadmat(path, simplify_cells=True)
    entries = mat["ImgList"]
    if isinstance(entries, dict):
        entries = [entries]
    out = []
    for e in entries:
        names = e["topNname"]
        if isinstance(names, str):
            names = [names]
        poses = e["P"]
        if isinstance(poses, np.ndarray) and poses.ndim == 2:
            poses = [poses]
        scores = e.get("topNscore", [])
        if isinstance(scores, (int, float)):
            scores = [scores]
        out.append(
            {
                "queryname": str(e["queryname"]),
                "topNname": [str(n) for n in names],
                "topNscore": list(np.asarray(scores, dtype=np.float64).ravel()),
                "P": [np.asarray(p, dtype=np.float64) for p in poses],
            }
        )
    return out


def _worker_init() -> None:
    """Pin spawned workers (PnP and PV pools) to the CPU backend: N workers
    racing to attach a single tunneled TPU would fail, and the per-item work
    is small enough that host cores win once they run in parallel."""
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except (AttributeError, RuntimeError, ValueError) as e:
        # the known failure shapes: unknown option (AttributeError /
        # ValueError across jax versions) or a backend already initialized
        # (RuntimeError).  Anything else is a bug that should surface, not
        # be swallowed — per-query failures are isolated at the stage level
        # (run_pnp_stage's run_isolated + manifest), not here.
        # sys.stderr directly, not the logger: this runs in a freshly
        # spawned pool worker whose stdout may be inherited mid-capture,
        # and stderr is where the parent's diagnostics are collected
        sys.stderr.write(
            f"warning: pool worker could not pin the CPU backend ({e}); "
            "workers may contend for the accelerator\n")


def _spawn_pool(num_workers: int):
    """Spawn-based process pool with the CPU-pinning initializer — shared by
    the PnP (per-query) and PV (per-scan-group) stages."""
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor

    return ProcessPoolExecutor(
        max_workers=num_workers,
        mp_context=mp.get_context("spawn"),
        initializer=_worker_init,
    )


def _pnp_one_query(config: LocalizationConfig, qi: int, qname: str,
                   top_names: List[str]) -> dict:
    """All top-N pose estimates for one query — the unit of host-side task
    parallelism (the reference's ``parfor ii = 1:Nq``,
    ir_top100_NC4D_localization_pnponly.m)."""
    from scipy.io import loadmat

    pnp_dir = os.path.join(config.output_dir, _pnp_dirname(config))
    qsize = image_size(os.path.join(config.query_path, qname))
    focal = query_focal(config, qsize[0], qsize[1])
    match_mat = loadmat(
        os.path.join(config.matches_dir, f"{qi + 1}.mat")
    )["matches"]
    # the match table's pano depth bounds how many candidates exist
    top_names = top_names[: min(config.pnp_topN, match_mat.shape[1])]
    poses: List[np.ndarray] = []
    for jj, db_fn in enumerate(top_names):
        xyzcut = load_xyzcut(
            os.path.join(config.cutout_path, db_fn + config.cutout_mat_suffix)
        )
        P_after = load_transformation(
            transformation_path(config.transformation_path, db_fn)
        )
        P, _ = run_pair_pnp(
            pnp_dir,
            qname,
            db_fn,
            match_mat[0, jj],
            qsize,
            xyzcut,
            P_after,
            focal,
            score_thr=config.match_score_thr,
            inlier_thr_deg=config.pnp_inlier_thr_deg,
            ransac_iters=config.ransac_iters,
            seed=config.seed,
            max_tentatives=config.max_tentatives,
        )
        poses.append(P)
        if config.progress:
            log.info(f"nc4dPE: {qname} vs {db_fn} DONE.")
    return {"queryname": qname, "topNname": top_names, "P": poses}


def run_pnp_stage(config: LocalizationConfig) -> List[dict]:
    """Pose per (query, top-N cutout) from the dense matches
    (ir_top100_NC4D_localization_pnponly.m).  Returns the ImgList and writes
    ``top_<N>_thr..._rthr....mat``; reloads it when it already exists.

    ``config.num_workers > 0`` fans queries out over a spawn-based process
    pool — the Python equivalent of the reference's MATLAB ``parfor`` over
    queries; the per-pair artifact files make retries/collisions safe.

    Per-query fault isolation (round 7): a query whose inputs are broken —
    unreadable matches .mat, missing cutout depth, undecodable query image —
    is retried with backoff and then QUARANTINED into the stage manifest
    (``<pnp_dir>/manifest.json``) with a classified failure record, instead
    of aborting the whole stage as the previous ``pool.map`` did on the
    first worker exception.  A quarantined query is excluded from the
    ImgList; downstream curve scoring already treats a missing query as
    not-localized (``pose_errors`` fills inf), so the run's result stays
    well-defined.
    """
    from ncnet_tpu.evaluation.inloc import _as_str, load_shortlist
    from ncnet_tpu.evaluation.resilience import (
        FaultPolicy,
        QuarantineBreaker,
        RunManifest,
        run_isolated,
    )

    out_path = os.path.join(config.output_dir, _pnp_matname(config))
    if os.path.exists(out_path):
        return _load_imglist(out_path)

    query_fns, pano_fns = load_shortlist(config.shortlist)
    n_queries = len(query_fns)
    if config.n_queries > 0:
        n_queries = min(n_queries, config.n_queries)
    args = [
        (config, qi, query_fns[qi],
         [_as_str(n) for n in np.asarray(pano_fns[qi]).ravel()])
        for qi in range(n_queries)
    ]
    pnp_dir = os.path.join(config.output_dir, _pnp_dirname(config))
    os.makedirs(pnp_dir, exist_ok=True)
    manifest = RunManifest(
        os.path.join(pnp_dir, "manifest.json"),
        meta={"stage": "pnp", "n_queries": n_queries,
              "matches_dir": config.matches_dir},
    )
    policy = FaultPolicy(retries=config.query_retries,
                         backoff_s=config.retry_backoff_s,
                         quarantine=config.quarantine)
    # N consecutive quarantines = systemic (bad matches_dir, dead pool
    # survivor): abort loudly instead of quarantining every query
    breaker = QuarantineBreaker(policy.max_consecutive_quarantines)
    imglist: List[dict] = []
    if config.num_workers > 0:
        with _spawn_pool(config.num_workers) as pool:
            futures = [pool.submit(_pnp_one_query, *a) for a in args]
            try:
                for a, fut in zip(args, futures):
                    first = {"fut": fut}

                    def work(a=a, first=first):
                        f = first["fut"]
                        first["fut"] = None
                        if f is None:  # retry: resubmit to the pool
                            f = pool.submit(_pnp_one_query, *a)
                        # the span is the parent's WAIT on the worker (the
                        # spawned process has no event sink); per-query
                        # compute beyond the first is hidden behind earlier
                        # waits, exactly what the trace should show
                        with span("pnp_query", query=a[2]):
                            return f.result()

                    ok, entry = run_isolated(
                        a[2], work, policy=policy, manifest=manifest,
                        label=f"PnP query {a[2]}",
                    )
                    breaker.note(not ok)
                    if ok:
                        imglist.append(entry)
            except BaseException:
                # abort paths (SystemicEvalError, quarantine=False) must
                # surface NOW: without cancelling, the pool's __exit__
                # would first wait out every pending future's discarded work
                pool.shutdown(wait=False, cancel_futures=True)
                raise
    else:
        for a in args:

            def _one(a=a):
                with span("pnp_query", query=a[2]):
                    return _pnp_one_query(*a)

            ok, entry = run_isolated(
                a[2], _one,
                policy=policy, manifest=manifest,
                label=f"PnP query {a[2]}",
            )
            breaker.note(not ok)
            if ok:
                imglist.append(entry)
    if manifest.quarantined_ids:
        # a degraded ImgList must NOT become the stage's resume artifact —
        # the exists-guard above would pin it forever.  Return this run's
        # partial result, but let the next run retry the quarantined
        # queries; the per-pair artifacts in pnp_dir make the recompute of
        # the completed queries cheap (run_pair_pnp resumes from them).
        log.warning("PnP stage completed with quarantined queries "
                    f"({', '.join(manifest.quarantined_ids)}); the stage "
                    ".mat is NOT written so a rerun retries them (completed "
                    "queries resume from their per-pair artifacts)",
                    kind="quarantine")
        return imglist
    os.makedirs(config.output_dir, exist_ok=True)
    _save_imglist(out_path, imglist)
    return imglist


def _pv_run_items(config: LocalizationConfig, items_ser,
                  prepared_queries=None, progress=None) -> Dict:
    """Score a batch of PV items (one scan group when pooled).  Module-level
    and plain-data-argumented so spawn workers can run it."""
    items = [PVItem(q, d, np.asarray(P)) for q, d, P in items_ser]

    def query_loader(fn: str) -> np.ndarray:
        from ncnet_tpu.data.datasets import load_image

        return load_image(os.path.join(config.query_path, fn))

    return run_pose_verification(
        items,
        query_loader,
        scan_dir=config.scan_path,
        trans_dir=config.transformation_path,
        focal_fn=lambda fn, img: query_focal(config, img.shape[0], img.shape[1]),
        out_dir=os.path.join(config.output_dir, _pv_dirname(config)),
        scan_suffix=config.scan_suffix,
        progress=config.progress if progress is None else progress,
        prepared_queries=prepared_queries,
    )


def run_pv_stage(
    config: LocalizationConfig, imglist: List[dict],
    pin_resume: bool = True,
) -> List[dict]:
    """Pose-verification rerank of each query's candidates
    (ht_top10_NC4D_PV_localization.m); writes/reloads the densePV ImgList.

    ``config.num_workers > 0`` fans the unique-scan groups out over a spawn
    process pool — the reference's ``parfor`` over scans; per-item .pv.mat
    artifacts keep pooled reruns collision-safe.

    ``pin_resume=False`` (used when the upstream PnP stage ran degraded —
    quarantined queries): neither reload nor write the stage-level resume
    .mat, so a degraded rerank can never be pinned as the experiment's
    final answer; the per-item .pv.mat artifacts still make the eventual
    clean rerun cheap.
    """
    from ncnet_tpu.localization.verification import group_items_by_scan

    out_path = os.path.join(config.output_dir, _pv_matname(config))
    if pin_resume and os.path.exists(out_path):
        return _load_imglist(out_path)

    items = [
        PVItem(e["queryname"], db_fn, P)
        for e in imglist
        for db_fn, P in zip(e["topNname"], e["P"])
    ]

    if config.num_workers > 0:
        from ncnet_tpu.data.datasets import load_image
        from ncnet_tpu.localization.verification import downsample_image

        group_map = sorted(group_items_by_scan(items).items())
        groups = [
            [(it.query_fn, it.db_fn, np.asarray(it.P)) for it in group]
            for _, group in group_map
        ]
        # decode + downsample every query ONCE in the parent and ship the
        # small (H/8) arrays to the workers — a query appears in up to topN
        # scan groups, so per-worker caches would redo the full-res decode
        # per group
        prepared: Dict[str, tuple] = {}
        for e in imglist:
            fn = e["queryname"]
            if fn not in prepared:
                img = load_image(os.path.join(config.query_path, fn))
                prepared[fn] = (
                    downsample_image(img),
                    query_focal(config, img.shape[0], img.shape[1]),
                )
        per_group_prepared = [
            {q: prepared[q] for q, _, _ in group} for group in groups
        ]
        scores: Dict = {}
        with _spawn_pool(config.num_workers) as pool:
            results = pool.map(
                _pv_run_items,
                [config] * len(groups),
                groups,
                per_group_prepared,
                [False] * len(groups),  # workers stay quiet; parent reports
            )
            for gi, ((key, _), part) in enumerate(zip(group_map, results)):
                scores.update(part)
                if config.progress:
                    log.info(f"ncnetPV: scan {key} ({gi + 1} / "
                             f"{len(groups)}) done.")
    else:
        with span("pv_score", items=len(items)):
            scores = _pv_run_items(
                config, [(it.query_fn, it.db_fn, it.P) for it in items]
            )

    reranked = []
    for e in imglist:
        s = [scores[(e["queryname"], n)] for n in e["topNname"]]
        names, poses, s = rerank_by_scores(e["topNname"], e["P"], s)
        reranked.append(
            {
                "queryname": e["queryname"],
                "topNname": names,
                "topNscore": s,
                "P": poses,
            }
        )
    if pin_resume:
        _save_imglist(out_path, reranked)
    else:
        log.warning("densePV stage ran on a degraded (quarantined) PnP "
                    "result; its stage .mat is NOT written so a rerun "
                    "recomputes from the retried PnP stage",
                    kind="quarantine")
    return reranked


def pnp_stage_degraded(config: LocalizationConfig) -> bool:
    """Whether the PnP stage's manifest records quarantined queries — the
    downstream signal that this run's ImgList is partial and no stage may
    pin a resume artifact built from it."""
    from ncnet_tpu.evaluation.resilience import manifest_has_quarantined

    return manifest_has_quarantined(
        os.path.join(config.output_dir, _pnp_dirname(config), "manifest.json")
    )


def run_localization(config: LocalizationConfig) -> Dict[str, np.ndarray]:
    """The full L6 pipeline; returns ``{method description: curve}`` and
    writes curves/figures/error txts into ``config.output_dir``."""
    imglist = run_pnp_stage(config)
    degraded = pnp_stage_degraded(config)
    methods = [
        MethodResult(
            "DensePE + NCNet",
            {e["queryname"]: (e["topNname"][0], e["P"][0]) for e in imglist},
        )
    ]
    if config.do_pose_verification:
        reranked = run_pv_stage(config, imglist, pin_resume=not degraded)
        methods.append(
            MethodResult(
                "InLoc + NCNet",
                {
                    e["queryname"]: (e["topNname"][0], e["P"][0])
                    for e in reranked
                },
            )
        )
    refposes = load_reference_poses(config.refposes)
    return plot_localization_curves(methods, refposes, config.output_dir)


def _variant_suffix(config: LocalizationConfig) -> str:
    """Result-affecting knobs this port adds over the reference (whose
    artifact names only encode topN/thr/rthr) must key the resume artifacts
    too, or a rerun with different settings silently reloads stale results."""
    s = ""
    if config.n_queries > 0:
        s += f"_nq{config.n_queries}"
    if config.seed != 0:
        s += f"_seed{config.seed}"
    if config.ransac_iters != 10000:
        s += f"_it{config.ransac_iters}"
    if config.max_tentatives:
        s += f"_sub{config.max_tentatives}"
    return s


def _pnp_dirname(config: LocalizationConfig) -> str:
    return (
        f"top_{config.pnp_topN}_PnP_thr{int(config.match_score_thr * 100):03d}"
        f"_rthr{int(config.pnp_inlier_thr_deg * 100):03d}"
        + _variant_suffix(config)
    )


def _pnp_matname(config: LocalizationConfig) -> str:
    return (
        f"top_{config.pnp_topN}_thr{int(config.match_score_thr * 100):03d}"
        f"_rthr{int(config.pnp_inlier_thr_deg * 100):03d}"
        + _variant_suffix(config) + ".mat"
    )


def _pv_dirname(config: LocalizationConfig) -> str:
    return _pnp_matname(config)[:-4] + "_densePV"


def _pv_matname(config: LocalizationConfig) -> str:
    return _pnp_matname(config)[:-4] + "_densePV.mat"
