"""TPU-native InLoc localization stage: the reference's MATLAB L6 pipeline.

The reference hands the dense matches written by ``eval_inloc`` to a MATLAB
harness (compute_densePE_NCNet.m + lib_matlab/) that depends on two external
repos (InLoc_demo, VLFeat).  This package is a self-contained Python/JAX
re-implementation of that whole downstream stage:

  geometry.py      camera model, pose distance (p2c.m, p2dist.m), image cap
  p3p.py           batched Grunert P3P + Kabsch and LO-RANSAC with the
                   hypothesis×point scoring on device (ht_lo_ransac_p3p)
  scan.py          cutout-name parsing, scan transformation files, depth-map
                   back-projection, scan point-cloud loading
  render.py        point-cloud → perspective z-buffer render (ht_Points2Persp)
  dsift.py         dense SIFT + RootSIFT on device (vl_phow + relja_rootsift)
  pnp.py           per-pair pose estimation (parfor_NC4D_PE_pnponly.m)
  verification.py  synthetic-view pose verification (parfor_nc4d_PV.m,
                   ht_top10_NC4D_PV_localization.m)
  curves.py        localization-rate curves (ht_plotcurve_WUSTL.m)
  visualize.py     side-by-side match plots (show_matches2_horizontal.m)
  driver.py        the compute_densePE_NCNet.m pipeline
"""

from ncnet_tpu.localization.geometry import (
    camera_center,
    cap_longest_side_shape,
    intrinsics,
    pixel_rays,
    pose_distance,
    project_points,
)
from ncnet_tpu.localization.p3p import (
    lo_ransac_p3p,
    p3p_solve,
    refine_pose_object_space,
)
from ncnet_tpu.localization.pnp import estimate_pose_from_matches
from ncnet_tpu.localization.driver import run_localization

__all__ = [
    "camera_center",
    "cap_longest_side_shape",
    "intrinsics",
    "pixel_rays",
    "pose_distance",
    "project_points",
    "p3p_solve",
    "lo_ransac_p3p",
    "refine_pose_object_space",
    "estimate_pose_from_matches",
    "run_localization",
]
