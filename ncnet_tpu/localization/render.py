"""Point-cloud → perspective z-buffer render (the reference's external
``ht_Points2Persp``, used by parfor_nc4d_PV.m to synthesize the query view
from a pose candidate for pose verification)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


def render_points_perspective(
    rgb: np.ndarray,
    xyz: np.ndarray,
    KP: np.ndarray,
    height: int,
    width: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Splat a colored point cloud through a 3×4 projective camera ``KP``.

    ``rgb (N,3) uint8``, ``xyz (N,3)`` world points.  Each point lands on its
    rounded pixel; the nearest-depth point per pixel wins (z-buffer via a
    depth-descending scatter — later writes are nearer).  Returns
    ``(RGBpersp (H,W,3) uint8, XYZpersp (H,W,3) float64)`` with zeros / NaN
    where no point projects — the NaN convention parfor_nc4d_PV.m keys its
    validity mask on (``RGB_flag = all(~isnan(XYZpersp), 3)``).
    """
    KP = np.asarray(KP, dtype=np.float64)
    uvw = np.asarray(xyz, dtype=np.float64) @ KP[:, :3].T + KP[:, 3]
    depth = uvw[:, 2]
    front = depth > 1e-9
    with np.errstate(divide="ignore", invalid="ignore"):
        u = uvw[:, 0] / depth
        v = uvw[:, 1] / depth
    col = np.round(u).astype(np.int64)
    row = np.round(v).astype(np.int64)
    ok = front & (col >= 0) & (col < width) & (row >= 0) & (row < height)
    ok &= np.isfinite(u) & np.isfinite(v)

    flat = row[ok] * width + col[ok]
    order = np.argsort(-depth[ok], kind="stable")  # nearest written last
    flat = flat[order]

    rgb_img = np.zeros((height * width, 3), dtype=np.uint8)
    xyz_img = np.full((height * width, 3), np.nan)
    rgb_img[flat] = np.asarray(rgb)[ok][order]
    xyz_img[flat] = np.asarray(xyz, dtype=np.float64)[ok][order]
    return (
        rgb_img.reshape(height, width, 3),
        xyz_img.reshape(height, width, 3),
    )
