"""Side-by-side match visualization (lib_matlab/show_matches2_horizontal.m).

Grayscale the two images, scale the shorter one to equal height, concatenate
horizontally, and draw tentative matches (blue) with inliers highlighted
(green points + connecting lines).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ncnet_tpu.localization.dsift import rgb_to_gray

_GAP = 10  # horizontal gap between the two images, as in the reference


def show_matches_horizontal(
    image1: np.ndarray,
    image2: np.ndarray,
    xy1: np.ndarray,
    xy2: np.ndarray,
    inliers: Optional[np.ndarray] = None,
    ax=None,
    linewidth: float = 0.5,
):
    """Plot matches ``xy1 (N,2)`` in image1 ↔ ``xy2 (N,2)`` in image2 (pixel
    coords).  Returns the matplotlib axis."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    g1 = rgb_to_gray(image1)
    g2 = rgb_to_gray(image2)
    h1, w1 = g1.shape
    h2, w2 = g2.shape
    xy1 = np.asarray(xy1, dtype=np.float64).reshape(-1, 2).copy()
    xy2 = np.asarray(xy2, dtype=np.float64).reshape(-1, 2).copy()
    if h1 <= h2:  # scale image2 down to image1's height
        s = h1 / h2
        g2 = _rescale(g2, s)
        xy2 = xy2 * s
    else:
        s = h2 / h1
        g1 = _rescale(g1, s)
        xy1 = xy1 * s
    h = min(g1.shape[0], g2.shape[0])
    cat = np.concatenate(
        [g1[:h], np.full((h, _GAP), 255.0), g2[:h]], axis=1
    )
    xoff = g1.shape[1] + _GAP

    if ax is None:
        _, ax = plt.subplots(
            figsize=(cat.shape[1] / 100.0, cat.shape[0] / 100.0)
        )
    ax.imshow(cat, cmap="gray")
    ax.set_axis_off()
    ax.scatter(xy1[:, 0], xy1[:, 1], s=10, c="b")
    ax.scatter(xy2[:, 0] + xoff, xy2[:, 1], s=10, c="b")
    if inliers is not None and np.any(inliers):
        inl = np.asarray(inliers, dtype=bool)
        ax.scatter(xy1[inl, 0], xy1[inl, 1], s=10, c="g")
        ax.scatter(xy2[inl, 0] + xoff, xy2[inl, 1], s=10, c="g")
        for (x1, y1), (x2, y2) in zip(xy1[inl], xy2[inl]):
            ax.plot(
                [x1, x2 + xoff], [y1, y2], "-g", linewidth=linewidth
            )
    return ax


def _rescale(gray: np.ndarray, scale: float) -> np.ndarray:
    """Bilinear rescale of a 2D array (align-corners, matching ops/image)."""
    from ncnet_tpu.ops.image import resize_bilinear_align_corners_np

    out_h = max(1, int(round(gray.shape[0] * scale)))
    out_w = max(1, int(round(gray.shape[1] * scale)))
    return resize_bilinear_align_corners_np(
        gray[:, :, None].astype(np.float32), out_h, out_w
    )[:, :, 0]
