"""Absolute pose from 3 ray↔point correspondences, and LO-RANSAC around it.

The reference solves PnP per image pair with an external MATLAB routine
``ht_lo_ransac_p3p(rays, X, thr_rad, 10000)`` (parfor_NC4D_PE_pnponly.m) —
10,000 sequential minimal samples with local optimization.  Here the whole
RANSAC is batched the TPU way:

  * all minimal samples are solved at once — Grunert's P3P reduces each
    sample to a quartic, whose roots come from one stacked companion-matrix
    ``eigvals`` call, and all candidate poses come from one stacked Kabsch
    (3×3 SVDs);
  * hypothesis scoring — the actual FLOPs, |hypotheses| × |points| angular
    residuals — runs on device as a jitted einsum over fixed-shape chunks
    (shapes bucketed so repeated calls hit the jit cache);
  * local optimization refines the best hypothesis on its inliers with the
    object-space orthogonal iteration of Lu-Hager-Mjolsness, re-scoring
    until the inlier set stops growing.

Pose convention: see geometry.py (``x_cam = R X + t``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

_REAL_ROOT_TOL = 1e-6  # |imag| ≤ tol·max(1,|real|) counts as a real root


def _quartic_roots(coeffs: np.ndarray) -> np.ndarray:
    """Roots of stacked quartics ``(H,5)`` (highest degree first) via the
    companion matrix; returns ``(H,4)`` complex (NaN-filled for degenerate
    leading coefficients)."""
    c = np.asarray(coeffs, dtype=np.float64)
    finite = np.isfinite(c).all(axis=1)  # degenerate samples (e.g. duplicate
    c = np.where(finite[:, None], c, 0.0)  # points) produce NaN coefficients
    lead_ok = finite & (np.abs(c[:, 0]) > 1e-12 * np.max(np.abs(c), axis=1))
    safe = np.where(lead_ok, c[:, 0], 1.0)
    monic = c / safe[:, None]
    H = c.shape[0]
    comp = np.zeros((H, 4, 4))
    comp[:, 1, 0] = comp[:, 2, 1] = comp[:, 3, 2] = 1.0
    comp[:, :, 3] = -monic[:, [4, 3, 2, 1]]
    roots = np.linalg.eigvals(comp)
    roots[~lead_ok] = np.nan
    return roots


def _kabsch(X: np.ndarray, Y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Stacked rigid alignment: for each item find (R, t) minimizing
    ``‖Y − (R X + t)‖``.  ``X, Y: (..., N, 3)`` → ``R (...,3,3), t (...,3)``."""
    Xc = X - X.mean(axis=-2, keepdims=True)
    Yc = Y - Y.mean(axis=-2, keepdims=True)
    C = np.swapaxes(Yc, -1, -2) @ Xc  # (...,3,3) cross-covariance (Y·Xᵀ)
    U, _, Vt = np.linalg.svd(C)
    det = np.linalg.det(U @ Vt)
    D = np.zeros_like(C)
    D[..., 0, 0] = 1.0
    D[..., 1, 1] = 1.0
    D[..., 2, 2] = det
    R = U @ D @ Vt
    t = Y.mean(axis=-2) - np.squeeze(R @ X.mean(axis=-2)[..., None], -1)
    return R, t


def p3p_solve(rays: np.ndarray, X: np.ndarray) -> np.ndarray:
    """Grunert's P3P, batched: ``rays (H,3,3)`` unit viewing rays and
    ``X (H,3,3)`` world points → candidate poses ``(H,4,3,4)`` (≤4 real
    solutions per sample, invalid slots NaN).

    Method (Grunert 1841, in the formulation of Haralick et al., "Review and
    Analysis of Solutions of the Three Point Perspective Pose Estimation
    Problem", IJCV 1994): with point-camera distances s₁,s₂,s₃ and
    inter-point distances a,b,c, the law of cosines gives three equations;
    substituting u = s₂/s₁, v = s₃/s₁ eliminates to a quartic in v.  Each
    real root yields camera-frame points sᵢ·rayᵢ, and Kabsch aligns the world
    triangle onto them.
    """
    rays = np.asarray(rays, dtype=np.float64)
    X = np.asarray(X, dtype=np.float64)
    if rays.ndim == 2:
        rays, X = rays[None], X[None]
    H = rays.shape[0]

    a2 = np.sum((X[:, 1] - X[:, 2]) ** 2, axis=1)
    b2 = np.sum((X[:, 0] - X[:, 2]) ** 2, axis=1)
    c2 = np.sum((X[:, 0] - X[:, 1]) ** 2, axis=1)
    cos_a = np.sum(rays[:, 1] * rays[:, 2], axis=1)
    cos_b = np.sum(rays[:, 0] * rays[:, 2], axis=1)
    cos_g = np.sum(rays[:, 0] * rays[:, 1], axis=1)

    with np.errstate(divide="ignore", invalid="ignore"):
        ac_b = (a2 - c2) / b2  # (a²−c²)/b²
        apc_b = (a2 + c2) / b2
        A4 = (ac_b - 1.0) ** 2 - 4.0 * (c2 / b2) * cos_a**2
        A3 = 4.0 * (
            ac_b * (1.0 - ac_b) * cos_b
            - (1.0 - apc_b) * cos_a * cos_g
            + 2.0 * (c2 / b2) * cos_a**2 * cos_b
        )
        A2 = 2.0 * (
            ac_b**2
            - 1.0
            + 2.0 * ac_b**2 * cos_b**2
            + 2.0 * ((b2 - c2) / b2) * cos_a**2
            - 4.0 * apc_b * cos_a * cos_b * cos_g
            + 2.0 * ((b2 - a2) / b2) * cos_g**2
        )
        A1 = 4.0 * (
            -ac_b * (1.0 + ac_b) * cos_b
            + 2.0 * (a2 / b2) * cos_g**2 * cos_b
            - (1.0 - apc_b) * cos_a * cos_g
        )
        A0 = (1.0 + ac_b) ** 2 - 4.0 * (a2 / b2) * cos_g**2

    roots = _quartic_roots(np.stack([A4, A3, A2, A1, A0], axis=1))  # (H,4)
    real = (
        np.abs(roots.imag) <= _REAL_ROOT_TOL * np.maximum(1.0, np.abs(roots.real))
    ) & np.isfinite(roots.real)
    v = np.where(real, roots.real, np.nan)  # (H,4)

    with np.errstate(divide="ignore", invalid="ignore"):
        u = (
            (-1.0 + ac_b)[:, None] * v**2
            - 2.0 * (ac_b * cos_b)[:, None] * v
            + (1.0 + ac_b)[:, None]
        ) / (2.0 * (cos_g[:, None] - v * cos_a[:, None]))
        s1 = np.sqrt(
            b2[:, None] / (1.0 + v**2 - 2.0 * v * cos_b[:, None])
        )
        s2 = u * s1
        s3 = v * s1

    ok = (
        np.isfinite(s1) & np.isfinite(s2) & np.isfinite(s3)
        & (s1 > 0) & (s2 > 0) & (s3 > 0)
    )  # (H,4)
    s = np.stack([s1, s2, s3], axis=-1)  # (H,4,3) distances per solution
    s = np.where(ok[..., None], s, 1.0)
    Y = s[..., None] * rays[:, None, :, :]  # (H,4,3pts,3) camera-frame points
    Xr = np.broadcast_to(X[:, None], Y.shape)
    R, t = _kabsch(Xr.reshape(-1, 3, 3), Y.reshape(-1, 3, 3))
    P = np.concatenate([R, t[:, :, None]], axis=2).reshape(H, 4, 3, 4)
    P[~ok] = np.nan
    return P


def refine_pose_object_space(
    rays: np.ndarray, X: np.ndarray, P0: np.ndarray, iters: int = 20
) -> np.ndarray:
    """Object-space pose refinement (Lu, Hager & Mjolsness, PAMI 2000):
    alternate the closed-form optimal translation with a Procrustes rotation
    update, minimizing ``Σ‖(I − fᵢfᵢᵀ)(R Xᵢ + t)‖²``.  Used as the LO step of
    the RANSAC."""
    rays = np.asarray(rays, dtype=np.float64)
    X = np.asarray(X, dtype=np.float64)
    V = rays[:, :, None] * rays[:, None, :]  # (N,3,3) line-of-sight projectors
    I = np.eye(3)
    try:
        S = np.linalg.inv((I - V).sum(axis=0))  # (Σ(I−Vᵢ))⁻¹
    except np.linalg.LinAlgError:
        # all rays coincident (e.g. every tentative maps to one query pixel):
        # translation along the common ray is unobservable — keep the
        # hypothesis pose rather than aborting the caller's whole run
        return np.asarray(P0, dtype=np.float64)[:3, :4].copy()
    R = np.asarray(P0[:3, :3], dtype=np.float64).copy()
    t = np.asarray(P0[:3, 3], dtype=np.float64).copy()
    for _ in range(iters):
        t = -S @ np.einsum("nij,nj->i", I - V, X @ R.T)
        q = np.einsum("nij,nj->ni", V, X @ R.T + t)  # ray-projected targets
        R, t = _kabsch(X[None], q[None])
        R, t = R[0], t[0]
        t = -S @ np.einsum("nij,nj->i", I - V, X @ R.T)
    return np.concatenate([R, t[:, None]], axis=1)


class RansacResult(NamedTuple):
    P: np.ndarray          # (3,4) pose, NaN-filled when no model found
    inliers: np.ndarray    # (N,) bool
    num_inliers: int


@functools.lru_cache(maxsize=32)
def _scoring_fn(chunk: int, n_pad: int):
    """Jitted (chunk,3,4)-poses × (n_pad,)-points angular-inlier counter.
    Returns per-hypothesis inlier counts and the best hypothesis's mask."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def score(R, t, rays, X, valid, cos_thr):
        xc = jnp.einsum("hij,nj->hni", R, X) + t[:, None, :]
        norm = jnp.linalg.norm(xc, axis=-1)
        cos = jnp.einsum("hni,ni->hn", xc, rays) / jnp.maximum(norm, 1e-12)
        inl = (cos > cos_thr) & valid[None, :]
        counts = jnp.sum(inl, axis=1)
        best = jnp.argmax(counts)
        return counts, best, inl[best]

    return score


def _score_hypotheses(
    P: np.ndarray,
    rays: np.ndarray,
    X: np.ndarray,
    thr_rad: float,
    chunk: int = 2048,
) -> Tuple[int, int, np.ndarray]:
    """Best hypothesis index, its inlier count and mask, over ``P (M,3,4)``.

    Device-scored in fixed-shape chunks: points are padded to a power-of-two
    bucket and hypotheses to a multiple of ``chunk`` so every call shape
    recurs (jit cache hits across the 3,560 pairs of an InLoc run).
    """
    M, N = P.shape[0], rays.shape[0]
    n_pad = 1 << max(6, int(np.ceil(np.log2(max(N, 1)))))
    rays_p = np.zeros((n_pad, 3), dtype=np.float32)
    X_p = np.zeros((n_pad, 3), dtype=np.float32)
    valid = np.zeros((n_pad,), dtype=bool)
    rays_p[:N] = rays
    X_p[:N] = X
    valid[:N] = True
    # NaN poses (invalid P3P roots) score zero through the cosine comparison
    Pf = np.nan_to_num(P.astype(np.float32), nan=0.0)
    cos_thr = np.float32(np.cos(thr_rad))
    score = _scoring_fn(chunk, n_pad)

    best_count, best_idx, best_mask = -1, -1, None
    for lo in range(0, M, chunk):
        block = Pf[lo : lo + chunk]
        if block.shape[0] < chunk:
            block = np.concatenate(
                [block, np.zeros((chunk - block.shape[0], 3, 4), np.float32)]
            )
        counts, b, mask = score(
            block[:, :, :3], block[:, :, 3], rays_p, X_p, valid, cos_thr
        )
        b = int(b)
        c = int(counts[b])
        if lo + b < M and c > best_count:
            best_count, best_idx = c, lo + b
            best_mask = np.asarray(mask)[:N]
    return best_idx, best_count, best_mask


def lo_ransac_p3p(
    rays: np.ndarray,
    X: np.ndarray,
    thr_rad: float,
    iters: int = 10000,
    seed: int = 0,
    lo_rounds: int = 3,
    rng: Optional[np.random.Generator] = None,
) -> RansacResult:
    """LO-RANSAC absolute pose (the ``ht_lo_ransac_p3p`` contract): unit
    ``rays (N,3)``, world points ``X (N,3)``, angular inlier threshold
    ``thr_rad``, ``iters`` minimal samples.  Degenerate input (<3 points)
    returns a NaN pose, as the caller does in the reference
    (parfor_NC4D_PE_pnponly.m ``P = nan(3,4)``)."""
    rays = np.asarray(rays, dtype=np.float64)
    X = np.asarray(X, dtype=np.float64)
    N = rays.shape[0]
    nan_result = RansacResult(
        np.full((3, 4), np.nan), np.zeros((N,), dtype=bool), 0
    )
    if N < 3:
        return nan_result

    rng = rng or np.random.default_rng(seed)
    # distinct index triples: draw (iters,N) priorities, take the 3 smallest
    # (kth=2 keeps N==3 legal) — exact sampling without rejection loops
    pri = rng.random((iters, N)).argpartition(2, axis=1)[:, :3]
    poses = p3p_solve(rays[pri], X[pri]).reshape(-1, 3, 4)
    keep = np.isfinite(poses[:, 0, 0])
    poses = poses[keep]
    if poses.shape[0] == 0:
        return nan_result

    best_idx, best_count, best_mask = _score_hypotheses(poses, rays, X, thr_rad)
    if best_count < 3:
        return nan_result
    P = poses[best_idx]

    # local optimization: refine on the current inlier set, keep if the
    # refit's consensus does not shrink, stop when it stops growing
    for _ in range(lo_rounds):
        P_ref = refine_pose_object_space(rays[best_mask], X[best_mask], P)
        _, count_ref, mask_ref = _score_hypotheses(
            P_ref[None], rays, X, thr_rad
        )
        if count_ref < best_count:
            break
        improved = count_ref > best_count
        P, best_count, best_mask = P_ref, count_ref, mask_ref
        if not improved:
            break
    return RansacResult(P, best_mask, int(best_count))
