"""Camera geometry for the localization stage.

Pose convention (matches the reference MATLAB code throughout lib_matlab/):
``P = [R | t]`` is a 3×4 world→camera map, ``x_cam = R @ X_world + t``; the
projective pixel is ``K @ x_cam``.  The camera center in world coordinates is
``C = -Rᵀ t`` (lib_matlab/p2c.m).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def camera_center(P: np.ndarray) -> np.ndarray:
    """World-coordinate camera center ``-Rᵀ t`` (lib_matlab/p2c.m)."""
    P = np.asarray(P, dtype=np.float64)
    return -P[:3, :3].T @ P[:3, 3]


def pose_distance(P1: np.ndarray, P2: np.ndarray) -> Tuple[float, float]:
    """(position error [m], orientation error [rad]) between two poses.

    Position error is the camera-center distance; orientation error is the
    geodesic angle ``acos((tr(R1⁻¹R2) − 1)/2)`` (lib_matlab/p2dist.m).
    """
    P1 = np.asarray(P1, dtype=np.float64)
    P2 = np.asarray(P2, dtype=np.float64)
    dpos = float(np.linalg.norm(camera_center(P1) - camera_center(P2)))
    R = np.linalg.solve(P1[:3, :3], P2[:3, :3])
    cos = np.clip((np.trace(R) - 1.0) / 2.0, -1.0, 1.0)
    return dpos, float(np.arccos(cos))


def intrinsics(focal: float, height: int, width: int) -> np.ndarray:
    """Pinhole K with the principal point at the image center — the query
    camera model of the PnP stage (parfor_NC4D_PE_pnponly.m builds
    ``Kq = [fl 0 W/2; 0 fl H/2; 0 0 1]``)."""
    return np.array(
        [
            [focal, 0.0, width / 2.0],
            [0.0, focal, height / 2.0],
            [0.0, 0.0, 1.0],
        ],
        dtype=np.float64,
    )


def iphone7_focal(height: int, width: int) -> float:
    """Default query focal length in pixels from the iPhone 7's 28 mm
    (35 mm-equivalent) lens: ``long_side · 28/36``.  The 35 mm-equivalence
    is defined against the sensor's LONG side (36 mm of a 36×24 frame), so
    portrait-stored queries (4032×3024 H×W) must use the height — keying on
    width alone would be ~33% low for them.  The reference reads a single
    constant ``params.data.q.fl`` from its external InLoc_demo setup; this
    reconstruction from the camera's EXIF spec is exposed as an overridable
    default (LocalizationConfig.query_focal_length)."""
    return max(height, width) * 28.0 / 36.0


def pixel_rays(K: np.ndarray, xy: np.ndarray) -> np.ndarray:
    """Unit-norm viewing rays ``K⁻¹ [x; y; 1]`` for pixel coords ``xy (N,2)``.

    The reference keeps the un-normalized ray (parfor_NC4D_PE_pnponly.m
    ``Kq^-1 * [x;y;1]``) and lets the angular-threshold RANSAC normalize;
    normalizing here once keeps every downstream dot product a cosine.
    """
    xy = np.asarray(xy, dtype=np.float64)
    ones = np.ones((xy.shape[0], 1))
    rays = np.linalg.solve(
        np.asarray(K, dtype=np.float64), np.concatenate([xy, ones], axis=1).T
    ).T
    return rays / np.linalg.norm(rays, axis=1, keepdims=True)


def project_points(
    P: np.ndarray, K: np.ndarray, X: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Project world points ``X (N,3)`` through ``K @ [R|t]``.

    Returns ``(xy (N,2), depth (N,))``; points behind the camera get negative
    depth (callers mask on it).
    """
    P = np.asarray(P, dtype=np.float64)
    x_cam = X @ P[:3, :3].T + P[:3, 3]
    depth = x_cam[:, 2]
    uvw = x_cam @ np.asarray(K, dtype=np.float64).T
    with np.errstate(divide="ignore", invalid="ignore"):
        xy = uvw[:, :2] / uvw[:, 2:3]
    return xy, depth


def cap_longest_side_shape(
    height: int, width: int, max_side: int = 1920
) -> Tuple[int, int]:
    """Output shape of the localization-stage image cap: longest side scaled
    down to ``max_side``, aspect preserved; never upscales
    (lib_matlab/at_imageresize_nc4d.m)."""
    longest = max(height, width)
    if longest <= max_side:
        return height, width
    scale = max_side / longest
    if height >= width:
        return max_side, int(round(width * scale))
    return int(round(height * scale)), max_side
