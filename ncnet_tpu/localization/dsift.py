"""Dense SIFT descriptors + the pose-verification similarity score.

The reference scores a pose candidate by rendering the scan into the query
camera and comparing dense RootSIFT descriptors between the real and the
synthetic view: ``score = 1 / median ‖d_q − d_synth‖`` over descriptors whose
center lands on rendered pixels (parfor_nc4d_PV.m; vl_phow 'sizes' 8 'step' 4
+ relja_rootsift, both external).  This module is a self-contained, jittable
dense SIFT in the same geometry — 4×4 spatial bins of ``bin_size`` pixels, 8
orientations, descriptors on a ``step``-pixel grid — so both images flow
through ONE fused XLA program each.  Exact vl_phow bit-parity is neither
needed nor attempted: the score only compares descriptors computed the same
way on both images.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

N_ORIENT = 8
N_BINS = 4  # spatial bins per side


def descriptor_grid(
    height: int, width: int, bin_size: int = 8, step: int = 4
) -> Tuple[np.ndarray, np.ndarray]:
    """Descriptor-center coordinates ``(ys, xs)`` such that every 4×4-bin
    support (half-width 1.5·bin_size) stays inside the image."""
    margin = int(1.5 * bin_size)
    ys = np.arange(margin, height - margin, step)
    xs = np.arange(margin, width - margin, step)
    return ys, xs


@functools.lru_cache(maxsize=8)
def _dsift_fn(height: int, width: int, bin_size: int, step: int):
    import jax
    import jax.numpy as jnp

    ys, xs = descriptor_grid(height, width, bin_size, step)
    offs = (bin_size * (np.arange(N_BINS) - (N_BINS - 1) / 2.0)).astype(int)
    # triangular (bilinear) spatial window, separable
    tri = 1.0 - np.abs(np.arange(-bin_size + 1, bin_size)) / bin_size
    tri = jnp.asarray(tri, jnp.float32)

    @jax.jit
    def dsift(img):
        """(H, W) float image → (len(ys), len(xs), 128) descriptors."""
        gy = jnp.gradient(img, axis=0)
        gx = jnp.gradient(img, axis=1)
        mag = jnp.sqrt(gx * gx + gy * gy)
        ang = jnp.arctan2(gy, gx)  # (-pi, pi]
        # soft orientation binning: linear split between the two nearest bins
        o = (ang / (2 * jnp.pi) * N_ORIENT) % N_ORIENT
        lo = jnp.floor(o)
        frac = o - lo
        lo = lo.astype(jnp.int32) % N_ORIENT
        hi = (lo + 1) % N_ORIENT
        omap = (
            jnp.zeros((N_ORIENT, height, width), jnp.float32)
            .at[lo, jnp.arange(height)[:, None], jnp.arange(width)[None, :]]
            .add(mag * (1 - frac))
            .at[hi, jnp.arange(height)[:, None], jnp.arange(width)[None, :]]
            .add(mag * frac)
        )
        # separable triangular pooling: each pixel of `p` holds one spatial
        # bin's weighted magnitude sum centered there
        pad = bin_size - 1
        p = jnp.pad(omap, ((0, 0), (pad, pad), (0, 0)))
        p = jax.vmap(
            lambda ch: jnp.apply_along_axis(
                lambda col: jnp.convolve(col, tri, mode="valid"), 0, ch
            )
        )(p)
        p = jnp.pad(p, ((0, 0), (0, 0), (pad, pad)))
        p = jax.vmap(
            lambda ch: jnp.apply_along_axis(
                lambda row: jnp.convolve(row, tri, mode="valid"), 1, ch
            )
        )(p)
        # gather the 4×4 bin responses for every descriptor center
        rows = ys[:, None] + offs[None, :]          # (Ny, 4)
        cols = xs[:, None] + offs[None, :]          # (Nx, 4)
        d = p[:, rows[:, None, :, None], cols[None, :, None, :]]
        # d: (8, Ny, Nx, 4, 4) → (Ny, Nx, 4, 4, 8) → 128
        d = jnp.transpose(d, (1, 2, 3, 4, 0)).reshape(len(ys), len(xs), -1)
        # SIFT normalization: L2 → clip 0.2 → L2
        n = jnp.linalg.norm(d, axis=-1, keepdims=True)
        d = d / jnp.maximum(n, 1e-9)
        d = jnp.minimum(d, 0.2)
        n = jnp.linalg.norm(d, axis=-1, keepdims=True)
        return d / jnp.maximum(n, 1e-9)

    return dsift


def dense_sift(img: np.ndarray, bin_size: int = 8, step: int = 4) -> np.ndarray:
    """Dense SIFT descriptors ``(Ny, Nx, 128)`` for a float grayscale image.
    An image too small to fit one descriptor support yields a (0, 0, 128)
    array rather than an error."""
    img = np.asarray(img, dtype=np.float32)
    ys, xs = descriptor_grid(img.shape[0], img.shape[1], bin_size, step)
    if len(ys) == 0 or len(xs) == 0:
        return np.zeros((len(ys), len(xs), N_BINS * N_BINS * N_ORIENT),
                        np.float32)
    fn = _dsift_fn(img.shape[0], img.shape[1], bin_size, step)
    return np.asarray(fn(img))


def rootsift(desc: np.ndarray) -> np.ndarray:
    """RootSIFT map (relja_rootsift): L1-normalize then element-wise sqrt —
    Euclidean distance between outputs is the Hellinger kernel distance."""
    d = np.asarray(desc, dtype=np.float32)
    n = np.sum(np.abs(d), axis=-1, keepdims=True)
    return np.sqrt(d / np.maximum(n, 1e-12))


def rgb_to_gray(img: np.ndarray) -> np.ndarray:
    """ITU-R BT.601 luma (MATLAB rgb2gray weights), float output in [0,255]
    for uint8 input."""
    img = np.asarray(img, dtype=np.float64)
    if img.ndim == 2:
        return img
    return img[..., 0] * 0.2989 + img[..., 1] * 0.5870 + img[..., 2] * 0.1140


def normalize_image_masked(img: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Zero-mean / unit-std normalization over the masked region (the
    reference's external ``image_normalization``): photometric gain/bias
    between the real query and the rendered view cancels before descriptor
    comparison."""
    img = np.asarray(img, dtype=np.float64)
    m = np.asarray(mask, dtype=bool)
    if not m.any():
        return np.zeros_like(img)
    mu = img[m].mean()
    sd = img[m].std()
    return (img - mu) / (sd + 1e-9)


def inpaint_nans(img: np.ndarray, iters: int = 100) -> np.ndarray:
    """Fill NaN holes by iterated 3×3 neighbor averaging (a diffusion
    equivalent of the reference's external ``inpaint_nans``) — dense SIFT's
    pooling windows must not see NaNs."""
    img = np.asarray(img, dtype=np.float64).copy()
    nan = ~np.isfinite(img)
    if not nan.any():
        return img
    img[nan] = np.nanmean(img) if np.isfinite(img).any() else 0.0
    known = ~nan
    kernel_sum = np.ones((3, 3))
    for _ in range(iters):
        padded = np.pad(img, 1, mode="edge")
        acc = np.zeros_like(img)
        for dy in (0, 1, 2):
            for dx in (0, 1, 2):
                acc += padded[dy : dy + img.shape[0], dx : dx + img.shape[1]]
        smoothed = acc / kernel_sum.sum()
        img = np.where(known, img, smoothed)
    return img


def pose_verification_score(
    query_gray: np.ndarray,
    synth_gray: np.ndarray,
    valid_mask: np.ndarray,
    bin_size: int = 8,
    step: int = 4,
) -> float:
    """Similarity between the query and a rendered synthetic view:
    ``1 / median ‖RootSIFT_q − RootSIFT_synth‖`` over descriptors centered on
    rendered pixels (parfor_nc4d_PV.m).  Returns 0.0 when nothing rendered.
    """
    mask = np.asarray(valid_mask, dtype=bool)
    if not mask.any():
        return 0.0
    q = np.asarray(query_gray, dtype=np.float64)
    ys, xs = descriptor_grid(q.shape[0], q.shape[1], bin_size, step)
    if len(ys) == 0 or len(xs) == 0:  # image smaller than one descriptor
        return 0.0
    q = normalize_image_masked(q, mask)
    s = np.where(mask, np.asarray(synth_gray, dtype=np.float64), np.nan)
    s = normalize_image_masked(inpaint_nans(s), mask)
    dq = rootsift(dense_sift(q, bin_size, step))
    ds = rootsift(dense_sift(s, bin_size, step))
    iseval = mask[ys[:, None], xs[None, :]]
    if not iseval.any():
        return 0.0
    err = np.linalg.norm(dq[iseval] - ds[iseval], axis=-1)
    med = float(np.median(err))
    return 1.0 / med if med > 0 else float("inf")
