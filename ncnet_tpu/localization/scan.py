"""InLoc scan assets: cutout names, scan transformations, depth back-projection.

The reference resolves a database cutout (e.g. ``DUC1/DUC_cutout_024_30_0.jpg``)
to its RGBD scan and the scan's local→global rigid transformation via two
external InLoc_demo helpers (``parse_WUSTL_cutoutname``,
``load_WUSTL_transformation``, called from parfor_NC4D_PE_pnponly.m and
at_pv_wrapper.m).  This module carries self-contained equivalents:

  * cutout filename → (floor, scene_id, scan_id), pattern
    ``<floor>/<scene>_cutout_<scan>_<pan>_<tilt>.<ext>``;
  * transformation text files: all whitespace rows of 4 floats are collected
    and the LAST 4×4 block is the local→global matrix ``P_after`` (the file's
    earlier block(s) hold the inverse/auxiliary transforms);
  * per-cutout ``XYZcut`` depth maps (.mat, one 3-vector per pixel, NaN where
    the scan has no return) gathered at match coordinates and mapped to global
    coordinates — the reference recipe (parfor_NC4D_PE_pnponly.m):
    db pixel = floor(size · normalized coord), zeros bumped to the first
    pixel, and only matches whose 3D is finite survive;
  * whole-scan point clouds (.mat with the scan's point list) transformed to
    global coordinates for the pose-verification render (at_pv_wrapper.m).
"""

from __future__ import annotations

import os
import re
from typing import NamedTuple, Tuple

import numpy as np

_CUTOUT_RE = re.compile(
    r"(?P<scene>[A-Za-z0-9]+)_cutout_(?P<scan>[A-Za-z0-9]+)_[^_]+_[^_.]+\.\w+$"
)


class CutoutInfo(NamedTuple):
    floor: str      # e.g. 'DUC1' — the path's leading directory
    scene_id: str   # e.g. 'DUC'
    scan_id: str    # e.g. '024'


def parse_cutout_name(name: str) -> CutoutInfo:
    """Split a cutout path into floor/scene/scan ids
    (parse_WUSTL_cutoutname + the floor split in parfor_NC4D_PE_pnponly.m)."""
    floor = name.replace("\\", "/").split("/")[0]
    m = _CUTOUT_RE.search(os.path.basename(name))
    if not m:
        raise ValueError(f"unrecognized cutout name: {name!r}")
    return CutoutInfo(floor, m.group("scene"), m.group("scan"))


def transformation_path(trans_dir: str, name: str) -> str:
    """Path of the scan transformation for a cutout:
    ``<trans_dir>/<floor>/transformations/<scene>_trans_<scan>.txt``
    (parfor_NC4D_PE_pnponly.m)."""
    info = parse_cutout_name(name)
    return os.path.join(
        trans_dir,
        info.floor,
        "transformations",
        f"{info.scene_id}_trans_{info.scan_id}.txt",
    )


def scan_path(scan_dir: str, name: str, suffix: str = ".ptx.mat") -> str:
    """Path of the full scan point cloud for a cutout:
    ``<scan_dir>/<floor>/<scene>_scan_<scan><suffix>``
    (ht_top10_NC4D_PV_localization.m)."""
    info = parse_cutout_name(name)
    return os.path.join(
        scan_dir, info.floor, f"{info.scene_id}_scan_{info.scan_id}{suffix}"
    )


def load_transformation(path: str) -> np.ndarray:
    """Local→global 4×4 from a WUSTL transformation text file.

    The file mixes prose/header lines with numeric rows; every maximal run of
    rows with exactly 4 floats is a matrix block, and the last 4-row block is
    ``P_after`` (the second return of the reference's
    ``load_WUSTL_transformation``).
    """
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            vals = []
            for p in parts:
                try:
                    vals.append(float(p))
                except ValueError:
                    vals = None
                    break
            rows.append(vals if vals and len(vals) == 4 else None)
    blocks = []
    run = []
    for r in rows + [None]:
        if r is not None:
            run.append(r)
        else:
            if len(run) >= 4:
                blocks.append(np.asarray(run[-4:], dtype=np.float64))
            run = []
    if not blocks:
        raise ValueError(f"no 4x4 block found in {path}")
    return blocks[-1]


def load_xyzcut(path: str) -> np.ndarray:
    """Per-pixel 3D map ``(H, W, 3)`` from a cutout's depth .mat
    (``XYZcut`` variable, parfor_NC4D_PE_pnponly.m)."""
    from scipy.io import loadmat

    mat = loadmat(path)
    xyz = np.asarray(mat["XYZcut"], dtype=np.float64)
    if xyz.ndim != 3 or xyz.shape[2] != 3:
        raise ValueError(f"XYZcut in {path} has shape {xyz.shape}")
    return xyz


def load_scan_pointcloud(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Whole-scan point list from a ``*_scan_*.ptx.mat``: returns
    ``(XYZ (N,3) float64, RGB (N,3) uint8)`` in SCAN-LOCAL coordinates.

    The reference's scan files store a cell array ``A`` with columns
    ``{X, Y, Z, ?, R, G, B}`` (at_pv_wrapper.m ``RGB=[A{5},A{6},A{7}]``,
    ``XYZ=[A{1},A{2},A{3}]``); scipy sees it as an object array.
    """
    from scipy.io import loadmat

    mat = loadmat(path)
    A = mat["A"]
    cols = [np.asarray(A[0, i]).reshape(-1) for i in range(A.shape[1])]
    xyz = np.stack(cols[0:3], axis=1).astype(np.float64)
    rgb = np.stack(cols[4:7], axis=1)
    return xyz, np.clip(rgb, 0, 255).astype(np.uint8)


def transform_points(P_after: np.ndarray, xyz: np.ndarray) -> np.ndarray:
    """Apply a 4×4 homogeneous transform to ``(N,3)`` points (at_pv_wrapper.m
    homogeneous divide included)."""
    h = xyz @ P_after[:3, :3].T + P_after[:3, 3]
    w = xyz @ P_after[3, :3].T + P_after[3, 3]
    return h / w[:, None]


def backproject_matches(
    xyzcut: np.ndarray,
    xy_norm: np.ndarray,
    P_after: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Database-side 3D points for matches in normalized [0,1] coordinates.

    Reference recipe (parfor_NC4D_PE_pnponly.m): pixel index =
    ``floor(size · coord)`` in MATLAB's 1-based indexing with zeros bumped to
    1 — equivalently, 0-based ``floor(size·coord) − 1`` clamped into range —
    then a global-coordinate map through the scan transformation, keeping only
    matches with finite 3D.

    Returns ``(X_global (M,3), keep (N,) bool, db_pixels (N,2) int)``.
    """
    H, W = xyzcut.shape[:2]
    xy = np.asarray(xy_norm, dtype=np.float64)
    col = np.floor(W * xy[:, 0]).astype(int)
    row = np.floor(H * xy[:, 1]).astype(int)
    col = np.clip(col, 1, W) - 1  # the reference's zero-fix, made 0-based
    row = np.clip(row, 1, H) - 1
    pts = xyzcut[row, col]  # (N,3) local scan coords
    pts_g = transform_points(np.asarray(P_after, dtype=np.float64), pts)
    keep = np.all(np.isfinite(pts_g), axis=1)
    return pts_g[keep], keep, np.stack([col, row], axis=1)
