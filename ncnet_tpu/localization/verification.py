"""Pose verification by synthetic-view rendering (the reference's densePV
stage: ht_top10_NC4D_PV_localization.m + at_pv_wrapper.m + parfor_nc4d_PV.m).

Each query's top-N pose candidates are re-scored by rendering the candidate's
scan into the query camera at 1/8 scale and comparing dense RootSIFT
descriptors between the real query and the render; candidates are re-ranked
by descending score.  Work is grouped by unique scan so each point cloud
loads once.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ncnet_tpu.localization import geometry
from ncnet_tpu.observability import get_logger

log = get_logger("localization")
from ncnet_tpu.localization.dsift import pose_verification_score, rgb_to_gray
from ncnet_tpu.localization.render import render_points_perspective
from ncnet_tpu.localization.scan import (
    load_scan_pointcloud,
    load_transformation,
    parse_cutout_name,
    scan_path,
    transformation_path,
    transform_points,
)

DOWNSAMPLE = 8  # the reference's dslevel = 8^-1 (parfor_nc4d_PV.m)


class PVItem(NamedTuple):
    query_fn: str
    db_fn: str
    P: np.ndarray


def downsample_image(img: np.ndarray, factor: int = DOWNSAMPLE) -> np.ndarray:
    """Box-filter 1/factor downsample (the render-vs-query comparison runs at
    1/8 scale).  Trailing rows/cols that do not fill a box are dropped."""
    h = img.shape[0] // factor * factor
    w = img.shape[1] // factor * factor
    x = np.asarray(img, dtype=np.float64)[:h, :w]
    x = x.reshape(h // factor, factor, w // factor, factor, -1).mean(axis=(1, 3))
    return x.squeeze(-1) if img.ndim == 2 else x


def verify_pose(
    query_img: np.ndarray,
    P: np.ndarray,
    scan_xyz: np.ndarray,
    scan_rgb: np.ndarray,
    focal: float,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Score one pose candidate against one FULL-RESOLUTION query image
    (downsamples internally; see :func:`verify_pose_downsampled`)."""
    return verify_pose_downsampled(
        downsample_image(query_img), P, scan_xyz, scan_rgb, focal
    )


def verify_pose_downsampled(
    q_small: np.ndarray,
    P: np.ndarray,
    scan_xyz: np.ndarray,
    scan_rgb: np.ndarray,
    focal: float,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Score one pose candidate against an already 1/8-downsampled query.

    ``q_small``: RGB float (H/8, W/8, 3); ``P``: 3×4 candidate; ``focal``:
    the FULL-resolution query focal (scaled internally, parfor_nc4d_PV.m
    ``fl·dslevel``); ``scan_xyz/rgb``: the candidate cutout's scan in GLOBAL
    coordinates.  Returns ``(score, RGBpersp, valid_mask)`` — score 0.0 for
    NaN poses, as in the reference.
    """
    P = np.asarray(P, dtype=np.float64)
    if not np.all(np.isfinite(P)):
        return 0.0, np.zeros((0, 0, 3), np.uint8), np.zeros((0, 0), bool)
    h, w = q_small.shape[:2]
    K = geometry.intrinsics(focal / DOWNSAMPLE, h, w)
    rgb_persp, xyz_persp = render_points_perspective(
        scan_rgb, scan_xyz, K @ P, h, w
    )
    valid = np.all(np.isfinite(xyz_persp), axis=2)
    score = pose_verification_score(
        rgb_to_gray(q_small), rgb_to_gray(rgb_persp), valid
    )
    return score, rgb_persp, valid


def group_items_by_scan(items: Sequence[PVItem]) -> Dict[str, List[PVItem]]:
    """Bucket verification jobs by their cutout's (floor, scene, scan) so each
    scan point cloud is loaded exactly once
    (ht_top10_NC4D_PV_localization.m's unique-scan parfor grouping)."""
    groups: Dict[str, List[PVItem]] = {}
    for it in items:
        info = parse_cutout_name(it.db_fn)
        key = f"{info.floor}/{info.scene_id}_{info.scan_id}"
        groups.setdefault(key, []).append(it)
    return groups


def run_pose_verification(
    items: Sequence[PVItem],
    query_loader: Callable[[str], np.ndarray],
    scan_dir: str,
    trans_dir: str,
    focal_fn: Callable[[str, np.ndarray], float],
    out_dir: str = "",
    scan_suffix: str = ".ptx.mat",
    progress: bool = True,
    prepared_queries: Optional[Dict[str, Tuple[np.ndarray, float]]] = None,
) -> Dict[Tuple[str, str], float]:
    """Score every (query, db, P) item, grouped by scan.  Returns
    ``{(query_fn, db_fn): score}``.

    ``query_loader(fn)`` → RGB uint8 array; ``focal_fn(fn, img)`` → query
    focal in pixels at full resolution.  When ``out_dir`` is set, per-item
    ``.pv.mat`` artifacts (score + render) are written and reloaded on rerun
    (resume-by-artifact, parfor_nc4d_PV.m's exist guard).

    ``prepared_queries``: ``{query_fn: (downsampled image, full-res focal)}``
    — callers that split the work across processes pass these so each query
    is decoded/downsampled once globally instead of once per scan group.
    """
    from scipy.io import loadmat

    from ncnet_tpu.localization.pnp import artifact_stem
    from ncnet_tpu.utils.io import atomic_savemat

    scores: Dict[Tuple[str, str], float] = {}
    # cache the 1/8-downsampled query (+ its full-res focal), not the full
    # image: 356 iPhone7 queries at full resolution would hold ~13 GB
    query_cache: Dict[str, Tuple[np.ndarray, float]] = dict(
        prepared_queries or {}
    )
    groups = group_items_by_scan(items)
    for gi, (key, group) in enumerate(sorted(groups.items())):
        scan_loaded = None
        for it in group:
            art = ""
            if out_dir:
                art = os.path.join(
                    out_dir, it.query_fn, artifact_stem(it.db_fn) + ".pv.mat"
                )
                if os.path.exists(art):
                    scores[(it.query_fn, it.db_fn)] = float(
                        loadmat(art)["score"].ravel()[0]
                    )
                    continue
            if scan_loaded is None:
                xyz_local, rgb = load_scan_pointcloud(
                    scan_path(scan_dir, it.db_fn, scan_suffix)
                )
                P_after = load_transformation(
                    transformation_path(trans_dir, it.db_fn)
                )
                scan_loaded = (transform_points(P_after, xyz_local), rgb)
            if it.query_fn not in query_cache:
                qimg = query_loader(it.query_fn)
                query_cache[it.query_fn] = (
                    downsample_image(qimg),
                    focal_fn(it.query_fn, qimg),
                )
            q_small, focal = query_cache[it.query_fn]
            score, rgb_persp, valid = verify_pose_downsampled(
                q_small, it.P, scan_loaded[0], scan_loaded[1], focal
            )
            scores[(it.query_fn, it.db_fn)] = score
            if art:
                os.makedirs(os.path.dirname(art), exist_ok=True)
                atomic_savemat(
                    art,
                    {"score": score, "RGBpersp": rgb_persp, "RGB_flag": valid},
                    do_compression=True,
                )
        if progress:
            log.info(f"ncnetPV: scan {key} ({gi + 1} / {len(groups)}) done.")
    return scores


def rerank_by_scores(
    topN_names: Sequence[str],
    poses: Sequence[np.ndarray],
    scores: Sequence[float],
):
    """Descending-score rerank of one query's candidate list
    (ht_top10_NC4D_PV_localization.m's sort).  Returns
    ``(names, poses, scores)`` reordered."""
    order = np.argsort(-np.asarray(scores, dtype=np.float64), kind="stable")
    return (
        [topN_names[i] for i in order],
        [poses[i] for i in order],
        [float(scores[i]) for i in order],
    )
